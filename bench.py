"""North-star benchmark: federated rounds/sec at K=1000 clients, B=100
classflip Byzantine, MNIST MLP, geometric-median aggregation.

BASELINE.json target: >= 50 rounds/sec (a "round" = displayInterval = 10
global iterations, the reference's unit at MNIST_Air_weight.py:286-287).
``vs_baseline`` is value / 50.

Prints exactly ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import jax.numpy as jnp

TARGET_ROUNDS_PER_SEC = 50.0  # BASELINE.json north star (v5e-8, K=1000, B=100)

K = 1000
B = 100
AGG = "gm2"
ATTACK = "classflip"
WARMUP_ROUNDS = 3
TIMED_ROUNDS = 50


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    # Watchdog: a wedged device tunnel can block JAX backend init (or any
    # dispatch) indefinitely, which would hang the whole bench harness.  A
    # healthy TPU run finishes in ~2-3 min incl. compiles; if we are still
    # alive at the deadline something is wedged — exit non-zero instead of
    # hanging.  Override for legitimately slow environments (e.g. a CPU
    # smoke run of the K=1000 config) with BENCH_WATCHDOG_SECS; 0 disables.
    import os
    import threading

    deadline = float(os.environ.get("BENCH_WATCHDOG_SECS", "900"))

    def _abort():
        print(
            f"bench: WATCHDOG — no completion after {deadline:.0f}s, aborting",
            file=sys.stderr,
        )
        sys.stderr.flush()
        os._exit(3)

    watchdog = threading.Timer(deadline, _abort)
    watchdog.daemon = True
    if deadline > 0:
        watchdog.start()

    import jax

    from byzantine_aircomp_tpu.data import datasets as data_lib
    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.harness import _make_trainer
    from byzantine_aircomp_tpu.fed.train import FedTrainer

    log(
        f"bench: backend={jax.default_backend()} devices={len(jax.devices())} "
        f"K={K} B={B} agg={AGG} attack={ATTACK}"
    )

    cfg = FedConfig(
        honest_size=K - B,
        byz_size=B,
        attack=ATTACK,
        agg=AGG,
        rounds=WARMUP_ROUNDS + 3 * TIMED_ROUNDS,
        display_interval=10,
        batch_size=50,
        eval_train=False,
        # reference caller overrides: maxiter=1000, tol=1e-5 (:350)
        agg_maxiter=1000,
        agg_tol=1e-5,
    )
    trainer = _make_trainer(cfg, FedTrainer)
    log(f"bench: dataset source={trainer.dataset.name}/{trainer.dataset.source} d={trainer.dim}")

    # warmup compiles the TIMED_ROUNDS-shaped multi-round program (one device
    # program for the whole timed block — no per-round host dispatch) and
    # executes it twice: the first post-compile execution runs measurably
    # below steady state (device-side caching/ramp on the tunneled chip)
    trainer.run_rounds(0, WARMUP_ROUNDS)
    trainer.run_rounds(WARMUP_ROUNDS, TIMED_ROUNDS)
    trainer.run_rounds(WARMUP_ROUNDS + TIMED_ROUNDS, TIMED_ROUNDS)
    # a host transfer of a value derived from the params is the only honest
    # completion barrier: on tunneled devices block_until_ready can return
    # before the dispatched programs actually finish
    float(jnp.sum(trainer.flat_params))
    log("bench: warmup done (compiled)")

    start = WARMUP_ROUNDS + 2 * TIMED_ROUNDS
    t0 = time.perf_counter()
    trainer.run_rounds(start, TIMED_ROUNDS)
    float(jnp.sum(trainer.flat_params))
    dt = time.perf_counter() - t0
    rps = TIMED_ROUNDS / dt

    loss, acc = trainer.evaluate("val")
    log(f"bench: {TIMED_ROUNDS} rounds in {dt:.3f}s -> {rps:.2f} rounds/sec "
        f"(val_loss={loss:.4f} val_acc={acc:.4f})")

    watchdog.cancel()
    print(
        json.dumps(
            {
                "metric": f"fl_rounds_per_sec_K{K}_B{B}_{ATTACK}_{AGG}_mnist_mlp",
                "value": round(rps, 3),
                "unit": "rounds/sec",
                "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
