"""North-star benchmark: federated rounds/sec at K=1000 clients, B=100
classflip Byzantine, MNIST MLP, geometric-median aggregation.

BASELINE.json target: >= 50 rounds/sec (a "round" = displayInterval = 10
global iterations, the reference's unit at MNIST_Air_weight.py:286-287).
``vs_baseline`` is value / 50.

Prints exactly ONE JSON line on stdout; progress goes to stderr.  The
line is a schema-versioned ``bench`` event (``obs.events.make_event``,
emitted through ``obs.sinks.StdoutSink``) carrying explicit provenance:
``platform`` (what actually ran), ``fallback_reason`` (why the
accelerator path was abandoned, null on a clean run), ``relay`` (the
tunnel-relay diagnosis when one was made) and the config fields
(``k``/``b``/``agg``/``attack``/``dataset``/``model``) the perf ledger
keys baselines on (``obs/ledger.py``; gate with
``analysis/perf_gate.py``).  Set ``BENCH_LEDGER=path`` to also append
the row to that ledger, and ``BENCH_TINY=1`` for a CI-sized config
(K=32, B=4).

Staged, tunnel-proof harness (round-1 failure mode: a wedged axon relay
blocks JAX backend init indefinitely -> 900 silent seconds -> watchdog
rc=3 with no diagnostics):

  stage 1  parent (never initializes a backend): probe backend init in a
           subprocess with the inherited env, BENCH_PROBE_SECS timeout
           (default 120).
  stage 2a probe ok on an accelerator -> run the real bench in a child with
           the inherited env (BENCH_RUN_SECS, default 600).
  stage 2b probe wedged / CPU-only / accelerator child failed -> run a
           scrubbed-env CPU fallback (PALLAS_AXON_POOL_IPS unset so the
           axon sitecustomize never boots the tunnel; JAX_PLATFORMS=cpu)
           with fewer timed rounds, annotated with ``platform`` +
           ``fallback_reason`` so the artifact is self-describing.

Either way the driver gets one parseable JSON line, never a silent hang.

A wedged probe is retried (``BENCH_PROBE_RETRIES``, default 2 extra
attempts with ``BENCH_PROBE_BACKOFF_SECS`` between them — transient relay
restarts recover within seconds) and every failed attempt's relay
diagnosis is recorded in ``fallback_reason`` so the artifact says WHICH
tunnel state was observed, per attempt, before the CPU fallback.

``BENCH_STREAM_KSWEEP=1`` switches to a different mode entirely: a small
cohort-streamed K-sweep (``fed/train.py`` ``--cohort-size`` path) that
emits one ``stream_ksweep`` row per K with rounds/sec AND the peak-bytes
columns (measured watermark + the ``obs/hbm.py`` streamed/resident
models), one JSON line per K on stdout.  Rows land in the ledger via
``BENCH_LEDGER`` or ``analysis/perf_gate.py --append``.  Further modes:
``BENCH_SIGNPACK=1`` (packed sign-channel rows), ``BENCH_MULTIROUND=1``
(dispatch-rim sweep), ``BENCH_HETERO=1`` (heterogeneity sweep —
ResNet18 on ``emnist_hard`` across Dirichlet levels).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_ROUNDS_PER_SEC = 50.0  # BASELINE.json north star (v5e-8, K=1000, B=100)

AGG = "gm2"
ATTACK = "classflip"


def bench_params() -> dict:
    """The benchmark configuration, env-tunable for CI smoke runs.

    Default is the north-star config (K=1000, B=100); ``BENCH_TINY=1``
    shrinks it to a CI-runnable size under a DIFFERENT metric name —
    tiny rows must never average into the north-star baseline."""
    if os.environ.get("BENCH_TINY"):
        k, b = 32, 4
    else:
        k, b = 1000, 100
    return {
        "k": k,
        "b": b,
        "agg": AGG,
        "attack": ATTACK,
        "dataset": "mnist",
        "model": "MLP",
        "metric": f"fl_rounds_per_sec_K{k}_B{b}_{ATTACK}_{AGG}_mnist_mlp",
    }


# module-level aliases kept for external readers of the historical names
_P = bench_params()
K, B, METRIC = _P["k"], _P["b"], _P["metric"]


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def make_bench_row(
    value: float,
    *,
    platform: str,
    timed_rounds: int,
    val_acc: float | None = None,
    fallback_reason: str | None = None,
    relay: str | None = None,
    params: dict | None = None,
) -> dict:
    """One schema-versioned ``bench`` event row (the stdout contract)."""
    from byzantine_aircomp_tpu.obs.events import make_event

    p = params or bench_params()
    row = make_event(
        "bench",
        metric=p["metric"],
        value=round(value, 3),
        unit="rounds/sec",
        vs_baseline=round(value / TARGET_ROUNDS_PER_SEC, 4),
        platform=platform,
        timed_rounds=timed_rounds,
        k=p["k"],
        b=p["b"],
        agg=p["agg"],
        attack=p["attack"],
        dataset=p["dataset"],
        model=p["model"],
        fallback_reason=fallback_reason,
        relay=relay,
    )
    if val_acc is not None:
        row["val_acc"] = round(float(val_acc), 4)
    if fallback_reason is not None:
        # historical field name, kept so existing BENCH_r*.json consumers
        # (and PERFORMANCE.md narrative greps) keep working
        row["error"] = fallback_reason
    return row


def emit_row(row: dict) -> None:
    """The one machine-readable stdout line, through the shared sink."""
    from byzantine_aircomp_tpu.obs.sinks import StdoutSink

    StdoutSink().emit(row)
    ledger_path = os.environ.get("BENCH_LEDGER")
    if ledger_path and row.get("platform") not in (None, "none"):
        from byzantine_aircomp_tpu.obs.ledger import (
            LEDGER_EXTRA_FIELDS, PerfLedger, config_key,
        )

        extra = {
            f: row[f] for f in LEDGER_EXTRA_FIELDS if row.get(f) is not None
        }
        PerfLedger(ledger_path).append(
            str(row["metric"]), float(row["value"]),
            unit=str(row.get("unit", "")),
            platform=str(row["platform"]),
            key=config_key(row),
            timed_rounds=row.get("timed_rounds"),
            note="bench.py" + (" (fallback)" if row.get("fallback_reason")
                              else ""),
            **extra,
        )
        log(f"appended row to ledger {ledger_path}")


# --------------------------------------------------------------------------
# child: the actual benchmark (runs with whatever backend the env selects)
# --------------------------------------------------------------------------

def run_child() -> None:
    from byzantine_aircomp_tpu.utils.env import condense_stderr_warnings

    # the XLA machine-feature wall of text (one multi-KB line per compile)
    # used to bury the progress log in BENCH_r*.json tails; full text goes
    # to BENCH_LOG_FILE when set, stderr gets a one-line summary
    restore_stderr = condense_stderr_warnings(
        os.environ.get("BENCH_LOG_FILE", "")
    )
    try:
        _run_child_inner()
    finally:
        restore_stderr()


def _run_child_inner() -> None:
    warmup = int(os.environ.get("BENCH_WARMUP_ROUNDS", "3"))
    timed = int(os.environ.get("BENCH_TIMED_ROUNDS", "50"))
    params = bench_params()

    import jax
    import jax.numpy as jnp

    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.harness import _make_trainer
    from byzantine_aircomp_tpu.fed.train import FedTrainer

    log(
        f"child: backend={jax.default_backend()} devices={len(jax.devices())} "
        f"K={params['k']} B={params['b']} agg={params['agg']} "
        f"attack={params['attack']} warmup={warmup} timed={timed}"
    )

    cfg = FedConfig(
        honest_size=params["k"] - params["b"],
        byz_size=params["b"],
        attack=params["attack"],
        agg=params["agg"],
        rounds=warmup + 3 * timed,
        display_interval=10,
        batch_size=50,
        eval_train=False,
        # reference caller overrides: maxiter=1000, tol=1e-5 (:350)
        agg_maxiter=1000,
        agg_tol=1e-5,
    )
    trainer = _make_trainer(cfg, FedTrainer)
    log(f"child: dataset source={trainer.dataset.name}/{trainer.dataset.source} d={trainer.dim}")

    # warmup compiles the timed-shaped multi-round program (one device
    # program for the whole timed block — no per-round host dispatch) and
    # executes it twice: the first post-compile execution runs measurably
    # below steady state (device-side caching/ramp on the tunneled chip)
    trainer.run_rounds(0, warmup)
    log("child: compile + first warmup block done")
    trainer.run_rounds(warmup, timed)
    trainer.run_rounds(warmup + timed, timed)
    # a host transfer of a value derived from the params is the only honest
    # completion barrier: on tunneled devices block_until_ready can return
    # before the dispatched programs actually finish
    float(jnp.sum(trainer.flat_params))
    log("child: warmup done")

    start = warmup + 2 * timed
    t0 = time.perf_counter()
    trainer.run_rounds(start, timed)
    float(jnp.sum(trainer.flat_params))
    dt = time.perf_counter() - t0
    rps = timed / dt

    loss, acc = trainer.evaluate("val")
    log(f"child: {timed} rounds in {dt:.3f}s -> {rps:.2f} rounds/sec "
        f"(val_loss={loss:.4f} val_acc={acc:.4f})")

    emit_row(
        make_bench_row(
            rps,
            platform=jax.default_backend(),
            timed_rounds=timed,
            val_acc=acc,
            params=params,
        )
    )


# --------------------------------------------------------------------------
# stream_ksweep mode: streamed-round scaling rows (BENCH_STREAM_KSWEEP=1)
# --------------------------------------------------------------------------

def run_stream_ksweep() -> None:
    """Cohort-streamed K-sweep: one ``stream_ksweep`` row per K.

    Answers the question the north-star bench cannot: how do rounds/sec
    and peak memory scale with K when the round never materializes the
    resident [K, d] stack (``fed/train.py`` ``--cohort-size`` streaming)?
    Each row carries the measured watermark (``obs/profile.device_memory``
    — source-labeled, host RSS on CPU) plus BOTH analytic peak models
    (``obs/hbm.streamed_peak_bytes`` and the resident
    ``modeled_peak_bytes``), so the ledger records the gap streaming
    opens.  Env knobs: ``BENCH_KSWEEP_KS`` (comma list),
    ``BENCH_KSWEEP_COHORT``, ``BENCH_KSWEEP_AGG``, ``BENCH_KSWEEP_ROUNDS``
    (timed rounds per K).  Runs on whatever backend the env selects — the
    CI smoke pins JAX_PLATFORMS=cpu.

    SERVICE mode (``BENCH_KSWEEP_SERVICE=1``): the K entries become
    POPULATION sizes for an always-on service round — each round draws a
    ``BENCH_KSWEEP_NODE``-participant cohort from the K-id population and
    streams it in ``BENCH_KSWEEP_COHORT`` chunks, optionally sharded over
    the population mesh (``BENCH_KSWEEP_POP_SHARDS``, comma list — one
    row per (K, pop_shards) pair; shard counts above the device count are
    skipped).  Service rows record ``k = population`` (the id space the
    round draws from — THE axis of the K=1M acceptance row) and carry
    ``population``, ``pop_shards`` and the per-host streamed peak model
    alongside the measured watermark.
    """
    ks = [
        int(s)
        for s in os.environ.get("BENCH_KSWEEP_KS", "64,256,1024").split(",")
        if s.strip()
    ]
    cohort = int(os.environ.get("BENCH_KSWEEP_COHORT", "32"))
    agg = os.environ.get("BENCH_KSWEEP_AGG", "median")
    timed = int(os.environ.get("BENCH_KSWEEP_ROUNDS", "2"))
    service = os.environ.get("BENCH_KSWEEP_SERVICE", "") not in ("", "0")
    node = int(os.environ.get("BENCH_KSWEEP_NODE", "1000"))
    shard_list = [
        int(s)
        for s in os.environ.get("BENCH_KSWEEP_POP_SHARDS", "1").split(",")
        if s.strip()
    ]

    import jax
    import jax.numpy as jnp

    from byzantine_aircomp_tpu.data import datasets as data_lib
    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.train import FedTrainer
    from byzantine_aircomp_tpu.obs import hbm as hbm_lib
    from byzantine_aircomp_tpu.obs.profile import device_memory

    platform = jax.default_backend()
    n_dev = len(jax.devices())
    log(f"stream_ksweep: backend={platform} Ks={ks} cohort={cohort} "
        f"agg={agg} timed={timed} service={service} "
        f"pop_shards={shard_list if service else '-'}")
    for k in ks:
        for ps in (shard_list if service else [1]):
            if service:
                if k % node:
                    log(f"stream_ksweep: skipping population {k} "
                        f"(not a multiple of node_size {node})")
                    continue
                if ps > 1 and n_dev < ps:
                    log(f"stream_ksweep: skipping pop_shards={ps} "
                        f"(only {n_dev} devices)")
                    continue
                if (node // cohort) % ps:
                    log(f"stream_ksweep: skipping pop_shards={ps} "
                        f"({node // cohort} chunks not divisible)")
                    continue
                cfg = FedConfig(
                    honest_size=node, byz_size=0, agg=agg,
                    cohort_size=cohort, rounds=1 + timed,
                    display_interval=1, batch_size=8, eval_train=False,
                    agg_maxiter=100, service="on", population=k,
                    straggler_prob=0.1, pop_shards=ps,
                )
                n_train = 4 * node
            else:
                if k % cohort:
                    log(f"stream_ksweep: skipping K={k} "
                        f"(not divisible by cohort {cohort})")
                    continue
                cfg = FedConfig(
                    honest_size=k, byz_size=0, agg=agg,
                    cohort_size=cohort, rounds=1 + timed,
                    display_interval=1, batch_size=8, eval_train=False,
                    agg_maxiter=100,
                )
                n_train = 4 * k
            ds = data_lib.load(
                "mnist", synthetic_train=n_train, synthetic_val=256
            )
            if ps > 1:
                # device count already checked above, so the harness's
                # engine pick always lands on the mesh trainer here
                from byzantine_aircomp_tpu.parallel import (
                    PopShardedFedTrainer,
                )
                trainer = PopShardedFedTrainer(cfg, dataset=ds)
            else:
                trainer = FedTrainer(cfg, dataset=ds)
            trainer.run_rounds(0, 1)  # compile + one warmup round
            float(jnp.sum(trainer.flat_params))
            t0 = time.perf_counter()
            trainer.run_rounds(1, timed)
            float(jnp.sum(trainer.flat_params))  # honest completion barrier
            dt = time.perf_counter() - t0
            mem = device_memory()
            row = make_bench_row(
                timed / dt,
                platform=platform,
                timed_rounds=timed,
                params={
                    "k": k, "b": 0, "agg": agg, "attack": None,
                    "dataset": "mnist", "model": "MLP",
                    "metric": "stream_ksweep",
                },
            )
            if service:
                # part of the ledger config key: rows at different shard
                # counts are different configurations (the scaling curve),
                # not noise around one baseline; None-skipped for classic
                # rows so their historical keys are unchanged
                row["pop_shards"] = ps
            row["cohort_size"] = cohort
            row["d"] = int(trainer.dim)
            row["peak_measured_bytes"] = int(mem["peak_bytes_in_use"])
            row["peak_source"] = str(mem["source"])
            if service:
                row["population"] = k
                # per-participant surviving state: the [population] avail
                # bools, expressed per drawn participant (fed/harness.py
                # uses the same accounting in its run_end summary)
                state_pc = k // node
                row["peak_streamed_modeled_bytes"] = (
                    hbm_lib.streamed_peak_bytes(
                        node, trainer.dim, cohort,
                        state_bytes_per_client=state_pc,
                    )
                )
                row["peak_per_host_modeled_bytes"] = (
                    hbm_lib.streamed_peak_bytes(
                        node, trainer.dim, cohort,
                        state_bytes_per_client=state_pc, pop_shards=ps,
                    )
                )
                row["peak_resident_modeled_bytes"] = (
                    hbm_lib.modeled_peak_bytes(node, trainer.dim)
                )
            else:
                row["peak_streamed_modeled_bytes"] = (
                    hbm_lib.streamed_peak_bytes(k, trainer.dim, cohort)
                )
                row["peak_resident_modeled_bytes"] = (
                    hbm_lib.modeled_peak_bytes(k, trainer.dim)
                )
            log(
                f"stream_ksweep: K={k}"
                + (f" ps={ps}" if service else "")
                + f" d={trainer.dim} {timed / dt:.3f} "
                f"rounds/sec, peak {mem['peak_bytes_in_use']} B "
                f"({mem['source']}), streamed model "
                f"{row['peak_streamed_modeled_bytes']} B, resident model "
                f"{row['peak_resident_modeled_bytes']} B"
            )
            emit_row(row)


# --------------------------------------------------------------------------
# signpack mode: packed one-bit sign-channel rows (BENCH_SIGNPACK=1)
# --------------------------------------------------------------------------

def run_signpack_bench() -> None:
    """Packed vs unpacked sign-channel rows: one per ``sign_bits``.

    Runs the SAME tiny signmv training config at ``--sign-bits 32``
    (legacy f32 ballots) and ``--sign-bits 1`` (bit-packed uint32 words +
    popcount reduce, ``fed/train.py`` packed resident path), emitting
    rounds/sec plus the ``bytes_moved`` columns from the ``obs/hbm.py``
    packed model.  Every row carries ``platform`` and — on the packed row
    — a non-null ``fallback_reason`` whenever the popcount reduce did NOT
    run the pallas kernel on a TPU (VMEM rejection, or a non-TPU
    backend), so the perf-smoke CI step can gate the bandwidth claim with
    ``perf_gate --expect-platform tpu`` and a relay-dead CPU fallback can
    never land as a green ~32x headline (the BENCH_r02–r05 trap).  Env
    knobs: ``BENCH_SIGNPACK_K``/``_B``/``_AGG``/``_ROUNDS``.
    """
    timed = int(os.environ.get("BENCH_SIGNPACK_ROUNDS", "3"))
    k = int(os.environ.get("BENCH_SIGNPACK_K", "32"))
    b = int(os.environ.get("BENCH_SIGNPACK_B", "4"))
    agg = os.environ.get("BENCH_SIGNPACK_AGG", "signmv")

    import jax
    import jax.numpy as jnp

    from byzantine_aircomp_tpu.data import datasets as data_lib
    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.train import FedTrainer
    from byzantine_aircomp_tpu.obs import hbm as hbm_lib
    from byzantine_aircomp_tpu.ops import pallas_kernels as pk

    platform = jax.default_backend()
    log(f"signpack: backend={platform} K={k} B={b} agg={agg} timed={timed}")
    for bits in (32, 1):
        cfg = FedConfig(
            honest_size=k - b,
            byz_size=b,
            attack="signflip",
            agg=agg,
            sign_eta=0.01,
            sign_bits=bits,
            rounds=1 + timed,
            display_interval=1,
            batch_size=8,
            eval_train=False,
        )
        ds = data_lib.load("mnist", synthetic_train=4 * k, synthetic_val=256)
        trainer = FedTrainer(cfg, dataset=ds)
        trainer.run_rounds(0, 1)  # compile + one warmup round
        float(jnp.sum(trainer.flat_params))
        t0 = time.perf_counter()
        trainer.run_rounds(1, timed)
        float(jnp.sum(trainer.flat_params))  # honest completion barrier
        dt = time.perf_counter() - t0
        d = int(trainer.dim)

        fallback = None
        if bits == 1:
            # why the packed reduce is NOT the TPU popcount kernel — the
            # provenance the --expect-platform gate makes unmissable
            fallback = pk.signpack_fused_reason(k) or (
                None if platform == "tpu" else
                f"packed reduce ran the XLA bit-plane realization "
                f"(backend={platform}, not tpu)"
            )
        row = make_bench_row(
            timed / dt,
            platform=platform,
            timed_rounds=timed,
            fallback_reason=fallback,
            params={
                "k": k, "b": b, "agg": agg, "attack": "signflip",
                "dataset": "mnist", "model": "MLP",
                # one metric per width: the ledger keys baselines on
                # (metric, platform, key) and the 1-bit and 32-bit rows
                # must never average into each other
                "metric": f"signpack_round_rps_sb{bits}",
            },
        )
        row["d"] = d
        row["sign_bits"] = bits
        row["bytes_moved"] = hbm_lib.packed_stack_bytes(k, d, bits)
        row["bytes_moved_f32"] = hbm_lib.stack_bytes(k, d)
        log(
            f"signpack: sb{bits} {timed / dt:.3f} rounds/sec, sign-channel "
            f"{row['bytes_moved']} B vs f32 {row['bytes_moved_f32']} B "
            f"({row['bytes_moved'] / row['bytes_moved_f32']:.4f}x)"
            + (f", fallback_reason={fallback!r}" if fallback else "")
        )
        emit_row(row)


# --------------------------------------------------------------------------
# multiround mode: dispatch-rim sweep rows (BENCH_MULTIROUND=1)
# --------------------------------------------------------------------------

def run_multiround_bench() -> None:
    """Dispatch-rim sweep: one row per ``--rounds-per-dispatch`` tier.

    Runs the FULL production driver (``FedTrainer.train()`` — per-round
    observability, eval cadence, checkpoint hooks, the host rim the R
    knob exists to amortize) on the committed signpack K=32 config at
    each ``R`` in ``BENCH_MULTIROUND_RLIST`` (default ``1,8,32``), and
    emits one ``multiround_train_rps_rdR`` row per tier.  The R value is
    baked into the metric name so same-R rows regression-test against
    each other in the ledger, and carried as ``rounds_per_dispatch`` so
    the sweep stays greppable as one family.

    The reported value is the STEADY-STATE amortized per-round rate,
    read off the driver's own event stream: the run is observed through
    a :class:`MemorySink`, and the rate is ``(rounds - R)`` divided by
    the timestamp gap between the FIRST dispatch's last ``round`` event
    (compile + first exec + first eval all behind it) and the final
    ``round`` event.  That window keeps everything the R knob amortizes
    — per-round eval at R=1 vs per-dispatch eval at R>1, host record
    appends, dispatch overhead — while excising compile, which would
    otherwise swamp the ratio at bench-sized budgets.  The driver's own
    ``roundsPerSec`` path is NOT used: it deliberately times only the
    device dispatch (no eval, no rim), so it cannot see the cost this
    sweep exists to measure.  ``val_acc`` rides on every row — the
    training math is bit-identical across R, so a val_acc that moves
    with R is a correctness regression, not noise.

    Env knobs: ``BENCH_MULTIROUND_K``/``_B``/``_AGG``/``_ROUNDS``/
    ``_RLIST``/``_VAL``.  ``_ROUNDS`` must be a multiple of every tier
    in the list (the driver enforces clean division).  ``_VAL`` sizes
    the synthetic validation split: the R=1 driver pays that eval every
    round while R>1 pays it once per dispatch, so a larger split makes
    the amortization the CI ratio gate measures stand out from
    device-compute noise on a shared CPU runner.

    ``BENCH_MULTIROUND_EXPECT_SPEEDUP=X`` turns the sweep into a gate
    (the ``adaptive_matrix --expect-speedup`` idiom): the highest tier's
    steady rate must be >= X times the R=1 rate, and ``val_acc`` must be
    IDENTICAL across every tier (the dispatch rim moves granularity, not
    math) — either breach exits nonzero.
    """
    k = int(os.environ.get("BENCH_MULTIROUND_K", "32"))
    b = int(os.environ.get("BENCH_MULTIROUND_B", "4"))
    agg = os.environ.get("BENCH_MULTIROUND_AGG", "signmv")
    rounds = int(os.environ.get("BENCH_MULTIROUND_ROUNDS", "96"))
    val = int(os.environ.get("BENCH_MULTIROUND_VAL", "256"))
    rlist = [
        int(r)
        for r in os.environ.get("BENCH_MULTIROUND_RLIST", "1,8,32").split(",")
        if r.strip()
    ]

    import jax

    from byzantine_aircomp_tpu import obs as obs_lib
    from byzantine_aircomp_tpu.data import datasets as data_lib
    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.train import FedTrainer
    from byzantine_aircomp_tpu.obs.sinks import MemorySink

    platform = jax.default_backend()
    log(
        f"multiround: backend={platform} K={k} B={b} agg={agg} "
        f"rounds={rounds} R list={rlist}"
    )
    rps_by_r: dict[int, float] = {}
    acc_by_r: dict[int, float] = {}
    for R in rlist:
        cfg = FedConfig(
            honest_size=k - b,
            byz_size=b,
            attack="signflip",
            agg=agg,
            sign_eta=0.01,
            rounds=rounds,
            rounds_per_dispatch=R,
            display_interval=1,
            batch_size=8,
            eval_train=False,
        )
        ds = data_lib.load("mnist", synthetic_train=4 * k, synthetic_val=val)
        trainer = FedTrainer(cfg, dataset=ds)
        sink = MemorySink()
        paths = trainer.train(obs=obs_lib.Observability(sink))
        d = int(trainer.dim)

        # steady window: from the FIRST dispatch's last round event
        # (compile + first exec + first eval all behind it) to the final
        # round event — everything the R knob amortizes, no compile
        ts_by_round = {e["round"]: e["ts"] for e in sink.by_kind("round")}
        steady = max(ts_by_round[rounds - 1] - ts_by_round[R - 1], 1e-9)
        rps = (rounds - R) / steady
        val_acc = paths["valAccPath"][-1]

        row = make_bench_row(
            rps,
            platform=platform,
            timed_rounds=rounds - R,
            val_acc=val_acc,
            params={
                "k": k, "b": b, "agg": agg, "attack": "signflip",
                "dataset": "mnist", "model": "MLP",
                "metric": f"multiround_train_rps_rd{R}",
            },
        )
        row["d"] = d
        row["rounds_per_dispatch"] = R
        rps_by_r[R] = rps
        acc_by_r[R] = round(float(val_acc), 6)
        log(
            f"multiround: rd{R} steady {rps:.3f} rounds/sec "
            f"({rounds - R} rounds in {steady:.3f}s past the first "
            f"dispatch, val_acc={val_acc:.4f})"
        )
        emit_row(row)

    expect = float(os.environ.get("BENCH_MULTIROUND_EXPECT_SPEEDUP", "0"))
    if expect and 1 in rps_by_r and len(rps_by_r) > 1:
        if len(set(acc_by_r.values())) != 1:
            log(f"multiround: GATE FAIL — val_acc moved with R: {acc_by_r}")
            sys.exit(1)
        top = max(r for r in rps_by_r if r > 1)
        ratio = rps_by_r[top] / rps_by_r[1]
        status = "ok" if ratio >= expect else "FAIL"
        log(
            f"multiround: gate {status} — rd{top} / rd1 = {ratio:.2f}x "
            f"(bar {expect:.1f}x), val_acc identical across tiers"
        )
        if ratio < expect:
            sys.exit(1)


# --------------------------------------------------------------------------
# hetero mode: heterogeneity sweep rows (BENCH_HETERO=1)
# --------------------------------------------------------------------------

def run_hetero_bench() -> None:
    """Heterogeneity sweep: one row per Dirichlet level.

    Runs the full production driver on a harder-than-mnist regime — the
    ``emnist_hard`` synthetic (62 classes, EMNIST moments, ~0.91 Bayes
    ceiling) under ``ResNet18`` — at each level in
    ``BENCH_HETERO_ALPHAS`` (default ``iid,0.3,0.1``; ``iid`` is the
    contiguous partition, floats are ``--partition dirichlet`` levels),
    and emits one ``hetero_train_rps_<label>`` row per level.  The level
    is baked into the metric name so same-level rows regression-test
    against each other in the ledger, and carried as ``dirichlet_alpha``
    / ``size_skew`` columns so a row stays self-describing.  ``val_acc``
    rides along: non-IID rows SHOULD show the accuracy drag the tuner's
    heterogeneity story is about — a non-IID row matching the IID one
    means the partition never took effect.

    The reported value is the steady-state per-round rate read off the
    driver's event stream (the multiround idiom): ``rounds - 1`` divided
    by the gap between the first and last ``round`` event, which excises
    compile but keeps eval cadence and the host rim.  Partitioning is
    host-side setup, so the rate should be flat across levels — a level
    that moves the rate is itself a finding.

    Env knobs: ``BENCH_HETERO_K``/``_B``/``_AGG``/``_ROUNDS``/
    ``_ALPHAS``/``_MODEL``/``_WIDTH``/``_DATASET``/``_TRAIN``/``_VAL``/
    ``_BATCH``/``_SKEW`` (a ``zipf:<s>`` spec composes quantity skew
    with the label skew on every level).
    """
    k = int(os.environ.get("BENCH_HETERO_K", "16"))
    b = int(os.environ.get("BENCH_HETERO_B", "3"))
    agg = os.environ.get("BENCH_HETERO_AGG", "mean")
    rounds = int(os.environ.get("BENCH_HETERO_ROUNDS", "8"))
    model = os.environ.get("BENCH_HETERO_MODEL", "ResNet18")
    width = int(os.environ.get("BENCH_HETERO_WIDTH", "8"))
    dataset = os.environ.get("BENCH_HETERO_DATASET", "emnist_hard")
    n_train = int(os.environ.get("BENCH_HETERO_TRAIN", "2048"))
    n_val = int(os.environ.get("BENCH_HETERO_VAL", "512"))
    batch = int(os.environ.get("BENCH_HETERO_BATCH", "8"))
    skew = os.environ.get("BENCH_HETERO_SKEW", "none")
    labels = [
        s.strip()
        for s in os.environ.get("BENCH_HETERO_ALPHAS", "iid,0.3,0.1").split(",")
        if s.strip()
    ]

    import jax

    from byzantine_aircomp_tpu import obs as obs_lib
    from byzantine_aircomp_tpu.data import datasets as data_lib
    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.train import FedTrainer
    from byzantine_aircomp_tpu.obs.sinks import MemorySink

    platform = jax.default_backend()
    log(
        f"hetero: backend={platform} K={k} B={b} agg={agg} model={model} "
        f"dataset={dataset} rounds={rounds} levels={labels} skew={skew}"
    )
    for label in labels:
        cfg_kw = dict(
            honest_size=k - b,
            byz_size=b,
            attack="signflip",
            agg=agg,
            rounds=rounds,
            display_interval=1,
            batch_size=batch,
            model=model,
            resnet_width=width,
            size_skew=skew,
            eval_train=False,
        )
        if label != "iid":
            cfg_kw["partition"] = "dirichlet"
            cfg_kw["dirichlet_alpha"] = float(label)
        cfg = FedConfig(**cfg_kw)
        ds = data_lib.load(
            dataset, synthetic_train=n_train, synthetic_val=n_val
        )
        trainer = FedTrainer(cfg, dataset=ds)
        sink = MemorySink()
        paths = trainer.train(obs=obs_lib.Observability(sink))

        ts = [e["ts"] for e in sink.by_kind("round")]
        steady = max(ts[-1] - ts[0], 1e-9)
        rps = (rounds - 1) / steady
        val_acc = paths["valAccPath"][-1]
        metric_label = "iid" if label == "iid" else f"a{label}"

        row = make_bench_row(
            rps,
            platform=platform,
            timed_rounds=rounds - 1,
            val_acc=val_acc,
            params={
                "k": k, "b": b, "agg": agg, "attack": "signflip",
                "dataset": dataset, "model": model,
                "metric": f"hetero_train_rps_{metric_label}",
            },
        )
        row["d"] = int(trainer.dim)
        row["dirichlet_alpha"] = None if label == "iid" else float(label)
        if skew != "none":
            row["size_skew"] = skew
        log(
            f"hetero: {metric_label} steady {rps:.3f} rounds/sec "
            f"({rounds - 1} rounds in {steady:.3f}s past compile, "
            f"val_acc={val_acc:.4f})"
        )
        emit_row(row)


# --------------------------------------------------------------------------
# parent: probe + dispatch (never initializes a backend, cannot hang)
# --------------------------------------------------------------------------

def _probe_backend(timeout: float | None):
    """Returns {'backend':..,'n':..} or None if init hung/failed.

    ``timeout=None`` (BENCH_WATCHDOG_SECS=0 / BENCH_PROBE_SECS=0) waits
    indefinitely — the documented watchdog-disable contract."""
    from byzantine_aircomp_tpu.utils.env import probe_backend_subprocess

    t0 = time.perf_counter()
    info = probe_backend_subprocess(timeout)
    if info is None:
        desc = "no limit" if timeout is None else f"{timeout:.0f}s"
        log(f"probe: backend init blocked or failed within {desc} — tunnel wedged?")
        return None
    log(f"probe: backend={info['backend']} devices={info['n']} init={time.perf_counter() - t0:.1f}s")
    return info


def _probe_backend_with_retry(timeout: float | None):
    """Probe with retries: ``(info_or_None, per_attempt_diagnostics)``.

    Round-1 postmortem addendum: a relay restart wedges init for ~seconds,
    not forever — one probe attempt at the wrong moment condemned a whole
    bench run to the CPU fallback.  Retry ``BENCH_PROBE_RETRIES`` times
    (default 2 extra attempts) with ``BENCH_PROBE_BACKOFF_SECS`` between
    them (default 15), and classify the relay after EVERY failed attempt:
    the diagnostics list distinguishes "relay dead the whole time" from
    "wedged once, listening later" in the final ``fallback_reason``.
    """
    from byzantine_aircomp_tpu.utils.env import diagnose_relay

    retries = max(int(os.environ.get("BENCH_PROBE_RETRIES", "2")), 0)
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF_SECS", "15"))
    diagnostics: list[str] = []
    for attempt in range(1 + retries):
        if attempt:
            log(f"probe: retry {attempt}/{retries} after {backoff:.0f}s backoff")
            time.sleep(backoff)
        info = _probe_backend(timeout)
        if info is not None:
            return info, diagnostics
        relay = diagnose_relay()
        diagnostics.append(f"attempt {attempt + 1}: relay {relay}")
        log(f"probe: attempt {attempt + 1} failed, relay {relay}")
    return None, diagnostics


def _run_bench_child(env: dict, timeout: float | None, timed_rounds: int):
    """Spawn this file as the bench child; returns parsed JSON dict or None."""
    env = dict(env)
    env["BENCH_CHILD"] = "1"
    env["BENCH_TIMED_ROUNDS"] = str(timed_rounds)
    # the parent owns the ledger append: a child-side append would double-
    # record when the parent annotates and re-emits the row
    env.pop("BENCH_LEDGER", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=None,  # stream child progress straight to our stderr
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        log(f"child exceeded {timeout:.0f}s watchdog, killed")
        return None
    if proc.returncode != 0:
        log(f"child failed rc={proc.returncode}")
        return None
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    log("child produced no JSON line")
    return None


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        run_child()
        return
    if os.environ.get("BENCH_STREAM_KSWEEP"):
        run_stream_ksweep()
        return
    if os.environ.get("BENCH_SIGNPACK"):
        run_signpack_bench()
        return
    if os.environ.get("BENCH_MULTIROUND"):
        run_multiround_bench()
        return
    if os.environ.get("BENCH_HETERO"):
        run_hetero_bench()
        return

    def _secs(name: str, default: str) -> float | None:
        # 0 disables the stage watchdog (the legacy BENCH_WATCHDOG_SECS
        # contract); BENCH_WATCHDOG_SECS, if set, overrides stage defaults
        v = float(os.environ.get(name, os.environ.get("BENCH_WATCHDOG_SECS", default)))
        return None if v == 0 else v

    probe_secs = _secs("BENCH_PROBE_SECS", "120")
    run_secs = _secs("BENCH_RUN_SECS", "600")
    cpu_secs = _secs("BENCH_CPU_SECS", "420")
    timed = int(os.environ.get("BENCH_TIMED_ROUNDS", "50"))
    cpu_timed = int(os.environ.get("BENCH_CPU_TIMED_ROUNDS", "10"))

    probe_desc = "disabled" if probe_secs is None else f"{probe_secs:.0f}s"
    log(f"probing device backend (timeout {probe_desc})")
    info, probe_diags = _probe_backend_with_retry(probe_secs)

    fallback_reason = None
    relay = None
    result = None
    if info is not None and info["backend"] != "cpu":
        result = _run_bench_child(os.environ, run_secs, timed_rounds=timed)
        if result is None:
            fallback_reason = (
                f"accelerator bench failed on backend={info['backend']}; "
                "cpu fallback"
            )
    elif info is None:
        # the LAST attempt's classification is the headline relay state;
        # the per-attempt trail rides in fallback_reason so the artifact
        # distinguishes dead-throughout from transiently-wedged
        relay = probe_diags[-1].split("relay ", 1)[-1] if probe_diags else None
        fallback_reason = (
            f"tunnel failure ({'; '.join(probe_diags)}): backend init did "
            f"not complete in {probe_desc} over {len(probe_diags)} probe "
            "attempt(s); cpu fallback"
        )
    else:
        fallback_reason = "no accelerator visible (cpu-only env); cpu fallback"

    if result is None:
        from byzantine_aircomp_tpu.utils.env import scrubbed_cpu_env

        log(f"falling back to scrubbed-env CPU bench ({cpu_timed} timed rounds)")
        result = _run_bench_child(scrubbed_cpu_env(), cpu_secs, timed_rounds=cpu_timed)

    if result is None:
        emit_row(
            make_bench_row(
                0.0,
                platform="none",
                timed_rounds=0,
                fallback_reason=(fallback_reason or "bench failed")
                + "; cpu fallback also failed",
                relay=relay,
            )
        )
        sys.exit(1)

    # annotate the child's row with the parent's provenance and re-emit as
    # the final stdout line (the driver parses the LAST JSON line)
    if fallback_reason is not None:
        result["fallback_reason"] = fallback_reason
        result["error"] = fallback_reason  # historical field name
    if relay is not None:
        result["relay"] = relay
    emit_row(result)
    if result.get("fallback_reason"):
        # loud, last, and unmissable: BENCH_r02–r05 were CPU fallbacks
        # that sat in the ledger unnoticed because the only provenance
        # was a JSON field nobody read.  The row itself stays honest
        # (platform + fallback_reason are in it) — this banner is for
        # the human watching the run.
        log("=" * 64)
        log(
            "WARNING: this bench row is a FALLBACK "
            f"(platform={result.get('platform')!r}, not the accelerator)"
        )
        log(f"  reason: {result['fallback_reason']}")
        log(
            "  do not read it as an accelerator headline; gate headline "
            "rows with perf_gate --expect-platform tpu"
        )
        log("=" * 64)


if __name__ == "__main__":
    main()
