"""North-star benchmark: federated rounds/sec at K=1000 clients, B=100
classflip Byzantine, MNIST MLP, geometric-median aggregation.

BASELINE.json target: >= 50 rounds/sec (a "round" = displayInterval = 10
global iterations, the reference's unit at MNIST_Air_weight.py:286-287).
``vs_baseline`` is value / 50.

Prints exactly ONE JSON line on stdout; progress goes to stderr.

Staged, tunnel-proof harness (round-1 failure mode: a wedged axon relay
blocks JAX backend init indefinitely -> 900 silent seconds -> watchdog
rc=3 with no diagnostics):

  stage 1  parent (never imports jax): probe backend init in a subprocess
           with the inherited env, BENCH_PROBE_SECS timeout (default 120).
  stage 2a probe ok on an accelerator -> run the real bench in a child with
           the inherited env (BENCH_RUN_SECS, default 600).
  stage 2b probe wedged / CPU-only / accelerator child failed -> run a
           scrubbed-env CPU fallback (PALLAS_AXON_POOL_IPS unset so the
           axon sitecustomize never boots the tunnel; JAX_PLATFORMS=cpu)
           with fewer timed rounds, and annotate the JSON line with
           ``platform`` + ``error`` so the artifact is self-describing.

Either way the driver gets one parseable JSON line, never a silent hang.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_ROUNDS_PER_SEC = 50.0  # BASELINE.json north star (v5e-8, K=1000, B=100)

K = 1000
B = 100
AGG = "gm2"
ATTACK = "classflip"
METRIC = f"fl_rounds_per_sec_K{K}_B{B}_{ATTACK}_{AGG}_mnist_mlp"


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# child: the actual benchmark (runs with whatever backend the env selects)
# --------------------------------------------------------------------------

def run_child() -> None:
    warmup = int(os.environ.get("BENCH_WARMUP_ROUNDS", "3"))
    timed = int(os.environ.get("BENCH_TIMED_ROUNDS", "50"))

    import jax
    import jax.numpy as jnp

    from byzantine_aircomp_tpu.fed.config import FedConfig
    from byzantine_aircomp_tpu.fed.harness import _make_trainer
    from byzantine_aircomp_tpu.fed.train import FedTrainer

    log(
        f"child: backend={jax.default_backend()} devices={len(jax.devices())} "
        f"K={K} B={B} agg={AGG} attack={ATTACK} warmup={warmup} timed={timed}"
    )

    cfg = FedConfig(
        honest_size=K - B,
        byz_size=B,
        attack=ATTACK,
        agg=AGG,
        rounds=warmup + 3 * timed,
        display_interval=10,
        batch_size=50,
        eval_train=False,
        # reference caller overrides: maxiter=1000, tol=1e-5 (:350)
        agg_maxiter=1000,
        agg_tol=1e-5,
    )
    trainer = _make_trainer(cfg, FedTrainer)
    log(f"child: dataset source={trainer.dataset.name}/{trainer.dataset.source} d={trainer.dim}")

    # warmup compiles the timed-shaped multi-round program (one device
    # program for the whole timed block — no per-round host dispatch) and
    # executes it twice: the first post-compile execution runs measurably
    # below steady state (device-side caching/ramp on the tunneled chip)
    trainer.run_rounds(0, warmup)
    log("child: compile + first warmup block done")
    trainer.run_rounds(warmup, timed)
    trainer.run_rounds(warmup + timed, timed)
    # a host transfer of a value derived from the params is the only honest
    # completion barrier: on tunneled devices block_until_ready can return
    # before the dispatched programs actually finish
    float(jnp.sum(trainer.flat_params))
    log("child: warmup done")

    start = warmup + 2 * timed
    t0 = time.perf_counter()
    trainer.run_rounds(start, timed)
    float(jnp.sum(trainer.flat_params))
    dt = time.perf_counter() - t0
    rps = timed / dt

    loss, acc = trainer.evaluate("val")
    log(f"child: {timed} rounds in {dt:.3f}s -> {rps:.2f} rounds/sec "
        f"(val_loss={loss:.4f} val_acc={acc:.4f})")

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(rps, 3),
                "unit": "rounds/sec",
                "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 4),
                "platform": jax.default_backend(),
                "timed_rounds": timed,
                "val_acc": round(float(acc), 4),
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# parent: probe + dispatch (no jax import, cannot hang on backend init)
# --------------------------------------------------------------------------

def _probe_backend(timeout: float | None):
    """Returns {'backend':..,'n':..} or None if init hung/failed.

    ``timeout=None`` (BENCH_WATCHDOG_SECS=0 / BENCH_PROBE_SECS=0) waits
    indefinitely — the documented watchdog-disable contract."""
    from byzantine_aircomp_tpu.utils.env import probe_backend_subprocess

    t0 = time.perf_counter()
    info = probe_backend_subprocess(timeout)
    if info is None:
        desc = "no limit" if timeout is None else f"{timeout:.0f}s"
        log(f"probe: backend init blocked or failed within {desc} — tunnel wedged?")
        return None
    log(f"probe: backend={info['backend']} devices={info['n']} init={time.perf_counter() - t0:.1f}s")
    return info


def _run_bench_child(env: dict, timeout: float | None, timed_rounds: int):
    """Spawn this file as the bench child; returns parsed JSON dict or None."""
    env = dict(env)
    env["BENCH_CHILD"] = "1"
    env["BENCH_TIMED_ROUNDS"] = str(timed_rounds)
    try:
        proc = subprocess.run(
            [sys.executable, "-u", os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=None,  # stream child progress straight to our stderr
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        log(f"child exceeded {timeout:.0f}s watchdog, killed")
        return None
    if proc.returncode != 0:
        log(f"child failed rc={proc.returncode}")
        return None
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    log("child produced no JSON line")
    return None


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        run_child()
        return

    def _secs(name: str, default: str) -> float | None:
        # 0 disables the stage watchdog (the legacy BENCH_WATCHDOG_SECS
        # contract); BENCH_WATCHDOG_SECS, if set, overrides stage defaults
        v = float(os.environ.get(name, os.environ.get("BENCH_WATCHDOG_SECS", default)))
        return None if v == 0 else v

    probe_secs = _secs("BENCH_PROBE_SECS", "120")
    run_secs = _secs("BENCH_RUN_SECS", "600")
    cpu_secs = _secs("BENCH_CPU_SECS", "420")
    timed = int(os.environ.get("BENCH_TIMED_ROUNDS", "50"))
    cpu_timed = int(os.environ.get("BENCH_CPU_TIMED_ROUNDS", "10"))

    probe_desc = "disabled" if probe_secs is None else f"{probe_secs:.0f}s"
    log(f"probing device backend (timeout {probe_desc})")
    info = _probe_backend(probe_secs)

    error = None
    result = None
    if info is not None and info["backend"] != "cpu":
        result = _run_bench_child(os.environ, run_secs, timed_rounds=timed)
        if result is None:
            error = f"accelerator bench failed on backend={info['backend']}; cpu fallback"
    elif info is None:
        from byzantine_aircomp_tpu.utils.env import diagnose_relay

        relay = diagnose_relay()
        error = (
            f"tunnel failure (relay {relay}): backend init did not complete "
            f"in {probe_desc}; cpu fallback"
        )
    else:
        error = "no accelerator visible (cpu-only env); cpu fallback"

    if result is None:
        from byzantine_aircomp_tpu.utils.env import scrubbed_cpu_env

        log(f"falling back to scrubbed-env CPU bench ({cpu_timed} timed rounds)")
        result = _run_bench_child(scrubbed_cpu_env(), cpu_secs, timed_rounds=cpu_timed)

    if result is None:
        result = {
            "metric": METRIC,
            "value": 0.0,
            "unit": "rounds/sec",
            "vs_baseline": 0.0,
            "platform": "none",
            "error": (error or "bench failed") + "; cpu fallback also failed",
        }
        print(json.dumps(result), flush=True)
        sys.exit(1)

    if error is not None:
        result["error"] = error
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
