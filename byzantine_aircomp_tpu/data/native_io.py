"""ctypes bindings for the native C++ data-ingestion library.

The reference leans on torchvision's Python loaders for dataset IO
(``/root/reference/MNIST_Air_weight.py:552-571``); this framework's
equivalent runtime component is ``native/dataio.cpp`` — an OpenMP C++
library that parses IDX (plain or gzip) and CIFAR-10 binary batches and does
the uint8 -> normalized-float32 transform, loaded here through a plain C ABI
(ctypes; no pybind11 in the image).

Every entry point degrades gracefully: if the shared library is absent and
cannot be built (no compiler, read-only checkout), callers get ``None`` from
:func:`library` and fall back to the pure-NumPy implementations in
``datasets.py``.  ``AIRCOMP_NO_NATIVE=1`` disables the native path outright.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO_NAME = "libaircomp_dataio.so"
_lib: Optional[ctypes.CDLL] = None
_lib_attempted = False


def _build() -> Optional[str]:
    so_path = os.path.abspath(os.path.join(_NATIVE_DIR, _SO_NAME))
    try:
        # always invoke make: it is a no-op when the .so is newer than the
        # sources, and rebuilds a stale library after dataio.cpp edits
        subprocess.run(
            ["make", "-s"],
            cwd=os.path.abspath(_NATIVE_DIR),
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError):
        pass  # no compiler / read-only tree: a prebuilt .so is still usable
    return so_path if os.path.exists(so_path) else None


def library() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if
    unavailable."""
    global _lib, _lib_attempted
    if _lib is not None or _lib_attempted:
        return _lib
    _lib_attempted = True
    if os.environ.get("AIRCOMP_NO_NATIVE"):
        return None
    so_path = _build()
    if so_path is None:
        return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None

    lib.aircomp_read_idx.restype = ctypes.c_int
    lib.aircomp_read_idx.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.aircomp_read_cifar_bin.restype = ctypes.c_int
    lib.aircomp_read_cifar_bin.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.aircomp_normalize_u8.restype = ctypes.c_int
    lib.aircomp_normalize_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.aircomp_free.restype = None
    lib.aircomp_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def _take_buffer(lib, ptr, shape, dtype=np.uint8) -> np.ndarray:
    """Copy a malloc'd native buffer into a NumPy array and free it."""
    n = int(np.prod(shape))
    arr = np.ctypeslib.as_array(ptr, shape=(n,)).copy().reshape(shape)
    lib.aircomp_free(ptr)
    return arr.astype(dtype, copy=False)


def read_idx(path: str) -> Optional[np.ndarray]:
    """Parse an IDX (optionally .gz) file natively; None on any failure."""
    lib = library()
    if lib is None:
        return None
    data = ctypes.POINTER(ctypes.c_uint8)()
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    rc = lib.aircomp_read_idx(path.encode(), ctypes.byref(data), dims, ctypes.byref(ndim))
    if rc != 0:
        return None
    shape = tuple(int(dims[i]) for i in range(ndim.value))
    return _take_buffer(lib, data, shape)


def read_cifar_bin(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Parse a CIFAR-10 binary batch natively -> (images [N,3,32,32] u8,
    labels [N] u8); None on any failure."""
    lib = library()
    if lib is None:
        return None
    img = ctypes.POINTER(ctypes.c_uint8)()
    lbl = ctypes.POINTER(ctypes.c_uint8)()
    n = ctypes.c_int64()
    rc = lib.aircomp_read_cifar_bin(
        path.encode(), ctypes.byref(img), ctypes.byref(lbl), ctypes.byref(n)
    )
    if rc != 0:
        return None
    images = _take_buffer(lib, img, (int(n.value), 3, 32, 32))
    labels = _take_buffer(lib, lbl, (int(n.value),))
    return images, labels


def normalize_u8(x: np.ndarray, mean, std) -> Optional[np.ndarray]:
    """(x/255 - mean)/std in parallel C++; None if the library is missing.

    Scalar stats normalize every element; sequence stats of length C apply
    per channel with C the trailing axis (HWC layout).
    """
    lib = library()
    if lib is None:
        return None
    means = np.atleast_1d(np.asarray(mean, np.float32))
    stds = np.atleast_1d(np.asarray(std, np.float32))
    if means.shape != stds.shape or means.ndim != 1:
        return None
    if len(means) > 1 and (x.ndim == 0 or x.shape[-1] != len(means)):
        return None
    src = np.ascontiguousarray(x, np.uint8)
    dst = np.empty(src.shape, np.float32)
    rc = lib.aircomp_normalize_u8(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.size,
        means.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        stds.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        len(means),
    )
    return dst if rc == 0 else None
