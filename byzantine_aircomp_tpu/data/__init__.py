from .datasets import (  # noqa: F401
    ClientSharding,
    Dataset,
    contiguous_shards,
    load,
    sample_client_batch_indices,
)
