from .datasets import (  # noqa: F401
    ClientSharding,
    Dataset,
    contiguous_shards,
    load,
    parse_size_skew,
    sample_client_batch_indices,
    zipf_shards,
)
