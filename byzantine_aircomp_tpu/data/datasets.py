"""Data layer: dataset ingestion, client sharding, on-device sampling.

TPU-native replacement for the reference's torchvision pipeline
(``/root/reference/MNIST_Air_weight.py:238-270, :552-571``):

* Datasets are loaded **once** into host numpy arrays (raw idx / CIFAR pickle
  parsing — no torchvision dependency), normalized with the reference's
  per-dataset statistics, then moved to device as a whole; every batch
  afterwards is an on-device gather, eliminating the reference's per-client
  DataLoader iterators and per-iteration host->device copies.
* Client sharding is the reference's contiguous equal-slice math
  ``pieces[i] = floor(i*N/K)`` (``:238-239``).
* Per-client with-replacement sampling (the reference's ``RandomSampler``
  with ``replacement=True``, ``:260-269``) becomes a ``jax.random.randint``
  index computation inside the jitted round step.
* When the real dataset is not on disk (this container has no network), a
  deterministic synthetic dataset with the same shapes/statistics is
  generated so every pipeline stays runnable end-to-end; the loader reports
  which source it used.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import DATASETS
from . import native_io

# normalization stats used by the reference transforms
MNIST_STATS = (0.1307, 0.3081)  # MNIST_Air_weight.py:555
EMNIST_STATS = (0.1736, 0.3317)  # EMNIST_Air_weight.py:563-569
CIFAR10_STATS = (
    (0.4914, 0.4822, 0.4465),
    (0.2470, 0.2435, 0.2616),
)

DATA_ROOTS = ("./dataset", "./data", os.path.expanduser("~/datasets"))


@dataclass
class Dataset:
    """Normalized train/val arrays, fully materialized.

    When the underlying pixels are 8-bit (all real datasets here, and the
    synthetic fallback, which quantizes itself to u8 so both representations
    agree), ``x_train_raw`` carries them unnormalized with ``stats`` so the
    trainer can keep the TRAIN set uint8 in HBM — 4x less per-iteration
    gather traffic than f32 — and fuse ``(u8/255 - mean)/std`` into the
    client step after the gather.  ``x_train`` stays the normalized f32 view
    for eval, oracles, and any consumer that wants plain arrays.
    """

    name: str
    x_train: np.ndarray  # [N, ...] float32, normalized
    y_train: np.ndarray  # [N] int32
    x_val: np.ndarray
    y_val: np.ndarray
    num_classes: int
    source: str  # "disk" or "synthetic"
    x_train_raw: Optional[np.ndarray] = None  # [N, ...] uint8, unnormalized
    stats: Optional[Tuple] = None  # (mean, std) per-dataset normalization

    @property
    def input_shape(self) -> Tuple[int, ...]:
        return self.x_train.shape[1:]


# ---------------------------------------------------------------------------
# raw-format parsers (no torchvision)


def _read_idx(path: str) -> np.ndarray:
    native = native_io.read_idx(path)  # C++ parser (native/dataio.cpp)
    if native is not None:
        return native
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(*relpaths: str) -> Optional[str]:
    for root in DATA_ROOTS:
        for rel in relpaths:
            for cand in (os.path.join(root, rel), os.path.join(root, rel + ".gz")):
                if os.path.exists(cand):
                    return cand
    return None


def _find_dir(name: str) -> Optional[str]:
    for root in DATA_ROOTS:
        cand = os.path.join(root, name)
        if os.path.isdir(cand):
            return cand
    return None


def _load_idx_pair(img_rel, lbl_rel):
    img = _find(*img_rel)
    lbl = _find(*lbl_rel)
    if img is None or lbl is None:
        return None
    return _read_idx(img), _read_idx(lbl)


def _normalize(x_u8: np.ndarray, mean, std) -> np.ndarray:
    native = native_io.normalize_u8(x_u8, mean, std)  # parallel C++ path
    if native is not None:
        return native
    m = np.asarray(mean, np.float32)
    s = np.asarray(std, np.float32)
    return ((x_u8.astype(np.float32) / 255.0) - m) / s


# ---------------------------------------------------------------------------
# synthetic fallback


def _synthetic_images(
    rng: np.random.Generator,
    protos: np.ndarray,
    n: int,
    shape: Tuple[int, ...],
    stats,
    label_noise: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic class-conditional images: shared per-class prototypes +
    pixel noise, pushed through the same normalization as real data.  Linearly
    separable enough that the reference models visibly learn, so accuracy
    curves exercise the full pipeline.  Pixels are quantized to uint8 before
    normalization so the raw-u8 and normalized-f32 views agree exactly, like
    real 8-bit datasets.

    ``label_noise`` = probability a sample's label is resampled uniformly over
    ALL C classes (train and val alike; the draw may land on the original
    class), which pins the Bayes-optimal accuracy at exactly
    1 - p*(C-1)/C regardless of model capacity — the knob behind the
    ``*_hard`` variants.  (Flipping to a uniform *other* class would give the
    different ceiling 1 - p; we use the all-classes form so the documented
    formula is exact.)"""
    num_classes = len(protos)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    x = protos[y] + 0.35 * rng.standard_normal((n,) + shape).astype(np.float32)
    if label_noise > 0.0:
        flip = rng.random(n) < label_noise
        y = np.where(
            flip,
            rng.integers(0, num_classes, size=n),
            y,
        ).astype(np.int32)
    u8 = np.round(np.clip(x, 0.0, 1.0) * 255.0).astype(np.uint8)
    mean, std = stats
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    return ((u8.astype(np.float32) / 255.0) - mean) / std, y, u8


def _synthetic(
    name, n_train, n_val, num_classes, shape, stats, label_noise: float = 0.0
) -> Dataset:
    rng = np.random.default_rng(2021)  # reference's fixed seed
    # prototypes are drawn ONCE and shared by train and val — otherwise the
    # val distribution would be unrelated to train and nothing could learn it
    protos = rng.uniform(0.1, 0.9, size=(num_classes,) + shape).astype(np.float32)
    x_tr, y_tr, u8_tr = _synthetic_images(
        rng, protos, n_train, shape, stats, label_noise
    )
    x_va, y_va, _ = _synthetic_images(
        rng, protos, n_val, shape, stats, label_noise
    )
    return Dataset(
        name, x_tr, y_tr, x_va, y_va, num_classes, "synthetic",
        x_train_raw=u8_tr, stats=stats,
    )


# ---------------------------------------------------------------------------
# dataset builders


@DATASETS.register("mnist")
def mnist(synthetic_train: int = 60000, synthetic_val: int = 10000, **_) -> Dataset:
    pair_tr = _load_idx_pair(
        ("MNIST/raw/train-images-idx3-ubyte", "train-images-idx3-ubyte"),
        ("MNIST/raw/train-labels-idx1-ubyte", "train-labels-idx1-ubyte"),
    )
    pair_va = _load_idx_pair(
        ("MNIST/raw/t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte"),
        ("MNIST/raw/t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte"),
    )
    if pair_tr and pair_va:
        m, s = MNIST_STATS
        return Dataset(
            "mnist",
            _normalize(pair_tr[0], m, s),
            pair_tr[1].astype(np.int32),
            _normalize(pair_va[0], m, s),
            pair_va[1].astype(np.int32),
            10,
            "disk",
            x_train_raw=np.ascontiguousarray(pair_tr[0]),
            stats=MNIST_STATS,
        )
    return _synthetic("mnist", synthetic_train, synthetic_val, 10, (28, 28), MNIST_STATS)


@DATASETS.register("mnist_hard")
def mnist_hard(synthetic_train: int = 60000, synthetic_val: int = 10000, **_) -> Dataset:
    """Always-synthetic MNIST-shaped set with a ~0.92 accuracy ceiling.

    The plain synthetic fallback is separable enough that strong models hit
    0.99+, where a robustness matrix cannot discriminate defenses (several
    round-1 cells saturated at 1.0000).  Uniform label resampling with
    p=0.09 (over all 10 classes, so the formula is exact) pins the
    Bayes-optimal val accuracy at 1 - p*9/10 = 0.919 — the real-MNIST
    paper figure's operating point (draw.ipynb cell 1, final acc ~0.92) —
    so every defense must pay for what it admits and no cell can sit at
    ceiling.  Used by the docs/RESULTS.md sweep; never loads from disk."""
    return _synthetic(
        "mnist_hard", synthetic_train, synthetic_val, 10, (28, 28), MNIST_STATS,
        label_noise=0.09,
    )


@DATASETS.register("emnist")
def emnist(synthetic_train: int = 100000, synthetic_val: int = 16000, **_) -> Dataset:
    """EMNIST byclass: 62 classes, 697,932 train samples when on disk
    (reference ``EMNIST_Air_weight.py:539-541``)."""
    pair_tr = _load_idx_pair(
        ("EMNIST/raw/emnist-byclass-train-images-idx3-ubyte",),
        ("EMNIST/raw/emnist-byclass-train-labels-idx1-ubyte",),
    )
    pair_va = _load_idx_pair(
        ("EMNIST/raw/emnist-byclass-test-images-idx3-ubyte",),
        ("EMNIST/raw/emnist-byclass-test-labels-idx1-ubyte",),
    )
    if pair_tr and pair_va:
        m, s = EMNIST_STATS
        return Dataset(
            "emnist",
            _normalize(pair_tr[0], m, s),
            pair_tr[1].astype(np.int32),
            _normalize(pair_va[0], m, s),
            pair_va[1].astype(np.int32),
            62,
            "disk",
            x_train_raw=np.ascontiguousarray(pair_tr[0]),
            stats=EMNIST_STATS,
        )
    return _synthetic(
        "emnist", synthetic_train, synthetic_val, 62, (28, 28), EMNIST_STATS
    )


@DATASETS.register("emnist_hard")
def emnist_hard(
    synthetic_train: int = 100000, synthetic_val: int = 16000, **_
) -> Dataset:
    """Always-synthetic EMNIST-shaped set (62 classes) with a pinned
    accuracy ceiling — the mnist_hard idiom at byclass width.

    Uniform label resampling with p=0.09 over 62 classes pins the
    Bayes-optimal val accuracy at ``1 - p*61/62 = 0.911``, so the
    heterogeneity bench rows (``BENCH_HETERO``) measure a workload that
    cannot sit at ceiling regardless of the Dirichlet alpha.  Never loads
    from disk — bench rows stay reproducible on any machine."""
    return _synthetic(
        "emnist_hard", synthetic_train, synthetic_val, 62, (28, 28),
        EMNIST_STATS, label_noise=0.09,
    )


def _read_cifar_bin(path: str):
    """CIFAR-10 binary batch -> (images [N,3,32,32] u8, labels [N] u8).

    Native C++ parser first (``native/dataio.cpp``), pure-NumPy row parse as
    the fallback so the binary distribution loads even without a compiler
    (record layout: 1 label byte + 3072 CHW pixel bytes per row)."""
    out = native_io.read_cifar_bin(path)
    if out is not None:
        return out
    try:
        raw = np.fromfile(path, np.uint8)
    except OSError:
        return None
    if raw.size == 0 or raw.size % 3073:
        return None
    rows = raw.reshape(-1, 3073)
    return (
        np.ascontiguousarray(rows[:, 1:]).reshape(-1, 3, 32, 32),
        np.ascontiguousarray(rows[:, 0]),
    )


def _cifar10_from_bin() -> Optional[Dataset]:
    """CIFAR-10 from the binary-batch distribution."""
    root = _find_dir("cifar-10-batches-bin")
    if root is None:
        return None
    train = [
        _read_cifar_bin(os.path.join(root, f"data_batch_{i}.bin"))
        for i in range(1, 6)
    ]
    test = _read_cifar_bin(os.path.join(root, "test_batch.bin"))
    if test is None or any(p is None for p in train):
        return None
    x_tr = np.concatenate([p[0] for p in train]).transpose(0, 2, 3, 1)
    y_tr = np.concatenate([p[1] for p in train])
    x_va = test[0].transpose(0, 2, 3, 1)
    mean, std = CIFAR10_STATS
    return Dataset(
        "cifar10",
        _normalize(x_tr, mean, std),
        y_tr.astype(np.int32),
        _normalize(x_va, mean, std),
        test[1].astype(np.int32),
        10,
        "disk",
        x_train_raw=np.ascontiguousarray(x_tr),
        stats=CIFAR10_STATS,
    )


@DATASETS.register("cifar10")
def cifar10(synthetic_train: int = 50000, synthetic_val: int = 10000, **_) -> Dataset:
    from_bin = _cifar10_from_bin()
    if from_bin is not None:
        return from_bin
    root = _find_dir("cifar-10-batches-py")
    if root is not None:
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(root, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(d[b"labels"])
        with open(os.path.join(root, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x_tr = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        x_va = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        mean, std = (np.asarray(v, np.float32) for v in CIFAR10_STATS)
        return Dataset(
            "cifar10",
            ((x_tr.astype(np.float32) / 255.0) - mean) / std,
            np.concatenate(ys).astype(np.int32),
            ((x_va.astype(np.float32) / 255.0) - mean) / std,
            np.asarray(d[b"labels"], np.int32),
            10,
            "disk",
            x_train_raw=np.ascontiguousarray(x_tr),
            stats=CIFAR10_STATS,
        )
    return _synthetic(
        "cifar10", synthetic_train, synthetic_val, 10, (32, 32, 3), CIFAR10_STATS
    )


@DATASETS.register("cifar10_hard")
def cifar10_hard(
    synthetic_train: int = 50000, synthetic_val: int = 10000, **_
) -> Dataset:
    """Always-synthetic CIFAR-shaped set with the same 0.919 accuracy
    ceiling as ``mnist_hard`` (uniform label resampling, p=0.09 over all 10
    classes).  The plain synthetic fallback is separable enough that a
    ResNet saturates ~1.0, where a robustness trajectory cannot
    discriminate defenses; the pinned ceiling keeps ordering differences
    visible.  Used by the BASELINE config-5 trajectory evidence
    (docs/RESULTS.md); never loads from disk."""
    return _synthetic(
        "cifar10_hard", synthetic_train, synthetic_val, 10, (32, 32, 3),
        CIFAR10_STATS, label_noise=0.09,
    )


def load(name: str, **kw) -> Dataset:
    return DATASETS.get(name)(**kw)


# ---------------------------------------------------------------------------
# client sharding + sampling


@dataclass(frozen=True)
class ClientSharding:
    """Contiguous equal slices: client i owns [offsets[i], offsets[i]+sizes[i]).

    ``pieces[i] = floor(i*N/K)`` — the reference's sharding math
    (``MNIST_Air_weight.py:238-239``)."""

    offsets: np.ndarray  # [K] int32
    sizes: np.ndarray  # [K] int32

    @property
    def num_clients(self) -> int:
        return len(self.sizes)


def contiguous_shards(n: int, k: int) -> ClientSharding:
    pieces = np.array([(i * n) // k for i in range(k + 1)], dtype=np.int64)
    return ClientSharding(
        offsets=pieces[:-1].astype(np.int32),
        sizes=np.diff(pieces).astype(np.int32),
    )


def dirichlet_shards(
    labels: np.ndarray, k: int, alpha: float, seed: int = 0
) -> tuple[np.ndarray, ClientSharding]:
    """Label-skewed non-IID partition (Hsu et al. 2019, arXiv:1909.06335):
    each class's samples are split among the K clients with proportions
    drawn from Dirichlet(alpha) — alpha -> 0 gives near-single-class
    clients, alpha -> inf recovers the IID split.

    Beyond the reference (which only has the approximately-IID contiguous
    split, ``MNIST_Air_weight.py:238-239``): non-IID client data is the
    standard stress axis for Byzantine-robust aggregation, where honest
    updates disperse and distance-based defenses degrade.

    Returns ``(perm, sharding)`` where ``perm`` is a permutation of
    ``arange(len(labels))`` and client i owns the PERMUTED index range
    ``[offsets[i], offsets[i]+sizes[i])`` — the caller permutes the train
    arrays once (host-side) and every existing contiguous-shard mechanism
    (on-device uniform sampling, u8 gather) applies unchanged.  Every
    client is guaranteed >= 1 sample (stolen from the largest shard if a
    draw leaves one empty; the on-device sampler's ``sizes - 1`` guard
    needs nonempty shards)."""
    labels = np.asarray(labels)
    if len(labels) < k:
        # the repair below can only guarantee nonempty shards when there
        # are at least k samples; a size-0 shard would make the on-device
        # sampler silently read a neighboring client's rows
        raise ValueError(
            f"dirichlet_shards needs >= 1 sample per client "
            f"(n={len(labels)} < k={k})"
        )
    rng = np.random.default_rng(seed)
    per_client: list[list[np.ndarray]] = [[] for _ in range(k)]
    for c in np.unique(labels):
        idx = np.flatnonzero(labels == c)
        rng.shuffle(idx)
        p = rng.dirichlet(np.full(k, float(alpha)))
        counts = np.floor(p * len(idx)).astype(np.int64)
        frac = p * len(idx) - counts
        short = len(idx) - int(counts.sum())
        counts[np.argsort(-frac)[:short]] += 1
        for i, part in enumerate(np.split(idx, np.cumsum(counts)[:-1])):
            per_client[i].append(part)
    # every parts list has one (possibly empty) array per label class, so
    # concatenate is always well-defined; an empty SHARD is a zero-length
    # result, repaired below
    shards = [np.concatenate(parts) for parts in per_client]
    for i, s in enumerate(shards):
        if len(s) == 0:
            donor = int(np.argmax([len(t) for t in shards]))
            shards[i], shards[donor] = shards[donor][:1], shards[donor][1:]
    sizes = np.array([len(s) for s in shards], dtype=np.int32)
    offsets = np.concatenate([[0], np.cumsum(sizes[:-1], dtype=np.int64)])
    perm = np.concatenate(shards)
    return perm, ClientSharding(
        offsets=offsets.astype(np.int32), sizes=sizes
    )


def zipf_shards(n: int, k: int, s: float) -> ClientSharding:
    """Quantity-skewed contiguous cut: client i (1-based) owns a share
    proportional to ``i^-s`` of the n-sample stream, boundaries placed at
    ``pieces[i] = floor(n * W_i / W_k)`` with ``W_i = sum_{j<=i} j^-s``.

    At ``s=0`` every weight is 1, ``W_i = i`` and the boundary formula
    degenerates to ``floor(i*n/k)`` — BIT-IDENTICAL to
    :func:`contiguous_shards`, which is the parity contract the
    ``--size-skew`` knob's tests pin.  Because the cut re-slices whatever
    index stream the caller already laid out (identity or the
    Dirichlet-permuted order), quantity skew composes with label skew
    without touching the on-device sampler.

    Every client is guaranteed >= 1 sample (requires ``n >= k``): a
    forward pass bumps collapsed boundaries, a backward clamp keeps the
    tail inside ``n``.  At ``s=0`` with ``n >= k`` the boundaries are
    already strictly increasing, so the repair is a no-op there and
    parity is preserved."""
    if n < k:
        raise ValueError(
            f"zipf_shards needs >= 1 sample per client (n={n} < k={k})"
        )
    if s < 0:
        raise ValueError(f"zipf exponent must be >= 0, got {s}")
    w = np.arange(1, k + 1, dtype=np.float64) ** (-float(s))
    cum = np.concatenate([[0.0], np.cumsum(w)])
    pieces = np.floor(n * cum / cum[-1]).astype(np.int64)
    pieces[-1] = n  # guard against float round-down at the tail
    for i in range(1, k + 1):  # >= 1 sample per client
        if pieces[i] <= pieces[i - 1]:
            pieces[i] = pieces[i - 1] + 1
    for i in range(k, 0, -1):  # keep the bumped tail inside n
        if pieces[i] > n - (k - i):
            pieces[i] = n - (k - i)
    return ClientSharding(
        offsets=pieces[:-1].astype(np.int32),
        sizes=np.diff(pieces).astype(np.int32),
    )


def parse_size_skew(spec: str) -> Optional[float]:
    """``"none"`` -> None, ``"zipf:<s>"`` -> s (validated s >= 0)."""
    if spec == "none":
        return None
    if not spec.startswith("zipf:"):
        raise ValueError(f"size_skew must be 'none' or 'zipf:<s>', got {spec!r}")
    s = float(spec.split(":", 1)[1])
    if s < 0:
        raise ValueError(f"zipf exponent must be >= 0, got {s}")
    return s


def sample_client_batch_indices(
    key: jax.Array,
    offsets: jnp.ndarray,
    sizes: jnp.ndarray,
    batch_size: int,
) -> jnp.ndarray:
    """[K, batch] global indices, uniform with replacement within each
    client's shard — the jitted equivalent of the reference's per-client
    ``RandomSampler(replacement=True)`` (``:260-269``)."""
    k = offsets.shape[0]
    u = jax.random.uniform(key, (k, batch_size), dtype=jnp.float32)
    local = jnp.floor(u * sizes[:, None].astype(jnp.float32)).astype(jnp.int32)
    local = jnp.minimum(local, sizes[:, None] - 1)  # guard u==1.0 edge
    return offsets[:, None] + local
