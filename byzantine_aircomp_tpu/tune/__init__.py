"""Defense auto-tuner: population-based search over the batchable
detector/policy constants, riding the experiment-axis vmap engine
(serve/batch.py) — see docs/DESIGN.md "Tuning the defense"."""

from .space import (  # noqa: F401
    DEFAULT_SPACE,
    SearchSpace,
    default_params,
    sample_candidates,
    validate_space,
)
from .objective import fold_pair, objective_score  # noqa: F401
from .tuner import TuneJournal, Tuner  # noqa: F401
