"""Search space over the batchable defense constants.

The tuner can only search knobs that ride the experiment axis as traced
data — a structural knob (ladder names, aggregator identity) would force
one XLA lowering per candidate and the whole population-per-lowering
economy collapses.  So the space is validated against the authoritative
batchable-knob split in ``serve/batch.py``: every searched knob must be
one of the detector/policy constants (``_DETECTOR_KNOBS`` /
``_POLICY_KNOBS``), integer knobs (warmup, ladder hysteresis counts,
min-flagged) must carry integer bounds, and bounds must be ordered.

A :class:`SearchSpace` is plain data — ``{knob: (lo, hi)}`` with an
optional ``"log"`` third element for scale-free constants (thresholds,
leak rates) — so a space can round-trip through the tune journal and a
resumed tune re-derives the exact candidate population.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..fed.config import FedConfig
from ..serve.batch import _DETECTOR_KNOBS, _INT_KNOBS, _POLICY_KNOBS

#: knob -> (lo, hi) or (lo, hi, "log"); plain dict so it journals as JSON
SearchSpace = Dict[str, tuple]

#: every knob the tuner may search: exactly the detector + policy
#: constants that are traced data on the experiment axis
TUNABLE_KNOBS: Tuple[str, ...] = tuple(_DETECTOR_KNOBS) + tuple(_POLICY_KNOBS)

#: the default space — wide brackets around the hand-picked IID defaults
#: (fed/config.py), log-scaled where the constant is scale-free.  The
#: z/cusum thresholds get generous headroom ABOVE the defaults because
#: the non-IID failure mode is thresholds that are too tight for honest
#: dispersion, and alpha/drift search the EMA baseline's adaptivity
DEFAULT_SPACE: SearchSpace = {
    "defense_z": (2.0, 16.0, "log"),
    "defense_cusum": (3.0, 48.0, "log"),
    "defense_alpha": (0.02, 0.5, "log"),
    "defense_drift": (0.1, 2.0, "log"),
    "defense_up": (2, 8),
    "defense_down": (8, 40),
    "defense_min_flagged": (1, 3),
    "defense_leak": (0.001, 0.05, "log"),
    "defense_floor": (0.5, 4.0),
}


def validate_space(space: SearchSpace) -> List[str]:
    """Raise ``ValueError`` naming the first contract violation; returns
    the sorted knob names on success."""
    if not space:
        raise ValueError("search space is empty")
    for knob, spec in space.items():
        if knob not in TUNABLE_KNOBS:
            raise ValueError(
                f"space knob {knob!r} is not a batchable defense constant "
                f"(tunable: {sorted(TUNABLE_KNOBS)}); structural knobs "
                f"cannot ride the experiment axis"
            )
        if not isinstance(spec, (tuple, list)) or len(spec) not in (2, 3):
            raise ValueError(
                f"space knob {knob!r}: spec must be (lo, hi) or "
                f"(lo, hi, 'log'), got {spec!r}"
            )
        lo, hi = spec[0], spec[1]
        if len(spec) == 3 and spec[2] != "log":
            raise ValueError(
                f"space knob {knob!r}: third element must be 'log', "
                f"got {spec[2]!r}"
            )
        if not (np.isfinite(lo) and np.isfinite(hi) and lo < hi):
            raise ValueError(
                f"space knob {knob!r}: bounds must be finite with lo < hi, "
                f"got ({lo}, {hi})"
            )
        if knob in _INT_KNOBS:
            if int(lo) != lo or int(hi) != hi:
                raise ValueError(
                    f"space knob {knob!r} is integer-valued; bounds must "
                    f"be integers, got ({lo}, {hi})"
                )
            if len(spec) == 3:
                raise ValueError(
                    f"space knob {knob!r} is integer-valued; log scale "
                    f"is not supported"
                )
        if len(spec) == 3 and lo <= 0:
            raise ValueError(
                f"space knob {knob!r}: log scale needs lo > 0, got {lo}"
            )
    return sorted(space)


def default_params(space: SearchSpace) -> Dict[str, float]:
    """The IID-default candidate: the hand-picked ``FedConfig`` defaults
    for every searched knob — the control lane each generation carries."""
    cfg = FedConfig()
    return {
        knob: (int if knob in _INT_KNOBS else float)(getattr(cfg, knob))
        for knob in sorted(space)
    }


def sample_candidates(
    space: SearchSpace, n: int, seed: int
) -> List[Dict[str, float]]:
    """``n`` deterministic candidates from ``space``.

    Candidate 0 is ALWAYS the IID defaults (:func:`default_params`) — the
    control the CI gate compares the winner against — and the remaining
    ``n - 1`` are independent draws from ``default_rng(seed)``.  Sampling
    is a pure function of ``(space, n, seed)``, which is what makes a
    journal-resumed tune bit-identical: the journal records the three
    inputs, not the floats."""
    if n < 1:
        raise ValueError(f"population must be >= 1, got {n}")
    validate_space(space)
    rng = np.random.default_rng(seed)
    out = [default_params(space)]
    for _ in range(n - 1):
        cand: Dict[str, float] = {}
        for knob in sorted(space):
            spec = space[knob]
            lo, hi = float(spec[0]), float(spec[1])
            if knob in _INT_KNOBS:
                cand[knob] = int(rng.integers(int(lo), int(hi) + 1))
            elif len(spec) == 3:
                cand[knob] = float(
                    np.exp(rng.uniform(np.log(lo), np.log(hi)))
                )
            else:
                cand[knob] = float(rng.uniform(lo, hi))
        out.append(cand)
    return out


def apply_params(cfg: FedConfig, params: Dict[str, float]) -> FedConfig:
    """A copy of ``cfg`` with the candidate's constants installed (the
    per-lane config the BatchRunner stacks)."""
    import copy

    out = copy.copy(cfg)
    for knob, value in params.items():
        setattr(out, knob, int(value) if knob in _INT_KNOBS else float(value))
    return out


def halving_schedule(
    population: int, generations: int, base_rounds: int, eta: int = 2
) -> List[Tuple[int, int]]:
    """The successive-halving plan: ``[(survivors_in, rounds)]`` per
    generation.  Generation g runs ``ceil(population / eta**g)``
    candidates (never below 1 — plus the always-resident control lane,
    handled by the tuner) for ``base_rounds * eta**g`` rounds, so the
    total lane-round budget stays roughly constant per generation while
    the surviving candidates earn longer horizons."""
    if eta < 2:
        raise ValueError(f"eta must be >= 2, got {eta}")
    plan = []
    for g in range(generations):
        count = max(1, -(-population // (eta ** g)))  # ceil div
        plan.append((count, base_rounds * (eta ** g)))
    return plan


def survivors(
    scores: Sequence[float], keep: int, protect: Sequence[int] = (0,)
) -> List[int]:
    """Indices promoted to the next generation: the ``protect``ed control
    lanes unconditionally, then the best-scoring candidates (ties broken
    by index, so the promotion is deterministic) until ``keep`` total."""
    order = sorted(
        range(len(scores)), key=lambda i: (-float(scores[i]), i)
    )
    out = [i for i in protect if i < len(scores)]
    for i in order:
        if len(out) >= keep:
            break
        if i not in out:
            out.append(i)
    return sorted(out)
