"""ASHA-style successive halving over the vmapped lane engine.

One tuner *generation* is ONE :class:`serve.batch.BatchRunner`: every
surviving candidate contributes a paired (attacked, benign) lane — same
seed, same knob constants, the benign twin distinguished only by pinning
its attack-onset iteration counter far negative so the attack never
activates (``ops/attacks.AttackSpec.onset_round``: pre-onset Byzantine
rows are bit-identical to honest ones).  All lanes ride one
``jit(vmap)`` lowering; candidate constants are per-lane traced data
(``BATCHABLE_KNOBS``), so a 16-candidate generation compiles exactly
once — the economy that makes population-based tuning affordable, and
the property the retrace gate pins (lowerings == generations).

Durability: every generation boundary is journaled (append-one-line
JSONL, the ``serve/journal.py`` idiom — torn tails tolerated).  Because
candidate sampling is a pure function of ``(space, population, seed)``
and the device rounds are deterministic (fold_in key discipline), a
SIGKILLed tune resumed from the journal reproduces the uninterrupted
tune bit-identically: completed generations restore their recorded
scores, a half-finished generation re-runs from its recorded candidate
set and lands on the same floats.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import threading
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import obs as obs_lib
from ..fed.config import FedConfig
from ..serve.batch import BatchRunner
from ..utils import io as io_lib
from . import objective as objective_lib
from . import space as space_lib

#: the benign-lane pin for the attack-onset iteration counter (carry
#: slot 5): far enough below zero that no realistic horizon's +1 per
#: iteration ever reaches the onset threshold, comfortably inside int32
BENIGN_PIN = -(2 ** 30)

#: carry slot index of the attack-onset iteration counter
#: (serve/batch.BatchRunner._carry_of order)
_ATTACK_ITER_SLOT = 5


class TuneJournal:
    """Append-only generation journal: one JSON line per state change,
    fsync-per-line durability via the shared ``io.open_append`` helper,
    torn-tail-tolerant replay (a killed append truncates at worst its
    own line — ``iter_jsonl`` skips it)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh = None

    def append(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._fh is None:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                self._fh = io_lib.open_append(self.path)
            self._fh.write(json.dumps(record, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def replay(self) -> List[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return []
        return [r for r in io_lib.iter_jsonl(self.path) if "op" in r]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


class Tuner:
    """Successive-halving defense tuner over one base config.

    ``base_cfg`` must carry an onset attack (``<name>@<round>``), a
    defense, and ``forensics`` on — the tuner validates rather than
    silently fixing, because those choices are part of what the tuned
    constants mean.  ``journal_path=None`` runs without durability (the
    unit-test / throwaway mode)."""

    def __init__(
        self,
        base_cfg: FedConfig,
        space: Optional[space_lib.SearchSpace] = None,
        *,
        population: int = 8,
        generations: int = 3,
        base_rounds: int = 8,
        eta: int = 2,
        seed: int = 0,
        journal_path: Optional[str] = None,
        obs: obs_lib.Observability = obs_lib.NULL,
        dataset=None,
        backend: str = "vmap",
        ff_penalty: float = objective_lib.DEFAULT_FF_PENALTY,
        ttd_weight: float = objective_lib.DEFAULT_TTD_WEIGHT,
        log: Optional[Callable[[str], None]] = None,
    ) -> None:
        if base_cfg.attack is None or "@" not in str(base_cfg.attack):
            raise ValueError(
                "tuner base config needs an onset attack ('<name>@<round>') "
                "— the paired benign lane is carved out of the onset gate"
            )
        if base_cfg.defense == "off":
            raise ValueError("tuner base config needs --defense != off")
        if base_cfg.forensics == "off":
            raise ValueError(
                "tuner base config needs forensics on (the objective folds "
                "the client_flag stream)"
            )
        self.base_cfg = base_cfg
        self.space = dict(space if space is not None else
                          space_lib.DEFAULT_SPACE)
        space_lib.validate_space(self.space)
        self.population = int(population)
        self.generations = int(generations)
        self.base_rounds = int(base_rounds)
        self.eta = int(eta)
        self.seed = int(seed)
        self.obs = obs
        self.ff_penalty = float(ff_penalty)
        self.ttd_weight = float(ttd_weight)
        self.backend = backend
        self.log = log or (lambda s: None)
        self.journal = TuneJournal(journal_path) if journal_path else None
        #: ONE retrace detector across every generation: the CI gate reads
        #: ``lowerings`` at the end and asserts it equals generations run
        self.retrace = obs_lib.RetraceDetector()
        if dataset is None:
            from ..data import datasets as data_lib

            dataset = data_lib.load(base_cfg.dataset)
        self.dataset = dataset
        self.candidates = space_lib.sample_candidates(
            self.space, self.population, self.seed
        )
        #: per-generation trail: [{gen, rounds, scored: {idx: fold},
        #: survivors: [idx]}]
        self.trail: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ plumbing

    @property
    def lowerings(self) -> int:
        return self.retrace.count("batch_round_fn")

    def _signature(self) -> Dict[str, Any]:
        """What a resumed tune must agree on — recorded at tune_start,
        asserted on resume so a journal can never silently mix runs."""
        return {
            "space": {k: list(v) for k, v in sorted(self.space.items())},
            "population": self.population,
            "generations": self.generations,
            "base_rounds": self.base_rounds,
            "eta": self.eta,
            "seed": self.seed,
            "attack": self.base_cfg.attack,
            "defense": self.base_cfg.defense,
            "partition": self.base_cfg.partition,
            "dirichlet_alpha": (
                self.base_cfg.dirichlet_alpha
                if self.base_cfg.partition == "dirichlet" else None
            ),
            "k": self.base_cfg.node_size,
            "byz": self.base_cfg.byz_size,
            "cfg_seed": self.base_cfg.seed,
        }

    def _lane_cfgs(self, params: Dict[str, float], rounds: int):
        """One candidate's (attacked, benign) lane configs: identical —
        the benign twin is made benign by the carry pin, not the cfg, so
        the pair shares every traced constant."""
        cfg = space_lib.apply_params(self.base_cfg, params)
        cfg.rounds = rounds
        return [cfg, copy.copy(cfg)]

    def _run_generation(
        self, gen: int, cand_idx: List[int], rounds: int
    ) -> Dict[int, Dict[str, Any]]:
        """Run one generation's candidates as paired lanes of ONE
        BatchRunner; returns {candidate index: objective fold}."""
        cfgs = []
        for idx in cand_idx:
            cfgs.extend(self._lane_cfgs(self.candidates[idx], rounds))
        runner = BatchRunner(
            cfgs, dataset=self.dataset, retrace=self.retrace,
            backend=self.backend,
        )
        # benign twins: pin the attack-onset counter (carry slot 5) far
        # negative — a pure per-lane device update on the already-stacked
        # carry, so the jitted program's shapes/dtypes are untouched and
        # the generation still lowers exactly once
        carry = list(runner.carry)
        attack_iter = carry[_ATTACK_ITER_SLOT]
        for lane in range(1, runner.n, 2):
            attack_iter = attack_iter.at[lane].set(jnp.int32(BENIGN_PIN))
        carry[_ATTACK_ITER_SLOT] = attack_iter
        runner.carry = tuple(carry)

        k = self.base_cfg.node_size
        byz = self.base_cfg.byz_size
        sinks = [obs_lib.MemorySink() for _ in range(runner.n)]
        obs_list = [obs_lib.Observability(s) for s in sinks]
        for lane, o in enumerate(obs_list):
            attacked = lane % 2 == 0
            o.emit(
                "run_start",
                title=f"tune_g{gen}_cand{cand_idx[lane // 2]}"
                      f"_{'attacked' if attacked else 'benign'}",
                backend="tune",
                rounds=rounds,
                start_round=0,
                k=k,
                byz=byz,
                # the explicit id set the audit pins on (last-byz resident
                # slots — the trainer's static mask); the benign twin's
                # header says byz too: its "byzantine" clients exist but
                # never activate, which is exactly why any flag there is
                # a false one
                byz_ids=list(range(k - byz, k)),
                agg=self.base_cfg.agg,
                attack=self.base_cfg.attack if attacked else None,
                defense=self.base_cfg.defense,
                seed=self.base_cfg.seed,
            )
        runner.train(obs_list=obs_list, log_fn=self.log)
        if runner.failed:
            raise RuntimeError(
                f"tune generation {gen}: lanes quarantined: {runner.failed}"
            )
        out: Dict[int, Dict[str, Any]] = {}
        for j, idx in enumerate(cand_idx):
            fold = objective_lib.fold_pair(
                sinks[2 * j].events, sinks[2 * j + 1].events,
                k=k, rounds=rounds,
                ff_penalty=self.ff_penalty, ttd_weight=self.ttd_weight,
            )
            out[idx] = fold
            self.obs.emit(
                "tune_candidate",
                gen=gen,
                candidate=idx,
                objective=fold["objective"],
                precision=fold["precision"],
                recall=fold["recall"],
                time_to_detect=fold["time_to_detect"],
                benign_flag_rate=fold["benign_flag_rate"],
                params=self.candidates[idx],
            )
        return out

    # ------------------------------------------------------------ the loop

    def run(self) -> Dict[str, Any]:
        """Drive the halving schedule to completion (resuming from the
        journal when one is attached); returns the result dict the
        ``docs/tuned_defense_*.json`` artifacts persist."""
        plan = space_lib.halving_schedule(
            self.population, self.generations, self.base_rounds, self.eta
        )
        done: Dict[int, Dict[str, Any]] = {}
        if self.journal is not None:
            records = self.journal.replay()
            starts = [r for r in records if r["op"] == "tune_start"]
            if starts:
                if starts[0]["signature"] != self._signature():
                    raise ValueError(
                        f"tune journal {self.journal.path} was written by a "
                        f"different tune configuration; refusing to resume"
                    )
            else:
                self.journal.append(
                    {"op": "tune_start", "signature": self._signature()}
                )
            for r in records:
                if r["op"] == "gen_done":
                    done[int(r["gen"])] = r

        alive = list(range(self.population))
        last_scores: Dict[int, Dict[str, Any]] = {}
        for gen, (count, rounds) in enumerate(plan):
            cand_idx = alive[:count]
            if gen in done:
                # completed before the kill: restore the recorded scores
                # (bit-identical by determinism — the journal is the proof
                # of work, not an approximation)
                rec = done[gen]
                scored = {
                    int(i): fold for i, fold in rec["scored"].items()
                }
                alive = [int(i) for i in rec["survivors"]]
                last_scores = scored
                self.trail.append({
                    "gen": gen, "rounds": rounds,
                    "candidates": [int(i) for i in rec["candidates"]],
                    "scored": scored, "survivors": list(alive),
                    "resumed": True,
                })
                self.log(f"[tune] gen {gen}: restored from journal")
                continue
            if self.journal is not None:
                self.journal.append({
                    "op": "gen_start", "gen": gen, "rounds": rounds,
                    "candidates": cand_idx,
                })
            scored = self._run_generation(gen, cand_idx, rounds)
            keep = plan[gen + 1][0] if gen + 1 < len(plan) else 1
            order = space_lib.survivors(
                [scored[i]["objective"] for i in cand_idx], keep
            )
            alive = [cand_idx[j] for j in order]
            last_scores = scored
            self.trail.append({
                "gen": gen, "rounds": rounds, "candidates": list(cand_idx),
                "scored": scored, "survivors": list(alive),
                "resumed": False,
            })
            self.obs.emit(
                "tune_generation",
                gen=gen,
                population=len(cand_idx),
                rounds=rounds,
                survivors=len(alive),
            )
            if self.journal is not None:
                self.journal.append({
                    "op": "gen_done", "gen": gen, "rounds": rounds,
                    "candidates": cand_idx,
                    "scored": {str(i): scored[i] for i in cand_idx},
                    "survivors": alive,
                })
            self.log(
                f"[tune] gen {gen}: {len(cand_idx)} candidates x "
                f"{rounds} rounds -> survivors {alive} "
                f"(lowerings={self.lowerings})"
            )

        # the winner among the FINAL generation's scores; candidate 0 (the
        # IID defaults) rode every generation as the protected control, so
        # the comparison is at equal budget
        final_idx = max(
            last_scores, key=lambda i: (last_scores[i]["objective"], -i)
        )
        result = {
            "signature": self._signature(),
            "schedule": [
                {"gen": g, "candidates": c, "rounds": r}
                for g, ((c, r)) in enumerate(plan)
            ],
            "default": {
                "params": self.candidates[0],
                **(last_scores.get(0) or {}),
            },
            "tuned": {
                "candidate": final_idx,
                "params": self.candidates[final_idx],
                **last_scores[final_idx],
            },
            "trail": self.trail,
            "lowerings": self.lowerings,
        }
        self.obs.emit(
            "tune_result",
            generations=len(plan),
            objective=last_scores[final_idx]["objective"],
            params=self.candidates[final_idx],
            candidate=final_idx,
        )
        if self.journal is not None:
            self.journal.append({
                "op": "tune_done",
                "candidate": final_idx,
                "params": self.candidates[final_idx],
                "objective": last_scores[final_idx]["objective"],
            })
            self.journal.close()
        return result


# --------------------------------------------------------------------------
# CLI: ``python -m byzantine_aircomp_tpu tune``
# --------------------------------------------------------------------------


def build_base_cfg(args) -> FedConfig:
    cfg = FedConfig()
    cfg.honest_size = args.k - args.b
    cfg.byz_size = args.b
    cfg.dataset = args.dataset
    cfg.model = args.model
    cfg.batch_size = args.batch_size
    cfg.gamma = args.gamma
    cfg.display_interval = args.interval
    cfg.seed = args.cfg_seed
    cfg.attack = f"{args.attack}@{args.onset}"
    cfg.agg = args.agg
    cfg.defense = args.defense
    cfg.defense_ladder = args.ladder
    cfg.forensics = "top"
    cfg.forensics_top = min(8, args.k)
    cfg.eval_train = False
    if args.alpha != "iid":
        cfg.partition = "dirichlet"
        cfg.dirichlet_alpha = float(args.alpha)
    if args.size_skew != "none":
        cfg.size_skew = args.size_skew
    cfg.rounds = 1  # per-generation budgets overwrite this
    cfg.validate()
    return cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "byzantine_aircomp_tpu tune",
        description="population-based defense auto-tuner (successive "
        "halving over the vmapped lane engine)",
    )
    ap.add_argument("--alpha", type=str, default="iid",
                    help="heterogeneity level: 'iid' (contiguous split) or "
                         "a Dirichlet concentration (e.g. 0.3, 0.1)")
    ap.add_argument("--size-skew", type=str, default="none",
                    help="per-client quantity skew ('zipf:<s>'), composed "
                         "with the label skew")
    ap.add_argument("--population", type=int, default=8)
    ap.add_argument("--generations", type=int, default=3)
    ap.add_argument("--rounds", type=int, default=8,
                    help="generation-0 round budget (doubles per rung)")
    ap.add_argument("--eta", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0,
                    help="candidate-sampling seed")
    ap.add_argument("--cfg-seed", type=int, default=2021,
                    help="the lanes' training seed")
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--b", type=int, default=3)
    ap.add_argument("--attack", type=str, default="signflip")
    ap.add_argument("--onset", type=int, default=2,
                    help="attack onset round (benign lanes never reach it)")
    ap.add_argument("--agg", type=str, default="mean")
    ap.add_argument("--defense", type=str, default="adaptive")
    ap.add_argument("--ladder", type=str,
                    default="mean,trimmed_mean,multi_krum")
    ap.add_argument("--dataset", type=str, default="mnist_hard")
    ap.add_argument("--model", type=str, default="MLP")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--gamma", type=float, default=1e-2)
    ap.add_argument("--interval", type=int, default=2,
                    help="iterations per round (displayInterval)")
    ap.add_argument("--synthetic-train", type=int, default=8192)
    ap.add_argument("--synthetic-val", type=int, default=1024)
    ap.add_argument("--ff-penalty", type=float,
                    default=objective_lib.DEFAULT_FF_PENALTY)
    ap.add_argument("--ttd-weight", type=float,
                    default=objective_lib.DEFAULT_TTD_WEIGHT)
    ap.add_argument("--backend", choices=["vmap", "map"], default="vmap")
    ap.add_argument("--journal", type=str, default="",
                    help="tune journal path (enables SIGKILL resume)")
    ap.add_argument("--obs-dir", type=str, default="",
                    help="write the tuner's event stream here")
    ap.add_argument("--out", type=str, default="",
                    help="write the result artifact JSON here")
    ap.add_argument("--assert-single-lowering", action="store_true",
                    help="exit 1 unless lowerings == generations run live")
    ap.add_argument("--assert-winner-at-least-default", action="store_true",
                    help="exit 1 unless the winner's objective >= the "
                         "IID-default control lane's (CI smoke gate)")
    args = ap.parse_args(argv)

    from ..data import datasets as data_lib

    dataset = data_lib.load(
        args.dataset,
        synthetic_train=args.synthetic_train,
        synthetic_val=args.synthetic_val,
    )
    base_cfg = build_base_cfg(args)
    obs = obs_lib.NULL
    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
        sink = obs_lib.JsonlSink(
            os.path.join(args.obs_dir, f"tune_{args.alpha}.events.jsonl")
        )
        obs = obs_lib.Observability(sink)
    tuner = Tuner(
        base_cfg,
        population=args.population,
        generations=args.generations,
        base_rounds=args.rounds,
        eta=args.eta,
        seed=args.seed,
        journal_path=args.journal or None,
        obs=obs,
        dataset=dataset,
        backend=args.backend,
        ff_penalty=args.ff_penalty,
        ttd_weight=args.ttd_weight,
        log=lambda s: print(s, flush=True),
    )
    result = tuner.run()
    result["alpha"] = args.alpha
    live_gens = sum(1 for t in tuner.trail if not t["resumed"])
    print(
        f"tune done: winner candidate {result['tuned']['candidate']} "
        f"objective={result['tuned']['objective']:.4f} "
        f"(default {result['default'].get('objective', float('nan')):.4f}) "
        f"benign_ff={result['tuned']['benign_flag_rate']:.4f} "
        f"(default {result['default'].get('benign_flag_rate', float('nan')):.4f}) "
        f"lowerings={tuner.lowerings}/{live_gens} live generations"
    )
    if args.out:
        parent = os.path.dirname(args.out)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, sort_keys=True, indent=1)
            f.write("\n")
        print(f"artifact -> {args.out}")
    obs.close()
    rc = 0
    if args.assert_single_lowering and tuner.lowerings != live_gens:
        print(
            f"FAIL: {tuner.lowerings} lowerings != {live_gens} live "
            f"generations (the one-lowering-per-generation contract broke)"
        )
        rc = 1
    if args.assert_winner_at_least_default:
        if result["tuned"]["objective"] < result["default"].get(
            "objective", -float("inf")
        ):
            print("FAIL: winner scored below the IID-default control lane")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
