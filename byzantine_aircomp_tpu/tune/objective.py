"""The tuner's scalar objective: one paired-lane event fold.

Each candidate runs as TWO lanes of the same vmapped program under the
same seed: an *attacked* lane (the configured attack active from its
onset round) and a *benign* lane (the attack-onset iteration counter
pinned so the attack never activates — every Byzantine row stays
bit-identical to an honest one, see ``ops/attacks.AttackSpec.onset_round``
and the tuner's carry pinning).  The pairing is the variance control:
both lanes share the data layout, the channel draws, and the detector
constants, so any flag the benign lane raises is attributable to the
constants — not to a different data order.

The attacked lane's ``client_flag`` stream goes through the SAME
``analysis/audit.py`` precision/recall/time-to-detect machinery every
offline forensic report uses (one fold implementation, no drift); the
benign lane's stream reduces to a false-flag rate.  The scalar is

    objective = precision + recall
                - ff_penalty * benign_flag_rate
                - ttd_weight * normalized_time_to_detect

with the benign-false-flag penalty explicit and dominant by default: a
detector that pages on honest non-IID clients is worse than a slightly
slower one, which is exactly the trade the IID-tuned defaults get wrong
at low Dirichlet alpha.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..analysis import audit as audit_lib

#: default penalty per unit of benign false-flag rate — sized so a
#: detector flagging one honest client per round (rate 1/K with K=16,
#: ~0.0625) loses ~0.6, i.e. more than the whole recall term can buy back
DEFAULT_FF_PENALTY = 10.0
#: weight of the normalized time-to-detect term (1.0 = never detected)
DEFAULT_TTD_WEIGHT = 0.25


def benign_flag_rate(events: List[dict], k: int, rounds: int) -> float:
    """Flagged ``client_flag`` events per client-round on a lane where no
    attack ever activated — every one is a false positive."""
    if k <= 0 or rounds <= 0:
        return 0.0
    flags = sum(
        1 for e in events
        if e.get("kind") == "client_flag" and e.get("flagged")
    )
    return flags / float(k * rounds)


def objective_score(
    precision: Optional[float],
    recall: Optional[float],
    time_to_detect: Optional[int],
    ff_rate: float,
    rounds: int,
    *,
    ff_penalty: float = DEFAULT_FF_PENALTY,
    ttd_weight: float = DEFAULT_TTD_WEIGHT,
) -> float:
    """The scalar the halving schedule ranks on (higher is better).

    ``precision=None`` (no flag ever raised) scores as 1.0 — an attacked
    lane that flags nothing pays through recall=0 and the full ttd term,
    not through a phantom precision penalty; ``recall=None`` (no ground
    truth) scores 0."""
    p = 1.0 if precision is None else float(precision)
    rec = 0.0 if recall is None else float(recall)
    if time_to_detect is None:
        ttd_norm = 1.0  # never detected: the worst the term can charge
    else:
        ttd_norm = min(1.0, max(0.0, float(time_to_detect) / max(1, rounds)))
    return p + rec - ff_penalty * ff_rate - ttd_weight * ttd_norm


def fold_pair(
    attacked_events: List[dict],
    benign_events: List[dict],
    *,
    k: int,
    rounds: int,
    ff_penalty: float = DEFAULT_FF_PENALTY,
    ttd_weight: float = DEFAULT_TTD_WEIGHT,
) -> Dict[str, object]:
    """One candidate's score from its two lanes' event streams.

    ``attacked_events`` must contain the lane's ``run_start`` header (the
    tuner emits it with the explicit ``byz_ids`` the audit pins on) and
    its ``client_flag`` stream; ``benign_events`` only needs the flag
    stream.  Returns the audit summary fields plus ``benign_flag_rate``
    and the scalar ``objective``."""
    summary = audit_lib.audit(attacked_events)["summary"]
    ff_rate = benign_flag_rate(benign_events, k, rounds)
    score = objective_score(
        summary["precision"], summary["recall"], summary["time_to_detect"],
        ff_rate, rounds, ff_penalty=ff_penalty, ttd_weight=ttd_weight,
    )
    return {
        "precision": summary["precision"],
        "recall": summary["recall"],
        "time_to_detect": summary["time_to_detect"],
        "flag_events": summary["flag_events"],
        "benign_flag_rate": ff_rate,
        "objective": score,
    }
