"""Crash-safe file writes.

Every artifact this framework persists (checkpoints, pickled metric
records, sweep grids) must survive an interrupted process: a run killed
mid-write must never leave a TRUNCATED file under the final name, because a
later resume/analysis pass would load garbage.  The standard POSIX recipe —
write a temp file in the destination directory, then ``os.replace`` (atomic
on the same filesystem) — is centralized here so every writer shares one
audited implementation.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, Callable, Iterator, Optional


def atomic_write(path: str, write_fn: Callable, mode: str = "wb") -> str:
    """Write ``path`` atomically: ``write_fn(file_obj)`` runs against a temp
    file in the same directory, which is renamed over ``path`` only after
    the write completes (and the ``os.fdopen`` context has flushed/closed).
    On ANY failure the temp file is removed and the previous ``path``
    content — if any — is left untouched."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def atomic_pickle(path: str, obj: Any) -> str:
    """Atomically pickle ``obj`` to ``path``."""
    return atomic_write(path, lambda f: pickle.dump(obj, f))


def open_append(path: str):
    """Open ``path`` for line-buffered text append, creating parent dirs.

    The append-safe counterpart to :func:`atomic_write` for GROWING
    artifacts (event streams, log tees) where replace-on-close would
    discard the tail a killed run already paid for.  Line buffering plus
    one-line-per-write() callers means a kill never tears a line and
    POSIX append semantics keep concurrent writers from interleaving
    within one."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    return open(path, "a", buffering=1)


def iter_jsonl(
    path: str,
    warn: Optional[Callable[[str], None]] = None,
    max_warn: int = 10,
) -> Iterator[dict]:
    """Yield parsed objects from a JSONL file, skipping undecodable lines.

    The read-side counterpart to :func:`open_append`: line-buffered
    appends mean a kill can tear AT MOST the final line (a partial write
    the OS flushed on process death) — but disk corruption, a crashed
    writer without line buffering, or a hostile file can damage INTERIOR
    lines too, and journal/event-stream replay must survive both: every
    torn/garbage/non-object line is skipped, never raised.  Skips are
    COUNTED: the first ``max_warn`` report per line through ``warn``,
    the rest are silent (a corrupt 100k-line stream must not flood the
    operator's terminal), and when any skips went UNREPORTED a summary
    line with the total closes the iteration — the caller always learns
    HOW MUCH is missing even past the cap; below the cap every skip was
    already reported individually, so no summary is added.  Byte
    truncation that splits a multibyte character is absorbed
    by ``errors="replace"``.  A missing file yields nothing — callers
    distinguish empty from absent with ``os.path.exists`` if they
    care."""
    if not os.path.exists(path):
        return
    skipped = 0

    def _skip(i: int, why: str) -> None:
        nonlocal skipped
        skipped += 1
        if warn is not None and skipped <= max_warn:
            warn(f"skipping {why} line {i + 1} of {path}")

    with open(path, "r", errors="replace") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                _skip(i, "malformed")
                continue
            if isinstance(obj, dict):
                yield obj
            else:
                _skip(i, "non-object")
    if skipped > max_warn and warn is not None:
        warn(
            f"{path}: skipped {skipped} unreadable line(s) total"
            f" ({skipped - max_warn} unreported)"
        )
