"""Orbax checkpointing for params pytrees (multi-host aware).

Complements the flat ``.npz`` fast path in ``fed.checkpoint`` (which stores
the [d] vector + round index): this writes the STRUCTURED params pytree via
orbax, which handles atomic commits and, on multi-host meshes, coordinates
the distributed save so each process writes only its addressable shards.

The reference has no checkpointing at all — its ``--inherit`` flag is dead
(``/root/reference/MNIST_Air_weight.py:22,:500``) and final weights are
discarded (``:472``).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax


_CKPTR = None


def _checkpointer():
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def step_dir(ckpt_dir: str, title: str, round_idx: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), title, f"round_{round_idx:06d}")


def save(ckpt_dir: str, title: str, round_idx: int, params: Any) -> str:
    """Write the params pytree for ``round_idx``; returns the step dir."""
    path = step_dir(ckpt_dir, title, round_idx)
    ckptr = _checkpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    return path


def latest_round(ckpt_dir: str, title: str) -> Optional[int]:
    root = os.path.join(os.path.abspath(ckpt_dir), title)
    if not os.path.isdir(root):
        return None
    rounds = [
        int(name.split("_")[1])
        for name in os.listdir(root)
        if name.startswith("round_") and name.split("_")[1].isdigit()
    ]
    return max(rounds) if rounds else None


def load(
    ckpt_dir: str, title: str, example_params: Any, round_idx: Optional[int] = None
) -> Optional[Tuple[int, Any]]:
    """Restore (round_idx, params). ``example_params`` supplies the target
    structure/shardings (pass the freshly-initialized pytree — on a mesh, one
    whose leaves carry the desired shardings)."""
    if round_idx is None:
        round_idx = latest_round(ckpt_dir, title)
        if round_idx is None:
            return None
    path = step_dir(ckpt_dir, title, round_idx)
    if not os.path.isdir(path):
        return None
    ref = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        example_params,
    )
    params = _checkpointer().restore(path, ref)
    return round_idx, params
