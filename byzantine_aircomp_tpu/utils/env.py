"""Tunnel-proof environment helpers shared by the driver entry points.

A wedged axon relay blocks JAX backend init indefinitely (every backend,
because the axon PJRT plugin hooks ``get_backend``).  The one reliable
bypass is to keep the plugin from booting at all: the axon sitecustomize
gates its ``register()`` call (the hang site) on ``PALLAS_AXON_POOL_IPS``.
"""

from __future__ import annotations

import os


def scrubbed_cpu_env(n_devices: int | None = None) -> dict:
    """A copy of ``os.environ`` that cannot touch the device tunnel:
    axon boot disabled, CPU platform forced, optionally ``n_devices``
    virtual host devices pinned via XLA_FLAGS."""
    env = dict(os.environ)
    # sitecustomize gates the PJRT register() call (the hang site when the
    # tunnel relay is wedged) on this variable — unset disables axon boot
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # persistent compilation cache: the dryrun's CNN stage and bench's CPU
    # fallback each cost minutes of XLA compile on the 1-core host; cache
    # them (jax defaults: only compiles >1s are stored)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", default_cache_dir())
    if n_devices is not None:
        parts = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        parts.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(parts)
    return env


#: substrings identifying the XLA:CPU feature-mismatch wall of text (one
#: multi-KB line per compile enumerating every ISA flag, ending in a
#: SIGILL warning — see the BENCH_r05.json / MULTICHIP_r05.json tails)
_XLA_FEATURE_WARNING_MARKERS = (
    "match the machine type for execution",
    "could lead to execution errors such as SIGILL",
)

_XLA_WARNING_SUMMARY = (
    "[env] XLA:CPU compile/host machine-feature mismatch warning suppressed "
    "(cached executable may use unsupported ISA extensions -> SIGILL)"
)


def condense_stderr_warnings(log_file: str = ""):
    """Collapse the XLA feature-mismatch wall of text to one stderr line.

    The warning is emitted by native code writing straight to fd 2 (it is
    not reachable through Python's ``warnings``/``logging``), so this
    installs an fd-level filter: stderr is swapped for a pipe, a reader
    thread forwards everything verbatim EXCEPT lines carrying the
    :data:`_XLA_FEATURE_WARNING_MARKERS`, which are replaced (once) by a
    one-line summary.  When ``log_file`` is set the full original text is
    appended there, so ``--log-file`` keeps the complete record.

    Returns a zero-arg ``restore()`` callable; callers wrap the run in
    ``try/finally``.  Safe to call when stderr is not a real fd (pytest
    capture replaces ``sys.stderr`` with an object — this filter only
    touches fd 2, and ``restore()`` always puts the original back).
    """
    import threading

    try:
        saved_fd = os.dup(2)
    except OSError:  # no usable stderr fd at all: nothing to filter
        return lambda: None
    read_fd, write_fd = os.pipe()
    os.dup2(write_fd, 2)
    os.close(write_fd)
    summarized = [False]

    def _matches(line: bytes) -> bool:
        return any(m.encode() in line for m in _XLA_FEATURE_WARNING_MARKERS)

    def _forward(chunk: bytes) -> None:
        try:
            os.write(saved_fd, chunk)
        except OSError:
            pass

    def _handle(line: bytes) -> None:
        if _matches(line):
            if log_file:
                try:
                    with open(log_file, "ab") as f:
                        f.write(line)
                except OSError:
                    pass
            if not summarized[0]:
                summarized[0] = True
                _forward(_XLA_WARNING_SUMMARY.encode() + b"\n")
        else:
            _forward(line)

    def _reader() -> None:
        buf = b""
        while True:
            try:
                chunk = os.read(read_fd, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                _handle(line + b"\n")
        if buf:
            _handle(buf)
        os.close(read_fd)

    thread = threading.Thread(
        target=_reader, name="stderr-condenser", daemon=True
    )
    thread.start()

    def restore() -> None:
        # putting the original fd back closes this process's last write end
        # of the pipe, so the reader sees EOF and drains whatever is left
        os.dup2(saved_fd, 2)
        thread.join(timeout=5.0)
        os.close(saved_fd)

    return restore


def default_cache_dir() -> str:
    """Repo-local persistent XLA compilation cache dir (gitignored) — the
    single derivation shared by conftest and the subprocess env, so the
    in-process and spawned-process caches cannot silently split."""
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        ".jax_cache",
    )


def diagnose_relay(ports=(8082, 8083), timeout: float = 3.0) -> str:
    """Classify the device-tunnel relay state without touching JAX.

    Returns ``"listening"`` (some relay port accepts connections — a hang is
    then a WEDGED relay), ``"dead"`` (connection refused everywhere — the
    relay process is gone and nothing in-container can restart it), or
    ``"unknown"`` (timeouts/other).  Used to make bench/dryrun artifacts
    self-describing about WHICH tunnel failure occurred."""
    import socket

    saw_refused = False
    for port in ports:
        s = socket.socket()
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", port))
            return "listening"
        except ConnectionRefusedError:
            saw_refused = True
        except OSError:
            pass
        finally:
            s.close()
    return "dead" if saw_refused else "unknown"


def probe_backend_subprocess(timeout: float | None):
    """Initialize the default-env JAX backend in a subprocess.

    Returns ``{'backend': str, 'n': int}`` on success, ``None`` if init
    hung past ``timeout`` (``None`` = wait indefinitely) or failed —
    without ever risking the caller's process on a wedged tunnel.
    """
    import json
    import subprocess
    import sys

    src = (
        "import jax, json; "
        "print(json.dumps({'backend': jax.default_backend(), 'n': len(jax.devices())}))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", src],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None
