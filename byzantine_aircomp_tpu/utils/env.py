"""Tunnel-proof environment helpers shared by the driver entry points.

A wedged axon relay blocks JAX backend init indefinitely (every backend,
because the axon PJRT plugin hooks ``get_backend``).  The one reliable
bypass is to keep the plugin from booting at all: the axon sitecustomize
gates its ``register()`` call (the hang site) on ``PALLAS_AXON_POOL_IPS``.
"""

from __future__ import annotations

import os


def scrubbed_cpu_env(n_devices: int | None = None) -> dict:
    """A copy of ``os.environ`` that cannot touch the device tunnel:
    axon boot disabled, CPU platform forced, optionally ``n_devices``
    virtual host devices pinned via XLA_FLAGS."""
    env = dict(os.environ)
    # sitecustomize gates the PJRT register() call (the hang site when the
    # tunnel relay is wedged) on this variable — unset disables axon boot
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # persistent compilation cache: the dryrun's CNN stage and bench's CPU
    # fallback each cost minutes of XLA compile on the 1-core host; cache
    # them (jax defaults: only compiles >1s are stored)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", default_cache_dir())
    if n_devices is not None:
        parts = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        parts.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(parts)
    return env


def default_cache_dir() -> str:
    """Repo-local persistent XLA compilation cache dir (gitignored) — the
    single derivation shared by conftest and the subprocess env, so the
    in-process and spawned-process caches cannot silently split."""
    return os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        ".jax_cache",
    )


def diagnose_relay(ports=(8082, 8083), timeout: float = 3.0) -> str:
    """Classify the device-tunnel relay state without touching JAX.

    Returns ``"listening"`` (some relay port accepts connections — a hang is
    then a WEDGED relay), ``"dead"`` (connection refused everywhere — the
    relay process is gone and nothing in-container can restart it), or
    ``"unknown"`` (timeouts/other).  Used to make bench/dryrun artifacts
    self-describing about WHICH tunnel failure occurred."""
    import socket

    saw_refused = False
    for port in ports:
        s = socket.socket()
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", port))
            return "listening"
        except ConnectionRefusedError:
            saw_refused = True
        except OSError:
            pass
        finally:
            s.close()
    return "dead" if saw_refused else "unknown"


def probe_backend_subprocess(timeout: float | None):
    """Initialize the default-env JAX backend in a subprocess.

    Returns ``{'backend': str, 'n': int}`` on success, ``None`` if init
    hung past ``timeout`` (``None`` = wait indefinitely) or failed —
    without ever risking the caller's process on a wedged tunnel.
    """
    import json
    import subprocess
    import sys

    src = (
        "import jax, json; "
        "print(json.dumps({'backend': jax.default_backend(), 'n': len(jax.devices())}))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", src],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None
