"""Utility subsystems: checkpointing, etc."""
