"""CLI mirroring the reference's argparse surface plus ``--backend``.

Reference flags (``/root/reference/MNIST_Air_weight.py:16-28``): ``--opt``,
``--agg``, ``--attack``, ``--var``, ``--inherit``, ``--mark``, ``--use-gpu``,
``--K``, ``--B``.  All are accepted here with the same names and defaults;
``--use-gpu`` is accepted-and-ignored (device selection is JAX's), and
``--inherit`` now actually works (resume from checkpoint) instead of being the
reference's dead flag (``:22,:500``).  New flags: ``--backend {jax,ref}``
(north-star gate; ``ref`` = NumPy oracle path), ``--preset`` (BASELINE.json
configs; flags present on the command line override the preset), ``--dataset``,
``--model``, ``--rounds``, ``--interval``, ``--batch-size``, ``--gamma``,
``--seed``, and the execution-layout/observability flags.

One subcommand lives outside the flag surface: ``serve`` boots the
multi-tenant experiment server (``serve/``; docs/SERVING.md) instead of
running a single training job.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from .fed.config import FedConfig

_SHARDING = {"auto": None, "on": True, "off": False}

# single source of truth: argparse dest -> (FedConfig field, converter).
# Both the kwargs construction and the preset explicit-override scan derive
# from this, so the two cannot drift.
def add_knob_flags(p) -> None:
    """The attack/defense magnitude + data-partition knobs, shared between
    the main CLI and the sweep tool so the two surfaces (and their help
    text) cannot drift."""
    p.add_argument("--partition", choices=["contiguous", "dirichlet"],
                   default="contiguous",
                   help="client data split (dirichlet = label-skewed "
                        "non-IID, Hsu et al. 2019)")
    p.add_argument("--dirichlet-alpha", type=float, default=0.3,
                   help="Dirichlet concentration for --partition dirichlet "
                        "(smaller = more label skew)")
    p.add_argument("--size-skew", type=str, default="none",
                   help="per-client quantity skew: 'zipf:<s>' re-cuts the "
                        "(possibly Dirichlet-permuted) sample stream into "
                        "Zipf(s)-proportioned shard sizes (composes with "
                        "label skew; zipf:0 = the equal cut)")
    p.add_argument("--participation", type=float, default=1.0,
                   help="fraction of clients active per iteration "
                        "(stratified honest/Byzantine draw; 1.0 = all, "
                        "the reference's behavior)")
    p.add_argument("--client-momentum", type=float, default=0.0,
                   help="client-side momentum beta (Karimireddy 2021 — "
                        "breaks time-coupled attacks; requires "
                        "local_steps=1); 0 = off")
    p.add_argument("--bucket-size", type=int, default=1,
                   help="server-side bucketing (Karimireddy 2022): "
                        "aggregate means of random s-client buckets — the "
                        "standard non-IID fix for median/krum; 1 = off")
    p.add_argument("--cohort-size", type=int, default=0,
                   help="stream the round over client chunks of this size "
                        "instead of the resident [K, d] stack (peak HBM "
                        "O(cohort*d)); must divide honest and Byzantine "
                        "sizes; 0 = resident path, bit-identical records")
    p.add_argument("--cohort-quantile", choices=["exact", "sketch"],
                   default="exact",
                   help="streamed median/trimmed_mean rung: exact "
                        "key-bisection (32 counting passes, resident-rank "
                        "parity) or mergeable histogram sketch (3 passes, "
                        "bounded bucket-width error)")
    p.add_argument("--cohort-sketch-bins", type=int, default=512,
                   help="histogram resolution of the quantile sketch "
                        "(--cohort-quantile sketch)")
    p.add_argument("--attack-param", type=float, default=None,
                   help="scalar attack magnitude (alie z / ipm eps / gaussian "
                        "sigma / minmax+minsum fixed gamma)")
    p.add_argument("--krum-m", type=int, default=None,
                   help="multi-Krum selection count (default: honest size)")
    p.add_argument("--clip-tau", type=float, default=None,
                   help="centered-clipping radius (agg=cclip); default: "
                        "adaptive per-step median client delta norm")
    p.add_argument("--clip-iters", type=int, default=3,
                   help="centered-clipping iterations (agg=cclip)")
    p.add_argument("--sign-eta", type=float, default=None,
                   help="one-bit OTA majority-vote step size (agg=signmv; "
                        "default: coordinatewise median delta magnitude)")
    p.add_argument("--sign-bits", type=int, choices=[1, 8, 16, 32],
                   default=32,
                   help="sign-channel payload width (agg=signmv/bev): 32 = "
                        "legacy f32 ballots, 1 = bit-packed uint32 words + "
                        "popcount reduce (needs --sign-eta), 8/16 = "
                        "quantize-dequantize emulation")
    p.add_argument("--dnc-iters", type=int, default=3,
                   help="dnc filtering rounds (agg=dnc)")
    p.add_argument("--dnc-sub-dim", type=int, default=10000,
                   help="dnc coordinate-subsample size (agg=dnc)")
    p.add_argument("--dnc-c", type=float, default=1.0,
                   help="dnc removal multiplier: ceil(c*B) flagged per "
                        "round (agg=dnc)")
    # fault-injection surface (ops/faults.py); knob flags override the
    # registered scenario's defaults and require --fault
    p.add_argument("--fault", type=str, default=None,
                   help="fault scenario name (dropout, deep_fade, csi, "
                        "corrupt, chaos); None = ideal deployment")
    p.add_argument("--dropout-prob", type=float, default=None,
                   help="per-round client dropout probability (stale-update "
                        "replay); overrides the --fault scenario")
    p.add_argument("--fade-floor", type=float, default=None,
                   help="deep-fade outage threshold on |h|^2 (rows below "
                        "are erased); overrides the --fault scenario")
    p.add_argument("--csi-std", type=float, default=None,
                   help="CSI estimation error log-magnitude std; overrides "
                        "the --fault scenario")
    p.add_argument("--corrupt-prob", type=float, default=None,
                   help="per-round payload-corruption probability for the "
                        "faulty clients; overrides the --fault scenario")
    p.add_argument("--corrupt-mode", choices=["nan", "inf", "saturate"],
                   default=None,
                   help="corrupted payload value class; overrides the "
                        "--fault scenario")
    p.add_argument("--corrupt-size", type=int, default=None,
                   help="number of corruption-eligible (honest) clients; "
                        "overrides the --fault scenario")
    # online-defense surface (defense/); knob flags require --defense
    p.add_argument("--defense", choices=["off", "monitor", "adaptive"],
                   default="off",
                   help="in-jit anomaly detection: monitor = score + report "
                        "only, adaptive = escalate the aggregator through "
                        "--defense-ladder (off is bit-identical to a run "
                        "without the defense)")
    p.add_argument("--defense-ladder", type=str,
                   default="mean,trimmed_mean,multi_krum",
                   help="comma-separated aggregator escalation ladder; "
                        "under adaptive the first rung must equal --agg")
    p.add_argument("--defense-warmup", type=int, default=5,
                   help="iterations of baseline building before any flag")
    p.add_argument("--defense-alpha", type=float, default=0.1,
                   help="EMA rate of the per-client score baseline")
    p.add_argument("--defense-drift", type=float, default=0.5,
                   help="CUSUM drift allowance (in robust z-units)")
    p.add_argument("--defense-cusum", type=float, default=8.0,
                   help="CUSUM change-point alarm threshold")
    p.add_argument("--defense-z", type=float, default=4.0,
                   help="instantaneous robust z-score alarm threshold")
    p.add_argument("--defense-up", type=int, default=3,
                   help="consecutive suspicious iterations per escalation")
    p.add_argument("--defense-down", type=int, default=20,
                   help="consecutive clean iterations per de-escalation")
    p.add_argument("--defense-min-flagged", type=int, default=1,
                   help="flagged clients that make an iteration suspicious")
    p.add_argument("--defense-floor", type=float, default=1.5,
                   help="leaky escalation-budget threshold above which the "
                        "rung floor pins at 1 (duty-cycle resistance; "
                        "0 disables the floor)")
    p.add_argument("--defense-leak", type=float, default=0.005,
                   help="per-iteration decay rate of the escalation budget")
    # service-round surface (fed/train.py); knob flags require --service on
    p.add_argument("--service", choices=["off", "on"], default="off",
                   help="always-on service rounds: draw each round's K "
                        "participants from a registered --population with "
                        "churn/deadline semantics and warm rollback (off "
                        "is bit-identical to a run without the feature)")
    p.add_argument("--population", type=int, default=0,
                   help="registered client population N_pop >> K; must be "
                        "a positive multiple of K (requires --service on)")
    p.add_argument("--churn-arrival", type=float, default=0.02,
                   help="per-iteration probability an offline population "
                        "client comes back online (Markov churn)")
    p.add_argument("--churn-departure", type=float, default=0.01,
                   help="per-iteration probability an online population "
                        "client goes offline (Markov churn)")
    p.add_argument("--straggler-prob", type=float, default=0.0,
                   help="per-iteration probability a drawn participant "
                        "misses the round deadline (its row is erased and "
                        "aggregation degrades to the effective K)")
    p.add_argument("--rollback", choices=["off", "on"], default="on",
                   help="warm rollback: on divergence restore the last "
                        "good round state and resume with a widened trim "
                        "fraction under a re-salted key stream")
    p.add_argument("--rollback-loss-factor", type=float, default=3.0,
                   help="divergence guard: trip when val loss exceeds this "
                        "multiple of the recent median")
    p.add_argument("--rollback-cusum", type=float, default=0.0,
                   help="divergence guard: trip when the defense CUSUM "
                        "maximum reaches this (0 = off; requires "
                        "--defense)")
    p.add_argument("--rollback-widen", type=float, default=1.5,
                   help="trim-fraction multiplier applied on each rollback")
    p.add_argument("--rollback-max", type=int, default=3,
                   help="rollback budget per run (after it is spent the "
                        "guard reports but no longer restores)")
    p.add_argument("--pop-shards", type=int, default=1,
                   help="shard the streamed service round's cohort chunks "
                        "over this many owners: a device mesh when the "
                        "devices exist (parallel/popmesh.py), a sequential "
                        "reference engine otherwise; 1 = the legacy "
                        "single-scan program (requires --service on with "
                        "--cohort-size when > 1)")
    # multi-round dispatch tier (fed/train.py _train_multi); the
    # granularity knobs require --rounds-per-dispatch > 1
    p.add_argument("--rounds-per-dispatch", type=int, default=1,
                   help="run R rounds as ONE device scan per dispatch; "
                        "records/events fold at dispatch exits, eval and "
                        "checkpoints move to R-round boundaries; 1 = the "
                        "exact per-round driver, bit-identical to builds "
                        "without the tier (R must divide --rounds)")
    p.add_argument("--eval-interval", type=int, default=0,
                   help="rounds between boundary evals under R>1 (0 = "
                        "every dispatch boundary; must be a multiple of "
                        "R; skipped rounds replicate the last eval in "
                        "the record)")
    p.add_argument("--dispatch-mode", choices=["exact", "degraded"],
                   default="exact",
                   help="R>1 granularity contract: 'degraded' opts into "
                        "R-boundary rollback/forensics granularity "
                        "(required to combine R>1 with --service on "
                        "--rollback on); 'exact' refuses combinations "
                        "that would silently coarsen")
    p.add_argument("--dispatch-prefetch", choices=["off", "on"],
                   default="off",
                   help="double-buffer the dispatch rim: launch dispatch "
                        "i+1 before folding dispatch i's host records so "
                        "host work overlaps device compute (timing-only; "
                        "records bit-identical)")
    p.add_argument("--async-writer", choices=["auto", "on", "off"],
                   default="auto",
                   help="bounded single-consumer writer thread owning "
                        "event appends, checkpoint serialization and the "
                        "record pickle (auto = on iff "
                        "--rounds-per-dispatch > 1); output-only")


ARG_TO_FIELD = {
    "opt": ("opt", None),
    "agg": ("agg", None),
    "attack": ("attack", None),
    "var": ("noise_var", None),
    "checkpoint_dir": ("checkpoint_dir", None),
    "inherit": ("inherit", None),
    "sharding": ("sharded", _SHARDING.get),
    "agg_impl": ("agg_impl", None),
    "fused_epilogue": ("fused_epilogue", None),
    "prng_impl": ("prng_impl", None),
    "stack_dtype": ("stack_dtype", None),
    "partition": ("partition", None),
    "dirichlet_alpha": ("dirichlet_alpha", None),
    "size_skew": ("size_skew", None),
    "participation": ("participation", None),
    "bucket_size": ("bucket_size", None),
    "cohort_size": ("cohort_size", None),
    "cohort_quantile": ("cohort_quantile", None),
    "cohort_sketch_bins": ("cohort_sketch_bins", None),
    "client_momentum": ("client_momentum", None),
    "attack_param": ("attack_param", None),
    "krum_m": ("krum_m", None),
    "clip_tau": ("clip_tau", None),
    "clip_iters": ("clip_iters", None),
    "sign_eta": ("sign_eta", None),
    "sign_bits": ("sign_bits", None),
    "dnc_iters": ("dnc_iters", None),
    "dnc_sub_dim": ("dnc_sub_dim", None),
    "dnc_c": ("dnc_c", None),
    "fault": ("fault", None),
    "dropout_prob": ("dropout_prob", None),
    "fade_floor": ("fade_floor", None),
    "csi_std": ("csi_std", None),
    "corrupt_prob": ("corrupt_prob", None),
    "corrupt_mode": ("corrupt_mode", None),
    "corrupt_size": ("corrupt_size", None),
    "defense": ("defense", None),
    "defense_ladder": ("defense_ladder", None),
    "defense_warmup": ("defense_warmup", None),
    "defense_alpha": ("defense_alpha", None),
    "defense_drift": ("defense_drift", None),
    "defense_cusum": ("defense_cusum", None),
    "defense_z": ("defense_z", None),
    "defense_up": ("defense_up", None),
    "defense_down": ("defense_down", None),
    "defense_min_flagged": ("defense_min_flagged", None),
    "defense_floor": ("defense_floor", None),
    "defense_leak": ("defense_leak", None),
    "service": ("service", None),
    "population": ("population", None),
    "churn_arrival": ("churn_arrival", None),
    "churn_departure": ("churn_departure", None),
    "straggler_prob": ("straggler_prob", None),
    "rollback": ("rollback", None),
    "rollback_loss_factor": ("rollback_loss_factor", None),
    "rollback_cusum": ("rollback_cusum", None),
    "rollback_widen": ("rollback_widen", None),
    "rollback_max": ("rollback_max", None),
    "pop_shards": ("pop_shards", None),
    "rounds_per_dispatch": ("rounds_per_dispatch", None),
    "eval_interval": ("eval_interval", None),
    "dispatch_mode": ("dispatch_mode", None),
    "dispatch_prefetch": ("dispatch_prefetch", None),
    "async_writer": ("async_writer", None),
    "profile_dir": ("profile_dir", None),
    "profile_rounds": ("profile_rounds", None),
    "hbm_warn_factor": ("hbm_warn_factor", None),
    "obs_dir": ("obs_dir", None),
    "obs_stdout": ("obs_stdout", None),
    "log_file": ("log_file", None),
    "quiet": ("quiet", None),
    "forensics": ("forensics", None),
    "forensics_top": ("forensics_top", None),
    "flight_window": ("flight_window", None),
    "metrics": ("metrics", None),
    "metrics_port": ("metrics_port", None),
    "alerts": ("alerts", None),
    "obs_rotate_mb": ("obs_rotate_mb", None),
    "trace": ("trace", None),
    "model_parallel": ("model_parallel", None),
    "rounds": ("rounds", None),
    "interval": ("display_interval", None),
    "batch_size": ("batch_size", None),
    "gamma": ("gamma", None),
    "weight_decay": ("weight_decay", None),
    "seed": ("seed", None),
    "model": ("model", None),
    "dataset": ("dataset", None),
    "mark": ("mark", None),
    "cache_dir": ("cache_dir", None),
    "resnet_width": ("resnet_width", None),
    "remat": ("remat", None),
    "no_eval_train": ("eval_train", lambda v: not v),
    "eval_train": ("eval_train", None),
    "local_steps": ("local_steps", None),
    "fedprox_mu": ("fedprox_mu", None),
    "server_opt": ("server_opt", None),
    "server_lr": ("server_lr", None),
    "server_momentum": ("server_momentum", None),
}


def build_parser() -> argparse.ArgumentParser:
    from . import presets

    p = argparse.ArgumentParser("byzantine_aircomp_tpu")
    # reference surface
    p.add_argument("--opt", type=str, default="SGD", help="optimizer")
    p.add_argument("--agg", type=str, default="gm", help="aggregator name")
    p.add_argument("--attack", type=str, default=None, help="attack name")
    p.add_argument("--var", type=float, default=None, help="channel noise variance")
    p.add_argument("--inherit", action="store_true", help="resume from checkpoint")
    p.add_argument("--mark", type=str, default="", help="mark on title")
    p.add_argument(
        "--use-gpu",
        type=str,
        default="true",
        help="accepted for reference-CLI compatibility; device choice is JAX's",
    )
    p.add_argument("--K", type=int, default=None, help="number of total devices")
    p.add_argument("--B", type=int, default=None, help="number of Byzantine devices")
    # framework surface
    p.add_argument("--backend", choices=["jax", "ref"], default="jax")
    p.add_argument(
        "--sharding",
        choices=["auto", "on", "off"],
        default="auto",
        help="shard clients over the device mesh (auto: when >1 device)",
    )
    p.add_argument(
        "--model-parallel",
        type=int,
        default=None,
        help="devices along the model (d) mesh axis",
    )
    p.add_argument(
        "--agg-impl",
        choices=["auto", "xla", "pallas"],
        default="auto",
        help="Weiszfeld step implementation (pallas = fused TPU kernel)",
    )
    p.add_argument(
        "--fused-epilogue",
        choices=["auto", "on", "off"],
        default="auto",
        help="single-HBM-pass sort-family aggregation epilogue "
             "(median/trimmed_mean selection + in-read OMA channel; "
             "auto = on for the pallas impl without faults)",
    )
    add_knob_flags(p)
    p.add_argument(
        "--prng-impl",
        choices=["threefry", "rbg", "unsafe_rbg"],
        default="threefry",
        help="per-round PRNG stream (rbg = fast TPU hardware RNG path)",
    )
    p.add_argument(
        "--stack-dtype",
        choices=["f32", "bf16"],
        default="f32",
        help="[K, d] client-stack dtype fed to the aggregator (bf16 halves "
             "the Weiszfeld re-read traffic; f32 arithmetic; experimental)",
    )
    p.add_argument("--dataset", type=str, default="mnist")
    p.add_argument("--model", type=str, default="MLP")
    p.add_argument(
        "--resnet-width", type=int, default=64,
        help="ResNet-18 stem width (64 = standard; smaller keeps the "
             "topology for scaled trajectory runs, scaling stated)",
    )
    p.add_argument(
        "--remat", action="store_true",
        help="rematerialize residual-block activations in backward "
             "(jax.checkpoint): trades FLOPs for the vmapped-clients "
             "activation memory that sets the single-chip ResNet ceiling",
    )
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--interval", type=int, default=10, help="displayInterval")
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--gamma", type=float, default=1e-2)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument(
        "--local-steps",
        type=int,
        default=1,
        help="local SGD steps per client per iteration (1 = reference FedSGD)",
    )
    p.add_argument(
        "--fedprox-mu",
        type=float,
        default=0.0,
        help="FedProx proximal coefficient (anchors client drift when "
             "--local-steps > 1; 0 = plain FedAvg/FedSGD)",
    )
    p.add_argument(
        "--server-opt",
        choices=["none", "momentum", "adam"],
        default="none",
        help="server optimizer over the pseudo-gradient (FedAvgM / FedAdam)",
    )
    p.add_argument("--server-lr", type=float, default=1.0)
    p.add_argument("--server-momentum", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=2021)
    p.add_argument("--cache-dir", type=str, default="")
    eval_group = p.add_mutually_exclusive_group()
    eval_group.add_argument("--no-eval-train", action="store_true")
    eval_group.add_argument(
        "--eval-train",
        action="store_true",
        help="force train-set eval on (e.g. over a preset that disables it)",
    )
    p.add_argument("--checkpoint-dir", type=str, default="")
    p.add_argument(
        "--profile-dir",
        type=str,
        default="",
        help="write a jax.profiler trace of the run here (Perfetto/XProf; "
        "rounds carry StepTraceAnnotation, eval/checkpoint named phases)",
    )
    p.add_argument(
        "--profile-rounds",
        type=str,
        default="",
        metavar="A:B",
        help="restrict the trace to the half-open round window [A, B) "
        "(requires --profile-dir)",
    )
    p.add_argument(
        "--hbm-warn-factor",
        type=float,
        default=2.0,
        help="warn when the measured device memory peak exceeds the "
        "analytic model by this factor (output-only)",
    )
    # observability (docs/OBSERVABILITY.md) — output-only knobs: never part
    # of the run title or config hash, no effect on the trained program
    p.add_argument(
        "--obs-dir",
        type=str,
        default="",
        help="write the schema-versioned per-round event stream (JSONL) here",
    )
    p.add_argument(
        "--obs-stdout",
        action="store_true",
        help="also emit structured events as JSON lines on stdout",
    )
    p.add_argument(
        "--log-file",
        type=str,
        default="",
        help="tee harness log lines to this file (append, flushed per line)",
    )
    # live telemetry (obs/metrics.py / exporter.py / alerts.py) — output-
    # only like the other obs knobs: derived from the event stream on the
    # host, never part of the title/config hash, record bit-identical off
    p.add_argument(
        "--metrics",
        choices=["off", "on"],
        default="off",
        help="fold the event stream into an in-process metrics registry "
        "(counters/gauges/histograms; implied by --metrics-port/--alerts)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="serve Prometheus /metrics + /healthz on this port for the "
        "duration of the run (0 = no exporter)",
    )
    p.add_argument(
        "--alerts",
        type=str,
        default="off",
        help="SLO alert rules evaluated each round: 'default' for the "
        "built-in pack (rollback rate, effective-K floor, stragglers, "
        "rounds/sec floor, HBM watermark, retrace, non-finite loss) or a "
        "path to a JSON rule list; alert events join the stream",
    )
    p.add_argument(
        "--obs-rotate-mb",
        type=float,
        default=0.0,
        help="rotate the --obs-dir event stream once the live file "
        "passes this many MiB (segments keep one seq envelope; 0 = off)",
    )
    p.add_argument(
        "--trace",
        choices=["off", "on"],
        default="off",
        help="distributed tracing: spans mint trace/span ids, nest, and "
        "propagate across serving hops via traceparent headers; assemble "
        "with analysis/trace_view.py (output-only — off is bit-identical)",
    )
    p.add_argument(
        "--quiet",
        action="store_true",
        help="suppress harness log lines on stdout (file tee still written)",
    )
    # client-level forensics (obs/forensics.py) — output-only like the obs
    # knobs: excluded from the title/config hash, record bit-identical off
    p.add_argument(
        "--forensics",
        choices=["off", "top", "full"],
        default="off",
        help="per-client flag provenance: 'top' emits client_flag events "
        "for flagged clients in the round's top-M, 'full' emits the whole "
        "top-M and arms the flight recorder (requires --defense)",
    )
    p.add_argument(
        "--forensics-top",
        type=int,
        default=8,
        help="M: suspicious clients extracted per round (<= K)",
    )
    p.add_argument(
        "--flight-window",
        type=int,
        default=8,
        help="W: rounds of detector carry kept in the flight-recorder ring",
    )
    p.add_argument(
        "--preset",
        choices=presets.names(),
        default=None,
        help="named BASELINE.json config; flags present on the command line "
        "override the preset",
    )
    # multi-host launch, one process per host.  --multihost alone relies on
    # cluster env auto-detection (TPU pods, GKE, Slurm); manual launches add
    # coordinator/num-processes/process-id.  Any of the four triggers
    # jax.distributed.initialize.
    p.add_argument(
        "--multihost",
        action="store_true",
        help="initialize jax.distributed (auto-detects the cluster env when "
        "the explicit flags are omitted)",
    )
    p.add_argument("--coordinator", type=str, default=None, help="host:port")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def _explicit_dests(argv: Sequence[str]) -> set:
    """Dests of the options actually present in ``argv``, detected by
    re-parsing with every default suppressed (argparse leaves an attribute
    unset when its default is SUPPRESS and the flag is absent)."""
    p = build_parser()
    for action in p._actions:
        action.default = argparse.SUPPRESS
    ns, _ = p.parse_known_args(list(argv))
    return set(vars(ns))


def config_from_args(args, argv: Optional[Sequence[str]] = None) -> FedConfig:
    def field_value(dest):
        field, conv = ARG_TO_FIELD[dest]
        v = getattr(args, dest)
        return field, (conv(v) if conv else v)

    if args.preset is not None:
        from . import presets

        if argv is None:
            # explicitness must be derived from the SAME argv that produced
            # ``args`` — guessing from sys.argv desyncs for programmatic
            # callers and silently clobbers preset fields
            raise ValueError(
                "config_from_args(args, argv) requires the original argv "
                "when --preset is used"
            )
        given = _explicit_dests(argv)
        overrides = {}
        for dest in ARG_TO_FIELD:
            if dest in given:
                field, value = field_value(dest)
                overrides[field] = value
        cfg = presets.get(args.preset, **overrides)
    else:
        kwargs = {}
        for dest in ARG_TO_FIELD:
            if dest == "eval_train":  # derived from no_eval_train here
                continue
            field, value = field_value(dest)
            kwargs[field] = value
        cfg = FedConfig(**kwargs)
    # reference --K/--B override: honestSize = K - B (:531-533); with K alone
    # the total node count becomes K, retaining the current Byzantine count
    if args.K is not None and args.B is not None:
        cfg.honest_size = args.K - args.B
        cfg.byz_size = args.B
    elif args.K is not None:
        cfg.honest_size = args.K - cfg.byz_size
    elif args.B is not None:
        cfg.honest_size = cfg.node_size - args.B
        cfg.byz_size = args.B
    return cfg


def serve_main(argv: Sequence[str]):
    """``python -m byzantine_aircomp_tpu serve``: boot the multi-tenant
    experiment server (docs/SERVING.md) and block until interrupted."""
    import time

    p = argparse.ArgumentParser("byzantine_aircomp_tpu serve")
    p.add_argument("--port", type=int, default=0,
                   help="HTTP port for the run API + /metrics + /healthz "
                        "(0 = OS-assigned ephemeral, printed at boot)")
    p.add_argument("--host", type=str, default="0.0.0.0")
    p.add_argument("--obs-root", type=str, default="./serve_runs",
                   help="root of the per-run output subtrees "
                        "(<obs-root>/<run_id>/ holds each tenant's events, "
                        "checkpoints, caches)")
    p.add_argument("--backend", choices=["vmap", "map"], default="vmap",
                   help="experiment-axis batching backend (map = "
                        "sequential lax.map escape hatch)")
    p.add_argument("--batch-window", type=float, default=0.25,
                   help="seconds to wait after a submission before "
                        "compiling, so concurrent tenants coalesce into "
                        "one batch (one XLA lowering)")
    p.add_argument("--queue-cap", type=int, default=0,
                   help="max queued runs before POST /runs answers 429 "
                        "(0 = unbounded; docs/RUNBOOK.md)")
    p.add_argument("--run-retries", type=int, default=1,
                   help="watchdog requeues per wedged run before it is "
                        "failed for good")
    p.add_argument("--run-backoff", type=float, default=2.0,
                   help="base seconds of the watchdog's exponential "
                        "requeue backoff (delay = backoff * 2^(retry-1))")
    p.add_argument("--wedge-secs", type=float, default=0.0,
                   help="seconds without a completed round before a "
                        "running run counts as wedged (0 = watchdog off); "
                        "/healthz reports 503 while any run is wedged")
    p.add_argument("--auth-token", type=str, default=None,
                   help="bearer token required on the mutating endpoints "
                        "(POST /runs, /cancel, /knobs return 401 without "
                        "'Authorization: Bearer <token>'); /metrics and "
                        "/healthz stay open for scrapes")
    args = p.parse_args(list(argv))
    from .serve.server import ExperimentServer

    server = ExperimentServer(
        args.obs_root,
        port=args.port,
        host=args.host,
        backend=args.backend,
        batch_window=args.batch_window,
        queue_cap=args.queue_cap,
        run_retries=args.run_retries,
        run_backoff=args.run_backoff,
        wedge_secs=args.wedge_secs,
        auth_token=args.auth_token,
    ).start()
    print(f"experiment server on {args.host}:{server.port} "
          f"(obs root: {args.obs_root})", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()


def main(argv: Optional[Sequence[str]] = None):
    import sys

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "root":
        from .serve.root import main as root_main

        return root_main(list(argv[1:]))
    if argv and argv[0] == "edge":
        from .serve.edge import main as edge_main

        return edge_main(list(argv[1:]))
    if argv and argv[0] == "tune":
        from .tune.tuner import main as tune_main

        return tune_main(list(argv[1:]))
    args = build_parser().parse_args(argv)
    if (
        args.multihost
        or args.coordinator is not None
        or args.num_processes is not None
        or args.process_id is not None
    ):
        from .parallel import multihost

        multihost.initialize(
            coordinator=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        print(multihost.process_summary())
    cfg = config_from_args(args, argv)
    if args.backend == "ref":
        from .backends.ref_trainer import run_ref

        return run_ref(cfg)
    from .fed.harness import run

    return run(cfg)


if __name__ == "__main__":
    main()
