"""CLI mirroring the reference's argparse surface plus ``--backend``.

Reference flags (``/root/reference/MNIST_Air_weight.py:16-28``): ``--opt``,
``--agg``, ``--attack``, ``--var``, ``--inherit``, ``--mark``, ``--use-gpu``,
``--K``, ``--B``.  All are accepted here with the same names and defaults;
``--use-gpu`` is accepted-and-ignored (device selection is JAX's), and
``--inherit`` now actually works (resume from checkpoint) instead of being the
reference's dead flag (``:22,:500``).  New flags: ``--backend {jax,ref}``
(north-star gate; ``ref`` = NumPy oracle path), ``--dataset``, ``--model``,
``--rounds``, ``--interval``, ``--batch-size``, ``--gamma``, ``--seed``.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from .fed.config import FedConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("byzantine_aircomp_tpu")
    # reference surface
    p.add_argument("--opt", type=str, default="SGD", help="optimizer")
    p.add_argument("--agg", type=str, default="gm", help="aggregator name")
    p.add_argument("--attack", type=str, default=None, help="attack name")
    p.add_argument("--var", type=float, default=None, help="channel noise variance")
    p.add_argument("--inherit", action="store_true", help="resume from checkpoint")
    p.add_argument("--mark", type=str, default="", help="mark on title")
    p.add_argument(
        "--use-gpu",
        type=str,
        default="true",
        help="accepted for reference-CLI compatibility; device choice is JAX's",
    )
    p.add_argument("--K", type=int, default=None, help="number of total devices")
    p.add_argument("--B", type=int, default=None, help="number of Byzantine devices")
    # framework surface
    p.add_argument("--backend", choices=["jax", "ref"], default="jax")
    p.add_argument(
        "--sharding",
        choices=["auto", "on", "off"],
        default="auto",
        help="shard clients over the device mesh (auto: when >1 device)",
    )
    p.add_argument(
        "--model-parallel",
        type=int,
        default=None,
        help="devices along the model (d) mesh axis",
    )
    p.add_argument(
        "--agg-impl",
        choices=["xla", "pallas"],
        default="xla",
        help="Weiszfeld step implementation (pallas = fused TPU kernel)",
    )
    p.add_argument("--dataset", type=str, default="mnist")
    p.add_argument("--model", type=str, default="MLP")
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--interval", type=int, default=10, help="displayInterval")
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--gamma", type=float, default=1e-2)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=2021)
    p.add_argument("--cache-dir", type=str, default="")
    p.add_argument("--no-eval-train", action="store_true")
    p.add_argument("--checkpoint-dir", type=str, default="")
    p.add_argument(
        "--profile-dir",
        type=str,
        default="",
        help="write a jax.profiler trace of the run here",
    )
    return p


def config_from_args(args) -> FedConfig:
    cfg = FedConfig(
        opt=args.opt,
        agg=args.agg,
        attack=args.attack,
        noise_var=args.var,
        checkpoint_dir=args.checkpoint_dir,
        inherit=args.inherit,
        sharded={"auto": None, "on": True, "off": False}[args.sharding],
        agg_impl=args.agg_impl,
        profile_dir=args.profile_dir,
        model_parallel=args.model_parallel,
        rounds=args.rounds,
        display_interval=args.interval,
        batch_size=args.batch_size,
        gamma=args.gamma,
        weight_decay=args.weight_decay,
        seed=args.seed,
        model=args.model,
        dataset=args.dataset,
        mark=args.mark,
        cache_dir=args.cache_dir,
        eval_train=not args.no_eval_train,
    )
    # reference --K/--B override: honestSize = K - B (:531-533)
    if args.K is not None and args.B is not None:
        cfg.honest_size = args.K - args.B
        cfg.byz_size = args.B
    elif args.K is not None:
        cfg.honest_size = args.K
    return cfg


def main(argv: Optional[Sequence[str]] = None):
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.backend == "ref":
        from .backends.ref_trainer import run_ref

        return run_ref(cfg)
    from .fed.harness import run

    return run(cfg)


if __name__ == "__main__":
    main()
