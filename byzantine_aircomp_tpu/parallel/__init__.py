from . import collective, mesh, multihost, popmesh  # noqa: F401
from .popmesh import PopShardedFedTrainer  # noqa: F401
from .sharded import ShardedFedTrainer  # noqa: F401
