from . import collective, mesh  # noqa: F401
from .sharded import ShardedFedTrainer  # noqa: F401
