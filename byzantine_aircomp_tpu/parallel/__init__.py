from . import collective, mesh, multihost  # noqa: F401
from .sharded import ShardedFedTrainer  # noqa: F401
