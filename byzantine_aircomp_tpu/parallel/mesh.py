"""Device mesh construction.

The scaling axes of this workload (SURVEY.md §5.7-5.8): **clients** (K — the
reference's sequential Python loop, here a sharded array axis) and **model**
(d — the flat parameter dimension, sharded for large models so the [K, d]
client-weight stack fits in HBM; K=1000 x ResNet-18 is ~44 GB in fp32).
The reference's only parallelism was intra-batch ``nn.DataParallel``
(``MNIST_Air_weight.py:439-440``); there is no NCCL/MPI to mirror — XLA
collectives over ICI/DCN are the communication backend.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

CLIENT_AXIS = "clients"
MODEL_AXIS = "model"


def factor_devices(n: int, model_parallel: Optional[int] = None) -> Tuple[int, int]:
    """Split n devices into (clients, model) axis sizes.

    Defaults to all-client parallelism (model axis 1) — the right call for
    the paper-scale models where d is small and K is the big axis.  An
    explicit ``model_parallel`` must divide n.
    """
    if model_parallel is None:
        return n, 1
    if n % model_parallel:
        raise ValueError(f"model_parallel={model_parallel} must divide {n} devices")
    return n // model_parallel, model_parallel


def make_mesh(
    devices: Optional[Sequence] = None, model_parallel: Optional[int] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n_c, n_m = factor_devices(len(devices), model_parallel)
    arr = np.asarray(devices).reshape(n_c, n_m)
    return Mesh(arr, (CLIENT_AXIS, MODEL_AXIS))


def stack_spec() -> PartitionSpec:
    """[K, d] client-weight stack: K over clients, d over model."""
    return PartitionSpec(CLIENT_AXIS, MODEL_AXIS)


def params_spec() -> PartitionSpec:
    """[d] flat params: sharded over the model axis (replicated when the
    model axis has size 1)."""
    return PartitionSpec(MODEL_AXIS)


def client_spec() -> PartitionSpec:
    """Per-client vectors/batches: leading K axis over clients."""
    return PartitionSpec(CLIENT_AXIS)


def replicated() -> PartitionSpec:
    return PartitionSpec()


def sharding(mesh: Mesh, spec: PartitionSpec) -> NamedSharding:
    return NamedSharding(mesh, spec)
