"""Multi-chip federated trainer.

``ShardedFedTrainer`` reuses the base trainer's pure round function unchanged
and turns it into an SPMD program over a (clients, model) mesh:

* the [K, d] gradient/weight stacks carry ``with_sharding_constraint``
  (K over the ``clients`` axis, d over ``model``), so per-client local steps
  run fully parallel across devices;
* the aggregated flat params are constrained to the ``model`` axis
  (replicated when model-parallel size is 1);
* XLA derives the collectives — the aggregators' sums become psums over ICI,
  exactly the structure made explicit in ``.collective`` (the two paths are
  tested against each other on the CPU mesh).

This is the TPU answer to the reference's sequential K-client loop
(``/root/reference/MNIST_Air_weight.py:291``): the reference's wall-clock
scales O(K); here K is a mesh axis.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from ..data import datasets as data_lib
from ..fed.config import FedConfig
from ..fed.train import FedTrainer
from . import mesh as mesh_lib


class ShardedFedTrainer(FedTrainer):
    def __init__(
        self,
        cfg: FedConfig,
        dataset: Optional[data_lib.Dataset] = None,
        mesh: Optional[Mesh] = None,
    ):
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        n_clients_axis = self.mesh.shape[mesh_lib.CLIENT_AXIS]
        if cfg.node_size % n_clients_axis:
            raise ValueError(
                f"node_size {cfg.node_size} must be divisible by the "
                f"'{mesh_lib.CLIENT_AXIS}' mesh axis ({n_clients_axis})"
            )
        if cfg.participation < 1.0:
            m = sum(cfg.participant_counts())
            if m % n_clients_axis:
                raise ValueError(
                    f"participation {cfg.participation} gives a {m}-row "
                    f"stack, not divisible by the '{mesh_lib.CLIENT_AXIS}' "
                    f"mesh axis ({n_clients_axis}); pick a fraction whose "
                    f"participant count divides the mesh"
                )
        if cfg.bucket_size > 1:
            n_buckets = sum(cfg.participant_counts()) // cfg.bucket_size
            if n_buckets % n_clients_axis:
                raise ValueError(
                    f"bucket_size {cfg.bucket_size} leaves {n_buckets} "
                    f"buckets, not divisible by the "
                    f"'{mesh_lib.CLIENT_AXIS}' mesh axis ({n_clients_axis})"
                )
        if cfg.cohort_size > 0 and cfg.cohort_size % n_clients_axis:
            # streamed rounds hand [cohort, d] chunks to the shard-mapped
            # client step, so the chunk (not K) is what the axis must divide
            raise ValueError(
                f"cohort_size {cfg.cohort_size} is not divisible by the "
                f"'{mesh_lib.CLIENT_AXIS}' mesh axis ({n_clients_axis}); "
                f"streamed chunks are sharded over that axis"
            )
        super().__init__(cfg, dataset=dataset)

        # GSPMD has no partitioning rule for pallas_call: with the [K, d]
        # stack sharded over 'clients', a pallas Weiszfeld step would be
        # compiled as an all-gather of the full stack onto every device
        # inside the while_loop.  Force the XLA impl, whose sums partition
        # into per-shard psums.  (Set before the round fn's first trace.)
        if self._agg_impl == "pallas" and self.mesh.size > 1:
            self._agg_impl = "xla"
        # Same constraint for the fused sort-family epilogue: its pallas
        # realization is a pallas_call over the client-sharded stack, and
        # even the XLA selection realization would interleave the deferred
        # in-aggregator channel apply with GSPMD resharding decisions we
        # have only validated single-device.  Multi-device meshes keep the
        # standalone channel pass + sort path (whose psum partitioning is
        # the tested layout); set before the round fn's first trace.
        if self.mesh.size > 1:
            self._fused_epilogue = False
        # Krum on a client-sharded stack: route through the explicit
        # ppermute ring (collective.ring_krum*) instead of letting GSPMD
        # partition the K x K Gram matmul, which can lower to an all-gather
        # of the whole [K, d] stack onto every device at ResNet scale.
        # Routing keys off the RESOLVED function (the registry owns name
        # aliasing), so new aliases cannot silently miss the ring path.
        if n_clients_axis > 1:
            from functools import partial

            from ..ops import aggregators as agg_lib
            from . import collective

            if self.agg_fn is agg_lib.krum:
                self.agg_fn = partial(collective.ring_krum, self.mesh)
            elif self.agg_fn is agg_lib.multi_krum:
                self.agg_fn = partial(collective.ring_multi_krum, self.mesh)
            elif self.agg_fn is agg_lib.bulyan:
                self.agg_fn = partial(collective.ring_bulyan, self.mesh)
        repl = mesh_lib.sharding(self.mesh, mesh_lib.replicated())
        p_shard = mesh_lib.sharding(self.mesh, mesh_lib.params_spec())
        self.x_train = jax.device_put(self.x_train, repl)
        self.y_train = jax.device_put(self.y_train, repl)
        self.flat_params = jax.device_put(self.flat_params, p_shard)
        if cfg.client_momentum:
            # the [K, d] momentum buffer follows the client-stack layout
            self.client_m = jax.device_put(
                self.client_m,
                mesh_lib.sharding(self.mesh, mesh_lib.stack_spec()),
            )
        if self.fault is not None:
            # fault carry: the [K, d] stale-update buffer follows the
            # client-stack layout; the [K] Gilbert-Elliott state replicates
            stale, ge_bad = self.fault_state
            if not isinstance(stale, tuple):
                stale = jax.device_put(
                    stale, mesh_lib.sharding(self.mesh, mesh_lib.stack_spec())
                )
            if not isinstance(ge_bad, tuple):
                ge_bad = jax.device_put(ge_bad, repl)
            self.fault_state = (stale, ge_bad)
        if self.defense is not None:
            # defense carry: [K] detector baselines and the scalar policy
            # counters all replicate (tiny; the scored stack is already
            # resident per-shard, and the lax.switch rung must agree on
            # every device)
            self.defense_state = jax.tree.map(
                lambda leaf: jax.device_put(leaf, repl), self.defense_state
            )
        if not isinstance(self.attack_iter, tuple):
            self.attack_iter = jax.device_put(self.attack_iter, repl)
        if cfg.service == "on":
            # service carry: [population] availability bools and the widen
            # scalar replicate (the drawn [K] rows are gathered in-program,
            # and every device must agree on the draw)
            self.service_state = jax.tree.map(
                lambda leaf: jax.device_put(leaf, repl), self.service_state
            )
            self._pop_shard = jax.device_put(self._pop_shard, repl)
        # server-opt state: [d]-shaped leaves follow the params layout,
        # scalars (e.g. adam's count) replicate
        self.server_opt_state = jax.tree.map(
            lambda leaf: jax.device_put(
                leaf, p_shard if getattr(leaf, "ndim", 0) == 1 else repl
            ),
            self.server_opt_state,
        )

    def _jit_compiler_options(self):
        """On the multi-device CPU mesh (CI / dryrun), device "threads"
        oversubscribe the host core(s): during a heavy sharded program the
        participants of a collective can reach the rendezvous more than
        XLA's default 40s apart, and rendezvous.cc then ABORTS the whole
        process ("Termination timeout ... exceeded").  Arrival skew on an
        oversubscribed host is not a hang — give the rendezvous room.
        Real accelerator backends keep their defaults."""
        if jax.default_backend() != "cpu" or self.mesh.size <= 1:
            return None
        return {
            "xla_cpu_collective_call_warn_stuck_seconds": 300,
            "xla_cpu_collective_call_terminate_timeout_seconds": 1200,
        }

    # the two vma (varying-manual-axes) moves every shard_mapped client
    # step needs:
    #
    # * ``pcast(fp, to='varying')`` BEFORE differentiating — jax.grad
    #   w.r.t. an INVARYING (replicated, in_spec P()) shard_map input
    #   auto-psums the cotangent across devices "for" the caller, which
    #   here would silently turn every client's gradient into the
    #   cross-device SUM of gradients (caught by the equality gates: the
    #   stack degenerated to one device's rows tiled mesh-wide);
    # * ``psum(out, 'model') / axis_size`` AFTER — the client step is
    #   replicated over the model axis (each model-group device holds the
    #   same clients), and averaging the bit-identical copies (exact for
    #   power-of-two axis sizes) demotes the result back to INVARYING over
    #   'model' so ``out_specs=P('clients')`` typechecks; there is no
    #   free varying->invarying cast in jax's vma system.
    def _shard_mapped_client_step(self, per_client_fn, n_outputs, *client_args):
        """Run a vmapped per-client function under an EXPLICIT shard_map
        over 'clients', with the replicated flat params as first operand.

        Left to GSPMD, a vmapped conv's cost model can repartition the
        per-client forward/backward to CHANNEL-parallel — all-gathering the
        client-sharded [m*B, H, W, C] batch and every conv activation on
        every local step (observed on the 8-device CPU mesh, where the
        resulting in-process AllGather can also blow XLA's collective
        rendezvous timeout and abort the process).  shard_map pins the
        intended layout: each device runs its own clients' full local step
        (params replicated FSDP-style — one [d] all-gather over 'model' at
        entry when model_parallel > 1), and every [m, ...] output comes out
        client-sharded; the aggregation stages then reshard d over 'model'
        via the existing constraint.

        ``client_args[0]`` is flat_params (in_spec P(), replicated); the
        rest are [m, ...] arrays (in_spec P('clients'))."""
        from jax.sharding import PartitionSpec as P

        axes = (mesh_lib.CLIENT_AXIS, mesh_lib.MODEL_AXIS)
        in_axes = (None,) + (0,) * (len(client_args) - 1)

        def local(fp, *rest):
            fp = jax.lax.pcast(fp, axes, to="varying")
            out = jax.vmap(per_client_fn, in_axes=in_axes)(fp, *rest)
            return jax.tree.map(
                lambda g: jax.lax.psum(g, mesh_lib.MODEL_AXIS)
                / jax.lax.axis_size(mesh_lib.MODEL_AXIS),
                out,
            )

        out_spec = P(mesh_lib.CLIENT_AXIS)
        return jax.shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(),) + (P(mesh_lib.CLIENT_AXIS),)
            * (len(client_args) - 1),
            out_specs=out_spec if n_outputs == 1 else (out_spec,) * n_outputs,
        )(*client_args)

    def _client_stack(self, flat_params, x, y, part_mask):
        return self._shard_mapped_client_step(
            self._per_client_weights, 1, flat_params, x, y, part_mask
        )

    def _client_stack_momentum(self, flat_params, x, y, part_mask, m_prev):
        return self._shard_mapped_client_step(
            self._per_client_momentum_step, 2,
            flat_params, x, y, part_mask, m_prev,
        )

    def _constrain_stack(self, w_stack):
        return jax.lax.with_sharding_constraint(
            w_stack, mesh_lib.sharding(self.mesh, mesh_lib.stack_spec())
        )

    def _constrain_params(self, flat_params):
        return jax.lax.with_sharding_constraint(
            flat_params, mesh_lib.sharding(self.mesh, mesh_lib.params_spec())
        )
