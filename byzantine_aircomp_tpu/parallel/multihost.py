"""Multi-host (multi-process) execution over DCN.

The reference is strictly single-process — there is no NCCL/MPI/process-group
code anywhere (SURVEY.md §2 preamble; ``torch.distributed`` is never
imported).  This framework's distributed communication backend is JAX's:
``jax.distributed`` brings up the cross-host runtime, every process
contributes its local chips, and the same SPMD round program the single-host
path jits is laid out over the GLOBAL device mesh — XLA routes the
aggregation collectives over ICI within a slice and DCN across hosts.
``ShardedFedTrainer`` needs no changes: both processes trace the identical
program against the global mesh and each executes its addressable shard
(validated by the two-process CPU test in test_multihost.py).

Mesh layout guidance: keep the ``model`` axis within a host/slice (ICI) and
let the ``clients`` axis span hosts — client shards only meet at the
aggregation psum, one [d]-sized reduction per round, which is the only
traffic that rides DCN.

Usage (one call per process, before any other JAX API touches devices)::

    from byzantine_aircomp_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:8476", num_processes=4,
                         process_id=rank)

or rely on the standard cluster env detection (TPU pods, GKE) by calling
``initialize()`` with no arguments.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    max_retries: int = 3,
    backoff_s: float = 1.0,
    timeout_s: Optional[float] = None,
) -> None:
    """Bring up the cross-host runtime (idempotent).

    With no arguments JAX auto-detects cluster environments (TPU pods, GKE,
    Slurm); explicit values cover manual launches.  After this returns,
    ``jax.devices()`` is the GLOBAL device list and meshes built from it span
    all hosts.

    A pod bring-up is the single flakiest moment of a multi-host run — the
    coordinator may simply not be listening yet when a worker process comes
    up.  Connection attempts are therefore bounded-retried with exponential
    backoff (``max_retries`` retries, ``backoff_s * 2**attempt`` sleeps,
    ``timeout_s`` per-attempt connect timeout).  On exhaustion the LAST error
    propagates and ``is_initialized()`` stays False — a later call may retry
    cleanly rather than seeing a half-up state.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    if timeout_s is not None:
        # jax's per-attempt connect timeout knob (seconds)
        kwargs["initialization_timeout"] = int(timeout_s)
    last_err: Optional[BaseException] = None
    for attempt in range(max_retries + 1):
        if attempt:
            time.sleep(backoff_s * 2 ** (attempt - 1))
        try:
            jax.distributed.initialize(**kwargs)
            _initialized = True
            return
        except (RuntimeError, ConnectionError, TimeoutError, OSError) as e:
            last_err = e
    raise RuntimeError(
        f"jax.distributed.initialize failed after {max_retries + 1} attempts"
    ) from last_err


def is_initialized() -> bool:
    return _initialized


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_summary() -> str:
    return (
        f"process {jax.process_index()}/{jax.process_count()}: "
        f"{len(jax.local_devices())} local of {len(jax.devices())} global "
        f"devices ({jax.default_backend()})"
    )
