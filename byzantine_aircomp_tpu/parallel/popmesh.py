"""Population-axis mesh engine for streamed service rounds (ISSUE 13).

``ops/shardctx.py`` defines the merge algebra and the two off-mesh
engines; this module supplies the third: :class:`MeshShardCtx` runs the
trainer's streamed chunk region inside ``shard_map`` over a 1-D
population mesh, each device scanning its own cohort-chunk range, with
the per-shard partial carries merged by collectives —

* integer/bool ``"sum"`` leaves by ``lax.psum`` (addition is associative
  and commutative mod 2^32, so the collective is EXACTLY the sequential
  fold: the median/trimmed-mean bisection's per-step rank counts, the
  quantile-sketch histograms, finite/flag counts and the packed
  sign-vote plane sums are bit-equal under any placement);
* every other tagged leaf (float partial sums, min/max key ranges,
  ``"stack"`` detector rows) by one ``lax.all_gather`` over the mesh
  axis — stacked in shard order — followed by the SAME canonical left
  fold the sequential engine uses (``shardctx.fold_leaves``), so the
  mesh result is bit-identical to ``SeqShardCtx`` at the same
  ``pop_shards`` by construction, not by accident of rounding.

The merged values are identical on every device, so everything after a
merge (the key-bisection guess updates, the gm2 Weiszfeld ``while_loop``
trip counts, the defense policy rung, the ``lax.switch`` ladder branch)
replicates deterministically and subsequent collectives stay aligned
across the mesh — no divergent control flow, one lowering per host.

``shard_map`` notes for this jaxlib: ``check_rep=False`` is required
(the replication-inference pass cannot prove ``all_gather``-merged
outputs replicated), and replicated ``in_specs=P()`` inputs are passed
whole to every device — exactly the contract the trainer's region body
expects (chunk ranges are selected by ``axis_index``, not by array
sharding, because the chunk scan GATHERS from the replicated train set
and index table rather than owning a slice of them).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fed.train import FedTrainer
from ..ops import aggregators as agg_lib
from ..ops import shardctx

POP_AXIS = "pop"


def make_pop_mesh(n_shards: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D population mesh over the first ``n_shards`` devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards < 2:
        raise ValueError(f"a population mesh wants >= 2 shards, got {n_shards}")
    if len(devices) < n_shards:
        raise ValueError(
            f"pop_shards={n_shards} needs {n_shards} devices, have "
            f"{len(devices)} (CI uses --xla_force_host_platform_device_count)"
        )
    return Mesh(np.asarray(devices[:n_shards]), (POP_AXIS,))


def _is_int_leaf(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer) or jnp.issubdtype(
        jnp.asarray(x).dtype, jnp.bool_
    )


class MeshShardCtx:
    """Collective pop-shard engine; lives inside a ``shard_map`` body."""

    def __init__(self, n_shards: int, axis: str = POP_AXIS):
        if n_shards < 2:
            raise ValueError("MeshShardCtx wants n_shards >= 2; use LOCAL")
        self.n_shards = n_shards
        self.axis = axis

    def varying(self, x):
        """Invarying -> device-varying promotion hook; identity on this
        jaxlib (grads w.r.t. replicated shard_map inputs are per-device
        local — no auto-psum — so no pcast is needed or available)."""
        return x

    def _merge_leaf(self, tag, part):
        if tag == "sum" and _is_int_leaf(part):
            return jax.lax.psum(part, self.axis)
        # float sums / min / max / stack: one shard-ordered all_gather,
        # then the sequential engine's own fold for bit-equality with it
        stacked = jax.lax.all_gather(part, self.axis)
        return shardctx.fold_leaves(stacked, tag, self.n_shards)

    def scan_idx_merge(self, n_chunks: int, body, init, spec):
        S = self.n_shards
        if n_chunks % S:
            raise ValueError(
                f"n_chunks {n_chunks} not divisible by pop_shards {S}"
            )
        cpp = n_chunks // S
        p = jax.lax.axis_index(self.axis)
        idxs = p * cpp + jnp.arange(cpp, dtype=jnp.int32)

        def step(carry, c_idx):
            return body(carry, c_idx), None

        part, _ = jax.lax.scan(step, init, idxs)
        return shardctx.merge_spec_tree(spec, part, S, self._merge_leaf)

    def scan_merge(self, rebuild, n_chunks: int, body, init, spec):
        return self.scan_idx_merge(
            n_chunks, lambda carry, c: body(carry, rebuild(c), c), init, spec
        )


def sharded_packed_vote_counts(
    mesh: Mesh, words: jnp.ndarray, d: int
) -> jnp.ndarray:
    """Packed sign-vote reduce with the [K, W] word rows sharded over the
    population mesh axis: per-shard bit-plane partial counts, merged by
    one ``psum``.  Integer counts, so bit-identical to the single-device
    ``ops.aggregators.packed_sign_votes`` for any row placement — the
    property the one-bit OTA channel needs to span hosts."""
    k = words.shape[0]
    if k % mesh.size:
        raise ValueError(
            f"K={k} word rows must divide over the {mesh.size}-way mesh"
        )

    def body(w_local):
        return jax.lax.psum(
            agg_lib._packed_vote_counts_xla(w_local, d), POP_AXIS
        )

    fn = shard_map(
        body, mesh=mesh, in_specs=(P(POP_AXIS),), out_specs=P(),
        check_rep=False,
    )
    return fn(words)


class PopShardedFedTrainer(FedTrainer):
    """FedTrainer whose streamed chunk region runs one ``shard_map``
    program over a population mesh (``--pop-shards`` devices).

    Everything outside the region — the service draw/churn, the round key
    splits, the server update, eval — is replicated: the per-round O(K)
    row masks and O(K*batch) index table cost nothing against the
    streamed peak, and replicating them keeps the straggler deadline mask
    identical on every host (the mesh-wide deadline min is satisfied by
    construction rather than negotiated).  Per-device HBM holds one
    cohort chunk's rebuild plus the replicated carry — ``obs/hbm.py``
    models the per-host budget.
    """

    def __init__(self, cfg, dataset=None, devices: Optional[Sequence] = None):
        if cfg.pop_shards < 2:
            raise ValueError(
                "PopShardedFedTrainer wants pop_shards >= 2 (use FedTrainer "
                "for the single-scan and sequential engines)"
            )
        self.pop_mesh = make_pop_mesh(cfg.pop_shards, devices)
        super().__init__(cfg, dataset=dataset)
        # replicated placement for the round inputs the region closes
        # over / receives: identical buffers on every mesh device, so the
        # first round's implicit transfers happen once, not per call
        repl = NamedSharding(self.pop_mesh, P())
        put = lambda t: jax.tree.map(
            lambda x: jax.device_put(x, repl) if hasattr(x, "dtype") else x, t
        )
        self.x_train = put(self.x_train)
        self.y_train = put(self.y_train)
        self.flat_params = put(self.flat_params)
        self.server_opt_state = put(self.server_opt_state)
        self.client_m = put(self.client_m)
        self.fault_state = put(self.fault_state)
        self.defense_state = put(self.defense_state)
        self.service_state = put(self.service_state)
        self.attack_iter = put(self.attack_iter)
        self._base_key = put(self._base_key)

    def _round_donate_argnums(self):
        # donating the replicated round carry through the shard_map
        # program is UNSOUND on this jaxlib's CPU client: the donated
        # input's per-device buffers are released even though the round
        # output aliases them, so a live output array's contents rot as
        # soon as later allocations reuse the memory (observed as
        # bit-identical loss trajectories with rotten final params, and
        # as phantom mid-run loss spikes).  TPU/GPU clients keep the full
        # donation set — the fixed per-host HBM budget depends on it.
        import jax

        if jax.default_backend() == "cpu":
            return ()
        return super()._round_donate_argnums()

    def _make_pop_ctx(self):
        return MeshShardCtx(self.cfg.pop_shards)

    def _pop_shard_region(self, fn, region_in):
        ctx = self._pop_ctx
        wrapped = shard_map(
            lambda rin: fn(ctx, rin),
            mesh=self.pop_mesh,
            in_specs=(P(),),
            out_specs=P(),
            check_rep=False,
        )
        return wrapped(region_in)
