"""Explicit shard_map collectives for the AirComp primitives.

The reference's over-the-air sum ``OMA2`` (``MNIST_Air_weight.py:408-414``)
*is* a psum with noise: each client transmits simultaneously and the receiver
observes the superposition.  On a TPU mesh this maps 1:1 onto
``jax.lax.psum`` over the client axis riding ICI — these shard_map kernels
make that mapping explicit (the pjit-constraint path in ``.sharded`` lets
XLA derive the same collectives automatically; both are provided, tested
against each other).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import channel
from .mesh import CLIENT_AXIS, MODEL_AXIS


def air_sum(
    mesh: Mesh,
    key: jax.Array,
    message: jnp.ndarray,
    p_max: float = 10.0,
    noise_var: Optional[float] = None,
    threshold=1.0,
) -> jnp.ndarray:
    """Sharded OMA2: [K, d] sharded over (clients, model) -> [d] sharded over
    model, one psum over the client axis.

    Numerically equivalent to :func:`..ops.channel.oma2` for the same key and
    invariant to the mesh layout: the per-client fades and the [d] receiver
    noise are drawn OUTSIDE the shard_map with oma2's exact key discipline
    (``key_h, key_n = split(key)``) and enter the kernel pre-sharded.  Inside,
    the full-row power (mean over d) needs one psum over the model axis and
    the over-the-air superposition is one psum over the client axis — the
    physics (one receiver, K simultaneous transmitters) mapped 1:1 onto ICI.
    Tested against ``oma2`` in test_sharding.py.
    """
    _, d_total = message.shape
    key_h, key_n = jax.random.split(key)
    h_r, h_i = channel.rayleigh_fade(key_h, message.shape[0])  # [K]
    if noise_var is not None:
        scale = math.sqrt(noise_var / 2.0)
        noise = scale * jax.random.normal(key_n, (d_total,), jnp.float32)
    else:
        noise = jnp.zeros((d_total,), jnp.float32)

    def local(msg, h_r, h_i, noise):
        h_sq = h_r**2 + h_i**2
        # mean(m^2) over the FULL row requires a psum over the model axis
        row_sumsq = jax.lax.psum(jnp.sum(msg**2, axis=1), MODEL_AXIS)
        p_upper = jnp.maximum(row_sumsq / d_total / h_sq, threshold)
        gain = jnp.sqrt(p_max / p_upper)
        partial = jnp.sum(msg * gain[:, None], axis=0)  # local clients
        total = jax.lax.psum(partial, CLIENT_AXIS)  # the over-the-air sum
        return total + noise

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(CLIENT_AXIS, MODEL_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS), P(MODEL_AXIS)),
        out_specs=P(MODEL_AXIS),
    )(message, h_r, h_i, noise)


def sharded_mean(mesh: Mesh, w_stack: jnp.ndarray) -> jnp.ndarray:
    """Column mean of the sharded [K, d] stack via one psum over clients."""
    k_total = w_stack.shape[0]

    def local(w):
        return jax.lax.psum(jnp.sum(w, axis=0), CLIENT_AXIS) / k_total

    return jax.shard_map(
        local, mesh=mesh, in_specs=P(CLIENT_AXIS, MODEL_AXIS), out_specs=P(MODEL_AXIS)
    )(w_stack)


def sharded_weiszfeld_step(
    mesh: Mesh, w_stack: jnp.ndarray, guess: jnp.ndarray, clamp: float = 1e-4
):
    """One ideal Weiszfeld update on the sharded stack.

    Distances need a psum over the model axis (each shard sees part of each
    row); the weighted sums need a psum over the client axis.  Two ICI
    collectives per step, everything else local.
    """

    def local(w, g):
        d_part = jnp.sum((w - g[None, :]) ** 2, axis=1)
        dist = jnp.sqrt(jax.lax.psum(d_part, MODEL_AXIS))
        dist = jnp.maximum(clamp, dist)
        inv = 1.0 / dist
        num = jax.lax.psum(jnp.sum(w * inv[:, None], axis=0), CLIENT_AXIS)
        den = jax.lax.psum(jnp.sum(inv), CLIENT_AXIS)
        return num / den

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(CLIENT_AXIS, MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=P(MODEL_AXIS),
    )(w_stack, guess)
