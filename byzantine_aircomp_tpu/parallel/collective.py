"""Explicit shard_map collectives for the AirComp primitives.

The reference's over-the-air sum ``OMA2`` (``MNIST_Air_weight.py:408-414``)
*is* a psum with noise: each client transmits simultaneously and the receiver
observes the superposition.  On a TPU mesh this maps 1:1 onto
``jax.lax.psum`` over the client axis riding ICI — these shard_map kernels
make that mapping explicit (the pjit-constraint path in ``.sharded`` lets
XLA derive the same collectives automatically; both are provided, tested
against each other).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import aggregators as agg_ops
from ..ops import channel
from .mesh import CLIENT_AXIS, MODEL_AXIS


def air_sum(
    mesh: Mesh,
    key: jax.Array,
    message: jnp.ndarray,
    p_max: float = 10.0,
    noise_var: Optional[float] = None,
    threshold=1.0,
) -> jnp.ndarray:
    """Sharded OMA2: [K, d] sharded over (clients, model) -> [d] sharded over
    model, one psum over the client axis.

    Numerically equivalent to :func:`..ops.channel.oma2` for the same key and
    invariant to the mesh layout: the per-client fades and the [d] receiver
    noise are drawn OUTSIDE the shard_map with oma2's exact key discipline
    (``key_h, key_n = split(key)``) and enter the kernel pre-sharded.  Inside,
    the full-row power (mean over d) needs one psum over the model axis and
    the over-the-air superposition is one psum over the client axis — the
    physics (one receiver, K simultaneous transmitters) mapped 1:1 onto ICI.
    Tested against ``oma2`` in test_sharding.py.
    """
    _, d_total = message.shape
    key_h, key_n = jax.random.split(key)
    h_r, h_i = channel.rayleigh_fade(key_h, message.shape[0])  # [K]
    if noise_var is not None:
        scale = math.sqrt(noise_var / 2.0)
        noise = scale * jax.random.normal(key_n, (d_total,), jnp.float32)
    else:
        noise = jnp.zeros((d_total,), jnp.float32)

    def local(msg, h_r, h_i, noise):
        h_sq = h_r**2 + h_i**2
        # mean(m^2) over the FULL row requires a psum over the model axis
        row_sumsq = jax.lax.psum(jnp.sum(msg**2, axis=1), MODEL_AXIS)
        p_upper = jnp.maximum(row_sumsq / d_total / h_sq, threshold)
        gain = jnp.sqrt(p_max / p_upper)
        partial = jnp.sum(msg * gain[:, None], axis=0)  # local clients
        total = jax.lax.psum(partial, CLIENT_AXIS)  # the over-the-air sum
        return total + noise

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(CLIENT_AXIS, MODEL_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS), P(MODEL_AXIS)),
        out_specs=P(MODEL_AXIS),
    )(message, h_r, h_i, noise)


def sharded_mean(mesh: Mesh, w_stack: jnp.ndarray) -> jnp.ndarray:
    """Column mean of the sharded [K, d] stack via one psum over clients."""
    k_total = w_stack.shape[0]

    def local(w):
        return jax.lax.psum(jnp.sum(w, axis=0), CLIENT_AXIS) / k_total

    return jax.shard_map(
        local, mesh=mesh, in_specs=P(CLIENT_AXIS, MODEL_AXIS), out_specs=P(MODEL_AXIS)
    )(w_stack)


def sharded_weiszfeld_step(
    mesh: Mesh, w_stack: jnp.ndarray, guess: jnp.ndarray, clamp: float = 1e-4
):
    """One ideal Weiszfeld update on the sharded stack.

    Distances need a psum over the model axis (each shard sees part of each
    row); the weighted sums need a psum over the client axis.  Two ICI
    collectives per step, everything else local.
    """

    def local(w, g):
        # full-row finiteness spans the model shards: one tiny [K/P] psum.
        # Non-finite rows are EXCLUDED (weight 0), matching the single-
        # device gm2 — without the mask, inv=0 times an Inf coordinate
        # would psum NaN into every output coordinate.
        finite = (
            jax.lax.psum(
                jnp.any(~jnp.isfinite(w), axis=1).astype(jnp.float32),
                MODEL_AXIS,
            )
            == 0.0
        )
        wm = jnp.where(finite[:, None], w, 0.0)
        d_part = jnp.sum((wm - g[None, :]) ** 2, axis=1)
        dist = jnp.sqrt(jax.lax.psum(d_part, MODEL_AXIS))
        dist = jnp.maximum(clamp, dist)
        inv = jnp.where(finite, 1.0 / dist, 0.0)
        num = jax.lax.psum(jnp.sum(wm * inv[:, None], axis=0), CLIENT_AXIS)
        den = jax.lax.psum(jnp.sum(inv), CLIENT_AXIS)
        return num / den

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(CLIENT_AXIS, MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=P(MODEL_AXIS),
    )(w_stack, guess)


def ring_krum_scores(
    mesh: Mesh, w_stack: jnp.ndarray, honest_size: int
) -> jnp.ndarray:
    """Krum scores over the sharded [K, d] stack via a ppermute ring.

    The reference computes the full K x K distance matrix on one device
    (``MNIST_Air_weight.py:199``); at K=1000 x ResNet-18 d the naive sharded
    equivalent (GSPMD matmul) may all-gather the whole [K, d] stack onto
    every device.  Here each of the P client-shards keeps its [K/P, d_loc]
    block resident; over P ring steps the blocks circulate over ICI
    (``lax.ppermute``) while each device computes one [K/P, K/P] Gram block
    per step on the MXU — classic ring all-pairs: peak per-device memory
    O(K/P * (d_loc + K)) and the compute/communication overlap XLA gives
    ring schedules.  A single end psum over the model axis completes the
    d-sharded inner products.

    Returns the [K] score vector sharded over the client axis (scores are
    tiny); argmin/top-k selection on it and the row gather from the sharded
    stack are left to the caller as GSPMD decisions.
    """
    p = mesh.shape[CLIENT_AXIS]
    k_total = w_stack.shape[0]
    if k_total % p:
        raise ValueError(f"K={k_total} not divisible by clients axis ({p})")
    k_sel = honest_size - 2 + 1  # smallest distances incl. self (ref :200-202)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def local(w):
        me = jax.lax.axis_index(CLIENT_AXIS)
        k_loc = w.shape[0]
        my_sq = jnp.sum(w * w, axis=1)  # [k_loc], partial over d-shard

        def accumulate(rows, blk, blk_sq, s):
            src = (me - s) % p  # ring position: who this block came from
            gram = jnp.dot(w, blk.T, preferred_element_type=jnp.float32)
            part = my_sq[:, None] + blk_sq[None, :] - 2.0 * gram
            return jax.lax.dynamic_update_slice(rows, part, (0, src * k_loc))

        def body(s, carry):
            blk, blk_sq, rows = carry
            rows = accumulate(rows, blk, blk_sq, s)
            blk = jax.lax.ppermute(blk, CLIENT_AXIS, perm)
            blk_sq = jax.lax.ppermute(blk_sq, CLIENT_AXIS, perm)
            return blk, blk_sq, rows

        # the zeros buffer must be marked device-varying before entering the
        # loop carry (its updates depend on the shard), else the VMA check
        # rejects the fori_loop carry
        rows0 = jax.lax.pcast(
            jnp.zeros((k_loc, k_total), w.dtype),
            (CLIENT_AXIS, MODEL_AXIS),
            to="varying",
        )
        # p - 1 hops move every block through every device; the last block's
        # Gram is computed OUTSIDE the loop so no dead final ppermute ships
        # the whole stack one extra hop (XLA cannot DCE a collective inside
        # a compiled loop)
        blk, blk_sq, rows = jax.lax.fori_loop(
            0, p - 1, body, (w, my_sq, rows0)
        )
        rows = accumulate(rows, blk, blk_sq, p - 1)
        # complete the d-sharded inner products, then apply the same
        # poisoned-row guards as ops.aggregators.pairwise_sq_dists: the
        # Gram form turns Inf rows into NaN distances (Inf - Inf), and a
        # NaN score sorts as BEST under top_k(-scores) — selecting the
        # poisoned row.  NaN -> +Inf (infinitely far), clamp cancellation,
        # and set self-distances to their exact value 0 for well-formed
        # rows but +Inf for poisoned ones (full squared norm non-finite —
        # covers Inf/NaN entries AND finite rows whose f32 norm overflows),
        # so a poisoned row scores Inf for ANY k_sel, including the
        # degenerate honest_size=2 / k_sel=1 case.
        dist = jax.lax.psum(rows, MODEL_AXIS)
        dist = jnp.where(jnp.isnan(dist), jnp.inf, dist)
        dist = jnp.maximum(dist, 0.0)
        full_sq = jax.lax.psum(my_sq, MODEL_AXIS)  # [k_loc]
        self_val = jnp.where(jnp.isfinite(full_sq), 0.0, jnp.inf)
        self_col = me * k_loc + jnp.arange(k_loc)
        dist = jnp.where(
            jnp.arange(k_total)[None, :] == self_col[:, None],
            self_val[:, None],
            dist,
        )
        neg_top, _ = jax.lax.top_k(-dist, k_sel)
        return -jnp.sum(neg_top, axis=1)  # [k_loc]

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=P(CLIENT_AXIS, MODEL_AXIS),
        out_specs=P(CLIENT_AXIS),
    )(w_stack)


def ring_krum(mesh: Mesh, w_stack: jnp.ndarray, *, honest_size: int, **_):
    """Single-Krum on the sharded stack.

    The winning row is extracted as a one-hot-weighted column sum rather
    than ``w_stack[argmin]``: a dynamic row index makes GSPMD all-gather
    the ENTIRE [K, d] stack onto every device before slicing (verified in
    HLO), while the masked contraction partitions into per-shard psums and
    keeps rejected Inf rows out of the sum (0*Inf = NaN otherwise)."""
    scores = ring_krum_scores(mesh, w_stack, honest_size)
    return agg_ops.selected_rows_mean(w_stack, jnp.argmin(scores)[None], 1)


def ring_multi_krum(
    mesh: Mesh,
    w_stack: jnp.ndarray,
    *,
    honest_size: int,
    m: Optional[int] = None,
    **_,
):
    """Multi-Krum on the sharded stack: mean of the m lowest-scoring rows.

    Averaged via the shared masked [K]-weight contraction
    (:func:`..ops.aggregators.selected_rows_mean`): a dynamic
    ``w_stack[idx]`` gather makes GSPMD all-gather the whole [K, d] stack,
    while the matvec partitions into per-shard psums."""
    m_sel = honest_size if m is None else int(m)
    scores = ring_krum_scores(mesh, w_stack, honest_size)
    _, idx = jax.lax.top_k(-scores, m_sel)
    return agg_ops.selected_rows_mean(w_stack, idx, m_sel)


def ring_bulyan(
    mesh: Mesh, w_stack: jnp.ndarray, *, honest_size: int, **_
):
    """Bulyan on the client-sharded stack.

    Krum scores come from the ppermute ring; the theta selected rows are
    extracted as a one-hot [theta, K] x [K, d] contraction (GSPMD partitions
    it into per-shard psums over the client axis — a dynamic ``w_stack[idx]``
    gather would all-gather the whole stack), leaving the [theta, d]
    selection sharded over the model axis; the coordinatewise
    median/trim/mean tail partitions over d untouched.
    """
    k = w_stack.shape[0]
    b = k - honest_size
    theta, beta = agg_ops.bulyan_sizes(k, b)
    scores = ring_krum_scores(mesh, w_stack, honest_size)
    _, idx = jax.lax.top_k(-scores, theta)
    sel_mat = jax.nn.one_hot(idx, k, dtype=w_stack.dtype)  # [theta, K]
    # select (not multiply) unpicked rows to 0 before the contraction: a
    # Krum-rejected row containing Inf would otherwise contribute
    # 0*Inf = NaN to every selected row
    picked = jnp.sum(sel_mat, axis=0) > 0  # [K]
    masked = jnp.where(picked[:, None], w_stack, 0.0)
    sel = jnp.dot(sel_mat, masked, preferred_element_type=jnp.float32)
    return agg_ops.bulyan_tail(sel, beta)
