from . import numpy_ref  # noqa: F401
