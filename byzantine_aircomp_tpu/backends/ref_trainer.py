"""``--backend=ref``: a NumPy oracle training loop (MLP only).

The north star keeps a non-JAX reference path behind the same CLI so the TPU
backend can be validated end-to-end ("matching CPU-reference test accuracy
within 0.5%").  This is a loop-style NumPy transcription of the reference's
``SGD`` round loop (``/root/reference/MNIST_Air_weight.py:226-372``) for the
linear MLP model: per-client manual softmax-regression gradients, the same
attack/channel/aggregation order, the same contiguous sharding and
with-replacement sampling.  Deliberately simple and slow — it exists to be
obviously correct.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ..data import datasets as data_lib
from ..fed.config import FedConfig
from . import numpy_ref


def _softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _ce_loss(logits, y):
    p = _softmax(logits)
    return -np.log(np.maximum(p[np.arange(len(y)), y], 1e-12))


def _init_mlp(rng: np.random.Generator, d_in: int, n_cls: int):
    # xavier-normal with relu gain, bias 0.01 (reference :92-95)
    std = np.sqrt(2.0) * np.sqrt(2.0 / (d_in + n_cls))
    w = rng.normal(0.0, std, (d_in, n_cls)).astype(np.float32)
    b = np.full((n_cls,), 0.01, np.float32)
    return np.concatenate([w.reshape(-1), b])


def _grad(flat, x, y, d_in, n_cls):
    w = flat[: d_in * n_cls].reshape(d_in, n_cls)
    b = flat[d_in * n_cls :]
    logits = x @ w + b
    delta = _softmax(logits)
    delta[np.arange(len(y)), y] -= 1.0
    delta /= len(y)
    gw = x.T @ delta
    gb = delta.sum(axis=0)
    return np.concatenate([gw.reshape(-1), gb])


def _eval(flat, x, y, d_in, n_cls):
    w = flat[: d_in * n_cls].reshape(d_in, n_cls)
    b = flat[d_in * n_cls :]
    logits = x @ w + b
    loss = float(_ce_loss(logits, y).mean())
    acc = float((logits.argmax(axis=1) == y).mean())
    return loss, acc


def run_ref(cfg: FedConfig, log_fn=print, dataset=None) -> Dict:
    assert cfg.model == "MLP", "ref backend implements the MLP path only"
    if cfg.local_steps != 1 or cfg.server_opt != "none" or cfg.fedprox_mu:
        raise NotImplementedError(
            "ref backend implements the reference's FedSGD only "
            "(local_steps=1, server_opt=none, fedprox_mu=0); got "
            f"local_steps={cfg.local_steps}, server_opt={cfg.server_opt!r}, "
            f"fedprox_mu={cfg.fedprox_mu}"
        )
    if cfg.attack is None:
        cfg.byz_size = 0
    cfg.validate()
    _KNOWN_ATTACKS = {
        "classflip", "dataflip", "gradascent", "weightflip", "signflip",
        "alie", "ipm", "gaussian", "minmax", "minsum",
    }
    if cfg.attack is not None and cfg.attack not in _KNOWN_ATTACKS:
        raise KeyError(
            f"ref backend: unknown attack {cfg.attack!r}; known: "
            f"{sorted(_KNOWN_ATTACKS)}"
        )
    # same contract as AttackSpec.param_name
    _PARAM_ATTACKS = {"alie", "ipm", "gaussian", "minmax", "minsum"}
    if cfg.attack_param is not None and cfg.attack not in _PARAM_ATTACKS:
        raise ValueError(
            f"attack {cfg.attack!r} takes no scalar parameter"
        )

    ds = dataset if dataset is not None else data_lib.load(cfg.dataset)
    n_cls = ds.num_classes
    x_tr = ds.x_train.reshape(len(ds.x_train), -1)
    y_tr = ds.y_train
    x_va = ds.x_val.reshape(len(ds.x_val), -1)
    y_va = ds.y_val
    d_in = x_tr.shape[1]

    k = cfg.node_size
    shards = data_lib.contiguous_shards(len(x_tr), k)

    rng = np.random.default_rng(cfg.seed)
    flat = _init_mlp(rng, d_in, n_cls)

    tr = _eval(flat, x_tr, y_tr, d_in, n_cls) if cfg.eval_train else (0.0, 0.0)
    va = _eval(flat, x_va, y_va, d_in, n_cls)
    paths: Dict[str, List[float]] = {
        "trainLossPath": [tr[0]],
        "trainAccPath": [tr[1]],
        "valLossPath": [va[0]],
        "valAccPath": [va[1]],
        "variencePath": [],
        "roundsPerSec": [],
    }
    log_fn(f"[ref backend] round 0: val loss={va[0]:.4f} acc={va[1]:.4f}")

    byz0 = cfg.honest_size  # Byzantine clients are the last byz_size rows
    for r in range(cfg.rounds):
        t0 = time.perf_counter()
        for _ in range(cfg.display_interval):
            w_stack = np.empty((k, flat.size), np.float32)
            for node in range(k):
                lo = shards.offsets[node]
                idx = lo + rng.integers(0, shards.sizes[node], cfg.batch_size)
                xb, yb = x_tr[idx], y_tr[idx]
                if node >= byz0 and cfg.attack == "classflip":
                    yb = (n_cls - 1) - yb
                elif node >= byz0 and cfg.attack == "dataflip":
                    xb = 1.0 - xb
                g = _grad(flat, xb, yb, d_in, n_cls)
                if node >= byz0 and cfg.attack == "gradascent":
                    g = -g
                w_stack[node] = flat - cfg.gamma * (g + cfg.weight_decay * flat)

            if cfg.attack == "weightflip" and cfg.byz_size:
                w_stack = numpy_ref.weightflip(w_stack, cfg.byz_size)
            elif cfg.attack == "signflip" and cfg.byz_size:
                w_stack[-cfg.byz_size :] *= -1.0
            elif cfg.attack == "alie" and cfg.byz_size:
                z = 1.5 if cfg.attack_param is None else cfg.attack_param
                w_stack = numpy_ref.alie(w_stack, cfg.byz_size, z=z)
            elif cfg.attack == "ipm" and cfg.byz_size:
                eps = 0.5 if cfg.attack_param is None else cfg.attack_param
                w_stack = numpy_ref.ipm(w_stack, cfg.byz_size, eps=eps)
            elif cfg.attack == "gaussian" and cfg.byz_size:
                sigma = 1.0 if cfg.attack_param is None else cfg.attack_param
                w_stack[-cfg.byz_size :] = sigma * rng.normal(
                    size=(cfg.byz_size, flat.size)
                ).astype(np.float32)
            elif cfg.attack == "minmax" and cfg.byz_size:
                w_stack = numpy_ref.minmax(
                    w_stack, cfg.byz_size, gamma=cfg.attack_param
                )
            elif cfg.attack == "minsum" and cfg.byz_size:
                w_stack = numpy_ref.minsum(
                    w_stack, cfg.byz_size, gamma=cfg.attack_param
                )

            # channel-dispatch rule (mirrors ops.aggregators.needs_oma_prepass):
            # gm and signmv run their own over-the-air transmission
            if cfg.noise_var is not None and cfg.agg not in ("gm", "signmv"):
                w_stack = numpy_ref.oma(rng, w_stack, cfg.noise_var)

            if cfg.agg == "gm":
                flat = numpy_ref.gm(
                    rng,
                    w_stack,
                    noise_var=cfg.noise_var,
                    guess=flat,
                    maxiter=cfg.agg_maxiter,
                    tol=cfg.agg_tol,
                    p_max=cfg.gm_p_max,
                ).astype(np.float32)
            elif cfg.agg == "gm2":
                flat = numpy_ref.gm2(
                    w_stack, guess=flat, maxiter=cfg.agg_maxiter, tol=cfg.agg_tol
                ).astype(np.float32)
            elif cfg.agg == "mean":
                flat = numpy_ref.mean(w_stack)
            elif cfg.agg == "median":
                flat = numpy_ref.median(w_stack)
            elif cfg.agg == "trimmed_mean":
                flat = numpy_ref.trimmed_mean(w_stack)
            elif cfg.agg in ("krum", "Krum"):
                flat = numpy_ref.krum(w_stack, cfg.honest_size).copy()
            elif cfg.agg == "multi_krum":
                flat = numpy_ref.multi_krum(w_stack, cfg.honest_size, m=cfg.krum_m)
            elif cfg.agg == "bulyan":
                flat = numpy_ref.bulyan(w_stack, cfg.honest_size)
            elif cfg.agg == "cclip":
                flat = numpy_ref.centered_clip(
                    w_stack, guess=flat,
                    clip_tau=cfg.clip_tau, clip_iters=cfg.clip_iters,
                )
            elif cfg.agg == "signmv":
                flat = numpy_ref.sign_majority_vote(
                    w_stack, guess=flat, noise_var=cfg.noise_var,
                    sign_eta=cfg.sign_eta, rng=rng,
                )
            else:
                raise KeyError(f"ref backend: unknown aggregator {cfg.agg!r}")

        w_h = w_stack[: cfg.honest_size]
        variance = float(((w_h - w_h.mean(axis=0)) ** 2).sum(axis=1).mean())
        dt = time.perf_counter() - t0

        tr = _eval(flat, x_tr, y_tr, d_in, n_cls) if cfg.eval_train else (0.0, 0.0)
        va = _eval(flat, x_va, y_va, d_in, n_cls)
        paths["trainLossPath"].append(tr[0])
        paths["trainAccPath"].append(tr[1])
        paths["valLossPath"].append(va[0])
        paths["valAccPath"].append(va[1])
        paths["variencePath"].append(variance)
        paths["roundsPerSec"].append(1.0 / dt)
        log_fn(
            f"[ref backend] round {r + 1}/{cfg.rounds}: "
            f"train acc={tr[1]:.4f} val acc={va[1]:.4f}"
        )
    return paths
