"""``--backend=ref``: a NumPy oracle training loop (MLP and CNN).

The north star keeps a non-JAX reference path behind the same CLI so the TPU
backend can be validated end-to-end ("matching CPU-reference test accuracy
within 0.5%").  This is a loop-style NumPy transcription of the reference's
``SGD`` round loop (``/root/reference/MNIST_Air_weight.py:226-372``): per
client manual gradients, the same attack/channel/aggregation order, the same
contiguous sharding and with-replacement sampling.  Deliberately simple and
slow — it exists to be obviously correct.

Models: the linear MLP (softmax regression, reference ``:53-62``) and the
CNN (conv5x5/32 + pool -> conv5x5/64 + pool -> fc -> fc, reference
``:63-90``) as explicit im2col NumPy forward/backward.  Both models' flat
parameter layouts match the flax pytree leaf order (alphabetical:
Conv_0/bias, Conv_0/kernel, Conv_1/bias, Conv_1/kernel, Dense_0/bias,
Dense_0/kernel, Dense_1/bias, Dense_1/kernel — see ``ops.flatten``).
Gradient-level agreement against ``jax.grad`` on identical flat vectors is
asserted by ``tests/test_parity.py::test_mlp_oracle_grad_matches_jax_grad``
and ``::test_cnn_oracle_grad_matches_jax_grad`` (plus the full 28x28
MNIST-shape variant), and ``::test_cnn_ref_backend_end_to_end`` covers the
CNN training loop end to end.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ..data import datasets as data_lib
from ..fed.config import FedConfig
from . import numpy_ref


def _softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _ce_loss(logits, y):
    p = _softmax(logits)
    return -np.log(np.maximum(p[np.arange(len(y)), y], 1e-12))


def _xavier_normal_relu(rng, shape, fan_in, fan_out):
    # xavier-normal with relu gain (reference weights_init, :92-95)
    std = np.sqrt(2.0) * np.sqrt(2.0 / (fan_in + fan_out))
    return rng.normal(0.0, std, shape).astype(np.float32)


class _NumpyMLP:
    """Softmax regression (reference MLP, :53-62): flat = [b, w.ravel()],
    the flax FlatSpec leaf order (alphabetical: Dense_0/bias, Dense_0/kernel)
    so oracle and JAX gradients compare on the SAME flat vector."""

    def __init__(self, d_in: int, n_cls: int):
        self.d_in, self.n_cls = d_in, n_cls

    def prepare(self, x):
        return x.reshape(len(x), -1)

    def init(self, rng) -> np.ndarray:
        w = _xavier_normal_relu(rng, (self.d_in, self.n_cls), self.d_in, self.n_cls)
        b = np.full((self.n_cls,), 0.01, np.float32)
        return np.concatenate([b, w.reshape(-1)])

    def _unpack(self, flat):
        n = self.n_cls
        return flat[n:].reshape(self.d_in, n), flat[:n]

    def logits(self, flat, x):
        w, b = self._unpack(flat)
        return x @ w + b

    def grad(self, flat, x, y):
        w, b = self._unpack(flat)
        delta = _softmax(x @ w + b)
        delta[np.arange(len(y)), y] -= 1.0
        delta /= len(y)
        return np.concatenate([delta.sum(axis=0), (x.T @ delta).reshape(-1)])


def _im2col(x: np.ndarray, kh: int, kw: int, pad: int) -> np.ndarray:
    """[B,H,W,C] -> [B,H,W,kh*kw*C] patches (stride 1, SAME-style pad),
    ordered (h, w, c) to match a flax [kh,kw,C,F] kernel reshaped to
    [kh*kw*C, F]."""
    b, h, w, c = x.shape
    xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    win = np.lib.stride_tricks.sliding_window_view(xp, (kh, kw), axis=(1, 2))
    # win: [B, H, W, C, kh, kw] -> [B, H, W, kh, kw, C]
    win = win.transpose(0, 1, 2, 4, 5, 3)
    return win.reshape(b, h, w, kh * kw * c)


def _col2im(g_patches: np.ndarray, shape, kh: int, kw: int, pad: int) -> np.ndarray:
    """Adjoint of :func:`_im2col`: scatter-add patch gradients back."""
    b, h, w, c = shape
    gp = g_patches.reshape(b, h, w, kh, kw, c)
    out = np.zeros((b, h + 2 * pad, w + 2 * pad, c), np.float32)
    for i in range(kh):
        for j in range(kw):
            out[:, i : i + h, j : j + w, :] += gp[:, :, :, i, j, :]
    return out[:, pad : pad + h, pad : pad + w, :]


def _maxpool2(x: np.ndarray):
    """2x2/2 max pool on [B,H,W,C]; returns (pooled, argmax mask).

    Ties (e.g. relu-zeroed windows) keep the FIRST max in row-major window
    order, matching XLA select_and_scatter."""
    b, h, w, c = x.shape
    win = x.reshape(b, h // 2, 2, w // 2, 2, c)
    pooled = win.max(axis=(2, 4))
    eq = win == pooled[:, :, None, :, None, :]  # [b,h2,i,w2,j,c]
    eqf = eq.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4, c)
    first = (np.cumsum(eqf, axis=3) == 1) & eqf  # first True along (i,j)
    mask = first.reshape(b, h // 2, w // 2, 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    return pooled, mask


def _maxpool2_back(g: np.ndarray, mask: np.ndarray) -> np.ndarray:
    b, hh, _, ww, _, c = mask.shape
    return (mask * g[:, :, None, :, None, :]).reshape(b, hh * 2, ww * 2, c)


class _NumpyCNN:
    """Reference CNN (:63-90) in explicit im2col NumPy, NHWC.

    Flat layout mirrors the flax FlatSpec leaf order (dict keys sorted):
    b1, k1[5,5,C,32], b2, k2[5,5,32,64], fb1, fk1[fc_in,W], fb2, fk2[W,n]."""

    def __init__(self, h: int, w: int, c_in: int, n_cls: int, fc_width: int):
        assert h % 4 == 0 and w % 4 == 0, "two 2x2 pools need H, W % 4 == 0"
        self.h, self.w, self.c_in = h, w, c_in
        self.n_cls, self.fc_width = n_cls, fc_width
        self.fc_in = (h // 4) * (w // 4) * 64
        shapes = [
            (32,), (5, 5, c_in, 32),
            (64,), (5, 5, 32, 64),
            (fc_width,), (self.fc_in, fc_width),
            (n_cls,), (fc_width, n_cls),
        ]
        self.shapes = shapes
        self.sizes = [int(np.prod(s)) for s in shapes]
        self.offsets = np.cumsum([0] + self.sizes[:-1]).tolist()

    def prepare(self, x):
        return x if x.ndim == 4 else x[..., None]

    def init(self, rng) -> np.ndarray:
        c = self.c_in
        parts = [
            np.full((32,), 0.01, np.float32),
            _xavier_normal_relu(rng, (5, 5, c, 32), 25 * c, 25 * 32),
            np.full((64,), 0.01, np.float32),
            _xavier_normal_relu(rng, (5, 5, 32, 64), 25 * 32, 25 * 64),
            np.full((self.fc_width,), 0.01, np.float32),
            _xavier_normal_relu(
                rng, (self.fc_in, self.fc_width), self.fc_in, self.fc_width
            ),
            np.full((self.n_cls,), 0.01, np.float32),
            _xavier_normal_relu(
                rng, (self.fc_width, self.n_cls), self.fc_width, self.n_cls
            ),
        ]
        return np.concatenate([p.reshape(-1) for p in parts])

    def _unpack(self, flat):
        return [
            flat[o : o + s].reshape(shape)
            for o, s, shape in zip(self.offsets, self.sizes, self.shapes)
        ]

    def _forward(self, flat, x):
        b1, k1, b2, k2, fb1, fk1, fb2, fk2 = self._unpack(flat)
        b = len(x)
        p1 = _im2col(x, 5, 5, 2)  # [B,H,W,25C]
        z1 = p1 @ k1.reshape(-1, 32) + b1
        a1 = np.maximum(z1, 0.0)
        q1, m1 = _maxpool2(a1)
        p2 = _im2col(q1, 5, 5, 2)
        z2 = p2 @ k2.reshape(-1, 64) + b2
        a2 = np.maximum(z2, 0.0)
        q2, m2 = _maxpool2(a2)
        f = q2.reshape(b, -1)
        z3 = f @ fk1 + fb1
        a3 = np.maximum(z3, 0.0)
        logits = a3 @ fk2 + fb2
        cache = (x, p1, z1, q1, m1, p2, z2, m2, q2, f, z3, a3)
        return logits, cache

    def logits(self, flat, x):
        return self._forward(flat, x)[0]

    def grad(self, flat, x, y):
        _, k1, _, k2, _, fk1, _, fk2 = self._unpack(flat)
        logits, cache = self._forward(flat, x)
        x_, p1, z1, q1, m1, p2, z2, m2, q2, f, z3, a3 = cache
        n = len(y)
        delta = _softmax(logits)
        delta[np.arange(n), y] -= 1.0
        delta /= n  # dL/dlogits, mean CE
        g_fk2 = a3.T @ delta
        g_fb2 = delta.sum(axis=0)
        g_a3 = delta @ fk2.T
        g_z3 = g_a3 * (z3 > 0)
        g_fk1 = f.T @ g_z3
        g_fb1 = g_z3.sum(axis=0)
        g_f = g_z3 @ fk1.T
        g_q2 = g_f.reshape(q2.shape)
        g_a2 = _maxpool2_back(g_q2, m2)
        g_z2 = g_a2 * (z2 > 0)
        g_k2 = p2.reshape(-1, p2.shape[-1]).T @ g_z2.reshape(-1, 64)
        g_b2 = g_z2.sum(axis=(0, 1, 2))
        g_p2 = g_z2 @ k2.reshape(-1, 64).T
        g_q1 = _col2im(g_p2, q1.shape, 5, 5, 2)
        g_a1 = _maxpool2_back(g_q1, m1)
        g_z1 = g_a1 * (z1 > 0)
        g_k1 = p1.reshape(-1, p1.shape[-1]).T @ g_z1.reshape(-1, 32)
        g_b1 = g_z1.sum(axis=(0, 1, 2))
        parts = [
            g_b1, g_k1.reshape(5, 5, self.c_in, 32),
            g_b2, g_k2.reshape(5, 5, 32, 64),
            g_fb1, g_fk1, g_fb2, g_fk2,
        ]
        return np.concatenate([p.reshape(-1) for p in parts]).astype(np.float32)


def _make_model(cfg: FedConfig, ds) -> object:
    sample = ds.x_train[:1]
    n_cls = ds.num_classes
    if cfg.model == "MLP":
        return _NumpyMLP(int(np.prod(sample.shape[1:])), n_cls)
    if cfg.model in ("CNN", "cnn"):
        if sample.ndim == 3:
            h, w, c = sample.shape[1], sample.shape[2], 1
        else:
            h, w, c = sample.shape[1], sample.shape[2], sample.shape[3]
        return _NumpyCNN(h, w, c, n_cls, cfg.fc_width)
    raise KeyError(f"ref backend: unknown model {cfg.model!r} (MLP or CNN)")


def _eval_model(model, flat, x, y, batch: int = 1024):
    losses, correct = 0.0, 0
    for lo in range(0, len(x), batch):
        xb, yb = x[lo : lo + batch], y[lo : lo + batch]
        logits = model.logits(flat, xb)
        losses += float(_ce_loss(logits, yb).sum())
        correct += int((logits.argmax(axis=1) == yb).sum())
    return losses / len(x), correct / len(x)


def run_ref(cfg: FedConfig, log_fn=print, dataset=None) -> Dict:
    if cfg.attack is None:
        cfg.byz_size = 0
    cfg.validate()
    if cfg.fault is not None:
        # the NumPy oracle reproduces the reference line-by-line; the
        # reference has no fault model, so an oracle run with faults on
        # would silently compare against a DIFFERENT program
        raise NotImplementedError(
            "ref backend has no fault-injection path; run --backend jax "
            "or drop --fault"
        )
    _KNOWN_ATTACKS = {
        "classflip", "dataflip", "gradascent", "weightflip", "signflip",
        "alie", "ipm", "gaussian", "minmax", "minsum",
    }
    if cfg.attack is not None and cfg.attack not in _KNOWN_ATTACKS:
        raise KeyError(
            f"ref backend: unknown attack {cfg.attack!r}; known: "
            f"{sorted(_KNOWN_ATTACKS)}"
        )
    # same contract as AttackSpec.param_name
    _PARAM_ATTACKS = {"alie", "ipm", "gaussian", "minmax", "minsum"}
    if cfg.attack_param is not None and cfg.attack not in _PARAM_ATTACKS:
        raise ValueError(
            f"attack {cfg.attack!r} takes no scalar parameter"
        )

    ds = dataset if dataset is not None else data_lib.load(cfg.dataset)
    n_cls = ds.num_classes
    model = _make_model(cfg, ds)
    x_tr = model.prepare(ds.x_train)
    y_tr = ds.y_train
    x_va = model.prepare(ds.x_val)
    y_va = ds.y_val

    k = cfg.node_size
    if cfg.partition == "dirichlet":
        # same derivation (seed, alpha) as the jax trainer, so both
        # backends train on the identical non-IID split
        perm, shards = data_lib.dirichlet_shards(
            y_tr, k, cfg.dirichlet_alpha, seed=cfg.seed
        )
        x_tr = x_tr[perm]
        y_tr = np.asarray(y_tr)[perm]
    else:
        shards = data_lib.contiguous_shards(len(x_tr), k)

    rng = np.random.default_rng(cfg.seed)
    flat = model.init(rng)

    tr = _eval_model(model, flat, x_tr, y_tr) if cfg.eval_train else (0.0, 0.0)
    va = _eval_model(model, flat, x_va, y_va)
    paths: Dict[str, List[float]] = {
        "trainLossPath": [tr[0]],
        "trainAccPath": [tr[1]],
        "valLossPath": [va[0]],
        "valAccPath": [va[1]],
        "variencePath": [],
        "roundsPerSec": [],
    }
    log_fn(f"[ref backend] round 0: val loss={va[0]:.4f} acc={va[1]:.4f}")

    # FedOpt server state (mirrors fed/train.py's optax transforms exactly:
    # sgd-with-trace momentum, adam with bias correction, over the
    # pseudo-gradient delta = w_round_start - aggregate)
    server_m = np.zeros_like(flat)
    server_v = np.zeros_like(flat)
    server_t = 0

    byz0 = cfg.honest_size  # Byzantine clients are the last byz_size rows
    # partial participation: stratified per-iteration draw, mirroring
    # fed/train.py (round(f*H) honest + round(f*B) Byzantine rows; the
    # RNG streams differ across backends as everywhere else — parity on
    # participation configs is distributional, not bitwise)
    part_h, part_b = cfg.participant_counts()
    # client momentum buffer (cfg.client_momentum doc): [K, d], zeros init
    client_m = (
        np.zeros((k, flat.size), np.float32) if cfg.client_momentum else None
    )
    for r in range(cfg.rounds):
        t0 = time.perf_counter()
        for _ in range(cfg.display_interval):
            if cfg.participation < 1.0:
                participants = np.concatenate([
                    rng.permutation(cfg.honest_size)[:part_h],
                    byz0 + rng.permutation(cfg.byz_size)[:part_b],
                ]).astype(np.int64)
            else:
                participants = np.arange(k)
            w_stack = np.empty((len(participants), flat.size), np.float32)
            for row, node in enumerate(participants):
                lo = shards.offsets[node]
                # local_steps > 1 = FedAvg regime (fed/train.py
                # _per_client_weights): E local SGD steps, each on a fresh
                # with-replacement batch; data/gradient attacks apply at
                # every local step; fedprox pulls toward the round start
                w_c = flat
                for _e in range(cfg.local_steps):
                    idx = lo + rng.integers(0, shards.sizes[node], cfg.batch_size)
                    xb, yb = x_tr[idx], y_tr[idx]
                    if node >= byz0 and cfg.attack == "classflip":
                        yb = (n_cls - 1) - yb
                    elif node >= byz0 and cfg.attack == "dataflip":
                        xb = 1.0 - xb
                    g = model.grad(w_c, xb, yb)
                    if node >= byz0 and cfg.attack == "gradascent":
                        g = -g
                    if client_m is not None:
                        # momentum-SGD client step (local_steps == 1 by
                        # validation): m <- beta*m + (1-beta)*(g + wd*w)
                        beta = cfg.client_momentum
                        g_tot = g + cfg.weight_decay * w_c
                        client_m[node] = (
                            beta * client_m[node] + (1.0 - beta) * g_tot
                        )
                        w_c = flat - cfg.gamma * client_m[node]
                        continue
                    if cfg.fedprox_mu:
                        g = g + cfg.fedprox_mu * (w_c - flat)
                    w_c = w_c - cfg.gamma * (g + cfg.weight_decay * w_c)
                w_stack[row] = w_c

            if cfg.attack == "weightflip" and part_b:
                w_stack = numpy_ref.weightflip(w_stack, part_b)
            elif cfg.attack == "signflip" and part_b:
                w_stack[-part_b :] *= -1.0
            elif cfg.attack == "alie" and part_b:
                z = 1.5 if cfg.attack_param is None else cfg.attack_param
                w_stack = numpy_ref.alie(w_stack, part_b, z=z)
            elif cfg.attack == "ipm" and part_b:
                eps = 0.5 if cfg.attack_param is None else cfg.attack_param
                w_stack = numpy_ref.ipm(w_stack, part_b, eps=eps)
            elif cfg.attack == "gaussian" and part_b:
                sigma = 1.0 if cfg.attack_param is None else cfg.attack_param
                w_stack[-part_b :] = sigma * rng.normal(
                    size=(part_b, flat.size)
                ).astype(np.float32)
            elif cfg.attack == "minmax" and part_b:
                w_stack = numpy_ref.minmax(
                    w_stack, part_b, gamma=cfg.attack_param
                )
            elif cfg.attack == "minsum" and part_b:
                w_stack = numpy_ref.minsum(
                    w_stack, part_b, gamma=cfg.attack_param
                )

            # channel-dispatch rule (mirrors ops.aggregators.needs_oma_prepass):
            # gm and signmv run their own over-the-air transmission
            if cfg.noise_var is not None and cfg.agg not in ("gm", "signmv"):
                w_stack = numpy_ref.oma(rng, w_stack, cfg.noise_var)

            # bucketing (fed/train.py's bucketing scope): aggregate the
            # [m/s, d] random-bucket means with the worst-case clean count
            agg_stack, agg_h = w_stack, part_h
            if cfg.bucket_size > 1:
                s_b = cfg.bucket_size
                m_rows = len(w_stack)
                bperm = rng.permutation(m_rows)
                agg_stack = (
                    w_stack[bperm]
                    .reshape(m_rows // s_b, s_b, -1)
                    .mean(axis=1)
                    .astype(np.float32)
                )
                agg_h = m_rows // s_b - part_b

            if cfg.agg == "gm":
                agg_out = numpy_ref.gm(
                    rng,
                    agg_stack,
                    noise_var=cfg.noise_var,
                    guess=flat,
                    maxiter=cfg.agg_maxiter,
                    tol=cfg.agg_tol,
                    p_max=cfg.gm_p_max,
                ).astype(np.float32)
            elif cfg.agg == "gm2":
                agg_out = numpy_ref.gm2(
                    agg_stack, guess=flat, maxiter=cfg.agg_maxiter, tol=cfg.agg_tol
                ).astype(np.float32)
            elif cfg.agg == "mean":
                agg_out = numpy_ref.mean(agg_stack)
            elif cfg.agg == "median":
                agg_out = numpy_ref.median(agg_stack)
            elif cfg.agg == "trimmed_mean":
                agg_out = numpy_ref.trimmed_mean(agg_stack)
            elif cfg.agg in ("krum", "Krum"):
                agg_out = numpy_ref.krum(agg_stack, agg_h).copy()
            elif cfg.agg == "multi_krum":
                agg_out = numpy_ref.multi_krum(agg_stack, agg_h, m=cfg.krum_m)
            elif cfg.agg == "bulyan":
                agg_out = numpy_ref.bulyan(agg_stack, agg_h)
            elif cfg.agg == "cclip":
                agg_out = numpy_ref.centered_clip(
                    agg_stack, guess=flat,
                    clip_tau=cfg.clip_tau, clip_iters=cfg.clip_iters,
                )
            elif cfg.agg == "dnc":
                agg_out = numpy_ref.dnc(
                    agg_stack, agg_h, rng, dnc_iters=cfg.dnc_iters,
                    dnc_sub_dim=cfg.dnc_sub_dim, dnc_c=cfg.dnc_c,
                )
            elif cfg.agg == "signmv":
                agg_out = numpy_ref.sign_majority_vote(
                    agg_stack, guess=flat, noise_var=cfg.noise_var,
                    sign_eta=cfg.sign_eta, rng=rng,
                )
            else:
                raise KeyError(f"ref backend: unknown aggregator {cfg.agg!r}")

            # server optimizer over the pseudo-gradient (FedAvgM / FedAdam;
            # fed/train.py:331-339 with optax.sgd(momentum)/optax.adam)
            if cfg.server_opt == "momentum":
                delta = flat - agg_out
                # optax trace: m <- delta + beta * m; update = -lr * m
                server_m = delta + cfg.server_momentum * server_m
                flat = (flat - cfg.server_lr * server_m).astype(np.float32)
            elif cfg.server_opt == "adam":
                delta = flat - agg_out
                server_t += 1
                b1, b2, eps = 0.9, 0.999, 1e-8
                server_m = b1 * server_m + (1.0 - b1) * delta
                server_v = b2 * server_v + (1.0 - b2) * delta * delta
                mhat = server_m / (1.0 - b1**server_t)
                vhat = server_v / (1.0 - b2**server_t)
                flat = (
                    flat - cfg.server_lr * mhat / (np.sqrt(vhat) + eps)
                ).astype(np.float32)
            else:  # "none": take the aggregate (reference :354-358)
                flat = agg_out

        w_h = w_stack[:part_h]
        variance = float(((w_h - w_h.mean(axis=0)) ** 2).sum(axis=1).mean())
        dt = time.perf_counter() - t0

        tr = _eval_model(model, flat, x_tr, y_tr) if cfg.eval_train else (0.0, 0.0)
        va = _eval_model(model, flat, x_va, y_va)
        paths["trainLossPath"].append(tr[0])
        paths["trainAccPath"].append(tr[1])
        paths["valLossPath"].append(va[0])
        paths["valAccPath"].append(va[1])
        paths["variencePath"].append(variance)
        paths["roundsPerSec"].append(1.0 / dt)
        log_fn(
            f"[ref backend] round {r + 1}/{cfg.rounds}: "
            f"train acc={tr[1]:.4f} val acc={va[1]:.4f}"
        )
    return paths
