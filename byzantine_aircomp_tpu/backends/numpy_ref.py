"""NumPy oracle implementations of the server-side math.

This is the framework's ``--backend=ref`` path and the unit-test oracle: a
direct, loop-style NumPy transcription of the semantics documented in
SURVEY.md (aggregators ``/root/reference/MNIST_Air_weight.py:131-204``,
channel ``:385-414``, weightflip ``:380-383``).  Deliberately *not*
TPU-idiomatic — its job is to be obviously correct so the JAX/Pallas paths
can be tested against it.
"""

from __future__ import annotations

import contextlib
import math
from typing import Optional

import numpy as np

DIST_CLAMP = 1e-4


def mean(w: np.ndarray) -> np.ndarray:
    return w.mean(axis=0)


def median(w: np.ndarray) -> np.ndarray:
    # torch.median(dim=0) semantics: lower middle order statistic for even K
    k = w.shape[0]
    return np.sort(w, axis=0)[(k - 1) // 2]


def trimmed_mean(w: np.ndarray, trim_ratio: float = 0.1) -> np.ndarray:
    k = w.shape[0]
    beta = int(k * trim_ratio)
    srt = np.sort(w, axis=0)
    return srt[beta : k - beta].mean(axis=0)


def _krum_scores(w: np.ndarray, honest_size: int) -> np.ndarray:
    # Mask non-finite rows BEFORE the broadcast: Inf - Inf would emit a
    # RuntimeWarning and produce NaN distances.  Matching the JAX path
    # (ops.aggregators.pairwise_sq_dists), any distance involving a
    # non-finite row is +Inf (never selected) and the diagonal is 0.
    # "poisoned" = non-finite entries OR an f32-overflowing squared norm
    # (finite ~1e20 entries overflow ||w||^2 to Inf and behave exactly like
    # an Inf row in the JAX path's f32 Gram form).  Overflow is judged by
    # ROUNDING the f64 sum to f32 (round-to-nearest-even, like the JAX
    # path's f32 accumulate) rather than a raw ``> f32max`` compare: f64
    # values in (f32max, f32max * (1 + 2^-25)] round DOWN to f32max — a
    # strict threshold test would call them overflowed when f32 arithmetic
    # keeps them finite.  Caveat: within a few ULP of the boundary the two
    # backends can still legitimately disagree — f32 accumulation ORDER in
    # the JAX reduce may overflow (or not) where the correctly-rounded f64
    # sum lands on the other side; exact parity there is unattainable.
    finite = np.isfinite(w).all(axis=1)
    sq64 = (w.astype(np.float64) ** 2).sum(axis=1)

    def _f32_overflows(x64: np.ndarray) -> np.ndarray:
        with np.errstate(over="ignore"):
            return np.isinf(x64.astype(np.float32))

    bad = ~finite | _f32_overflows(sq64)
    wz = np.where(~bad[:, None], w, 0.0).astype(np.float64)
    dist = ((wz[:, None, :] - wz[None, :, :]) ** 2).sum(axis=-1)
    # emulate the JAX path's f32 Gram-form overflow for rows that are NOT
    # individually poisoned: when sq_i + sq_j overflows f32, the Gram form
    # computes Inf - 2*gram -> Inf (or Inf - Inf = NaN -> +Inf), so two
    # colluding rows with norm^2 just under f32max are "infinitely far"
    # from each other in f32 even though their true distance is small (the
    # broadcast form above would see 0 and let them win selection, which
    # the JAX path rejects — parity demands the f32 semantics).  By AM-GM
    # 2*|gram| <= sq_i + sq_j, so the sq-sum test covers the gram term.
    pair_over = _f32_overflows(sq64[:, None] + sq64[None, :])
    dist[pair_over] = np.inf
    dist[_f32_overflows(dist)] = np.inf  # f32 saturation of the distance
    dist[bad, :] = np.inf
    dist[:, bad] = np.inf
    np.fill_diagonal(dist, 0.0)
    # a poisoned row's own diagonal is ALSO +Inf (not the usual exact 0):
    # with honest_size=2, k_sel=1 and a 0 diagonal would give the poisoned
    # row score 0 — winning the selection.  Inf on the diagonal makes its
    # score Inf for ANY k_sel, closing the degenerate case (matching
    # ops.aggregators.pairwise_sq_dists).
    dist[bad, bad] = np.inf
    k_sel = honest_size - 2 + 1
    scores = np.sort(dist, axis=1)[:, :k_sel].sum(axis=1)
    # the f32 emulation must extend to the SCORE level too: in the
    # colluding band the distances are huge-but-finite in f64 while the
    # JAX path's f32 top_k sum saturates to Inf — saturate to match, so
    # rejected rows rank identically (all Inf) in both backends
    scores[_f32_overflows(scores)] = np.inf
    return scores


def krum(w: np.ndarray, honest_size: int) -> np.ndarray:
    return w[int(np.argmin(_krum_scores(w, honest_size)))]


def multi_krum(w: np.ndarray, honest_size: int, m: Optional[int] = None) -> np.ndarray:
    m_sel = honest_size if m is None else m
    idx = np.argsort(_krum_scores(w, honest_size))[:m_sel]
    return w[idx].mean(axis=0)


def _exclude_nonfinite_rows(w: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """(masked stack, per-row finite mask): rows containing Inf/NaN zeroed —
    the iterative aggregators (gm/gm2/centered_clip) EXCLUDE non-finite
    rows, same semantics as the JAX paths (ops.aggregators._finite_rows)."""
    finite = np.isfinite(w).all(axis=1)
    return np.where(finite[:, None], w, 0.0), finite


def gm2(
    w: np.ndarray,
    guess: Optional[np.ndarray] = None,
    maxiter: int = 1000,
    tol: float = 1e-5,
) -> np.ndarray:
    w, finite = _exclude_nonfinite_rows(w)
    if guess is None:
        guess = w.sum(axis=0) / max(finite.sum(), 1)
    else:
        guess = guess.copy()
    for _ in range(maxiter):
        dist = np.maximum(DIST_CLAMP, np.linalg.norm(w - guess, axis=1))
        inv = np.where(finite, 1.0 / dist, 0.0)
        nxt = (w * inv[:, None]).sum(axis=0) / inv.sum()
        movement = np.linalg.norm(guess - nxt)
        guess = nxt
        if movement <= tol:
            break
    return guess


def oma(
    rng: np.random.Generator, message: np.ndarray, noise_var: float
) -> np.ndarray:
    k, d = message.shape
    std = 1.0 / math.sqrt(2.0)
    h_r = rng.normal(0.0, std, (k, 1))
    h_i = rng.normal(0.0, std, (k, 1))
    n_r = rng.normal(0.0, math.sqrt(noise_var), (k, d))
    n_i = rng.normal(0.0, math.sqrt(noise_var), (k, d))
    return message + (h_r * n_r + h_i * n_i) / (h_r**2 + h_i**2)


def oma2(
    rng: np.random.Generator,
    message: np.ndarray,
    p_max: float = 10.0,
    noise_var: Optional[float] = None,
    threshold: float = 1.0,
) -> np.ndarray:
    k, d = message.shape
    std = 1.0 / math.sqrt(2.0)
    h_r = rng.normal(0.0, std, (k,))
    h_i = rng.normal(0.0, std, (k,))
    h_sq = h_r**2 + h_i**2
    p_upper = np.maximum((message**2).mean(axis=-1) / h_sq, threshold)
    gain = np.sqrt(p_max / p_upper)
    out = (message * gain[:, None]).sum(axis=0)
    if noise_var is not None:
        out = out + rng.normal(0.0, math.sqrt(noise_var / 2.0), (d,))
    return out


def gm(
    rng: np.random.Generator,
    w: np.ndarray,
    noise_var: Optional[float] = None,
    guess: Optional[np.ndarray] = None,
    maxiter: int = 1000,
    tol: float = 1e-5,
    p_max: float = 1.0,
) -> np.ndarray:
    w, finite = _exclude_nonfinite_rows(w)
    if guess is None:
        guess = w.sum(axis=0) / max(finite.sum(), 1)
    else:
        guess = guess.copy()
    # np.errstate: in the noise-dominated regime the AirComp GM can diverge
    # (the reference physics — torch produces Inf/NaN silently there); the
    # oracle must transcribe that semantics without NumPy's RuntimeWarnings,
    # which pytest escalates to errors for backends/ (pyproject).  The
    # guards are NARROW by design (round-4 advisor): the expressions that
    # consume a possibly-diverged ``guess``/``noisy`` are always masked,
    # but the message build and the oma2 channel are masked ONLY once the
    # iterate has demonstrably diverged (non-finite scaler) — before that
    # point a warning there is a genuine numeric bug and stays an error.
    for _ in range(maxiter):
        with np.errstate(over="ignore", invalid="ignore"):
            scaler = math.sqrt(float((guess**2).mean()))
            dist = np.maximum(DIST_CLAMP, np.linalg.norm(w - guess, axis=1))
        inv = np.where(finite, 1.0 / dist, 0.0)
        # nan-safe: NaN < x is False, so a NaN scaler is also guarded.  The
        # threshold marks divergence BEFORE the first masked overflow: msg
        # entries scale like scaler/DIST_CLAMP = 1e4*scaler, so their f32
        # squares overflow once scaler ~ 1e15 — no convergent federated
        # iterate is within 10 orders of magnitude of that norm.
        guard = (
            contextlib.nullcontext()
            if scaler < 1e15
            else np.errstate(over="ignore", invalid="ignore")
        )
        with guard:
            msg = np.concatenate(
                [w * inv[:, None], scaler * inv[:, None]], axis=1
            )
            noisy = oma2(
                rng, msg, p_max=p_max, noise_var=noise_var,
                threshold=500.0 * scaler**2,
            )
        with np.errstate(over="ignore", invalid="ignore"):
            nxt = noisy[:-1] / noisy[-1] * scaler
            movement = np.linalg.norm(guess - nxt)
        guess = nxt
        if movement <= tol:
            break
    return guess


def weightflip(w: np.ndarray, byz_size: int) -> np.ndarray:
    out = w.copy()
    s = w[:-byz_size].sum(axis=0)
    out[-byz_size:] = -w[-byz_size:] - 2.0 * s / byz_size
    return out


def bulyan(w: np.ndarray, honest_size: int) -> np.ndarray:
    """Oracle for the framework's batch Bulyan (an extension — the reference
    ships single-Krum only): theta = K - 2B lowest Krum scores selected, then
    per coordinate the beta = theta - 2B values closest to the selection's
    (lower-middle) median are averaged."""
    k = len(w)
    b = k - honest_size
    theta = k - 2 * b
    beta = theta - 2 * b
    if beta < 1:  # same K > 4B contract as the JAX path
        raise ValueError(
            f"bulyan needs K > 4B (K={k}, B={b} -> theta={theta}, beta={beta})"
        )
    idx = np.argsort(_krum_scores(w, honest_size))[:theta]
    sel = w[idx]
    med = median(sel)
    out = np.empty(w.shape[1], np.float32)
    for j in range(w.shape[1]):
        order = np.argsort(np.abs(sel[:, j] - med[j]), kind="stable")[:beta]
        out[j] = sel[order, j].mean()
    return out


def dnc(
    w: np.ndarray,
    honest_size: int,
    rng: np.random.Generator,
    dnc_iters: int = 3,
    dnc_sub_dim: int = 10000,
    dnc_c: float = 1.0,
) -> np.ndarray:
    """Oracle for the framework's DnC (an extension; Shejwalkar &
    Houmansadr NDSS 2021): per round, sample coordinates, center, score
    clients by squared projection onto the top singular vector, flag the
    ceil(c*B) highest; aggregate = mean of never-flagged clients.  Uses
    exact SVD where the jax path power-iterates — agreement is
    distributional (same flagged sets on well-separated stacks)."""
    k, d = w.shape
    b = k - honest_size
    n_remove = int(np.ceil(dnc_c * b))
    if n_remove * dnc_iters >= k:  # same contract as the jax path
        raise ValueError(
            f"dnc removes ceil(c*B)={n_remove} clients per round x "
            f"{dnc_iters} rounds but K={k}; need K > removals"
        )
    finite = np.isfinite(w).all(axis=1)
    keep = finite.copy()
    r = min(d, dnc_sub_dim)
    for _ in range(dnc_iters):
        cols = rng.integers(0, d, r)  # with replacement, as the jax path
        sub = np.where(finite[:, None], w[:, cols], 0.0)
        centered = sub - sub.sum(axis=0) / max(finite.sum(), 1)
        centered = np.where(finite[:, None], centered, 0.0)
        _, _, vt = np.linalg.svd(centered, full_matrices=False)
        scores = (centered @ vt[0]) ** 2
        # -Inf, as the jax path: the removal budget targets live rows
        scores = np.where(finite, scores, -np.inf)
        if n_remove:
            keep[np.argsort(scores)[-n_remove:]] = False
    if keep.any():
        return w[keep].mean(axis=0).astype(np.float32)
    return (
        np.where(finite[:, None], w, 0.0).sum(axis=0)
        / max(finite.sum(), 1)
    ).astype(np.float32)


def sign_majority_vote(
    w: np.ndarray,
    guess: np.ndarray,
    noise_var: Optional[float] = None,
    sign_eta: Optional[float] = None,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Oracle for the framework's one-bit OTA aggregator (an extension):
    new = guess + eta * sign(sum_i sign(w_i - guess) + n), eta = sign_eta or
    the coordinatewise LOWER-MIDDLE median of |w_i - guess| (torch
    order-statistic semantics, matching the jax path).  Non-finite deltas
    cast a 0 ballot and count as Inf for the eta median, as in the jax
    path."""
    delta = w - guess[None, :]
    finite = np.isfinite(delta)
    votes = np.where(finite, np.sign(delta), 0.0).sum(axis=0)
    if noise_var is not None:
        assert rng is not None
        votes = votes + rng.normal(
            0.0, np.sqrt(noise_var / 2.0), votes.shape
        )
    if sign_eta is None:
        absd = np.where(finite, np.abs(delta), np.inf)
        eta = np.sort(absd, axis=0)[(len(w) - 1) // 2]
        # mirror the jax path: an Inf median (>= ceil(K/2) non-finite
        # deltas, outside the B < K/2 contract) degrades to a no-op step
        # rather than Inf * sign(0) = NaN on tied votes
        eta = np.where(np.isfinite(eta), eta, 0.0)
    else:
        eta = np.float32(sign_eta)
    return (guess + eta * np.sign(votes)).astype(np.float32)


# ---------------------------------------------------------------------------
# packed one-bit sign wire (sign_bits=1) — oracles for the jax pipeline in
# ops/aggregators.py (pack_signs / packed_sign_votes) and the pallas
# popcount kernel.  Wire format: [K, W = ceil(d/32)] uint32, LSB-first
# (coordinate c at bit c % 32 of word c // 32); bit 1 = ballot +1
# (delta >= 0, +0.0 votes +1), bit 0 = ballot -1; a row with ANY
# non-finite coordinate packs all-zero words and leaves k_valid, so it
# casts zero ballots in both the packed and (row-coarsened) unpacked vote.


def pack_signs(w: np.ndarray, guess: np.ndarray):
    """Oracle packer: ``(words [K, ceil(d/32)] uint32, k_valid int)``."""
    delta = np.asarray(w, np.float32) - np.asarray(guess, np.float32)[None, :]
    finite = np.isfinite(delta).all(axis=1)
    k, d = delta.shape
    w_cnt = -(-d // 32)
    bits = np.zeros((k, w_cnt * 32), np.uint32)
    bits[:, :d] = (delta >= 0) & finite[:, None]
    weights = (np.uint32(1) << np.arange(32, dtype=np.uint32))
    words = (bits.reshape(k, w_cnt, 32) * weights).sum(
        axis=-1, dtype=np.uint64
    ).astype(np.uint32)
    return words, int(finite.sum())


def packed_vote_counts(words: np.ndarray, d: int) -> np.ndarray:
    """Oracle popcount reduce: per-coordinate set-bit counts [d] int64."""
    planes = (
        words[:, :, None] >> np.arange(32, dtype=np.uint32)[None, None, :]
    ) & np.uint32(1)
    return planes.sum(axis=0).reshape(-1)[:d].astype(np.int64)


def packed_sign_step(
    w: np.ndarray,
    guess: np.ndarray,
    sign_eta: float,
    noise: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Oracle for the sign_bits=1 signmv/bev step: pack, popcount, recover
    the signed ballot sum as ``2*counts - k_valid`` (each set bit +1, each
    clear bit of a valid row -1), step ``sign_eta`` in the voted
    direction.  ``noise`` is the receiver AWGN draw for signmv (bev, a
    receiver-side rung, passes None)."""
    words, k_valid = pack_signs(w, guess)
    counts = packed_vote_counts(words, w.shape[1])
    votes = (2 * counts - k_valid).astype(np.float64)
    if noise is not None:
        votes = votes + noise
    return (
        np.asarray(guess, np.float32)
        + np.float32(sign_eta) * np.sign(votes).astype(np.float32)
    )


def centered_clip(
    w: np.ndarray,
    guess: Optional[np.ndarray] = None,
    clip_tau: Optional[float] = None,
    clip_iters: int = 3,
) -> np.ndarray:
    """Oracle for the framework's centered-clipping aggregator (an
    extension; Karimireddy et al. 2021): v += mean(clip(w_i - v, tau)).
    ``clip_tau=None`` = adaptive per-step tau: the LOWER-MIDDLE median of
    the client delta norms (non-finite rows counted as +Inf, Inf median
    degraded to 0), matching the jax path."""
    w, finite = _exclude_nonfinite_rows(w)
    if guess is None:
        v = w.sum(axis=0) / max(finite.sum(), 1)
    else:
        v = np.asarray(guess, np.float64)
    for _ in range(clip_iters):
        delta = np.where(finite[:, None], w - v[None, :], 0.0)
        norms = np.maximum(np.linalg.norm(delta, axis=1), 1e-12)
        if clip_tau is None:
            srt = np.sort(np.where(finite, norms, np.inf))
            tau = srt[(len(w) - 1) // 2]
            tau = tau if np.isfinite(tau) else 0.0
        else:
            tau = clip_tau
        scale = np.minimum(1.0, tau / norms)
        v = v + (delta * scale[:, None]).mean(axis=0)
    return v.astype(np.float32)


def alie(w: np.ndarray, byz_size: int, z: float = 1.5) -> np.ndarray:
    """Oracle for the framework's ALIE attack: Byzantine rows at
    mu_honest - z * sigma_honest per coordinate."""
    out = w.copy()
    honest = w[:-byz_size]
    out[-byz_size:] = honest.mean(axis=0) - z * honest.std(axis=0)
    return out


def ipm(w: np.ndarray, byz_size: int, eps: float = 0.5) -> np.ndarray:
    """Oracle for the framework's IPM attack: Byzantine rows at
    -eps * mean(honest)."""
    out = w.copy()
    out[-byz_size:] = -eps * w[:-byz_size].mean(axis=0)
    return out


def _agr_row(honest: np.ndarray, predicate, iters: int = 25) -> np.ndarray:
    """Oracle bisection for the AGR-agnostic attacks (minmax/minsum): the
    malicious row mu + gamma * p, p = -mu/|mu|, with the largest gamma
    satisfying ``predicate(row)``."""
    mu = honest.mean(axis=0)
    p = -mu / max(np.linalg.norm(mu), 1e-12)
    diff = honest[:, None, :] - honest[None, :, :]
    pair = (diff**2).sum(axis=-1)
    dev = np.linalg.norm(honest - mu[None, :], axis=1)
    lo, hi = 0.0, float(np.sqrt(pair.max()) + dev.max() + 1.0)
    for _ in range(iters):
        mid = 0.5 * (lo + hi)
        if predicate(mu + mid * p, pair):
            lo = mid
        else:
            hi = mid
    return (mu + lo * p).astype(np.float32)


def _gamma_row(honest: np.ndarray, gamma: float) -> np.ndarray:
    # mu + gamma*p with p = -mu/|mu| — the fixed-gamma bypass of _agr_row
    mu = honest.mean(axis=0)
    return mu - gamma * mu / max(np.linalg.norm(mu), 1e-12)


def minmax(w: np.ndarray, byz_size: int, gamma: Optional[float] = None) -> np.ndarray:
    """Oracle for the framework's min-max AGR-agnostic attack."""
    out = w.copy()
    honest = w[:-byz_size]
    if gamma is not None:
        row = _gamma_row(honest, gamma)
    else:
        row = _agr_row(
            honest,
            lambda m, pair: ((honest - m) ** 2).sum(axis=1).max() <= pair.max(),
        )
    out[-byz_size:] = row
    return out


def minsum(w: np.ndarray, byz_size: int, gamma: Optional[float] = None) -> np.ndarray:
    """Oracle for the framework's min-sum AGR-agnostic attack."""
    out = w.copy()
    honest = w[:-byz_size]
    if gamma is not None:
        row = _gamma_row(honest, gamma)
    else:
        row = _agr_row(
            honest,
            lambda m, pair: ((honest - m) ** 2).sum() <= pair.sum(axis=1).max(),
        )
    out[-byz_size:] = row
    return out


def bev(
    w: np.ndarray,
    guess: np.ndarray,
    sign_eta: Optional[float] = None,
) -> np.ndarray:
    """Oracle for the framework's best-effort-voting rung (an extension;
    BEV-SGD, arXiv:2110.09660): new = guess + eta * sign(sum_i
    sign(w_i - guess)), equal-weight per-coordinate ballots.  eta =
    sign_eta or the coordinatewise LOWER-MIDDLE median of |w_i - guess|
    with non-finite deltas counted as +Inf (an Inf median degrades the
    coordinate to a no-op step), matching the jax path."""
    delta = w - guess[None, :]
    finite = np.isfinite(delta)
    votes = np.where(finite, np.sign(delta), 0.0).sum(axis=0)
    if sign_eta is None:
        absd = np.where(finite, np.abs(delta), np.inf)
        eta = np.sort(absd, axis=0)[(len(w) - 1) // 2]
        eta = np.where(np.isfinite(eta), eta, 0.0)
    else:
        eta = np.float32(sign_eta)
    return (guess + eta * np.sign(votes)).astype(np.float32)


def _masked_median(x: np.ndarray, mask: np.ndarray) -> float:
    srt = np.sort(np.where(mask, x, np.inf))
    return float(srt[max(int(mask.sum()) - 1, 0) // 2])


def defense_client_scores(
    w: np.ndarray, guess: np.ndarray
) -> "tuple[np.ndarray, np.ndarray]":
    """Oracle for ``defense/scores.client_scores``: per-client composite
    anomaly score (relative norm excess + direction disagreement +
    pairwise-distance excess), medians/centroid over finite rows only,
    non-finite rows scoring exactly 0."""
    finite = np.isfinite(w).all(axis=1)
    delta = (w - guess[None, :]).astype(np.float32)
    safe = np.where(finite[:, None], delta, 0.0)
    norms = np.sqrt((safe * safe).sum(axis=1))
    med_norm = _masked_median(norms, finite)
    norm_term = np.maximum(norms / max(med_norm, 1e-12) - 1.0, 0.0)
    cent = safe.sum(axis=0) / max(int(finite.sum()), 1)
    cent_norm = np.sqrt((cent * cent).sum())
    cos = (safe * cent[None, :]).sum(axis=1) / (
        np.maximum(norms, 1e-12) * max(cent_norm, 1e-12)
    )
    cos_term = np.maximum(1.0 - cos, 0.0)
    diff = w[:, None, :] - w[None, :, :]
    dists = (diff * diff).sum(axis=-1)
    pair_mask = finite[None, :] & ~np.eye(len(w), dtype=bool)
    n_others = np.maximum(pair_mask.sum(axis=1), 1)
    dist_mean = np.where(pair_mask, dists, 0.0).sum(axis=1) / n_others
    med_dist = _masked_median(dist_mean, finite)
    dist_term = np.maximum(dist_mean / max(med_dist, 1e-12) - 1.0, 0.0)
    score = np.where(finite, norm_term + cos_term + dist_term, 0.0)
    return score.astype(np.float32), finite


def mimic(
    w: np.ndarray, byz_size: int, ema: np.ndarray, cusum: np.ndarray
) -> np.ndarray:
    """Oracle for the framework's mimic attack (an extension; the ByzFL
    taxonomy's replay attacker): every Byzantine row replays the honest
    client the detector currently trusts most (minimal CUSUM, EMA as the
    tie-break)."""
    out = w.copy()
    honest = w[:-byz_size]
    h = len(honest)
    tgt = int(np.argmin(cusum[:h] + 1e-3 * ema[:h]))
    out[-byz_size:] = honest[tgt]
    return out


def under_radar(
    w: np.ndarray,
    byz_size: int,
    step: int,
    ema: np.ndarray,
    dev: np.ndarray,
    cusum: np.ndarray,
    guess: np.ndarray,
    *,
    alpha: float = 0.1,
    drift: float = 0.5,
    z_thresh: float = 4.0,
    cusum_thresh: float = 8.0,
    warmup: int = 5,
    clip: float = 3.0,
    eps: float = 1e-6,
    margin: float = 0.9,
    iters: int = 25,
) -> np.ndarray:
    """Oracle for the framework's under-the-radar attack (an extension):
    fixed-count bisection on the push distance gamma along the steered
    ALIE/IPM direction, landing the Byzantine rows' NEXT detector scores
    just under margin * the flag thresholds (instantaneous z AND the
    would-be CUSUM).  During detector warmup the constraint is vacuous
    and gamma runs to the top of the bracket."""
    honest = w[:-byz_size]
    mu = honest.mean(axis=0)
    sig = honest.std(axis=0)
    mu_n = max(np.linalg.norm(mu), 1e-12)
    sig_n = max(np.linalg.norm(sig), 1e-12)
    u = -(mu / mu_n + sig / sig_n)
    u = u / max(np.linalg.norm(u), 1e-12)
    warm = step >= warmup

    def stack_at(gamma):
        out = w.copy()
        out[-byz_size:] = mu + gamma * u
        return out

    def ok(gamma):
        if not warm:
            return True
        score, _ = defense_client_scores(stack_at(gamma), guess)
        z = (score - ema) / (dev + eps)
        cus = np.minimum(
            np.maximum(cusum + np.clip(z, -clip, clip) - drift, 0.0),
            2.0 * cusum_thresh,
        )
        return bool(
            (z[-byz_size:] <= margin * z_thresh).all()
            and (cus[-byz_size:] <= margin * cusum_thresh).all()
        )

    diff = honest[:, None, :] - honest[None, :, :]
    pair = (diff * diff).sum(axis=-1)
    lo, hi = 0.0, float(2.0 * (mu_n + sig_n) + np.sqrt(pair.max()))
    for _ in range(iters):
        mid = 0.5 * (lo + hi)
        if ok(mid):
            lo = mid
        else:
            hi = mid
    return stack_at(lo)
