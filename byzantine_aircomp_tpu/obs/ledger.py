"""Persisted performance ledger: turn bench snapshots into an enforced
trajectory.

The repo accumulates bench rows (``bench.py``, ``BENCH_r*.json``,
``docs/bench_tpu_*.json``) but until now nothing *read* them — a 2x
rounds/sec regression, or a silent CPU fallback posing as a TPU number
(the ``BENCH_r05`` blind spot), would ship unnoticed.  The ledger is the
machine that reads them:

* one JSONL file (``docs/perf_ledger.jsonl`` by default, appended
  through the existing :class:`obs.sinks.JsonlSink`) holding ``perf``
  rows — ``{metric, value, unit, platform, key, note, ts}``;
* baselines keyed on ``(metric, platform, key)`` where ``key`` encodes
  the config-relevant knobs (:func:`config_key`) — rows measured under
  different configs never average into one baseline;
* noise-robust statistics: median + MAD over the last N same-platform
  rows, so one outlier snapshot cannot move the baseline the way a mean
  would;
* a :func:`PerfLedger.compare` verdict: ``ok`` / ``regression`` /
  ``improvement`` / ``new_metric`` / ``platform_mismatch``.  The
  platform gate is absolute — a CPU-fallback row is NEVER compared
  against a TPU baseline; it either matches CPU history or comes back
  ``platform_mismatch``.

``analysis/perf_gate.py`` is the CLI that wires a bench row + this
ledger into a CI exit code.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

from .events import make_event
from .sinks import JsonlSink

#: default on-disk location, relative to the repo root
DEFAULT_LEDGER_PATH = "docs/perf_ledger.jsonl"

#: row fields that define a comparable configuration (sorted into ``key``);
#: deliberately excludes output-only knobs and per-run facts (timed_rounds,
#: ts, value) — mirrors the config_hash philosophy at bench granularity
CONFIG_KEY_FIELDS = ("k", "b", "agg", "attack", "dataset", "model",
                     "pop_shards")

#: descriptive row fields worth carrying INTO the ledger when present —
#: not part of the config key, but they make a row self-describing (the
#: stream_ksweep rows' peak-bytes columns live here: measured watermark
#: plus the obs/hbm.py streamed and resident models)
LEDGER_EXTRA_FIELDS = (
    "cohort_size",
    "d",
    "peak_measured_bytes",
    "peak_source",
    "peak_streamed_modeled_bytes",
    "peak_resident_modeled_bytes",
    # packed one-bit sign channel (ops/aggregators.pack_signs): modeled
    # wire/reduce traffic of the row's realization vs the f32 baseline it
    # replaces, and the payload width that produced it — the columns the
    # ~32x bandwidth acceptance gate reads (analysis/perf_gate.py)
    "bytes_moved",
    "bytes_moved_f32",
    "sign_bits",
    # service-mode stream_ksweep rows (BENCH_KSWEEP_SERVICE): rows record
    # k = population (the id space the round draws from), and carry the
    # per-host streamed model from obs/hbm.py when the round ran sharded
    # over the population mesh (pop_shards > 1 is part of the config key)
    "population",
    "peak_per_host_modeled_bytes",
    # multi-round dispatch tier (BENCH_MULTIROUND): how many rounds each
    # device dispatch scanned — the R axis of the dispatch-rim sweep the
    # ≥10x acceptance gate reads (the R value is also baked into the
    # metric name, so same-R rows regression-test against each other)
    "rounds_per_dispatch",
    # heterogeneity sweep rows (BENCH_HETERO): the Dirichlet level and
    # quantity-skew spec behind the row — the alpha label is also baked
    # into the metric name, so same-level rows regression-test against
    # each other while the columns keep the row self-describing
    "dirichlet_alpha",
    "size_skew",
)

#: relative band half-width tolerated as noise (±10%)
DEFAULT_REL_TOL = 0.10
#: MAD multiples folded into the band (1.4826 * MAD ~ sigma for normals)
DEFAULT_MAD_SIGMAS = 4.0
#: baseline window: last N same-(metric, platform, key) rows
DEFAULT_WINDOW = 10


def config_key(row: Dict[str, Any]) -> str:
    """Canonical config-knob key for a row: ``k=1000|b=100|agg=gm2|...``
    over whichever :data:`CONFIG_KEY_FIELDS` the row carries (sorted).
    Rows without any config fields (legacy ``BENCH_r*.json`` snapshots)
    key to ``""`` — treated as a wildcard by :meth:`PerfLedger.compare`
    so history predating the keying scheme stays comparable."""
    parts = [
        f"{f}={row[f]}"
        for f in sorted(CONFIG_KEY_FIELDS)
        if row.get(f) is not None
    ]
    return "|".join(parts)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_stats(values: List[float]) -> Dict[str, float]:
    """Median + MAD (median absolute deviation) of ``values``."""
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    return {"median": med, "mad": mad, "n": len(values)}


class PerfLedger:
    """Read/append/compare interface over one perf-ledger JSONL file."""

    def __init__(self, path: str = DEFAULT_LEDGER_PATH) -> None:
        self.path = path

    def rows(self) -> List[Dict[str, Any]]:
        """All parseable rows, in file order; malformed lines are skipped
        with a stderr note (a killed append may truncate the tail)."""
        out: List[Dict[str, Any]] = []
        try:
            fh = open(self.path)
        except OSError:
            return out
        with fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    print(
                        f"[ledger] skipping malformed line {i + 1} in "
                        f"{self.path}",
                        file=sys.stderr,
                    )
                    continue
                if isinstance(row, dict) and "metric" in row:
                    out.append(row)
        return out

    def append(
        self,
        metric: str,
        value: float,
        *,
        unit: str = "",
        platform: str = "",
        key: str = "",
        **extra: Any,
    ) -> Dict[str, Any]:
        """Append one ``perf`` row through a :class:`JsonlSink` (same
        append-one-line-and-flush durability as every event stream)."""
        event = make_event(
            "perf",
            metric=metric,
            value=value,
            unit=unit,
            platform=platform,
            key=key,
            **extra,
        )
        sink = JsonlSink(self.path)
        try:
            sink.emit(event)
        finally:
            sink.close()
        return event

    def history(
        self, metric: str, platform: str, key: str = ""
    ) -> List[float]:
        """Same-(metric, platform, key) values, file order (oldest first)."""
        return [
            float(r["value"])
            for r in self._candidates(metric, key)
            if r.get("platform") == platform and "value" in r
        ]

    def _candidates(self, metric: str, key: str) -> List[Dict[str, Any]]:
        rows = [r for r in self.rows() if r.get("metric") == metric]
        if not key:
            return rows
        # legacy rows with no key act as wildcards; a NON-empty key that
        # differs means a genuinely different config under the same metric
        # name — excluded from the baseline
        return [r for r in rows if r.get("key", "") in ("", key)]

    def compare(
        self,
        metric: str,
        value: float,
        *,
        platform: str,
        key: str = "",
        window: int = DEFAULT_WINDOW,
        rel_tol: float = DEFAULT_REL_TOL,
        mad_sigmas: float = DEFAULT_MAD_SIGMAS,
        higher_is_better: bool = True,
    ) -> Dict[str, Any]:
        """Verdict for a fresh measurement against the ledger.

        The noise band is ``max(rel_tol, mad_sigmas * 1.4826 * MAD /
        |median|)`` — at least ±``rel_tol`` relative (so a quiet
        synthetic history still tolerates ±10% jitter), widened when the
        recorded history is itself noisy.  ``ratio`` is value/median
        oriented so that < 1 is worse regardless of
        ``higher_is_better``.
        """
        verdict: Dict[str, Any] = {
            "metric": metric,
            "value": value,
            "platform": platform,
            "key": key,
        }
        candidates = self._candidates(metric, key)
        if not candidates:
            verdict["verdict"] = "new_metric"
            return verdict
        same_platform = [
            r for r in candidates if r.get("platform") == platform
        ]
        if not same_platform:
            # the BENCH_r05 blind spot: a cpu-fallback row must never be
            # scored against an accelerator baseline
            verdict["verdict"] = "platform_mismatch"
            verdict["baseline_platforms"] = sorted(
                {str(r.get("platform")) for r in candidates}
            )
            return verdict
        hist = [
            float(r["value"]) for r in same_platform if "value" in r
        ][-window:]
        stats = robust_stats(hist)
        med, mad = stats["median"], stats["mad"]
        verdict["baseline"] = {**stats, "window": window}
        if med == 0:
            verdict["verdict"] = "ok"  # degenerate baseline: nothing to scale
            return verdict
        raw_ratio = value / med
        ratio = raw_ratio if higher_is_better else 1.0 / raw_ratio
        band = max(rel_tol, mad_sigmas * 1.4826 * mad / abs(med))
        verdict["ratio"] = ratio
        verdict["band"] = band
        if ratio < 1.0 - band:
            verdict["verdict"] = "regression"
        elif ratio > 1.0 + band:
            verdict["verdict"] = "improvement"
        else:
            verdict["verdict"] = "ok"
        return verdict
