"""Scrape endpoint: /metrics (Prometheus text format) + /healthz.

A zero-dependency stdlib HTTP server on a daemon thread, so an always-on
service run (``--service on``) can be watched by any Prometheus-
compatible scraper — or plain curl — without adding a client library to
the image.  The server only READS the :class:`~.metrics.MetricsRegistry`
(whose lock makes each scrape a consistent point-in-time view); it never
touches the training thread, the event stream, or the record.

Lifecycle: the harness starts the exporter right after the registry is
built (so scrapes succeed while the first round is still compiling) and
closes it in the same ``finally`` that closes the sinks — run end AND
crash both shut the port down cleanly.  ``port=0`` binds an OS-assigned
ephemeral port (tests); the bound port is on ``.port`` after
``start()``.

``routes`` lets a caller mount extra endpoints on the same port without
subclassing the handler: a callable ``(method, raw_path, body, headers)
-> Optional[(status, content_type, body_bytes)]`` tried before the
built-in ``/metrics``/``/healthz`` handling (``None`` falls through).
``raw_path`` keeps the query string (the edge-root fold poll passes
epoch/edge as query params) and ``headers`` is a plain lower-cased dict
(the experiment server's bearer-token check reads ``authorization``).
The experiment server (``serve/server.py``) and the aggregation root
(``serve/root.py``) ride this hook so one socket serves both a control
plane and the scrape surface.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from .metrics import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: extra-route hook: (method, raw_path_with_query, body, headers) ->
#: (status, content_type, body) or None to fall through to the built-ins
RouteFn = Callable[[str, str, bytes, Dict[str, str]], Optional[tuple]]


class MetricsExporter:
    """Background /metrics + /healthz server over one registry."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "0.0.0.0",
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        routes: Optional[RouteFn] = None,
    ) -> None:
        self.registry = registry
        self._requested_port = port
        self._host = host
        self._health_fn = health_fn
        self._routes = routes
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # silence request spam
                pass

            def _reply(self, status, ctype, body) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _try_routes(self, method: str) -> bool:
                if exporter._routes is None:
                    return False
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                headers = {
                    k.lower(): v for k, v in self.headers.items()
                }
                hit = exporter._routes(method, self.path, body, headers)
                if hit is None:
                    return False
                self._reply(*hit)
                return True

            def do_POST(self) -> None:
                if not self._try_routes("POST"):
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def do_GET(self) -> None:
                if self._try_routes("GET"):
                    pass
                elif self.path.split("?", 1)[0] == "/metrics":
                    body = exporter.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.split("?", 1)[0] == "/healthz":
                    health = (
                        exporter._health_fn() if exporter._health_fn
                        else {"ok": True}
                    )
                    body = json.dumps(health).encode()
                    self.send_response(200 if health.get("ok") else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="aircomp-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
