"""Static HBM-traffic models, shared by benchmarks and the trainer.

The analytic per-aggregation byte model used to live inside
``benchmarks/agg_kernels.py``, so the microbench and the training harness
could silently disagree about what "single HBM pass" means.  It now lives
here: the benchmark imports :func:`epilogue_hbm_bytes` and the harness
reports the same accounting in its ``run_start`` event, so a regression
in either surface shows up against one model.

The models are STATIC — derived from shapes and the documented access
patterns (docs/DESIGN.md's epilogue section), not measured.  The
compile-time measured counterpart is ``benchmarks/hbm_compile.py``
(XLA's ``memory_analysis``), which answers the peak-allocation question;
this module answers the traffic question.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: aggregators whose epilogue is the sort-family selection the fused paths
#: realize (ops/aggregators.py dispatch)
SORT_FAMILY = ("median", "trimmed_mean")


def stack_bytes(k: int, d: int, dtype_bytes: int = 4) -> int:
    """Bytes of one [K, d] client stack."""
    return k * d * dtype_bytes


def packed_stack_bytes(k: int, d: int, bits: int = 1) -> int:
    """Bytes of the packed sign-channel payload for a [K, d] delta stack.

    ``bits=1`` is the bit-packed uint32 wire (``ops.aggregators
    .pack_signs``): K rows of ``ceil(d/32)`` whole words — ~1/32 of the
    f32 stack, the acceptance-gated ratio.  ``bits=8/16`` model the
    quantize-dequantize emulation's hypothetical wire (``k*d*bits/8``,
    exact since bytes need no word padding); ``bits=32`` degenerates to
    :func:`stack_bytes`."""
    if bits == 1:
        return k * (-(-d // 32)) * 4
    return k * d * bits // 8


def packed_vote_hbm_bytes(k: int, d: int, impl: str = "pallas") -> int:
    """Analytic HBM bytes of one packed majority-vote reduce.

    Both realizations read the [K, W] uint32 words exactly once.  The
    pallas kernel stores a [32, Wp] int32 counts tile per word column and
    the caller's transpose fix-up re-reads/writes it in coordinate order
    (O(d), counted honestly — it is ~K/32 times smaller than the word
    read); the XLA bit-plane fallback materializes the same [W, 32]
    counts.  Compare against ``stack_bytes(k, d) * 34`` (the f32 select
    reduce) or the 3-pass sort lower bound for the bandwidth table."""
    w_cnt = -(-d // 32)
    words = k * w_cnt * 4
    counts = 32 * w_cnt * 4  # [32, W] int32 counts (write + fix-up read)
    out = d * 4
    if impl == "pallas":
        kp, wp = -(-k // 8) * 8, -(-w_cnt // 128) * 128
        words = kp * wp * 4  # padded word tiles, DMA'd into VMEM once
        counts = 32 * wp * 4
    return words + 2 * counts + out


def epilogue_hbm_bytes(
    impl: str, k: int, d: int, b: int, channel: bool
) -> int:
    """Analytic HBM bytes per sort-family aggregation epilogue (f32).

    ``impl`` is one of ``sort`` (full XLA bitonic sort — a LOWER bound of
    3 stack-sized round trips), ``select`` (XLA key bisection: 32 cheap
    counting passes over int32 keys + one value pass), or ``pallas`` (the
    single-HBM-pass peel kernel: each padded tile is DMA'd into VMEM
    exactly once).  ``channel`` adds the OMA terms: the [K, d] noise pair
    folded into the fused reads, or the standalone read-modify-write pass
    the sort path pays first.
    """
    stack = k * d * 4
    out = d * 4
    if impl == "pallas":
        kp, dp = -(-k // 8) * 8, -(-d // 128) * 128
        tiles = (kp * dp * 4) * (3 if channel else 1)  # w (+ n_r, n_i)
        return tiles + out
    if impl == "select":
        # keys materialize once (stack read), 32 bisection count passes
        # re-read them, one final masked-sum pass reads values
        core = stack * 34
        if channel:
            core += 3 * stack  # n_r + n_i reads, post-channel stack write
        return core + out
    if impl == "sort":
        # sort: LOWER bound — read stack, write sorted, re-read kept band
        core = 3 * stack
        if channel:
            core += 4 * stack  # standalone OMA pass: read w, n_r, n_i, write
        return core + out
    raise ValueError(f"unknown epilogue impl {impl!r}")


def aggregator_hbm_model(
    agg: str,
    k: int,
    d: int,
    *,
    impl: str = "xla",
    fused: bool = False,
    channel: bool = False,
    trim: int = 0,
) -> Dict[str, Any]:
    """Per-round aggregation HBM accounting for the harness's run_start
    event.  Sort-family aggregators get the full epilogue model under the
    realization the trainer actually resolved (``fused`` + ``impl``);
    iterative aggregators (gm & co. re-read the stack once per Weiszfeld
    step — iteration count is data-dependent) report the per-iteration
    stack read and a null total."""
    sb = stack_bytes(k, d)
    if agg in SORT_FAMILY:
        impl_name = (
            ("pallas" if impl == "pallas" else "select") if fused else "sort"
        )
        hbm = epilogue_hbm_bytes(impl_name, k, d, trim, channel)
        return {
            "agg": agg,
            "impl": impl_name,
            "stack_bytes": sb,
            "hbm_bytes": hbm,
            "hbm_x": round(hbm / sb, 3),
        }
    return {
        "agg": agg,
        "impl": impl,
        "stack_bytes": sb,
        "hbm_bytes": None,
        "hbm_x": None,
        "bytes_per_weiszfeld_iter": sb,
    }


def streamed_peak_bytes(
    k: int,
    d: int,
    cohort: int,
    *,
    dtype_bytes: int = 4,
    data_bytes: int = 0,
    chunk_copies: int = 3,
    param_copies: int = 6,
    state_bytes_per_client: int = 0,
    pop_shards: int = 1,
) -> int:
    """Peak-allocation model for the COHORT-STREAMED round program
    (``--cohort-size > 0``) — the counterpart of :func:`modeled_peak_bytes`
    whose resident [K, d] stack term is replaced by the streamed carry:

    * ``chunk_copies`` [cohort, d] buffers — the rebuilt chunk, its
      per-chunk transform transient (attack/channel ``where``), and the
      per-client local-step batch working set, all of which XLA reuses
      across scan steps;
    * ``param_copies`` [d] f32 vectors — params plus the scan-carried
      streaming accumulators (sum_all / sum_finite / Weiszfeld num /
      bisection lo+hi rows);
    * ``state_bytes_per_client`` * K — the surviving O(K) per-client state
      (defense detector [K] rows, Gilbert-Elliott bools); 0 when those
      features are off;
    * ``data_bytes`` — the uploaded dataset, unchanged by streaming.

    Peak scales as O(cohort*d + d + K), never O(K*d): the quantity the
    K-sweep acceptance demo and the harness watermark cross-check read.

    ``pop_shards > 1`` turns this into the PER-HOST budget under the
    population mesh (``parallel/popmesh.py``).  The mesh divides the
    wall-clock chunk count, not the buffers: each owner scans its own
    chunk range with the same chunk/param/state working set because the
    carry is replicated rather than partitioned.  What sharding ADDS per
    host is the merge transient — one shard-ordered ``all_gather`` stacks
    the S per-shard partial carries (the [d] float accumulators and the
    per-client state rows) before the canonical fold — so those terms
    exist S-fold for the fold's lifetime.  The int-summed leaves (rank
    counts, sketch histograms, vote planes) merge by ``psum`` and never
    stack.  The result must be compared against the PER-DEVICE watermark
    (``obs/profile.py per_device_memory``), never a mesh-wide total.
    """
    chunk = cohort * d * dtype_bytes
    params = d * dtype_bytes
    peak = (
        chunk_copies * chunk
        + param_copies * params
        + state_bytes_per_client * k
        + data_bytes
    )
    if pop_shards > 1:
        peak += (pop_shards - 1) * (
            param_copies * params + state_bytes_per_client * k
        )
    return peak


def modeled_peak_bytes(
    k: int,
    d: int,
    *,
    dtype_bytes: int = 4,
    data_bytes: int = 0,
    stack_copies: int = 3,
    param_copies: int = 4,
) -> int:
    """Static peak-allocation model for the training program, the
    cross-check target for measured ``peak_bytes_in_use`` watermarks
    (``obs/profile.py``).

    The resident [K, d] stack dominates; ``stack_copies`` covers the
    worst transient (stack + perturbed/sorted copy + channel pair) and
    ``param_copies`` the [d] vectors (params, update, optimizer-ish
    temporaries).  ``data_bytes`` is the uploaded dataset.  Deliberately
    conservative and shape-only — the measured side
    (``benchmarks/hbm_compile.py``) answers the exact question; this
    model exists so a watermark wildly above it (factor
    ``hbm_warn_factor``) raises a flag on device-sourced measurements.
    """
    stack = stack_bytes(k, d, dtype_bytes)
    params = d * dtype_bytes
    return stack_copies * stack + param_copies * params + data_bytes
