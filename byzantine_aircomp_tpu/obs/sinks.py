"""Event sinks: where structured telemetry goes.

Every runner in this repo (harness, sweep, fault matrix, benchmarks)
emits the same schema-versioned JSON events (``obs.events``); a sink is
the one-way pipe those events leave through.  Four concrete sinks cover
the deployment matrix:

* :class:`JsonlSink` — one JSON object per line, appended to a file.
  Default mode appends + flushes EVERY line, so a run killed by a
  timeout (or piped through a dying consumer) keeps its tail up to the
  last completed event; ``atomic=True`` instead buffers and writes the
  whole file through :func:`utils.io.atomic_write` at close — for
  summary artifacts where a torn half-file is worse than no file.
* :class:`StdoutSink` — the same JSON lines on stdout, flushed per line
  (machine-readable pipe surface; human logs go to stderr / the log tee).
* :class:`MemorySink` — in-process list, for tests and programmatic
  callers.
* :class:`MultiSink` — fan-out to several sinks (e.g. stdout + file).

Sinks never mutate the events they are handed and never raise into the
training loop for a full disk mid-run — emit failures after a successful
open surface once as a warning on stderr and the sink disables itself.

Every concrete sink stamps a per-sink monotonic ``seq`` envelope key on a
COPY of each event before writing it (``ts`` is wall-clock and therefore
non-monotonic under resume/append — ``seq`` is the ordering key analysis
tools sort by).  :class:`JsonlSink` in append mode continues the counter
from the existing line count, so a resumed run's stream stays totally
ordered end to end.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, TextIO

from ..utils import io as io_lib


def _dumps(event: Dict[str, Any]) -> str:
    # compact separators: event streams are read by machines; allow
    # non-finite floats (benchmarks report NaN deltas deliberately)
    return json.dumps(event, separators=(",", ":"), default=str)


def rotated_segments(path: str) -> List[str]:
    """Rotated siblings of a live stream ``path``, oldest first.

    :class:`JsonlSink` size rotation renames the live file to
    ``<path>.0001``, ``<path>.0002``, ... — an extension that can never
    match the ``*.events.jsonl`` glob the analysis tools use to discover
    RUNS, so a rotated run still presents exactly one live path and the
    loaders pull the segments in via this helper."""
    import glob
    import os

    return sorted(
        p for p in glob.glob(path + ".[0-9][0-9][0-9][0-9]")
        if os.path.isfile(p)
    )


class EventSink:
    """Interface: ``emit`` one event dict; ``close`` flushes/releases."""

    def _stamp(self, event: Dict[str, Any]) -> Dict[str, Any]:
        """Return a COPY of ``event`` carrying this sink's next monotonic
        ``seq`` (the original dict is never mutated — the same event may be
        fanned out to several sinks, each with its own counter)."""
        seq = getattr(self, "_seq", 0)
        self._seq = seq + 1
        return {**event, "seq": seq}

    def emit(self, event: Dict[str, Any]) -> None:
        raise NotImplementedError

    def flush(self) -> None:  # pragma: no cover - trivial default
        pass

    def close(self) -> None:  # pragma: no cover - trivial default
        pass

    # context-manager sugar so scripts can ``with sink:``
    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NullSink(EventSink):
    """Drops everything — the obs-disabled path costs one method call."""

    def emit(self, event: Dict[str, Any]) -> None:
        pass


class MemorySink(EventSink):
    """Collects events in ``self.events`` (tests, programmatic callers)."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []

    def emit(self, event: Dict[str, Any]) -> None:
        self.events.append(self._stamp(event))

    def by_kind(self, kind: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e.get("kind") == kind]


class StdoutSink(EventSink):
    """One JSON line per event on stdout, flushed immediately."""

    def __init__(self, stream: Optional[TextIO] = None) -> None:
        self._stream = stream

    def emit(self, event: Dict[str, Any]) -> None:
        stream = self._stream or sys.stdout
        stream.write(_dumps(self._stamp(event)) + "\n")
        stream.flush()


class JsonlSink(EventSink):
    """Append-safe (default) or atomic-at-close JSONL file sink.

    Append mode writes each event as ONE ``write()`` call of a complete
    line and flushes, so a kill between events never leaves a torn line
    and concurrent appenders (multi-process sweeps sharing a file) never
    interleave partial records.  ``fresh`` records whether the file was
    empty/absent at construction — writers that lead with a header line
    (benchmarks/trajectory.py) key on it instead of re-implementing the
    ``tell() == 0`` dance.

    ``rotate_mb`` > 0 caps the live file: once a write carries it past
    the threshold it is renamed to the next ``<path>.NNNN`` segment and
    a fresh live file opens.  The in-memory ``seq`` counter keeps
    running across rotations (and a resumed sink counts lines across
    ALL segments), so the multi-segment stream keeps one monotonic
    ``seq`` envelope and the seq-ordered loaders read it unchanged.
    Always-on service runs stay bounded per file instead of growing one
    unbounded JSONL.
    """

    def __init__(
        self, path: str, atomic: bool = False, rotate_mb: float = 0.0
    ) -> None:
        import os

        self.path = path
        self._atomic = atomic
        self._failed = False
        self._rotate_bytes = int(rotate_mb * 2**20)
        segments = rotated_segments(path) if not atomic else []
        self.fresh = (
            not os.path.exists(path) or os.path.getsize(path) == 0
        ) and not segments
        if atomic:
            self._rows: List[str] = []
            self._fh: Optional[TextIO] = None
        else:
            self._fh = io_lib.open_append(path)
            if not self.fresh:
                # resume/append: continue ``seq`` from the existing line
                # count — across rotated segments — so the stream stays
                # totally ordered across restarts
                try:
                    n = 0
                    for p in segments + [path]:
                        if os.path.exists(p):
                            with open(p, "r") as fh:
                                n += sum(1 for _ in fh)
                    self._seq = n
                except OSError:
                    pass

    def emit(self, event: Dict[str, Any]) -> None:
        if self._failed:
            return
        line = _dumps(self._stamp(event))
        try:
            if self._atomic:
                self._rows.append(line)
            else:
                assert self._fh is not None
                self._fh.write(line + "\n")
                self._fh.flush()
                if (
                    self._rotate_bytes
                    and self._fh.tell() >= self._rotate_bytes
                ):
                    self._rotate()
        except OSError as e:  # disk full mid-run: degrade, don't kill training
            self._failed = True
            print(
                f"[obs] WARNING: event sink {self.path} failed ({e}); "
                "further events dropped",
                file=sys.stderr,
            )

    def _rotate(self) -> None:
        """Rename the live file to the next numbered segment and reopen."""
        import os

        assert self._fh is not None
        self._fh.close()
        existing = rotated_segments(self.path)
        nxt = 1
        if existing:
            nxt = int(existing[-1].rsplit(".", 1)[1]) + 1
        os.replace(self.path, f"{self.path}.{nxt:04d}")
        self._fh = io_lib.open_append(self.path)

    def flush(self) -> None:
        if self._fh is not None and not self._failed:
            self._fh.flush()

    def close(self) -> None:
        if self._atomic:
            if self._rows and not self._failed:
                rows = self._rows
                io_lib.atomic_write(
                    self.path,
                    lambda f: f.write("".join(r + "\n" for r in rows)),
                    mode="w",
                )
            self._rows = []
        elif self._fh is not None:
            self._fh.close()
            self._fh = None


class MultiSink(EventSink):
    """Fan one event stream out to several sinks."""

    def __init__(self, sinks: List[EventSink]) -> None:
        self.sinks = list(sinks)

    def emit(self, event: Dict[str, Any]) -> None:
        for s in self.sinks:
            s.emit(event)

    def flush(self) -> None:
        for s in self.sinks:
            s.flush()

    def close(self) -> None:
        for s in self.sinks:
            s.close()
