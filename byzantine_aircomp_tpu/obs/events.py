"""Event schema: one versioned shape for every runner's telemetry.

Before this module each tool invented its own JSON: the harness pickled
reference-format path lists, the sweep printed ad-hoc cell rows, and each
benchmark hand-rolled its emission.  Now every event is a flat JSON object
with three reserved keys —

* ``v``    — integer schema version (:data:`SCHEMA_VERSION`), bumped on any
  breaking field change so downstream loaders can dispatch;
* ``kind`` — the event type (``run_start``, ``round``, ``span``, ``retrace``,
  ``run_end``, ``bench``, ``sweep_cell``, ``fault_cell``, ...);
* ``ts``   — wall-clock epoch seconds at emission (gap analysis only;
  NEVER used for metrics — durations come from span events — and NEVER
  used for ordering: wall-clock is non-monotonic under resume/append).

Sinks additionally stamp a fourth envelope key at emission time:

* ``seq``  — per-sink monotonic sequence number (``obs/sinks.py``).  A
  JSONL sink reopened in append mode continues from the existing line
  count, so ``seq`` is the total order analysis tools sort by even when a
  resumed run interleaves wall-clock timestamps.  It is stamped by the
  sink (on a copy — sinks never mutate events), so events validated
  before emission legitimately lack it; ``validate_event`` treats it as
  optional.

A fourth reserved key is stamped at ``make_event`` time:

* ``host_id`` — the emitting process's ``jax.process_index()`` (0 when
  jax is absent, uninitialized, or single-process).  ``seq`` is only
  per-SINK monotonic; on a multi-host population mesh each process
  appends its own stream, and the analysis loaders merge them into one
  total order by ``(host_id, seq)`` (``analysis/obs_report.py``,
  ``analysis/tail.py``).  Old v<5 streams lack the key; loaders default
  it to 0.

The per-round ``round`` event mirrors — field for field — the reference
pickled record the harness still writes (bitwise untouched; the event
stream is written ALONGSIDE it).  :data:`REFERENCE_KEY_MAP` is the
machine-readable statement of that mapping, including the intentional
``variencePath`` spelling the reference's draw.ipynb consumes.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from . import trace

# v2: added the sink-stamped ``seq`` envelope key and the forensics kinds
# ``client_flag`` / ``forensic_dump`` (obs/forensics.py).
# v3: added the live-telemetry kinds ``alert`` (obs/alerts.py SLO rule
# transitions) and ``metrics_snapshot`` (end-of-run registry dump from
# obs/metrics.py).  Any change to a kind's required fields MUST bump this
# — tests/test_schema.py pins a golden fingerprint per version and fails
# CI on silent drift (``python tests/test_schema.py --regen`` prints the
# new golden row and the doc table stubs a bump requires).
# v4: added the multi-tenant serving kinds ``run_submitted`` /
# ``run_cancelled`` / ``knob_swap`` (serve/runs.py control-plane audit
# trail — every tenant-visible state change lands in the run's own
# event stream).
# v5: added the ``host_id`` envelope key (``jax.process_index()`` at
# emission, 0 off-mesh) so multi-host population-sharded runs whose
# processes each append their own stream can be merged into one total
# order by ``(host_id, seq)`` — ``seq`` alone is only per-sink monotonic,
# and two hosts' sinks both start at 0.
# v6: added the crash-safety serving kinds ``run_failed`` (a lane
# quarantined by the BatchRunner health guards, or a run whose retries
# are exhausted — exactly one per failed run), ``run_requeued`` (the
# watchdog cancelled a wedged run and scheduled a bounded-backoff
# retry), and ``journal_replay`` (a restarted server re-adopted this run
# from the durable journal — ``status`` says resumed/restarted and
# ``round`` the checkpoint it resumes from).
# v7: added the 2-tier aggregation kinds (serve/root.py): ``edge_partial``
# (one accepted, HMAC-verified wire partial — ``bytes`` is its raw
# ingress size, the quantity the perf ledger's bytes/round row sums),
# ``edge_reject`` (a zero-trust rejection: ``reason`` is bad_mac /
# replay — attacker-producible, never contained — or bad_round /
# bad_seq, authenticated violations that accrue strikes),
# ``edge_quarantine`` (an edge contained — partial_timeout,
# bad_payload, nonfinite_partial, result_mismatch, strike_limit), and
# ``edge_round`` (a round closed over the live set; ``degraded`` marks
# a surviving-edge fold).
# v8: added the defense auto-tuner kinds (tune/tuner.py): ``tune_candidate``
# (one scored candidate — its knob params, the paired-lane fold's
# precision/recall/benign false-flag rate, and the scalar objective),
# ``tune_generation`` (one successive-halving generation closed:
# population, per-generation round budget, promoted survivor count), and
# ``tune_result`` (the tune's winner — exactly one per completed tune,
# carrying the tuned constants the artifact file persists).
# v9: added the elastic-scheduling kinds (serve/runs.py + serve/elastic.py):
# ``lane_group`` (one per group round boundary, scheduler-scoped — group
# width, live-lane count, the occupancy ratio the >90% acceptance gauge
# reads, and the admission-queue depth behind it) and ``lane_refill`` (a
# drained lane's slot reseated from the admission queue mid-group: which
# lane, the incoming tenant's own resume round, and the group round the
# splice landed at — the journal's ``refill`` op is the durable twin).
# v10: added the optional trace-context envelope keys ``trace_id`` (32-hex,
# shared by every event one logical request touches, across processes),
# ``span_id`` (16-hex — on a ``span`` event the span's own id, on any
# other event the enclosing span at emission), and ``parent_span_id``
# (the span this one nests under; absent on trace roots).  Stamped by
# ``make_event`` only while an ``obs.trace`` context is active — with
# ``--trace off`` (the default) nothing activates the context, so
# streams stay byte-identical to v9 modulo this version bump.  No kind's
# required fields changed, so the fingerprint matches v9's (the v5
# precedent: envelope-only additions).
SCHEMA_VERSION = 10

# round-event field -> reference pickled-record key it mirrors
# (round r's event carries metrics the record stores at index r+1 for the
# eval paths — index 0 is the pre-training eval — and index r for the
# per-round paths; see docs/OBSERVABILITY.md)
REFERENCE_KEY_MAP = {
    "train_loss": "trainLossPath",
    "train_acc": "trainAccPath",
    "val_loss": "valLossPath",
    "val_acc": "valAccPath",
    "variance": "variencePath",  # sic — reference spelling, kept verbatim
    "rounds_per_sec": "roundsPerSec",
    "dropped": "faultDroppedPath",
    "erased": "faultErasedPath",
    "corrupt": "faultCorruptPath",
    "effective_k": "effectiveKPath",
    # service-round fields (kind "round" under --service on; the round
    # closes at its deadline, so these are per-round participation
    # telemetry — see fed/train.py service_metrics)
    "available": "serviceAvailPath",
    "absent": "serviceAbsentPath",
    "late": "serviceLatePath",
    # defense-event fields (kind "defense"; defense/events.PATH_KEYS is the
    # authoritative copy — tests/test_defense.py pins the two in sync)
    "rung": "defenseRungPath",
    "flagged": "defenseFlaggedPath",
    "suspicious_iters": "defenseSuspiciousPath",
    "score_max": "defenseScorePath",
    "cusum_max": "defenseCusumPath",
    "transitions": "defenseTransitionsPath",
}

# per-kind required fields (beyond the reserved v/kind/ts trio); kinds not
# listed here are free-form carriers (bench rows keep their historical keys)
_REQUIRED: Dict[str, tuple] = {
    "run_start": ("title", "backend", "rounds", "start_round"),
    "round": ("round", "val_loss", "val_acc", "variance"),
    "span": ("name", "ms"),
    "retrace": ("counts", "steady_state_ok"),
    "run_end": ("elapsed_secs", "rounds_run"),
    "defense": ("round", "rung", "flagged"),
    # service rounds (fed/train.py): per-round participation summary and
    # the (rare) warm-rollback restore event
    "participation": ("round", "available", "absent", "late", "effective_k"),
    "rollback": ("round", "restored_round", "reason", "epoch"),
    # measurement layer (obs/profile.py, obs/ledger.py)
    "profile": ("dir",),
    "perf": ("metric", "value", "platform"),
    # client-level forensics (obs/forensics.py): one event per suspicious
    # client per round (``client`` is the stable population id under
    # --service on, the stack row otherwise), and the flight-recorder
    # dump notice pointing at the flight_<round>.json artifact
    "client_flag": ("round", "client", "score", "rung", "flagged"),
    "forensic_dump": ("round", "path", "reason", "window"),
    # live telemetry (obs/metrics.py, obs/alerts.py): an SLO rule edge
    # (``firing`` True on breach, False on clear — steady state is NOT
    # re-emitted every round) and the end-of-run metrics-registry dump
    "alert": ("round", "rule", "severity", "value", "firing"),
    "metrics_snapshot": ("round", "metrics"),
    # multi-tenant serving (serve/runs.py): control-plane audit events in
    # the run's own stream — submission (with the batch-group signature),
    # cancellation (at which round the lane went dark), and each accepted
    # between-rounds knob hot-swap
    "run_submitted": ("run_id", "title", "signature"),
    "run_cancelled": ("run_id", "round"),
    "knob_swap": ("run_id", "round", "knob", "value"),
    # crash-safe serving (serve/runs.py, serve/journal.py): quarantine /
    # watchdog terminal failure (exactly one per failed run, with the
    # machine-readable reason), the watchdog's bounded-backoff requeue
    # notice, and the journal-replay adoption marker a restarted server
    # writes into each re-adopted run's stream
    "run_failed": ("run_id", "round", "reason"),
    "run_requeued": ("run_id", "round", "retries", "reason"),
    "journal_replay": ("run_id", "status", "round"),
    # elastic lane scheduling (serve/runs.py group loop): the per-round
    # group occupancy sample the >90% acceptance gauge reads, and the
    # mid-group reseat of a drained lane from the admission queue
    "lane_group": ("round", "lanes", "live", "occupancy", "queue_depth"),
    "lane_refill": ("run_id", "lane", "round", "group_round"),
    # 2-tier aggregation (serve/root.py): the root's zero-trust audit
    # trail — accepted partials (with wire bytes for the ingress ledger),
    # rejections (reason: bad_mac/replay/...), edge containment, and the
    # per-round fleet close (degraded marks a surviving-edge fold)
    "edge_partial": ("round", "edge", "seq", "bytes"),
    "edge_reject": ("edge", "reason"),
    "edge_quarantine": ("edge", "reason"),
    "edge_round": ("round", "epoch", "edges", "degraded", "ingress_bytes"),
    # defense auto-tuner (tune/tuner.py): one event per scored candidate
    # (paired benign+attacked lane fold), one per closed generation, and
    # exactly one tune_result carrying the winning constants
    "tune_candidate": ("gen", "candidate", "objective", "precision",
                       "recall", "benign_flag_rate"),
    "tune_generation": ("gen", "population", "rounds", "survivors"),
    "tune_result": ("generations", "objective", "params"),
}


def _host_id() -> int:
    """The emitting process's mesh rank — 0 unless a multi-process jax
    runtime is up.  Resolved lazily per event (not at import) so a late
    ``parallel.multihost.initialize`` is still reflected, and guarded so
    event emission never depends on jax being importable."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def make_event(kind: str, **fields: Any) -> Dict[str, Any]:
    """Stamp ``fields`` into a schema-versioned event dict.

    While a trace context is active (``obs.trace`` — only ever under
    ``--trace on``) the envelope additionally carries ``trace_id`` and,
    when the context names an enclosing span, ``span_id``.  Explicit
    ``fields`` win — a span event's own ids are never overwritten.
    """
    event: Dict[str, Any] = {
        "v": SCHEMA_VERSION,
        "kind": kind,
        "ts": time.time(),
        "host_id": _host_id(),
    }
    ctx = trace.current()
    if ctx is not None:
        event["trace_id"] = ctx[0]
        if ctx[1] is not None:
            event["span_id"] = ctx[1]
    event.update(fields)
    return event


def validate_event(event: Dict[str, Any]) -> Dict[str, Any]:
    """Raise ``ValueError`` unless ``event`` is schema-valid; returns it."""
    for key in ("v", "kind", "ts"):
        if key not in event:
            raise ValueError(f"event missing reserved key {key!r}: {event}")
    if event["v"] != SCHEMA_VERSION:
        raise ValueError(
            f"schema version {event['v']} != {SCHEMA_VERSION}: {event}"
        )
    missing = [
        k for k in _REQUIRED.get(event["kind"], ()) if k not in event
    ]
    if missing:
        raise ValueError(
            f"{event['kind']} event missing fields {missing}: {event}"
        )
    return event


class Collector:
    """Turns the trainer's per-round metrics (the jitted round's
    ``RoundMetrics`` scalars plus the fault counters) into ``round``
    events on a sink.

    The trainer hands over exactly what it appends to the
    reference-compatible path lists, so the two streams cannot drift:
    one code path computes the numbers, the collector only reshapes.
    """

    def __init__(self, sink) -> None:
        self._sink = sink

    def round_event(
        self,
        round_idx: int,
        *,
        train_loss: float,
        train_acc: float,
        val_loss: float,
        val_acc: float,
        variance: float,
        round_secs: Optional[float] = None,
        rounds_per_sec: Optional[float] = None,
        compiled: Optional[bool] = None,
        fault_metrics: Optional[Dict[str, float]] = None,
        service_metrics: Optional[Dict[str, float]] = None,
        memory: Optional[Dict[str, Any]] = None,
    ) -> None:
        fields: Dict[str, Any] = dict(
            round=round_idx,
            train_loss=train_loss,
            train_acc=train_acc,
            val_loss=val_loss,
            val_acc=val_acc,
            variance=variance,
        )
        if round_secs is not None:
            fields["round_secs"] = round_secs
        if rounds_per_sec is not None:
            fields["rounds_per_sec"] = rounds_per_sec
        if compiled is not None:
            fields["compiled"] = compiled
        if fault_metrics:
            fields.update(fault_metrics)
        if service_metrics:
            fields.update(service_metrics)
        if memory:
            # watermark trio from obs.profile.device_memory — flat fields,
            # with mem_source labeling device allocator stats vs host RSS
            fields["bytes_in_use"] = memory.get("bytes_in_use")
            fields["peak_bytes_in_use"] = memory.get("peak_bytes_in_use")
            fields["mem_source"] = memory.get("source")
        self._sink.emit(make_event("round", **fields))
