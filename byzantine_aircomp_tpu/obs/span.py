"""``span()`` — wall-clock timing of named phases, emitted as events.

The training loop's phases (compile vs steady-state rounds, eval,
checkpointing, setup/data loading) each get a ``span`` event with a
monotonic-clock duration.  JAX dispatch is asynchronous, so a span that
should charge device work to itself must end on a
``jax.block_until_ready`` barrier — pass the arrays (or a thunk
returning them) as ``sync=``; spans around host-side work omit it and
cost two clock reads.

The context manager yields a mutable dict: fields set on it inside the
body land on the emitted event (e.g. the round span's ``compiled`` flag,
known only after the body has run).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

from .events import make_event


class SpanTimer:
    def __init__(self, sink) -> None:
        self._sink = sink

    @contextlib.contextmanager
    def span(
        self, name: str, sync: Optional[Any] = None, **fields: Any
    ) -> Iterator[Dict[str, Any]]:
        extra: Dict[str, Any] = dict(fields)
        t0 = time.perf_counter()
        try:
            yield extra
            if sync is not None:
                import jax

                jax.block_until_ready(sync() if callable(sync) else sync)
        except BaseException:
            # a span interrupted by an exception still reports, flagged —
            # the tail of a crashed run is exactly when timing data matters
            extra.setdefault("error", True)
            ms = (time.perf_counter() - t0) * 1e3
            self._sink.emit(make_event("span", name=name, ms=round(ms, 3), **extra))
            raise
        ms = (time.perf_counter() - t0) * 1e3
        self._sink.emit(make_event("span", name=name, ms=round(ms, 3), **extra))
