"""``span()`` — wall-clock timing of named phases, emitted as events.

The training loop's phases (compile vs steady-state rounds, eval,
checkpointing, setup/data loading) each get a ``span`` event with a
monotonic-clock duration.  JAX dispatch is asynchronous, so a span that
should charge device work to itself must end on a
``jax.block_until_ready`` barrier — pass the arrays (or a thunk
returning them) as ``sync=``; spans around host-side work omit it and
cost two clock reads.

The context manager yields a mutable dict: fields set on it inside the
body land on the emitted event (e.g. the round span's ``compiled`` flag,
known only after the body has run).

With ``traced`` set (the ``--trace on`` knob), every span additionally
mints a 16-hex ``span_id``, inherits ``trace_id`` from the ambient
context (minting a fresh trace when it is the first span), records the
enclosing span as ``parent_span_id``, and pushes itself onto the
context-local parent stack for the body's duration — so spans nest and
any event emitted inside the body is stamped with the enclosing span
(see ``obs/trace.py``).  Untraced (the default), none of that runs and
the emitted event is byte-identical to the historical shape.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

from . import trace as trace_lib
from .events import make_event


class SpanTimer:
    def __init__(self, sink) -> None:
        self._sink = sink
        # flipped by Observability.from_config under --trace on; an
        # output-only knob, so it never forks config_hash or records
        self.traced = False

    @contextlib.contextmanager
    def span(
        self, name: str, sync: Optional[Any] = None, **fields: Any
    ) -> Iterator[Dict[str, Any]]:
        extra: Dict[str, Any] = dict(fields)
        token = None
        if self.traced:
            ctx = trace_lib.current()
            if "trace_id" not in extra:
                extra["trace_id"] = (
                    ctx[0] if ctx is not None else trace_lib.new_trace_id()
                )
            if (
                "parent_span_id" not in extra
                and ctx is not None
                and ctx[1] is not None
                and ctx[0] == extra["trace_id"]
            ):
                extra["parent_span_id"] = ctx[1]
            extra["span_id"] = trace_lib.new_span_id()
            token = trace_lib.push(extra["trace_id"], extra["span_id"])
        t0 = time.perf_counter()
        try:
            yield extra
            if sync is not None:
                import jax

                jax.block_until_ready(sync() if callable(sync) else sync)
        except BaseException:
            # a span interrupted by an exception still reports, flagged —
            # the tail of a crashed run is exactly when timing data matters
            extra.setdefault("error", True)
            ms = (time.perf_counter() - t0) * 1e3
            if token is not None:
                trace_lib.pop(token)
                token = None
            self._sink.emit(make_event("span", name=name, ms=round(ms, 3), **extra))
            raise
        finally:
            if token is not None:
                trace_lib.pop(token)
        ms = (time.perf_counter() - t0) * 1e3
        self._sink.emit(make_event("span", name=name, ms=round(ms, 3), **extra))
