"""Observability: structured telemetry across the AirComp stack.

One subsystem replaces the scattered per-tool emission the repo grew —
pickled path lists here, hand-rolled JSONL there, ad-hoc stdout ``log()``
lines everywhere:

* :mod:`.sinks`   — where events go (JSONL file / stdout / memory / fan-out)
* :mod:`.events`  — the schema-versioned event shapes + the reference-record
  field mapping
* :mod:`.span`    — phase timing (compile vs steady-state, eval, checkpoint)
* :mod:`.retrace` — lowering counters that catch steady-state recompilation
* :mod:`.hbm`     — static HBM-traffic models shared by benchmarks and trainer
* :mod:`.forensics` — per-client flag provenance (in-jit top-M extraction,
  ``client_flag`` events) + the host-side flight recorder
* :mod:`.profile` — jax.profiler device traces + memory watermarks
* :mod:`.ledger`  — persisted perf ledger with noise-robust regression verdicts

:class:`Observability` is the façade the harness/trainer thread through:
``obs.span(...)`` / ``obs.round(...)`` / ``obs.emit(...)``.  The disabled
path is :data:`NULL` (a null sink) — with ``--obs-dir``/``--obs-stdout``
unset no file is touched, no event is built beyond a dict that is
immediately dropped, and the training program (trace, RNG stream, pickled
record) is bit-identical to a build without this package.
"""

from __future__ import annotations

import os
from typing import Optional

from .events import (  # noqa: F401
    REFERENCE_KEY_MAP,
    SCHEMA_VERSION,
    Collector,
    make_event,
    validate_event,
)
from .forensics import FlightRecorder, emit_round_flags  # noqa: F401
from .ledger import PerfLedger, config_key, robust_stats  # noqa: F401
from .profile import (  # noqa: F401
    NULL_PROFILER,
    Profiler,
    device_memory,
    parse_rounds,
)
from .retrace import RetraceDetector, RetraceError  # noqa: F401
from .sinks import (  # noqa: F401
    EventSink,
    JsonlSink,
    MemorySink,
    MultiSink,
    NullSink,
    StdoutSink,
)
from .span import SpanTimer


class Observability:
    """Façade bundling a sink with the span timer and round collector."""

    def __init__(self, sink: EventSink) -> None:
        self.sink = sink
        self.enabled = not isinstance(sink, NullSink)
        self._spans = SpanTimer(sink)
        self.collector = Collector(sink)

    def emit(self, kind: str, **fields) -> None:
        self.sink.emit(make_event(kind, **fields))

    def span(self, name: str, sync=None, **fields):
        return self._spans.span(name, sync=sync, **fields)

    def round(self, round_idx: int, **metrics) -> None:
        self.collector.round_event(round_idx, **metrics)

    def close(self) -> None:
        self.sink.close()


#: the disabled singleton — shared, stateless, close() is a no-op
NULL = Observability(NullSink())


def events_path(obs_dir: str, title: str) -> str:
    """The per-run event-stream file: keyed on the ckpt title (run title +
    config hash) so a resumed run APPENDS to its own stream and two
    different configs can never interleave one file."""
    return os.path.join(obs_dir, f"{title}.events.jsonl")


def from_config(cfg, title: str) -> Observability:
    """Build the configured Observability for a run (``NULL`` when both
    ``obs_dir`` and ``obs_stdout`` are unset)."""
    sinks = []
    if getattr(cfg, "obs_dir", ""):
        sinks.append(JsonlSink(events_path(cfg.obs_dir, title)))
    if getattr(cfg, "obs_stdout", False):
        sinks.append(StdoutSink())
    if not sinks:
        return NULL
    return Observability(sinks[0] if len(sinks) == 1 else MultiSink(sinks))
