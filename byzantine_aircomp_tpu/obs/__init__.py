"""Observability: structured telemetry across the AirComp stack.

One subsystem replaces the scattered per-tool emission the repo grew —
pickled path lists here, hand-rolled JSONL there, ad-hoc stdout ``log()``
lines everywhere:

* :mod:`.sinks`   — where events go (JSONL file / stdout / memory / fan-out)
* :mod:`.events`  — the schema-versioned event shapes + the reference-record
  field mapping
* :mod:`.span`    — phase timing (compile vs steady-state, eval, checkpoint)
* :mod:`.retrace` — lowering counters that catch steady-state recompilation
* :mod:`.hbm`     — static HBM-traffic models shared by benchmarks and trainer
* :mod:`.forensics` — per-client flag provenance (in-jit top-M extraction,
  ``client_flag`` events) + the host-side flight recorder
* :mod:`.profile` — jax.profiler device traces + memory watermarks
* :mod:`.ledger`  — persisted perf ledger with noise-robust regression verdicts
* :mod:`.metrics` — live in-process metrics registry fed by the event stream
* :mod:`.exporter` — Prometheus-text /metrics + /healthz scrape endpoint
* :mod:`.alerts`  — declarative SLO rules evaluated each round on the registry

:class:`Observability` is the façade the harness/trainer thread through:
``obs.span(...)`` / ``obs.round(...)`` / ``obs.emit(...)``.  The disabled
path is :data:`NULL` (a null sink) — with ``--obs-dir``/``--obs-stdout``
unset no file is touched, no event is built beyond a dict that is
immediately dropped, and the training program (trace, RNG stream, pickled
record) is bit-identical to a build without this package.
"""

from __future__ import annotations

import os
from typing import Optional

from .events import (  # noqa: F401
    REFERENCE_KEY_MAP,
    SCHEMA_VERSION,
    Collector,
    make_event,
    validate_event,
)
from .alerts import AlertEngine, load_rules  # noqa: F401
from .exporter import MetricsExporter  # noqa: F401
from .forensics import FlightRecorder, emit_round_flags  # noqa: F401
from .ledger import PerfLedger, config_key, robust_stats  # noqa: F401
from .metrics import (  # noqa: F401
    LabeledRegistry,
    MetricsRegistry,
    MetricsSink,
)
from .profile import (  # noqa: F401
    NULL_PROFILER,
    Profiler,
    device_memory,
    parse_rounds,
)
from .retrace import RetraceDetector, RetraceError  # noqa: F401
from .sinks import (  # noqa: F401
    EventSink,
    JsonlSink,
    MemorySink,
    MultiSink,
    NullSink,
    StdoutSink,
)
from .span import SpanTimer
from . import trace  # noqa: F401
from .writer import AsyncSink, WriterThread, resolve_async  # noqa: F401


class Observability:
    """Façade bundling a sink with the span timer and round collector.

    The live-telemetry attachments are optional and host-side only:
    ``registry``/``metrics_sink`` when ``--metrics`` is on (the sink
    rides in the ordinary fan-out), ``alert_engine`` when ``--alerts``
    is set (evaluated after every round event, on every execution path
    — resident, streamed, service — because all three share this
    façade), and ``exporter`` when the harness opened a scrape port
    (closed here so crash and run end both release it).
    """

    def __init__(
        self,
        sink: EventSink,
        registry=None,
        metrics_sink=None,
        alert_engine=None,
    ) -> None:
        self.sink = sink
        self.enabled = not isinstance(sink, NullSink)
        self._spans = SpanTimer(sink)
        self.collector = Collector(sink)
        self.registry = registry
        self.metrics_sink = metrics_sink
        self.alert_engine = alert_engine
        self.exporter = None
        # (trace_id, root_span_id) under --trace on for serve-managed
        # runs: the identity every retrospective span_event hangs off
        self.trace_root = None

    @property
    def traced(self) -> bool:
        return self._spans.traced

    @traced.setter
    def traced(self, value: bool) -> None:
        self._spans.traced = bool(value)

    def emit(self, kind: str, **fields) -> None:
        self.sink.emit(make_event(kind, **fields))

    def span(self, name: str, sync=None, **fields):
        return self._spans.span(name, sync=sync, **fields)

    def span_event(self, name: str, ms: float, **fields) -> None:
        """Emit a retrospectively-timed span (measured outside a context
        manager — e.g. queue wait, a lane's slice of a vmapped round).

        No-op unless this façade is traced: these spans exist only for
        the trace layer, so ``--trace off`` streams stay bit-identical
        to pre-trace builds.  ``trace_id``/``span_id``/``parent_span_id``
        in ``fields`` win; otherwise ids come from :attr:`trace_root`
        (span_id always minted fresh, parent defaulting to the root
        span so per-run streams assemble into one tree).
        """
        if not self._spans.traced:
            return
        if "trace_id" not in fields:
            if self.trace_root is not None:
                fields["trace_id"] = self.trace_root[0]
            else:
                ctx = trace.current()
                fields["trace_id"] = (
                    ctx[0] if ctx is not None else trace.new_trace_id()
                )
        fields.setdefault("span_id", trace.new_span_id())
        if (
            "parent_span_id" not in fields
            and self.trace_root is not None
            and self.trace_root[0] == fields["trace_id"]
            and self.trace_root[1] is not None
            and fields["span_id"] != self.trace_root[1]
        ):
            fields["parent_span_id"] = self.trace_root[1]
        self.sink.emit(
            make_event("span", name=name, ms=round(float(ms), 3), **fields)
        )

    def round(self, round_idx: int, **metrics) -> None:
        self.collector.round_event(round_idx, **metrics)
        if self.alert_engine is not None:
            # rule windows sample AFTER the round event folded into the
            # registry, so a rule at round r sees the state through r
            self.alert_engine.evaluate(round_idx, self.sink)

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        self.sink.close()


#: the disabled singleton — shared, stateless, close() is a no-op
NULL = Observability(NullSink())


def events_path(obs_dir: str, title: str) -> str:
    """The per-run event-stream file: keyed on the ckpt title (run title +
    config hash) so a resumed run APPENDS to its own stream and two
    different configs can never interleave one file."""
    return os.path.join(obs_dir, f"{title}.events.jsonl")


def from_config(
    cfg, title: str, writer: Optional[WriterThread] = None
) -> Observability:
    """Build the configured Observability for a run (``NULL`` when no
    obs knob is set).  ``--metrics-port`` and ``--alerts`` imply the
    metrics registry; the registry implies nothing else — a
    metrics-only run writes no file and prints no event.

    ``writer`` (the harness's async rim, obs/writer.py) moves the I/O
    sinks — JSONL file and stdout — behind :class:`AsyncSink` so event
    appends leave the round critical path.  The metrics sink stays
    SYNCHRONOUS regardless: the alert engine samples the registry right
    after each round event inside :meth:`Observability.round`, so the
    registry must fold the event before that call returns."""
    sinks = []
    if getattr(cfg, "obs_dir", ""):
        sinks.append(
            JsonlSink(
                events_path(cfg.obs_dir, title),
                rotate_mb=getattr(cfg, "obs_rotate_mb", 0.0),
            )
        )
    if getattr(cfg, "obs_stdout", False):
        sinks.append(StdoutSink())
    if writer is not None:
        sinks = [AsyncSink(s, writer) for s in sinks]
    metrics_on = (
        getattr(cfg, "metrics", "off") == "on"
        or getattr(cfg, "metrics_port", 0) > 0
        or getattr(cfg, "alerts", "off") != "off"
    )
    registry = metrics_sink = alert_engine = None
    if metrics_on:
        registry = MetricsRegistry()
        metrics_sink = MetricsSink(registry)
        sinks.append(metrics_sink)
        if getattr(cfg, "alerts", "off") != "off":
            alert_engine = AlertEngine(load_rules(cfg.alerts), registry)
    if not sinks:
        return NULL
    out = Observability(
        sinks[0] if len(sinks) == 1 else MultiSink(sinks),
        registry=registry,
        metrics_sink=metrics_sink,
        alert_engine=alert_engine,
    )
    # output-only: flips span emission into id-minting mode, never the
    # training program (config_hash skips it alongside the other obs knobs)
    out.traced = getattr(cfg, "trace", "off") == "on"
    return out
