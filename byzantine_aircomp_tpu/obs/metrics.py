"""Live metrics: an in-process registry fed by the event stream.

Everything observability in this repo flows through schema-versioned
events (``obs/events.py``) so the jitted round fn never carries a
telemetry branch.  This module keeps that invariant for LIVE health
signals: :class:`MetricsSink` is just another :class:`~.sinks.EventSink`
in the fan-out — it folds each event into a :class:`MetricsRegistry` of
counters, gauges, and bounded-bucket histograms, and the registry is what
the scrape endpoint (``obs/exporter.py``) renders and the SLO engine
(``obs/alerts.py``) evaluates.  Derived state only: killing the metrics
path changes no event, no record byte, no RNG draw.

Thread-safety: the harness thread writes (one ``emit`` per event) while
the exporter's HTTP thread reads (``render``/``snapshot``).  One
registry-wide lock covers both sides, so a scrape can never observe a
torn histogram (bucket counts that do not sum to the series count).

Cardinality is bounded twice: histograms use FIXED bucket edges (no
per-value growth), and each metric family holds at most
:data:`MAX_SERIES` label-sets — overflow label values fold into
``"__overflow__"`` so a hostile/buggy label (e.g. a per-client id) can
never grow the registry without bound on an always-on service run.

All metric names carry the ``aircomp_`` prefix.  ``aircomp_events_total
{kind=...}`` counts every event seen, which is the scrape-vs-stream
parity anchor the tests pin: at quiesce the scraped counter equals the
event-stream line count.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from .sinks import EventSink

#: per-family label-set cap; the overflow fold keeps scrapes bounded
MAX_SERIES = 64

#: fixed bucket upper bounds for round-duration histograms (seconds);
#: the +Inf bucket is implicit
ROUND_SECONDS_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: fixed bucket upper bounds for HTTP request-latency histograms
#: (seconds) — server handling is sub-second in the common case, so the
#: grid starts finer than the round grid
HTTP_SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

#: run-phase gauge values (aircomp_run_phase)
PHASE_STARTING, PHASE_RUNNING, PHASE_DONE = 0, 1, 2


def _labelkey(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """One metric family: name, type, help text, and its label series.

    NOT self-locking — the registry's lock guards every touch, so a
    family never needs (and never takes) its own.
    """

    def __init__(self, name: str, kind: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.help = help_text
        self.buckets = tuple(buckets) if buckets else ()
        # label-key tuple -> float (counter/gauge) or
        # [bucket_counts list, sum, count] (histogram)
        self.series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _key(self, labels: Dict[str, str]):
        key = _labelkey(labels)
        if key not in self.series and len(self.series) >= MAX_SERIES:
            key = _labelkey({k: "__overflow__" for k, _ in key}) or key
        return key

    def inc(self, amount: float, labels: Dict[str, str]) -> None:
        key = self._key(labels)
        self.series[key] = self.series.get(key, 0.0) + amount

    def set(self, value: float, labels: Dict[str, str]) -> None:
        self.series[self._key(labels)] = float(value)

    def observe(self, value: float, labels: Dict[str, str]) -> None:
        key = self._key(labels)
        if key not in self.series:
            self.series[key] = [[0] * len(self.buckets), 0.0, 0]
        counts, total, n = self.series[key]
        for i, edge in enumerate(self.buckets):
            if value <= edge:
                counts[i] += 1
                break
        self.series[key] = [counts, total + value, n + 1]


class MetricsRegistry:
    """Thread-safe counter/gauge/histogram registry with a Prometheus
    text renderer.  Families are created lazily on first touch; a
    name reused with a different type raises (the drift would render
    an invalid exposition)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_text: str,
                buckets: Optional[Tuple[float, ...]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = _Family(name, kind, help_text, buckets)
            self._families[name] = fam
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} registered as {fam.kind}, used as {kind}"
            )
        return fam

    def inc(self, name: str, amount: float = 1.0, help_text: str = "",
            **labels: str) -> None:
        with self._lock:
            self._family(name, "counter", help_text).inc(amount, labels)

    def set(self, name: str, value: float, help_text: str = "",
            **labels: str) -> None:
        with self._lock:
            self._family(name, "gauge", help_text).set(value, labels)

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = ROUND_SECONDS_BUCKETS,
                help_text: str = "", **labels: str) -> None:
        with self._lock:
            self._family(name, "histogram", help_text,
                         buckets).observe(value, labels)

    def value(self, name: str, **labels: str) -> Optional[float]:
        """Current scalar of a counter/gauge series (None when the family
        or series does not exist yet — the alert engine treats absent as
        rule-specific).  Histograms return their observation count."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            v = fam.series.get(_labelkey(labels))
            if v is None:
                return None
            return float(v[2]) if fam.kind == "histogram" else float(v)

    def quantile(self, name: str, q: float,
                 **labels: str) -> Optional[float]:
        """Bucket-resolution quantile of a histogram series: the
        smallest bucket upper bound whose cumulative count reaches the
        nearest-rank position — a conservative (upper-bound) estimate,
        which is the right bias for an SLO ceiling.  ``math.inf`` when
        the rank lands in the implicit +Inf bucket; None when the
        family/series is absent or empty (the alert engine skips, same
        as ``value``)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram":
                return None
            v = fam.series.get(_labelkey(labels))
            if v is None:
                return None
            counts, _total, n = v
            if n <= 0:
                return None
            rank = max(1, math.ceil(float(q) * n))
            cum = 0
            for edge, c in zip(fam.buckets, counts):
                cum += c
                if cum >= rank:
                    return float(edge)
            return math.inf

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy of every series, taken under the lock so a
        histogram's bucket counts always sum to its count."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, fam in sorted(self._families.items()):
                series = []
                for key, v in sorted(fam.series.items()):
                    entry: Dict[str, Any] = {"labels": dict(key)}
                    if fam.kind == "histogram":
                        counts, total, n = v
                        entry.update(
                            buckets=list(counts), sum=total, count=n
                        )
                    else:
                        entry["value"] = v
                    series.append(entry)
                out[name] = {"type": fam.kind, "series": series}
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    lines.append(f"# HELP {name} {fam.help}")
                lines.append(f"# TYPE {name} {fam.kind}")
                for key, v in sorted(fam.series.items()):
                    lbl = _render_labels(dict(key))
                    if fam.kind == "histogram":
                        counts, total, n = v
                        cum = 0
                        for edge, c in zip(fam.buckets, counts):
                            cum += c
                            le = _render_labels({**dict(key), "le": _fmt(edge)})
                            lines.append(f"{name}_bucket{le} {cum}")
                        inf = _render_labels({**dict(key), "le": "+Inf"})
                        lines.append(f"{name}_bucket{inf} {n}")
                        lines.append(f"{name}_sum{lbl} {_fmt(total)}")
                        lines.append(f"{name}_count{lbl} {n}")
                    else:
                        lines.append(f"{name}{lbl} {_fmt(v)}")
        return "\n".join(lines) + "\n"


class LabeledRegistry:
    """A write-through view of a :class:`MetricsRegistry` that stamps a
    fixed label set (e.g. ``run_id``) onto every series it touches.

    The multi-tenant control plane gives each run a
    ``MetricsSink(LabeledRegistry(shared, run_id=...))`` so one scrape
    endpoint exposes every tenant's counters side by side —
    ``aircomp_events_total{kind="round",run_id="r42"}`` — without the
    per-kind fold methods knowing anything about tenancy.  Explicit
    labels win over the fixed ones on collision (none of the built-in
    folds uses ``run_id``, so in practice they merge).  Reads
    (``render``/``snapshot``) go straight to the base registry; ``value``
    merges the fixed labels so per-run alert engines query their own
    series."""

    def __init__(self, base: MetricsRegistry, **labels: str) -> None:
        self.base = base
        self.labels = {k: str(v) for k, v in labels.items()}

    def inc(self, name: str, amount: float = 1.0, help_text: str = "",
            **labels: str) -> None:
        self.base.inc(name, amount, help_text, **{**self.labels, **labels})

    def set(self, name: str, value: float, help_text: str = "",
            **labels: str) -> None:
        self.base.set(name, value, help_text, **{**self.labels, **labels})

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = ROUND_SECONDS_BUCKETS,
                help_text: str = "", **labels: str) -> None:
        self.base.observe(name, value, buckets, help_text,
                          **{**self.labels, **labels})

    def value(self, name: str, **labels: str) -> Optional[float]:
        return self.base.value(name, **{**self.labels, **labels})

    def quantile(self, name: str, q: float,
                 **labels: str) -> Optional[float]:
        return self.base.quantile(name, q, **{**self.labels, **labels})

    def snapshot(self) -> Dict[str, Any]:
        return self.base.snapshot()

    def render(self) -> str:
        return self.base.render()


def _fmt(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in sorted(labels.items()):
        escaped = str(v).replace("\\", "\\\\").replace('"', '\\"')
        parts.append(f'{k}="{escaped}"')
    return "{" + ",".join(parts) + "}"


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


class MetricsSink(EventSink):
    """Folds the event stream into a :class:`MetricsRegistry`.

    Joins the ordinary sink fan-out, so it sees exactly what the JSONL
    stream records — including the ``alert`` events the rule engine
    emits back through the same fan-out (counted like any other kind;
    no recursion, because counting never emits).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        # byz count of the run being folded (from run_start): a client
        # flag raised on a byz=0 run is by construction a false flag —
        # the signal the benign_false_flag_rate SLO pages on
        self._byz: Optional[int] = None

    # EventSink interface ------------------------------------------------
    def emit(self, event: Dict[str, Any]) -> None:
        reg = self.registry
        kind = event.get("kind", "unknown")
        reg.inc("aircomp_events_total",
                help_text="events seen by the metrics sink, by kind",
                kind=kind)
        handler = getattr(self, f"_on_{kind}", None)
        if handler is not None:
            handler(event)

    # per-kind folds -----------------------------------------------------
    def _on_run_start(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.set("aircomp_run_phase", PHASE_RUNNING,
                help_text="0=starting 1=running 2=done")
        reg.set("aircomp_run_start_ts", e.get("ts", 0.0),
                help_text="run_start wall-clock epoch seconds")
        if e.get("k") is not None:
            reg.set("aircomp_clients_k", e["k"],
                    help_text="configured round size K")
        self._byz = e.get("byz")
        if e.get("byz") is not None:
            reg.set("aircomp_clients_byz", e["byz"],
                    help_text="configured Byzantine count B")
        if e.get("rounds") is not None:
            reg.set("aircomp_rounds_scheduled", e["rounds"],
                    help_text="scheduled round horizon")

    def _on_span(self, e: Dict[str, Any]) -> None:
        # stage-latency histograms: every span folds into
        # aircomp_stage_seconds{stage=<name>} (span names are a small
        # closed set — setup/round/dispatch/eval/checkpoint/run/
        # queue_wait/writer_task/... — so cardinality stays bounded even
        # before the MAX_SERIES fold), and queue_wait additionally feeds
        # the dedicated admission-wait histogram the SLO rule samples
        reg = self.registry
        ms = e.get("ms")
        if ms is None or not _finite(ms):
            return
        secs = float(ms) / 1e3
        stage = str(e.get("name", "unknown"))
        reg.observe("aircomp_stage_seconds", secs,
                    help_text="span-derived stage latency, by span name",
                    stage=stage)
        if stage == "queue_wait":
            reg.observe("aircomp_queue_wait_seconds", secs,
                        help_text="admission queue wait "
                        "(run_submitted to lane seat)")

    def _on_round(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.inc("aircomp_rounds_total", help_text="completed rounds")
        reg.set("aircomp_round", e.get("round", -1),
                help_text="last completed round index")
        reg.set("aircomp_last_round_ts", e.get("ts", 0.0),
                help_text="last round event wall-clock epoch seconds")
        for field, gauge in (
            ("train_loss", "aircomp_train_loss"),
            ("val_loss", "aircomp_val_loss"),
            ("val_acc", "aircomp_val_acc"),
            ("variance", "aircomp_variance"),
            ("rounds_per_sec", "aircomp_rounds_per_sec"),
            ("effective_k", "aircomp_effective_k"),
        ):
            v = e.get(field)
            if v is not None and _finite(v):
                reg.set(gauge, float(v))
        if any(
            e.get(f) is not None and not _finite(e.get(f))
            for f in ("train_loss", "val_loss", "variance")
        ):
            reg.inc("aircomp_nonfinite_loss_total",
                    help_text="rounds with a non-finite loss/variance")
        if e.get("round_secs") is not None:
            reg.observe("aircomp_round_seconds", float(e["round_secs"]),
                        help_text="wall-clock seconds per round")
        for field, counter in (
            ("dropped", "aircomp_fault_dropped_total"),
            ("erased", "aircomp_fault_erased_total"),
            ("corrupt", "aircomp_fault_corrupt_total"),
        ):
            v = e.get(field)
            if v is not None and _finite(v):
                reg.inc(counter, float(v),
                        help_text=f"fault-injection {field} clients, summed")
        # device-allocator watermarks only: host RSS includes the
        # interpreter/compiler and must never drive the HBM SLO
        if str(e.get("mem_source", "")).startswith("device"):
            if _finite(e.get("bytes_in_use")):
                reg.set("aircomp_bytes_in_use", float(e["bytes_in_use"]),
                        help_text="device bytes in use at round end")
            if _finite(e.get("peak_bytes_in_use")):
                reg.set("aircomp_peak_bytes_in_use",
                        float(e["peak_bytes_in_use"]),
                        help_text="device peak bytes in use")

    def _on_participation(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        for field, gauge in (
            ("available", "aircomp_participation_available"),
            ("absent", "aircomp_participation_absent"),
            ("late", "aircomp_participation_late"),
            ("effective_k", "aircomp_effective_k"),
        ):
            if _finite(e.get(field)):
                reg.set(gauge, float(e[field]),
                        help_text=f"per-round service {field}")
        if _finite(e.get("late")):
            reg.inc("aircomp_late_total", float(e["late"]),
                    help_text="deadline-missing clients, summed over rounds")

    def _on_rollback(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.inc("aircomp_rollbacks_total",
                help_text="warm-rollback restores (divergence guard trips)")
        if _finite(e.get("epoch")):
            reg.set("aircomp_rollback_epoch", float(e["epoch"]),
                    help_text="current rollback epoch (key salt)")

    def _on_defense(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        if _finite(e.get("rung")):
            reg.set("aircomp_defense_rung", float(e["rung"]),
                    help_text="current escalation-ladder rung")
        if _finite(e.get("flagged")):
            reg.set("aircomp_defense_flagged", float(e["flagged"]),
                    help_text="clients flagged by the detector this round")

    def _on_client_flag(self, e: Dict[str, Any]) -> None:
        if e.get("flagged"):
            self.registry.inc("aircomp_client_flags_total",
                              help_text="client_flag events with flagged=true")
            if self._byz == 0:
                # on a byz=0 run EVERY flag is a false positive — the
                # dedicated counter gives the benign_false_flag_rate rule
                # crisp semantics (a byz>0 run's genuine detections never
                # touch it)
                self.registry.inc(
                    "aircomp_benign_flags_total",
                    help_text="client flags raised on byz=0 runs "
                              "(every one is a false positive)",
                )

    def _on_retrace(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        counts = e.get("counts") or {}
        if _finite(counts.get("round_fn")):
            reg.set("aircomp_retrace_round_lowerings",
                    float(counts["round_fn"]),
                    help_text="round_fn lowerings this run (SLO: exactly 1)")
        reg.set("aircomp_retrace_steady_state_ok",
                1.0 if e.get("steady_state_ok") else 0.0,
                help_text="1 when the steady-state retrace audit passed")

    def _on_run_end(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.set("aircomp_run_phase", PHASE_DONE,
                help_text="0=starting 1=running 2=done")
        if _finite(e.get("rounds_per_sec")):
            reg.set("aircomp_rounds_per_sec", float(e["rounds_per_sec"]))
        mem = e.get("memory") or {}
        if _finite(mem.get("modeled_peak_bytes")):
            reg.set("aircomp_hbm_modeled_peak_bytes",
                    float(mem["modeled_peak_bytes"]),
                    help_text="obs/hbm.py analytic peak model")
            # the watermark SLO ratio only exists for device-sourced
            # measurements — host RSS would trip it on every CPU run
            if (str(mem.get("source", "")).startswith("device")
                    and _finite(mem.get("peak_bytes_in_use"))
                    and float(mem["modeled_peak_bytes"]) > 0):
                reg.set(
                    "aircomp_hbm_watermark_ratio",
                    float(mem["peak_bytes_in_use"])
                    / float(mem["modeled_peak_bytes"]),
                    help_text="measured device peak / modeled peak",
                )

    def _on_alert(self, e: Dict[str, Any]) -> None:
        if e.get("firing"):
            self.registry.inc(
                "aircomp_alerts_total",
                help_text="alert rule rising edges",
                rule=str(e.get("rule", "?")),
                severity=str(e.get("severity", "?")),
            )

    # crash-safe serving (serve/runs.py events, previously journal-only):
    # terminal failures by machine-readable cause, watchdog requeues, and
    # journal re-adoptions — the counters operators alert on without
    # tailing the journal

    @staticmethod
    def _failure_cause(reason: str) -> str:
        if reason.startswith("quarantined"):
            return "quarantine"
        if "wedged" in reason:
            return "wedged"
        return "error"

    def _on_run_failed(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reason = str(e.get("reason", ""))
        cause = self._failure_cause(reason)
        reg.inc("aircomp_run_failures_total",
                help_text="terminal run failures, by cause",
                cause=cause)
        if cause == "quarantine":
            reg.inc("aircomp_quarantines_total",
                    help_text="lane quarantines (run-level containment)")

    def _on_run_requeued(self, e: Dict[str, Any]) -> None:
        self.registry.inc(
            "aircomp_requeues_total",
            help_text="watchdog bounded-backoff requeues",
        )

    def _on_journal_replay(self, e: Dict[str, Any]) -> None:
        self.registry.inc(
            "aircomp_journal_replays_total",
            help_text="runs re-adopted from the durable journal on boot",
            status=str(e.get("status", "?")),
        )

    # elastic lane scheduling (serve/runs.py group loop): per-round
    # occupancy samples — the >90% acceptance bar, and the series the
    # lane_occupancy_floor alert windows over — plus the refill counter

    def _on_lane_group(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        if _finite(e.get("occupancy")):
            reg.set("aircomp_lane_occupancy", float(e["occupancy"]),
                    help_text="live lanes / group width, sampled per round")
        if _finite(e.get("live")):
            reg.set("aircomp_lanes_live", float(e["live"]),
                    help_text="lanes with a seated live tenant")
        if _finite(e.get("lanes")):
            reg.set("aircomp_lanes_total", float(e["lanes"]),
                    help_text="lane-group width (vmapped batch size)")
        if _finite(e.get("queue_depth")):
            reg.set("aircomp_admission_queue_depth", float(e["queue_depth"]),
                    help_text="runs queued for admission to a lane group")

    def _on_lane_refill(self, e: Dict[str, Any]) -> None:
        self.registry.inc(
            "aircomp_lane_refills_total",
            help_text="drained lane slots reseated from the admission queue",
        )

    # 2-tier aggregation (serve/root.py events): the root's zero-trust
    # counters — ingress volume, rejections by reason, containment, and
    # degraded-round visibility (obs/alerts.py pages on quarantine rate)

    def _on_edge_partial(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.inc("aircomp_edge_partials_total",
                help_text="accepted HMAC-verified edge partials")
        if _finite(e.get("bytes")):
            reg.inc("aircomp_edge_ingress_bytes_total", float(e["bytes"]),
                    help_text="raw wire bytes accepted by the root")

    def _on_edge_reject(self, e: Dict[str, Any]) -> None:
        self.registry.inc(
            "aircomp_edge_rejects_total",
            help_text="rejected edge submissions, by reason",
            reason=str(e.get("reason", "?")),
        )

    def _on_edge_quarantine(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        # unlabeled total first: the edge_quarantine_rate alert samples
        # it directly (registry.value with no labels reads the unlabeled
        # series), with the per-reason breakdown alongside for operators
        reg.inc("aircomp_edge_quarantines_total",
                help_text="edges contained by the root")
        reg.inc("aircomp_edge_quarantine_reasons_total",
                help_text="edge quarantines, by reason",
                reason=str(e.get("reason", "?")))

    def _on_edge_round(self, e: Dict[str, Any]) -> None:
        reg = self.registry
        reg.inc("aircomp_edge_rounds_total",
                help_text="2-tier rounds closed over the live set")
        if e.get("degraded"):
            reg.inc("aircomp_edge_degraded_rounds_total",
                    help_text="rounds folded over a surviving edge subset")
        if _finite(e.get("edges")):
            reg.set("aircomp_edge_live", float(e["edges"]),
                    help_text="live (non-quarantined) edges")
        if _finite(e.get("ingress_bytes")):
            reg.set("aircomp_edge_round_ingress_bytes",
                    float(e["ingress_bytes"]),
                    help_text="root ingress bytes for the last closed round")

    # health -------------------------------------------------------------

    #: seconds without a completed round before a "running" run reports
    #: itself wedged (/healthz flips to 503 through the exporter's
    #: ok-keyed status).  0 disables the check — wedge detection is
    #: opt-in (`--wedge-secs` arms the serve-side watchdog, which sets
    #: this on the sinks it owns); a standalone sink never flips its
    #: health on wall-clock alone.  The age only exists once a first
    #: round has completed, so a long initial compile never trips it.
    wedge_secs: float = 0.0

    def health(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The /healthz body: run phase, last-round age, rollback epoch.

        ``ok`` goes False — and the exporter's ``/healthz`` returns 503,
        so k8s-style probes work without parsing the body — when the run
        claims to be running but no round has completed for longer than
        :attr:`wedge_secs`; a ``reason`` key is added only then (the
        healthy body shape is unchanged)."""
        import time as _time

        reg = self.registry
        phase_num = reg.value("aircomp_run_phase")
        phase = {None: "starting", float(PHASE_STARTING): "starting",
                 float(PHASE_RUNNING): "running",
                 float(PHASE_DONE): "done"}.get(phase_num, "running")
        last_ts = reg.value("aircomp_last_round_ts")
        age = None
        if last_ts is not None:
            age = round((now if now is not None else _time.time()) - last_ts, 3)
        last_round = reg.value("aircomp_round")
        epoch = reg.value("aircomp_rollback_epoch")
        wedged = (
            phase == "running"
            and self.wedge_secs > 0
            and age is not None
            and age > self.wedge_secs
        )
        body = {
            "ok": not wedged,
            "phase": phase,
            "last_round": None if last_round is None else int(last_round),
            "last_round_age_secs": age,
            "rollback_epoch": 0 if epoch is None else int(epoch),
            "alerts_firing": int(reg.value("aircomp_alerts_firing") or 0),
        }
        if wedged:
            body["reason"] = (
                f"wedged: no completed round in {age:.0f}s "
                f"(threshold {self.wedge_secs:g}s)"
            )
        return body
