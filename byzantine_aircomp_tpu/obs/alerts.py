"""Declarative SLO rules evaluated live on the metrics registry.

A production federated service cannot learn it diverged by reading
files after the run: the health signals PR 7's service rounds already
emit (rollback trips, effective-K, deadline misses, rounds/sec) need a
standing machine-checked bar.  This module is that bar, kept
config-as-data: a rule is a plain dict —

    {"name": "rollback_rate", "metric": "aircomp_rollbacks_total",
     "window": 8, "reduce": "delta", "op": "ge", "value": 1,
     "severity": "page", "absent": 0.0, "min_samples": 2}

— sample the metric each round into a sliding window, reduce
(``last``/``mean``/``min``/``max``/``delta`` = newest-oldest), compare
(``gt``/``ge``/``lt``/``le``) against a threshold (a constant ``value``,
optionally scaled off another metric via ``value_metric``/``value_scale``
— e.g. the effective-K floor is ``0.5 * aircomp_clients_k``).  ``absent``
gives the sample to record while the metric does not exist yet (counters
that are only created on their first increment sample as 0.0); rules
without it simply skip until the metric appears, so e.g. the HBM
watermark rule stays silent on CPU runs where no device watermark exists.

The engine emits schema-versioned ``alert`` events on EDGES only —
``firing=true`` when a rule starts breaching, ``firing=false`` when it
clears — through the same sink fan-out every other event uses, so alerts
land in the JSONL stream, the live tail, and the metrics registry
(``aircomp_alerts_total``) without a second pipeline.  ``--gate`` turns
a finished stream's alert events into a CI exit code, the same shape as
``analysis/perf_gate.py``; ``--self-check`` proves every default rule
fires on a synthetic breach and stays quiet on a healthy trace.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .events import make_event
from .metrics import MetricsRegistry, MetricsSink

SEVERITIES = ("info", "warn", "page")
REDUCES = ("last", "mean", "min", "max", "delta")
OPS = {
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
}

_RULE_KEYS = {
    "name", "metric", "labels", "window", "reduce", "op", "value",
    "value_metric", "value_scale", "severity", "min_samples", "absent",
    "quantile",
}


@dataclass
class Rule:
    """One SLO: a windowed predicate over a registry metric."""

    name: str
    metric: str
    op: str
    value: Optional[float] = None
    labels: Dict[str, str] = field(default_factory=dict)
    window: int = 1
    reduce: str = "last"
    value_metric: Optional[str] = None
    value_scale: float = 1.0
    severity: str = "warn"
    min_samples: int = 1
    absent: Optional[float] = None
    # when set (0 < q < 1), the metric must be a histogram and each
    # round's sample is its bucket-resolution q-quantile instead of the
    # scalar/count ``value`` returns — the shape span-derived latency
    # SLOs need (queue-wait p99, round critical-path ceiling)
    quantile: Optional[float] = None

    def __post_init__(self) -> None:
        if self.quantile is not None and not (0.0 < self.quantile < 1.0):
            raise ValueError(
                f"rule {self.name!r}: quantile must be in (0, 1), "
                f"got {self.quantile!r}"
            )
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity must be one of {SEVERITIES}, "
                f"got {self.severity!r}"
            )
        if self.reduce not in REDUCES:
            raise ValueError(
                f"rule {self.name!r}: reduce must be one of {REDUCES}, "
                f"got {self.reduce!r}"
            )
        if self.op not in OPS:
            raise ValueError(
                f"rule {self.name!r}: op must be one of {tuple(OPS)}, "
                f"got {self.op!r}"
            )
        if self.value is None and self.value_metric is None:
            raise ValueError(
                f"rule {self.name!r}: needs value or value_metric"
            )
        if self.window < 1 or self.min_samples < 1:
            raise ValueError(
                f"rule {self.name!r}: window/min_samples must be >= 1"
            )
        if self.reduce == "delta" and self.min_samples < 2:
            self.min_samples = 2  # a one-sample delta is always 0

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "Rule":
        unknown = set(spec) - _RULE_KEYS
        if unknown:
            raise ValueError(
                f"rule {spec.get('name', '?')!r}: unknown keys {sorted(unknown)}"
            )
        return cls(**spec)


# the default SLO pack for always-on service rounds.  Thresholds are
# deliberately loose — these page on "the run is broken", not "the run
# is slow today"; tune per-deployment via --alerts <rules.json>.
DEFAULT_RULES: List[Dict[str, Any]] = [
    # any divergence-guard trip inside the window pages (delta over a
    # counter that samples 0.0 until its first increment)
    {"name": "rollback_rate", "metric": "aircomp_rollbacks_total",
     "window": 8, "reduce": "delta", "op": "ge", "value": 1,
     "severity": "page", "absent": 0.0, "min_samples": 2},
    # effective-K floor: any round's contributing cohort below K/2
    {"name": "effective_k_floor", "metric": "aircomp_effective_k",
     "window": 4, "reduce": "min", "op": "lt",
     "value_metric": "aircomp_clients_k", "value_scale": 0.5,
     "severity": "warn"},
    # sustained deadline misses: mean late clients above K/2
    {"name": "straggler_rate", "metric": "aircomp_participation_late",
     "window": 8, "reduce": "mean", "op": "gt",
     "value_metric": "aircomp_clients_k", "value_scale": 0.5,
     "severity": "warn", "min_samples": 4},
    # throughput floor: sustained sub-0.01 rounds/sec means wedged
    {"name": "rounds_per_sec_floor", "metric": "aircomp_rounds_per_sec",
     "window": 8, "reduce": "mean", "op": "lt", "value": 0.01,
     "severity": "warn", "min_samples": 8},
    # measured device peak vs the obs/hbm.py model (ratio gauge only
    # exists for device-sourced watermarks — silent on CPU hosts)
    {"name": "hbm_watermark", "metric": "aircomp_hbm_watermark_ratio",
     "reduce": "last", "op": "gt", "value": 2.0, "severity": "warn"},
    # steady-state recompilation: >1 lowering is a silent multi-x TPU
    # slowdown (the retrace gauge lands at run end; finalize catches it)
    {"name": "retrace_lowerings",
     "metric": "aircomp_retrace_round_lowerings",
     "reduce": "last", "op": "gt", "value": 1, "severity": "page"},
    # non-finite train/val loss or variance reached the record
    {"name": "nonfinite_loss", "metric": "aircomp_nonfinite_loss_total",
     "window": 8, "reduce": "delta", "op": "ge", "value": 1,
     "severity": "page", "absent": 0.0, "min_samples": 2},
    # 2-tier containment: ANY edge quarantined inside the window pages —
    # an evicted edge is lost capacity AND a possible compromise
    # (bogus payload, result dissent, repeated authenticated
    # violations); see RUNBOOK.md
    {"name": "edge_quarantine_rate",
     "metric": "aircomp_edge_quarantines_total",
     "window": 8, "reduce": "delta", "op": "ge", "value": 1,
     "severity": "page", "absent": 0.0, "min_samples": 2},
    # false-flag guard for honest deployments: on a byz=0 run every
    # client flag is by construction a false positive (the failure mode
    # IID-tuned detector constants hit on non-IID honest clients —
    # docs/DESIGN.md "Tuning the defense").  The metric only counts
    # flags folded from byz=0 streams, so a byz>0 run's genuine
    # detections never fire this
    {"name": "benign_false_flag_rate",
     "metric": "aircomp_benign_flags_total",
     "window": 8, "reduce": "delta", "op": "ge", "value": 1,
     "severity": "warn", "absent": 0.0, "min_samples": 2},
    # elastic lane groups: occupancy (live lanes / width, sampled per
    # round by the scheduler's lane_group events) sagging below 90% for
    # 4 straight samples means the refill path is not keeping lanes fed
    # despite a queue (or the queue itself ran dry under churn).  No
    # ``absent`` stand-in: runs without a lane group stay silent.
    {"name": "lane_occupancy_floor", "metric": "aircomp_lane_occupancy",
     "window": 4, "reduce": "max", "op": "lt", "value": 0.9,
     "severity": "warn", "min_samples": 4},
    # span-derived latency SLOs (PR 20).  Both sample histograms the
    # MetricsSink folds from span events at bucket-resolution quantiles;
    # no ``absent`` stand-in, so runs that never emit the span (no
    # admission queue / no round spans folded yet) stay silent.
    # admission wait: a tenant queued more than 30s at p99 means the
    # scheduler is starved or the group is wedged behind a slow lane
    {"name": "queue_wait_p99", "metric": "aircomp_queue_wait_seconds",
     "reduce": "last", "op": "gt", "value": 30.0, "quantile": 0.99,
     "severity": "warn"},
    # round critical-path ceiling: the server-measured round span (the
    # whole dispatch critical path, not just device time) above 60s at
    # p99 — loose on purpose; tune per-deployment
    {"name": "round_critical_path", "metric": "aircomp_stage_seconds",
     "labels": {"stage": "round"}, "reduce": "last", "op": "gt",
     "value": 60.0, "quantile": 0.99, "severity": "warn"},
]


def load_rules(spec: str) -> List[Rule]:
    """``"default"`` -> the built-in pack; anything else is a path to a
    JSON list of rule dicts."""
    if spec == "default":
        dicts = DEFAULT_RULES
    else:
        with open(spec) as f:
            dicts = json.load(f)
        if not isinstance(dicts, list):
            raise ValueError(f"alert rules file {spec}: expected a JSON list")
    return [Rule.from_dict(dict(d)) for d in dicts]


class _RuleState:
    __slots__ = ("samples", "firing", "fired", "last_value")

    def __init__(self, window: int) -> None:
        self.samples: deque = deque(maxlen=window)
        self.firing = False
        self.fired = 0
        self.last_value: Optional[float] = None


class AlertEngine:
    """Evaluates a rule list against a registry, once per round.

    Edge-triggered: one ``alert`` event when a rule starts firing, one
    (``firing=false``) when it clears.  The per-rule sliding windows are
    owned by the harness thread — the exporter thread only reads the
    registry, never the engine.
    """

    def __init__(self, rules: List[Rule], registry: MetricsRegistry) -> None:
        self.rules = list(rules)
        self.registry = registry
        self._state = {r.name: _RuleState(r.window) for r in self.rules}

    def evaluate(self, round_idx: int, sink) -> List[Dict[str, Any]]:
        """Sample + reduce + compare every rule; emit edge events on
        ``sink``.  Returns the alert events emitted this call."""
        emitted: List[Dict[str, Any]] = []
        for rule in self.rules:
            st = self._state[rule.name]
            if rule.quantile is not None:
                sample = self.registry.quantile(
                    rule.metric, rule.quantile, **rule.labels
                )
            else:
                sample = self.registry.value(rule.metric, **rule.labels)
            if sample is None:
                if rule.absent is None:
                    continue  # metric not born yet and no stand-in
                sample = rule.absent
            st.samples.append(float(sample))
            if len(st.samples) < rule.min_samples:
                continue
            reduced = _reduce(rule.reduce, st.samples)
            st.last_value = reduced
            threshold = rule.value
            if rule.value_metric is not None:
                ref = self.registry.value(rule.value_metric)
                if ref is None:
                    continue  # no reference metric -> rule not in force
                threshold = ref * rule.value_scale
            breach = OPS[rule.op](reduced, threshold)
            if breach != st.firing:
                st.firing = breach
                if breach:
                    st.fired += 1
                event = make_event(
                    "alert",
                    round=round_idx,
                    rule=rule.name,
                    severity=rule.severity,
                    metric=rule.metric,
                    value=reduced,
                    threshold=threshold,
                    firing=breach,
                )
                sink.emit(event)
                emitted.append(event)
        self.registry.set(
            "aircomp_alerts_firing",
            float(sum(1 for s in self._state.values() if s.firing)),
            help_text="alert rules currently in breach",
        )
        return emitted

    def finalize(self, round_idx: int, sink) -> Dict[str, Any]:
        """One last evaluation (run-end gauges — retrace count, HBM
        watermark ratio — only exist now) plus the run summary."""
        self.evaluate(round_idx, sink)
        rules_out = {}
        worst = None
        total = 0
        for rule in self.rules:
            st = self._state[rule.name]
            rules_out[rule.name] = {
                "fired": st.fired,
                "firing": st.firing,
                "severity": rule.severity,
                "last_value": st.last_value,
            }
            total += st.fired
            if st.fired and (
                worst is None
                or SEVERITIES.index(rule.severity) > SEVERITIES.index(worst)
            ):
                worst = rule.severity
        return {"rules": rules_out, "total_fired": total, "worst": worst}


def _reduce(how: str, samples: deque) -> float:
    if how == "last":
        return samples[-1]
    if how == "mean":
        return sum(samples) / len(samples)
    if how == "min":
        return min(samples)
    if how == "max":
        return max(samples)
    return samples[-1] - samples[0]  # delta: newest - oldest in window


# --------------------------------------------------------------------------
# CLI: --self-check scenario table and --gate (stream -> exit code)
# --------------------------------------------------------------------------


def _mk(kind: str, **fields) -> Dict[str, Any]:
    return make_event(kind, **fields)


def _scenarios() -> Dict[str, Dict[str, List[Dict[str, Any]]]]:
    """Per-rule synthetic traces: ``breach`` must fire the rule,
    ``healthy`` must leave the whole engine quiet.  Events are fed
    through a real MetricsSink so the scenarios exercise the same fold
    the harness uses."""
    K = 8

    def rounds(n, start=0, **over):
        out = []
        for r in range(start, start + n):
            fields = dict(round=r, train_loss=0.5, train_acc=0.8,
                          val_loss=0.5, val_acc=0.8, variance=1.0,
                          round_secs=0.02, rounds_per_sec=50.0)
            fields.update(over)
            out.append(_mk("round", **fields))
        return out

    def participation(r, late=0, absent=0):
        eff = K - late
        return _mk("participation", round=r, available=K - absent,
                   absent=absent, late=late, effective_k=eff)

    start = [_mk("run_start", title="t", backend="cpu", rounds=16,
                 start_round=0, k=K)]
    healthy_service = start + [
        e for r in range(10)
        for e in (participation(r), rounds(1, start=r)[0])
    ]
    return {
        "rollback_rate": {
            "healthy": healthy_service,
            "breach": start + rounds(4) + [
                _mk("rollback", round=4, restored_round=3,
                    reason="non_finite", epoch=1),
            ] + rounds(2, start=4),
        },
        "effective_k_floor": {
            "healthy": healthy_service,
            "breach": start + [participation(0, late=K - 3)] + rounds(1),
        },
        "straggler_rate": {
            "healthy": healthy_service,
            "breach": start + [
                e for r in range(6)
                for e in (participation(r, late=K - 3), rounds(1, start=r)[0])
            ],
        },
        "rounds_per_sec_floor": {
            "healthy": healthy_service,
            "breach": start + rounds(10, rounds_per_sec=0.001),
        },
        "hbm_watermark": {
            "healthy": start + rounds(2) + [
                _mk("run_end", elapsed_secs=1.0, rounds_run=2,
                    memory={"source": "device:0", "peak_bytes_in_use": 90,
                            "modeled_peak_bytes": 100}),
            ],
            "breach": start + rounds(2) + [
                _mk("run_end", elapsed_secs=1.0, rounds_run=2,
                    memory={"source": "device:0", "peak_bytes_in_use": 300,
                            "modeled_peak_bytes": 100}),
            ],
        },
        "retrace_lowerings": {
            "healthy": start + rounds(2) + [
                _mk("retrace", counts={"round_fn": 1}, steady_state_ok=True),
            ],
            "breach": start + rounds(2) + [
                _mk("retrace", counts={"round_fn": 3}, steady_state_ok=False),
            ],
        },
        "nonfinite_loss": {
            "healthy": healthy_service,
            "breach": start + rounds(2) + rounds(
                1, start=2, val_loss=float("nan")
            ) + rounds(1, start=3),
        },
        "edge_quarantine_rate": {
            "healthy": healthy_service,
            "breach": start + rounds(2) + [
                _mk("edge_quarantine", edge=2, reason="partial_timeout"),
            ] + rounds(2, start=2),
        },
        "benign_false_flag_rate": {
            # healthy is deliberately NOT flag-free: a byz=2 run's genuine
            # detection must leave the benign counter (and the whole pack)
            # untouched — only byz=0 streams feed it
            "healthy": [
                _mk("run_start", title="t", backend="cpu", rounds=16,
                    start_round=0, k=K, byz=2),
            ] + rounds(2) + [
                _mk("client_flag", round=2, client=7, score=9.0, rung=1,
                    flagged=True),
            ] + rounds(2, start=2),
            "breach": [
                _mk("run_start", title="t", backend="cpu", rounds=16,
                    start_round=0, k=K, byz=0),
            ] + rounds(2) + [
                _mk("client_flag", round=2, client=3, score=4.0, rung=0,
                    flagged=True),
            ] + rounds(2, start=2),
        },
        "queue_wait_p99": {
            # a tenant seated in 50ms: p99 resolves to a sub-second
            # bucket edge, far under the 30s ceiling
            "healthy": start + [
                _mk("span", name="queue_wait", ms=50.0, run_id="r1"),
            ] + rounds(4),
            # 90s in the admission queue lands in the +Inf bucket; the
            # quantile saturates and the ceiling fires
            "breach": start + [
                _mk("span", name="queue_wait", ms=90_000.0, run_id="r1"),
            ] + rounds(4),
        },
        "round_critical_path": {
            "healthy": start + [
                _mk("span", name="round", ms=20.0, round=0),
            ] + rounds(4),
            "breach": start + [
                _mk("span", name="round", ms=120_000.0, round=0),
            ] + rounds(4),
        },
        "lane_occupancy_floor": {
            # a single-round sag (one lane draining before its refill
            # lands) must NOT fire: the window max sees the recovery
            "healthy": start + [
                e for r in range(6)
                for e in (
                    _mk("lane_group", round=r, lanes=8,
                        live=7 if r == 3 else 8,
                        occupancy=0.875 if r == 3 else 1.0,
                        queue_depth=0),
                    rounds(1, start=r)[0],
                )
            ],
            # sustained half-empty group: refill starved for 5 rounds
            "breach": start + [
                e for r in range(5)
                for e in (
                    _mk("lane_group", round=r, lanes=8, live=4,
                        occupancy=0.5, queue_depth=0),
                    rounds(1, start=r)[0],
                )
            ],
        },
    }


def _run_scenario(events: List[Dict[str, Any]]):
    """Feed a synthetic trace through MetricsSink + AlertEngine the way
    the harness does: fold each event, evaluate after each round event,
    finalize at the end.  Returns {rule name: rising edges}."""
    from .sinks import MemorySink

    registry = MetricsRegistry()
    msink = MetricsSink(registry)
    out = MemorySink()
    engine = AlertEngine(load_rules("default"), registry)
    last_round = 0
    for e in events:
        msink.emit(e)
        if e["kind"] == "round":
            last_round = e["round"]
            engine.evaluate(e["round"], out)
    summary = engine.finalize(last_round, out)
    return {
        name: info["fired"] for name, info in summary["rules"].items()
    }


def self_check() -> int:
    """Every default rule fires on its breach trace and the WHOLE pack
    stays quiet on its healthy trace.  Prints the scenario table."""
    failures = 0
    names = {r["name"] for r in DEFAULT_RULES}
    scen = _scenarios()
    missing = sorted(names - set(scen))
    if missing:
        print(f"FAIL: default rules without a scenario: {missing}")
        failures += 1
    print(f"{'rule':<22} {'breach':>8} {'healthy':>8}  verdict")
    for name in sorted(scen):
        fired_breach = _run_scenario(scen[name]["breach"])
        fired_healthy = _run_scenario(scen[name]["healthy"])
        ok = fired_breach.get(name, 0) >= 1 and sum(
            fired_healthy.values()
        ) == 0
        verdict = "ok" if ok else "FAIL"
        if not ok:
            failures += 1
            noisy = {k: v for k, v in fired_healthy.items() if v}
            if noisy:
                verdict += f" (healthy trace fired {noisy})"
            if fired_breach.get(name, 0) < 1:
                verdict += " (breach trace did not fire)"
        print(
            f"{name:<22} {fired_breach.get(name, 0):>8} "
            f"{sum(fired_healthy.values()):>8}  {verdict}"
        )
    print("self-check:", "FAIL" if failures else "ok")
    return 1 if failures else 0


def gate(events_path: str, fail_on: str = "page") -> int:
    """Exit code from a finished stream's alert events: 1 when any
    rising edge at or above ``fail_on`` severity fired."""
    from ..analysis.defense_trace import load_events

    floor = SEVERITIES.index(fail_on)
    bad = [
        e for e in load_events(events_path)
        if e.get("kind") == "alert" and e.get("firing")
        and SEVERITIES.index(e.get("severity", "info")) >= floor
    ]
    for e in bad:
        print(
            f"ALERT {e.get('severity')}: {e.get('rule')} at round "
            f"{e.get('round')} (value={e.get('value')}, "
            f"threshold={e.get('threshold')})"
        )
    print(
        f"alert gate: {len(bad)} firing alert(s) at severity >= {fail_on}"
        + ("" if bad else " — ok")
    )
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="SLO alert engine: self-check scenarios or gate a "
        "finished event stream"
    )
    ap.add_argument("--self-check", action="store_true",
                    help="run the default-rule scenario table")
    ap.add_argument("--gate", metavar="EVENTS_JSONL",
                    help="exit 1 if the stream has firing alerts at or "
                    "above --fail-on severity")
    ap.add_argument("--fail-on", choices=SEVERITIES, default="page")
    args = ap.parse_args(argv)
    if args.self_check:
        return self_check()
    if args.gate:
        return gate(args.gate, args.fail_on)
    ap.error("nothing to do: pass --self-check or --gate")
    return 2


if __name__ == "__main__":
    sys.exit(main())
