"""Client-level forensics: in-jit top-M flag provenance + flight recorder.

The defense events (``defense/events.py``) say *how many* clients were
flagged per round and the max CUSUM — never *which* client, *why*, or
*with what margin*.  This module is the attribution layer:

* **In-jit top-M extraction** — a fixed-shape ``lax.top_k`` over the
  detector's per-client scores, gathering the score components
  (norm/cosine/pairwise-distance), the pre-update z-score, the post-update
  CUSUM, and the margins to both alarm thresholds into one ``[M, NUM_COLS]``
  f32 matrix per iteration.  The client-id column holds the stable
  population id under ``--service on`` and the stack row otherwise.  The
  matrix rides the round scan's per-iteration outputs exactly like the
  defense metrics (``()`` when forensics is off), so the round fn stays at
  one lowering.  The streamed path keeps a running top-M in the cohort
  scan carry (:func:`stream_init` / :func:`merge_top_m`), merging each
  cohort's candidates without materializing the full population.
* **Host-side emission** — :func:`emit_round_flags` turns the round-level
  matrix (iterations merged by :func:`merge_interval`, so one client can
  surface its peak iteration) into ``client_flag`` events, deduped by
  client id keeping the max-score row.
* **Flight recorder** — :class:`FlightRecorder`, a host-side ring buffer
  of the last W rounds of full detector carry + round summary stats,
  dumped to a ``flight_<round>.json`` artifact exactly once per
  rollback/divergence-guard trip and once at run end (reason
  ``run_end`` -> ``flight_run_end.json``).

Everything here is output-only: no RNG, no carried device state, no
record keys — ``--forensics off`` runs are bit-identical to a build
without this module (the knobs are excluded from ``config_hash`` in
``fed/harness.py``).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import io as io_lib
from .events import SCHEMA_VERSION

#: column layout of the in-jit forensic matrix ([M, NUM_COLS] f32).  The
#: client-id column is f32 (exact for ids < 2^24 — populations are far
#: smaller); ``rung`` is stamped after the policy update via `with_rung`.
COLUMNS = (
    "client",
    "score",
    "z",
    "cusum",
    "margin_z",
    "margin_cusum",
    "norm_term",
    "cos_term",
    "dist_term",
    "flagged",
    "rung",
)
NUM_COLS = len(COLUMNS)
_SCORE_COL = COLUMNS.index("score")
_RUNG_COL = COLUMNS.index("rung")


def candidate_rows(ids, score, components, ema_pre, dev_pre, cusum_post,
                   flags, p):
    """Per-client forensic candidate rows ``[rows, NUM_COLS]`` (in-jit).

    ``ids`` are the stable client identities for these rows (population
    ids under service subsampling, stack rows otherwise); ``ema_pre`` /
    ``dev_pre`` are the detector baselines BEFORE this iteration's update
    (the z-score the detector actually thresholded), ``cusum_post`` the
    statistic AFTER it (the value compared against ``p.cusum_thresh``).
    The rung column is left 0 — callers stamp it with :func:`with_rung`
    once the policy update has run.
    """
    import jax.numpy as jnp

    f32 = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    z = (f32(score) - f32(ema_pre)) / (f32(dev_pre) + p.eps)
    cusum = f32(cusum_post)
    return jnp.stack(
        [
            f32(ids),
            f32(score),
            z,
            cusum,
            z - p.z_thresh,
            cusum - p.cusum_thresh,
            f32(components[:, 0]),
            f32(components[:, 1]),
            f32(components[:, 2]),
            f32(flags),
            jnp.zeros_like(z),
        ],
        axis=1,
    )


def top_m(rows, m: int):
    """Fixed-shape top-``m`` rows by score (``lax.top_k``; in-jit)."""
    import jax.numpy as jnp
    from jax import lax

    _, idx = lax.top_k(rows[:, _SCORE_COL], m)
    return jnp.take(rows, idx, axis=0)


def merge_top_m(carry, rows, m: int):
    """Merge a carried ``[m, NUM_COLS]`` top-M with new candidate rows
    (streamed path: one call per cohort chunk inside the obs scan)."""
    import jax.numpy as jnp

    return top_m(jnp.concatenate([carry, rows], axis=0), m)


def stream_init(m: int):
    """Initial streamed-scan carry: ``[m, NUM_COLS]`` with a ``-inf``
    score column so every real row displaces a sentinel (a population has
    at least ``m`` rows — validated in ``fed/config.py``)."""
    import jax.numpy as jnp

    init = jnp.zeros((m, NUM_COLS), jnp.float32)
    return init.at[:, _SCORE_COL].set(-jnp.inf)


def with_rung(mat, rung):
    """Stamp the active rung (scalar, post policy-update) into the rung
    column of a forensic matrix."""
    import jax.numpy as jnp

    return mat.at[:, _RUNG_COL].set(jnp.asarray(rung, jnp.float32))


def merge_interval(mats, m: int):
    """Reduce the scan's stacked ``[interval, m, NUM_COLS]`` iteration
    matrices to one round-level ``[m, NUM_COLS]`` top-M.  A client flagged
    in several iterations appears once per iteration here; host-side
    emission dedupes keeping its peak-score row."""
    return top_m(mats.reshape(-1, NUM_COLS), m)


def rows_to_records(mat) -> List[Dict[str, Any]]:
    """Host side: np ``[M, NUM_COLS]`` -> per-client dicts, deduped by
    client id (max score wins), sorted by descending score."""
    mat = np.asarray(mat, np.float64)
    best: Dict[int, np.ndarray] = {}
    for row in mat:
        if not np.isfinite(row[_SCORE_COL]):
            continue  # unfilled streamed sentinel
        cid = int(row[0])
        if cid not in best or row[_SCORE_COL] > best[cid][_SCORE_COL]:
            best[cid] = row
    records = []
    for row in sorted(best.values(), key=lambda r: -r[_SCORE_COL]):
        rec: Dict[str, Any] = {name: float(v) for name, v in zip(COLUMNS, row)}
        rec["client"] = int(row[0])
        rec["flagged"] = bool(row[COLUMNS.index("flagged")] > 0.5)
        rec["rung"] = int(row[_RUNG_COL])
        records.append(rec)
    return records


def emit_round_flags(obs, round_idx: int, mat, *, mode: str) -> int:
    """Emit ``client_flag`` events for a round's forensic matrix.

    ``mode == "top"`` emits only the rows the detector actually flagged;
    ``mode == "full"`` emits the whole top-M (margins on unflagged
    near-threshold clients are exactly what the audit wants for
    precision analysis).  Returns the number of events emitted.
    """
    n = 0
    for rec in rows_to_records(mat):
        if mode == "top" and not rec["flagged"]:
            continue
        obs.emit("client_flag", round=round_idx, **rec)
        n += 1
    return n


class FlightRecorder:
    """Ring buffer of the last W rounds of detector carry + summary stats.

    ``record`` is called once per completed round from the host loop
    (forensics ``full`` only — it forces a device->host transfer of the
    detector state); ``dump`` writes the whole window to a JSON artifact
    and emits one ``forensic_dump`` event.  The trainer calls it exactly
    once per rollback/divergence-guard trip (adjacent to the ``rollback``
    event) and the harness once more at run end.
    """

    def __init__(self, window: int, out_dir: str) -> None:
        self.window = int(window)
        self.out_dir = out_dir
        self._ring: collections.deque = collections.deque(maxlen=self.window)
        self.dumps: List[str] = []

    def record(
        self,
        round_idx: int,
        *,
        detector_state=None,
        policy_state=None,
        defense_metrics=None,
        forensic_rows=None,
        summary: Optional[Dict[str, Any]] = None,
    ) -> None:
        def _tolist(x):
            return None if x is None else np.asarray(x).tolist()

        snap: Dict[str, Any] = {"round": int(round_idx)}
        if detector_state is not None:
            step, ema, dev, cusum = detector_state
            snap["detector"] = {
                "step": int(np.asarray(step)),
                "ema": _tolist(ema),
                "dev": _tolist(dev),
                "cusum": _tolist(cusum),
            }
        if policy_state is not None:
            snap["policy"] = _tolist(policy_state)
        if defense_metrics is not None:
            snap["defense_metrics"] = _tolist(defense_metrics)
        if forensic_rows is not None:
            snap["top_m"] = rows_to_records(forensic_rows)
        if summary:
            snap["summary"] = dict(summary)
        self._ring.append(snap)

    def dump(self, round_idx: int, reason: str, obs=None) -> Optional[str]:
        """Write ``flight_<round>.json`` (``flight_run_end.json`` for the
        run-end dump) and emit a ``forensic_dump`` event; returns the path
        (None when the window is empty — nothing recorded yet)."""
        if not self._ring:
            return None
        name = (
            "flight_run_end.json" if reason == "run_end"
            else f"flight_{int(round_idx)}.json"
        )
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, name)
        payload = {
            "v": SCHEMA_VERSION,
            "reason": reason,
            "round": int(round_idx),
            "window": self.window,
            "rounds": list(self._ring),
        }
        io_lib.atomic_write(
            path, lambda f: json.dump(payload, f, default=str), mode="w"
        )
        self.dumps.append(path)
        if obs is not None:
            obs.emit(
                "forensic_dump",
                round=int(round_idx),
                path=path,
                reason=reason,
                window=self.window,
                rounds_recorded=len(payload["rounds"]),
            )
        return path
