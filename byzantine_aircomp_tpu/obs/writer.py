"""The async host rim: a bounded single-consumer writer thread.

Under ``--rounds-per-dispatch`` the jitted round program costs
milliseconds and the host rim — JSONL event appends, checkpoint
serialization, the end-of-run record pickle — becomes the critical
path.  This module moves that rim onto ONE daemon consumer thread so
the dispatch loop enqueues and returns; it never touches the disk.

Ordering contract
-----------------
A single consumer drains a single FIFO queue, so tasks run in exactly
the order they were submitted.  :class:`AsyncSink` rides this: the
inner sink stamps its monotonic per-sink ``seq`` envelope (see
``obs/sinks.py``) ON the writer thread, so the drained stream is
seq-ordered even when multiple producer threads raced on ``emit`` —
whatever interleaving won the queue IS the stream order.  Checkpoint
saves and their journal callbacks are submitted as ONE task, so a
checkpoint can never be journaled before its bytes are durable.

Backpressure, not loss
----------------------
The queue is bounded (``maxsize``); a full queue blocks the producer in
``submit`` until the consumer catches up.  A slow disk therefore slows
the run down gracefully — it never drops events and never grows the
queue without bound.

Failure degradation
-------------------
A task that raises is recorded (first error kept on ``.error``, one
stderr warning) and the consumer keeps draining — mirroring
``JsonlSink``'s degrade-on-OSError contract: a failing sink must not
deadlock or kill training.  ``drain()`` blocks until every task
submitted so far has finished; the harness drains before sinks close so
run end never races the rim.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

from . import trace as trace_lib
from .sinks import EventSink, MultiSink

_STOP = object()


def _sync_sinks(sink):
    """The synchronous leaves under ``sink`` — AsyncSink unwrapped to its
    inner, MultiSink fanned out.  Writer-rim span events MUST emit on
    these directly: the emitting code runs ON the writer thread, and
    enqueueing from the consumer can deadlock at a full queue."""
    if isinstance(sink, AsyncSink):
        return _sync_sinks(sink.inner)
    if isinstance(sink, MultiSink):
        out = []
        for s in sink.sinks:
            out.extend(_sync_sinks(s))
        return out
    return [sink]


def resolve_async(cfg) -> bool:
    """Whether the harness should stand up a writer thread for ``cfg``:
    ``--async-writer on`` forces it, ``off`` forbids it, and ``auto``
    (default) enables it exactly when the multi-round dispatch tier is
    active (R=1 runs keep the synchronous rim and stay bit-identical in
    behavior AND timing to the pre-writer builds)."""
    mode = getattr(cfg, "async_writer", "auto")
    if mode == "on":
        return True
    if mode == "off":
        return False
    return getattr(cfg, "rounds_per_dispatch", 1) > 1


class WriterThread:
    """Bounded single-consumer task queue on a daemon thread.

    ``submit(fn)`` enqueues a zero-arg callable (blocking at the bound),
    ``drain()`` waits for everything submitted so far, ``close()`` drains
    and joins the thread.  After ``close`` a late ``submit`` runs the
    task inline — teardown paths degrade to the synchronous rim instead
    of losing work.
    """

    def __init__(self, maxsize: int = 256, name: str = "obs-writer") -> None:
        self._q: "queue.Queue" = queue.Queue(maxsize)
        self._error: Optional[BaseException] = None
        self._warned = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    @property
    def error(self) -> Optional[BaseException]:
        """The FIRST task failure, if any (later ones only count)."""
        return self._error

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueue ``fn``; blocks while the queue is at its bound
        (backpressure — a slow consumer throttles the producer, it never
        drops work)."""
        if self._closed:
            self._run(fn)
            return
        self._q.put(fn)

    def submit_traced(
        self,
        fn: Callable[[], None],
        task: str,
        sink: Optional[EventSink] = None,
        **fields: Any,
    ) -> None:
        """``submit``, plus trace attribution of the off-thread work.

        When a trace context is active at SUBMIT time (only ever under
        ``--trace on``) the task is wrapped to emit a ``writer_task``
        span after it runs: ``ms`` is the on-thread execution time,
        ``queued_ms`` the time spent waiting in the rim queue, and
        ``parent_span_id`` the span that submitted it — so checkpoint
        serialization and record pickles are attributed to the round
        that caused them instead of orphaned on the writer thread.  The
        span emits on ``sink``'s synchronous leaves (never back through
        the queue — the consumer must not block on itself).  With no
        active context this is exactly ``submit``.
        """
        ctx = trace_lib.current()
        if ctx is None or sink is None:
            self.submit(fn)
            return
        from .events import make_event  # local: avoid import cycle

        trace_id, parent = ctx
        leaves = _sync_sinks(sink)
        t_submit = time.perf_counter()

        def wrapped() -> None:
            t0 = time.perf_counter()
            try:
                fn()
            finally:
                t1 = time.perf_counter()
                extra = dict(fields)
                extra["trace_id"] = trace_id
                extra["span_id"] = trace_lib.new_span_id()
                if parent is not None:
                    extra["parent_span_id"] = parent
                ev = make_event(
                    "span",
                    name="writer_task",
                    ms=round((t1 - t0) * 1e3, 3),
                    task=task,
                    queued_ms=round((t0 - t_submit) * 1e3, 3),
                    **extra,
                )
                for leaf in leaves:
                    try:
                        leaf.emit(ev)
                    except Exception:  # noqa: BLE001 - span is best-effort
                        pass

        self.submit(wrapped)

    def _run(self, fn: Callable[[], None]) -> None:
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 - degrade, don't die
            if self._error is None:
                self._error = exc
            if not self._warned:
                self._warned = True
                print(
                    f"[obs] WARNING: async writer task failed "
                    f"({type(exc).__name__}: {exc}); the writer keeps "
                    f"draining and the run continues",
                    file=sys.stderr,
                )

    def _loop(self) -> None:
        while True:
            fn = self._q.get()
            try:
                if fn is _STOP:
                    return
                self._run(fn)
            finally:
                self._q.task_done()

    def drain(self) -> None:
        """Block until every task submitted so far has run (the run-end
        contract: records/streams are complete when this returns)."""
        self._q.join()

    def close(self) -> None:
        """Drain and stop the consumer.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_STOP)
        self._thread.join()


class AsyncSink(EventSink):
    """Rides an inner sink on a :class:`WriterThread`.

    ``emit`` enqueues the inner emit — the inner sink stamps its ``seq``
    envelope on the writer thread, where the single consumer serializes
    stamping and appending into one total order.  ``flush``/``close``
    drain first, so a closed stream is complete and seq-monotonic with
    zero lost events.
    """

    def __init__(self, inner: EventSink, writer: WriterThread) -> None:
        self.inner = inner
        self._writer = writer

    def emit(self, event: Dict[str, Any]) -> None:
        inner = self.inner
        self._writer.submit(lambda: inner.emit(event))

    def flush(self) -> None:
        self._writer.drain()
        self.inner.flush()

    def close(self) -> None:
        self._writer.drain()
        self.inner.close()
