"""Programmatic device profiling + memory watermarks.

Two measurement surfaces the analytic side (``obs/hbm.py``,
``obs/span.py``) cannot provide:

* **Device traces** — :class:`Profiler` wraps ``jax.profiler`` so a run
  started with ``--profile-dir DIR`` produces a trace directory loadable
  in Perfetto/XProf, with every round a named ``StepTraceAnnotation``
  (``round`` / ``step_num=r``) and eval/checkpoint phases named
  ``TraceAnnotation`` regions.  ``--profile-rounds A:B`` restricts the
  capture to the half-open round window ``[A, B)`` so a long run can
  trace three steady-state rounds instead of gigabytes of everything.
  With ``profile_dir`` unset every method is a no-op returning a shared
  ``nullcontext`` — zero device syncs, zero allocations per round.

* **Memory watermarks** — :func:`device_memory` reads
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``)
  from the first addressable device that reports them.  CPU backends
  report none, so the fallback is the process RSS (current from
  ``/proc/self/statm``, peak from ``ru_maxrss``) labeled
  ``source: "host_rss"`` — watermark fields are always present on
  ``round`` events of an observed run, and downstream consumers key on
  ``source`` before comparing against the device-side HBM model.

``jax`` is imported lazily inside methods: ``bench.py``'s parent process
(and any other jax-free caller) can import :mod:`obs` without dragging
in a backend.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional, Tuple

#: reusable no-op context (``contextlib.nullcontext`` is re-entrant and
#: stateless, so one shared instance serves every disabled annotation)
_NULL_CTX = contextlib.nullcontext()


def parse_rounds(spec: str) -> Tuple[int, int]:
    """Parse a ``--profile-rounds A:B`` half-open window ``[A, B)``.

    Raises ``ValueError`` on anything but ``int:int`` with
    ``0 <= A < B`` — config validation calls this, so a bad spec dies at
    startup, not at round A.
    """
    parts = spec.split(":")
    if len(parts) != 2:
        raise ValueError(
            f"profile_rounds must be 'A:B' (half-open round window), got {spec!r}"
        )
    try:
        a, b = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(f"profile_rounds bounds must be integers, got {spec!r}")
    if a < 0 or b <= a:
        raise ValueError(
            f"profile_rounds needs 0 <= A < B, got {spec!r}"
        )
    return a, b


class Profiler:
    """jax.profiler driver for one run.

    Whole-run mode (no window): the harness calls :meth:`start` before
    the training loop and :meth:`close` after.  Window mode
    (``profile_rounds='A:B'``): the trainer's :meth:`round_start` /
    :meth:`round_end` hooks open the trace entering round A and close it
    leaving round B-1.  Either way :meth:`step` wraps each round in a
    ``StepTraceAnnotation`` and :meth:`phase` names eval/checkpoint
    regions — both return the shared null context while no trace is
    active, so annotations outside the window (or with profiling off)
    cost one attribute check.
    """

    def __init__(self, profile_dir: str = "",
                 window: Optional[Tuple[int, int]] = None) -> None:
        self.profile_dir = profile_dir
        self.window = window
        self._active = False
        #: True once any trace was captured (drives the ``profile`` event)
        self.captured = False

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    # -- trace lifecycle ------------------------------------------------
    def _start_trace(self) -> None:
        import jax

        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self._active = True
        self.captured = True

    def _stop_trace(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self._active = False

    def start(self) -> None:
        """Whole-run capture: open the trace now (no-op in window mode —
        the round hooks own the lifecycle there)."""
        if self.enabled and self.window is None:
            self._start_trace()

    def round_start(self, round_idx: int) -> None:
        """Window mode: open the trace when ``round_idx`` enters [A, B)."""
        if (
            self.enabled
            and self.window is not None
            and not self._active
            and self.window[0] <= round_idx < self.window[1]
        ):
            self._start_trace()

    def round_end(self, round_idx: int) -> None:
        """Window mode: close the trace after the last window round."""
        if (
            self._active
            and self.window is not None
            and round_idx >= self.window[1] - 1
        ):
            self._stop_trace()

    def close(self) -> None:
        """Stop any open trace (harness ``finally`` — a run killed inside
        the window still flushes what it captured)."""
        if self._active:
            self._stop_trace()

    # -- annotations ----------------------------------------------------
    def step(self, round_idx: int):
        """Named per-round step region (``round`` in Perfetto/XProf)."""
        if not self._active:
            return _NULL_CTX
        import jax

        return jax.profiler.StepTraceAnnotation("round", step_num=round_idx)

    def phase(self, name: str):
        """Named phase region (``eval`` / ``checkpoint``)."""
        if not self._active:
            return _NULL_CTX
        import jax

        return jax.profiler.TraceAnnotation(name)


#: the disabled singleton — shared, every method a no-op
NULL_PROFILER = Profiler()


def from_config(cfg) -> Profiler:
    """Build the run's Profiler from ``profile_dir`` / ``profile_rounds``
    (:data:`NULL_PROFILER` when profiling is off)."""
    profile_dir = getattr(cfg, "profile_dir", "")
    if not profile_dir:
        return NULL_PROFILER
    spec = getattr(cfg, "profile_rounds", "")
    return Profiler(profile_dir, parse_rounds(spec) if spec else None)


# -- memory watermarks --------------------------------------------------

def _host_rss() -> Tuple[int, int]:
    """(current, peak) resident-set bytes of this process."""
    page = os.sysconf("SC_PAGE_SIZE")
    try:
        with open("/proc/self/statm") as f:
            current = int(f.read().split()[1]) * page
    except (OSError, ValueError, IndexError):
        current = 0
    try:
        import resource

        # ru_maxrss is KiB on Linux
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        peak = current
    return current, max(current, peak)


def device_memory(devices=None) -> Dict[str, object]:
    """Current + peak memory watermarks.

    Returns ``{"bytes_in_use", "peak_bytes_in_use", "source"}`` where
    ``source`` is ``"device:<platform>"`` when ``memory_stats()`` is
    available (TPU/GPU allocator stats) or ``"host_rss"`` on backends
    that report none (CPU).  Consumers MUST check ``source`` before
    comparing against the analytic HBM model — a host RSS includes the
    interpreter and compiler, not just program buffers.
    """
    if devices is None:
        import jax

        devices = jax.local_devices()
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            return {
                "bytes_in_use": int(stats["bytes_in_use"]),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", stats["bytes_in_use"])
                ),
                "source": f"device:{dev.platform}",
            }
    current, peak = _host_rss()
    return {
        "bytes_in_use": current,
        "peak_bytes_in_use": peak,
        "source": "host_rss",
    }


def per_device_memory(devices=None):
    """Per-device watermark rows for mesh runs.

    One dict per device that reports allocator stats — ``{"device",
    "platform", "bytes_in_use", "peak_bytes_in_use", "source"}`` — so a
    population-sharded round can be judged against the PER-HOST budget
    (``obs/hbm.py streamed_peak_bytes(pop_shards=...)``) rather than the
    first device's or a mesh-wide number.  Backends whose devices report
    no stats (CPU, including the virtual-device CI mesh, where every
    "device" shares one host allocator) yield a single ``host_rss`` row;
    consumers MUST check ``source`` before cross-checking, same contract
    as :func:`device_memory`.
    """
    if devices is None:
        import jax

        devices = jax.local_devices()
    rows = []
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            rows.append(
                {
                    "device": int(getattr(dev, "id", len(rows))),
                    "platform": dev.platform,
                    "bytes_in_use": int(stats["bytes_in_use"]),
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", stats["bytes_in_use"])
                    ),
                    "source": f"device:{dev.platform}",
                }
            )
    if rows:
        return rows
    current, peak = _host_rss()
    return [
        {
            "device": None,
            "platform": None,
            "bytes_in_use": current,
            "peak_bytes_in_use": peak,
            "source": "host_rss",
        }
    ]
