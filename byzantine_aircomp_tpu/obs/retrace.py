"""Retrace detector: count lowerings of the jitted hot-path functions.

A steady-state federated run compiles the round program ONCE and then
re-dispatches it; any further lowering means a shape / dtype / static-arg
leak re-entered the compiler mid-run — the classic silent 100x
regression.  ``jax.jit`` re-executes the wrapped Python callable exactly
when it traces, so a plain Python counter wrapped UNDER the jit boundary
counts lowerings with zero effect on the traced program (the wrapper is
invisible to XLA: same jaxpr, same RNG stream, same outputs).

``FedTrainer`` wraps its round / multi-round / eval functions through one
detector unconditionally (the counter is two dict ops per trace);
enforcement is opt-in via :meth:`check` — the harness warns, CI raises.
Eval legitimately lowers once per distinct split shape (train vs val
chunk counts differ), so the steady-state gate applies to the round
functions only.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional


class RetraceError(RuntimeError):
    """Raised by :meth:`RetraceDetector.check` in ``error`` mode."""


class RetraceDetector:
    def __init__(self) -> None:
        self.counts: Dict[str, int] = {}

    def wrap(self, name: str, fn: Callable) -> Callable:
        """Wrap ``fn`` (BEFORE jit) so each trace increments ``counts[name]``."""
        self.counts.setdefault(name, 0)

        @functools.wraps(fn)
        def traced(*args, **kwargs):
            self.counts[name] += 1
            return fn(*args, **kwargs)

        return traced

    def count(self, name: str) -> int:
        return self.counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counts)

    def check(
        self,
        name: str,
        max_lowerings: int = 1,
        error: bool = False,
        warn_fn: Optional[Callable[[str], None]] = None,
    ) -> bool:
        """True iff ``name`` lowered at most ``max_lowerings`` times.

        On violation: raises :class:`RetraceError` when ``error``,
        otherwise calls ``warn_fn`` (if given) with a diagnostic line.
        """
        n = self.count(name)
        ok = n <= max_lowerings
        if not ok:
            msg = (
                f"steady-state retracing: {name} lowered {n}x "
                f"(expected <= {max_lowerings}) — a shape/dtype/static-arg "
                "leak is re-entering the compiler mid-run"
            )
            if error:
                raise RetraceError(msg)
            if warn_fn is not None:
                warn_fn(f"WARNING: {msg}")
        return ok
