"""Trace context — W3C-``traceparent``-style ids for the event stream.

A trace is a 32-hex id shared by every event a single logical request
touches, across processes (server, lane group, edges, root, writer
rim).  A span is a 16-hex id naming one timed phase inside the trace;
spans nest via ``parent_span_id``.  This module owns the ambient
context: a context-local ``(trace_id, span_id)`` pair that
``events.make_event`` stamps onto every event emitted while it is
active, and that ``span.SpanTimer`` pushes/pops as spans open and
close.

The context lives in a ``contextvars.ContextVar``: new threads start
with no context, so a traced tenant on one lane never bleeds ids into
a neighbour's stream, and with tracing off nothing ever activates the
context — emission stays byte-identical to the untraced schema.

``span_id`` may be ``None`` in an active context: "this trace, no
parent span yet" — events then carry only ``trace_id`` and spans
opened under it become trace roots rather than orphans.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
from typing import Iterator, Optional, Tuple

# (trace_id, span_id-or-None); None default == tracing inactive
_ctx: contextvars.ContextVar[Optional[Tuple[str, Optional[str]]]] = (
    contextvars.ContextVar("aircomp_trace", default=None)
)

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current() -> Optional[Tuple[str, Optional[str]]]:
    """The active ``(trace_id, span_id)`` pair, or None when untraced."""
    return _ctx.get()


def push(trace_id: str, span_id: Optional[str]):
    """Activate a context; returns the token for ``pop``."""
    return _ctx.set((trace_id, span_id))


def pop(token) -> None:
    _ctx.reset(token)


@contextlib.contextmanager
def activate(
    trace_id: str, span_id: Optional[str] = None
) -> Iterator[None]:
    token = push(trace_id, span_id)
    try:
        yield
    finally:
        pop(token)


def traceparent() -> Optional[str]:
    """The active context as a ``traceparent`` header value, or None.

    A context with no span id is not representable on the wire (the
    header requires a parent id), so it also returns None.
    """
    ctx = _ctx.get()
    if ctx is None or ctx[1] is None:
        return None
    return f"00-{ctx[0]}-{ctx[1]}-01"


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, span_id)`` from a ``traceparent`` value, else None.

    Tolerant of case and surrounding whitespace; rejects the all-zero
    ids the W3C spec reserves as invalid.
    """
    if not header:
        return None
    m = _TRACEPARENT_RE.match(str(header).strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id
