"""In-jit per-client anomaly scores + robust change-point detector.

Everything here runs INSIDE the jitted round step on the already-resident
[K, d] client stack — no extra HBM pass beyond what aggregation reads
anyway, no host round-trips, no RNG consumption (the detector is a pure
function of the stack, so the round's key stream is untouched whatever the
defense mode).  The detector state rides the scan carry exactly like the
fault state (``ops/faults.py``), keeping the retrace audit at one lowering.

Three cheap statistics per client (:func:`client_scores`):

* **update norm** ``||w_i - g||`` relative to the finite-median norm — a
  sign-flipped or scaled row moves ~2||g|| while honest rows move ~gamma;
* **cosine to the finite centroid** of the updates — honest gradients
  roughly agree in direction, an inverted row anti-correlates;
* **pairwise-distance summary** reusing :func:`ops.aggregators
  .pairwise_sq_dists` — the mean squared distance to the finite rows,
  relative to its finite median (the Krum intuition as a score, not a
  selection).

The composite score is scale-free (each term is a relative excess over the
honest median), so one threshold works across models/learning rates.

Per-client baselines (:func:`detector_update`) are robust EMAs with a
huberized innovation — a striking attacker cannot drag its own baseline up
fast enough to hide — plus a one-sided CUSUM change-point statistic, the
classic detector for "small persistent shift" onsets that a pure z-test
misses.  Non-finite rows (deep-fade erasures, NaN corruption from
``ops/faults.py``) are EXCLUDED from every median and their detector state
is held frozen, so a fault burst neither flags as an attack nor poisons
the baselines it will be compared against when it recovers.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..ops import aggregators as agg_lib

#: detector carry: (step i32 scalar, ema [K] f32, dev [K] f32, cusum [K] f32)
DetectorState = tuple


@dataclass(frozen=True)
class DetectorParams:
    """Static detector knobs (FedConfig defense_* fields; see fed/config.py
    for semantics and defaults)."""

    alpha: float = 0.1       # EMA smoothing for baseline mean / deviation
    drift: float = 0.5       # CUSUM allowance k (in robust sigmas)
    z_thresh: float = 4.0    # instantaneous flag at z > z_thresh sigmas
    cusum_thresh: float = 8.0  # change-point flag at cusum > this
    warmup: int = 5          # iterations before flags/CUSUM arm
    clip: float = 3.0        # huber clip on the baseline innovation (sigmas)
    eps: float = 1e-6        # deviation floor


def init_detector(k: int) -> DetectorState:
    return (
        jnp.int32(0),
        jnp.zeros(k, jnp.float32),
        jnp.zeros(k, jnp.float32),
        jnp.zeros(k, jnp.float32),
    )


def masked_median(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Median of ``x[mask]`` with static shapes: masked-out entries sort to
    +inf and the order-statistic index becomes the dynamic ``(n-1)//2``
    (the same idiom as the degraded coordinatewise median).  n = 0 returns
    +inf — callers guard (an all-masked stack is finite-guard territory)."""
    n = jnp.sum(mask)
    srt = jnp.sort(jnp.where(mask, x, jnp.inf))
    idx = jnp.maximum(n - 1, 0) // 2
    return jnp.take(srt, idx)


def client_score_components(w_stack: jnp.ndarray, guess: jnp.ndarray):
    """Per-client anomaly score with its three components kept separate.

    Returns ``(score [K], finite [K], components [K, 3])`` where the
    component columns are (norm_term, cos_term, dist_term) in the order of
    the docstring below.  :func:`client_scores` is this function minus the
    components — same expressions, so the two are bit-identical and the
    unused components are dead code when the caller drops them (forensics
    off traces the same program).

    Each term is a nonnegative RELATIVE excess (honest rows score ~0):

        relu(norm_i / med(norm) - 1)        magnitude blow-up
      + relu(1 - cos(delta_i, centroid))    direction disagreement
      + relu(dist_i / med(dist) - 1)        pairwise-distance outlier

    Medians and the centroid run over FINITE rows only; non-finite rows
    score exactly 0 (they carry no evidence of Byzantine intent — the
    fault subsystem already accounts for them via effective-K).
    """
    finite = agg_lib._finite_rows(w_stack)
    delta = (w_stack - guess[None, :]).astype(jnp.float32)
    safe_delta = jnp.where(finite[:, None], delta, 0.0)

    norms = jnp.sqrt(jnp.sum(safe_delta * safe_delta, axis=1))
    med_norm = masked_median(norms, finite)
    # med_norm can be +inf only when zero rows are finite; the jnp.where
    # on `finite` below zeroes every score in that degenerate round
    norm_term = jnp.maximum(norms / jnp.maximum(med_norm, 1e-12) - 1.0, 0.0)

    cent = agg_lib._finite_centroid(delta, finite)
    cent_norm = jnp.sqrt(jnp.sum(cent * cent))
    cos = jnp.sum(safe_delta * cent[None, :], axis=1) / (
        jnp.maximum(norms, 1e-12) * jnp.maximum(cent_norm, 1e-12)
    )
    cos_term = jnp.maximum(1.0 - cos, 0.0)

    # mean squared distance to the OTHER finite rows; poisoned rows hold
    # inf distances, masked out of every honest row's mean
    dists = agg_lib.pairwise_sq_dists(w_stack)
    pair_mask = finite[None, :] & ~jnp.eye(w_stack.shape[0], dtype=bool)
    n_others = jnp.maximum(jnp.sum(pair_mask, axis=1), 1)
    dist_mean = (
        jnp.sum(jnp.where(pair_mask, dists, 0.0), axis=1) / n_others
    )
    med_dist = masked_median(dist_mean, finite)
    dist_term = jnp.maximum(
        dist_mean / jnp.maximum(med_dist, 1e-12) - 1.0, 0.0
    )

    score = jnp.where(finite, norm_term + cos_term + dist_term, 0.0)
    components = jnp.where(
        finite[:, None],
        jnp.stack([norm_term, cos_term, dist_term], axis=1),
        0.0,
    )
    return score, finite, components


def client_scores(w_stack: jnp.ndarray, guess: jnp.ndarray):
    """Composite per-client anomaly score [K] plus the finite-row mask [K]
    (see :func:`client_score_components` for the score's definition)."""
    score, finite, _ = client_score_components(w_stack, guess)
    return score, finite


def detector_update(
    det: DetectorState,
    score: jnp.ndarray,
    finite: jnp.ndarray,
    p: DetectorParams,
    first=None,
):
    """One detector step: robust EMA baselines + one-sided CUSUM.

    Returns ``(new_state, flags [K] bool)``.  The baseline innovation is
    huberized (clipped at ``p.clip`` robust sigmas) so an attacking client
    barely moves its own baseline; the deviation is an EMA of |clipped
    residual| (a robust scale proxy).  Step 0 seeds ema/dev directly from
    the first observation.  CUSUM accumulates only after warmup — with a
    near-zero seeded deviation the first z-scores are noise, not evidence.
    The CUSUM increment uses the CLIPPED z and the statistic saturates at
    2x its alarm threshold: detection only needs the threshold crossing,
    and an unbounded accumulation would otherwise take arbitrarily long to
    decay after the attacker goes quiet — starving the policy's clean-run
    counter and making de-escalation unreachable.  Non-finite rows hold
    their state and never flag (mirrors the NumPy oracle in
    tests/test_defense.py line for line).

    ``first`` (optional [rows] bool) overrides the seeding condition:
    under service subsampling the detector is population-keyed and a
    client's FIRST observation can land at any step, so the trainer
    passes its own never-updated marker (``dev == 0``) instead of the
    default full-participation ``step == 0``.
    """
    step, ema, dev, cusum = det
    warm = step >= p.warmup
    if first is None:
        first = step == 0

    sigma = dev + p.eps
    resid = score - ema
    z = resid / sigma
    clipped = jnp.clip(resid, -p.clip * sigma, p.clip * sigma)
    ema_new = jnp.where(first, score, ema + p.alpha * clipped)
    dev_new = jnp.where(
        first,
        jnp.abs(score) + p.eps,
        (1.0 - p.alpha) * dev + p.alpha * jnp.abs(clipped),
    )
    z_c = jnp.clip(z, -p.clip, p.clip)
    cusum_new = jnp.where(
        warm,
        jnp.minimum(
            jnp.maximum(cusum + z_c - p.drift, 0.0), 2.0 * p.cusum_thresh
        ),
        jnp.zeros_like(cusum),
    )
    flags = warm & ((z > p.z_thresh) | (cusum_new > p.cusum_thresh)) & finite

    ema = jnp.where(finite, ema_new, ema)
    dev = jnp.where(finite, dev_new, dev)
    cusum = jnp.where(finite, cusum_new, cusum)
    return (step + 1, ema, dev, cusum), flags
