"""Defense observability glue: round-metric unpacking + ``defense`` events.

The jitted round step reduces its per-iteration defense observations to
ONE [6] device vector (fed/train.py ``_round_core``) — rung at round end,
max flagged clients, suspicious-iteration count, max composite score, max
CUSUM, and intra-round rung transitions.  This module is the single place
that knows that packing: the trainer, the harness record keys, and the
``defense`` event emitted through the existing obs sinks all read it via
:func:`round_metrics`, so the wire format cannot drift between consumers.

Event schema (``obs/events.py`` registers the required trio): kind
``defense`` with ``round`` / ``rung`` / ``flagged`` required, plus mode,
the active rung's aggregator name, the previous round's rung and the
derived transition direction — enough for ``analysis/defense_trace.py``
to reconstruct the full escalation history from the stream alone.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# order of the [6] per-round defense-metrics vector the jitted round emits
METRIC_KEYS = (
    "rung", "flagged", "suspicious_iters", "score_max", "cusum_max",
    "transitions",
)

# defense-event field -> harness record path key (mirrors the fault-path
# naming; obs/events.REFERENCE_KEY_MAP carries the same mapping)
PATH_KEYS = {
    "rung": "defenseRungPath",
    "flagged": "defenseFlaggedPath",
    "suspicious_iters": "defenseSuspiciousPath",
    "score_max": "defenseScorePath",
    "cusum_max": "defenseCusumPath",
    "transitions": "defenseTransitionsPath",
}


def round_metrics(device_vec) -> Dict[str, float]:
    """Unpack the round's [6] defense-metrics vector to named floats
    (counts arrive as exact float integers; rung as a float index)."""
    vals = [float(v) for v in np.asarray(device_vec)]
    return dict(zip(METRIC_KEYS, vals))


def active_agg(mode: str, ladder, rung: int, base_agg: str) -> str:
    """The aggregator actually applied this round: the rung's ladder entry
    under ``adaptive``, always the configured one under ``monitor`` (the
    rung is tracked as what WOULD run, but never switches)."""
    return ladder[rung] if mode == "adaptive" else base_agg


def emit_round(
    obs,
    round_idx: int,
    *,
    mode: str,
    agg: str,
    metrics: Dict[str, float],
    prev_rung: Optional[int] = None,
) -> None:
    """One ``defense`` event per round on the configured sinks.

    ``prev_rung`` (the previous round's end rung, host-tracked) turns the
    carried rung into an explicit transition field: "escalate" /
    "deescalate" / None for steady state.
    """
    rung = int(metrics["rung"])
    transition = None
    if prev_rung is not None and rung != prev_rung:
        transition = "escalate" if rung > prev_rung else "deescalate"
    obs.emit(
        "defense",
        round=round_idx,
        mode=mode,
        rung=rung,
        agg=agg,
        prev_rung=prev_rung,
        transition=transition,
        flagged=metrics["flagged"],
        suspicious_iters=metrics["suspicious_iters"],
        score_max=metrics["score_max"],
        cusum_max=metrics["cusum_max"],
        transitions=metrics["transitions"],
    )
