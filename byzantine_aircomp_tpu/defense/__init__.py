"""Online defense: in-jit anomaly scoring + adaptive aggregator escalation.

The paper's receiver commits to one robust aggregator for the whole run,
but the attack surface is dynamic — Byzantine clients can behave honestly
for hundreds of rounds, then strike (``--attack signflip@100``).  This
package is the runtime layer that watches the received stack and reacts:

* :mod:`.scores`  — per-client anomaly statistics from the already-resident
  [K, d] stack + robust EMA/CUSUM change-point detector (zero extra RNG,
  state in the scan carry like ``ops/faults.py``);
* :mod:`.policy`  — the escalation ladder (``mean -> trimmed_mean ->
  multi_krum`` by default) as a branchless ``lax.switch`` with hysteresis;
* :mod:`.events`  — per-round ``defense`` events through the existing obs
  sinks + the round-metric packing shared with the harness record.

Modes (``--defense``): ``off`` — no defense code is traced, the program /
RNG stream / pickled record / config hash are bit-identical to a build
without this package; ``monitor`` — detector + would-be rung tracked and
reported, aggregation untouched (trajectory bit-identical to ``off``);
``adaptive`` — the active rung picks the aggregator in-jit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from . import events  # noqa: F401  (re-export for trainer/harness/analysis)
from .policy import (  # noqa: F401
    PolicyParams,
    aggregate_switch,
    init_policy,
    make_branch_table,
    policy_update,
    validate_ladder,
)
from .scores import (  # noqa: F401
    DetectorParams,
    client_score_components,
    client_scores,
    detector_update,
    init_detector,
)

#: full defense carry: (detector_state, policy_state) — empty () when off
DefenseState = tuple


@dataclass(frozen=True)
class DefenseSpec:
    """Resolved static defense configuration for one run."""

    mode: str                      # "monitor" | "adaptive"
    ladder: Tuple[str, ...]
    detector: DetectorParams
    policy: PolicyParams


def from_config(cfg) -> "DefenseSpec | None":
    """Build the spec from FedConfig (None when ``defense == 'off'``).
    Ladder validation already ran in ``cfg.validate()``."""
    if cfg.defense == "off":
        return None
    ladder = cfg.defense_ladder_names()
    return DefenseSpec(
        mode=cfg.defense,
        ladder=ladder,
        detector=DetectorParams(
            alpha=cfg.defense_alpha,
            drift=cfg.defense_drift,
            z_thresh=cfg.defense_z,
            cusum_thresh=cfg.defense_cusum,
            warmup=cfg.defense_warmup,
        ),
        policy=PolicyParams(
            up_n=cfg.defense_up,
            down_m=cfg.defense_down,
            min_flagged=cfg.defense_min_flagged,
            n_rungs=len(ladder),
            budget_leak=cfg.defense_leak,
            floor_thresh=cfg.defense_floor,
        ),
    )


def init_state(spec: "DefenseSpec | None", k: int) -> DefenseState:
    """Initial scan-carried defense state for K clients (``()`` when off,
    so the default program's carry and donation slots stay cost-free —
    the fault-state idiom)."""
    if spec is None:
        return ()
    return (init_detector(k), init_policy())
