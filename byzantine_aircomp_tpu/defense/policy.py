"""Escalation-ladder policy: hysteresis state machine + branchless switch.

The policy is a pure function of carried state — (rung, up_streak,
down_streak) ride the scan carry next to the detector state — so the whole
defense, scoring through aggregator selection, stays inside the ONE jitted
round program (retrace audit unchanged at a single lowering).

Hysteresis: a round-iteration is *suspicious* when at least
``min_flagged`` clients flag.  ``up_n`` consecutive suspicious iterations
escalate one rung (streak resets, so climbing the whole ladder takes
``up_n`` per rung — a transient cannot jump straight to the most
expensive defense); ``down_m`` consecutive clean iterations de-escalate
one rung.  Either counter resets on the opposite observation.

Duty-cycle resistance (the break-matrix fix): pure streak hysteresis is
breakable by an attacker that bursts, sleeps exactly through the
de-escalation window, and repeats (``ops/attacks.duty_cycle`` probes
precisely this) — every burst restarts against the cheapest rung.  The
policy therefore carries a LEAKY ESCALATION BUDGET: each escalation adds
one unit, the budget decays by ``budget_leak`` per iteration, and while
it sits above ``floor_thresh`` the rung cannot de-escalate below 1.  A
single transient escalation (budget ~1) decays away without ever
tripping the floor; repeated escalations integrate faster than the leak
drains, so a duty-cycled attacker finds the ladder still raised when the
next burst lands.  ``floor_thresh <= 0`` disables the floor (the seed
behavior, kept reachable for before/after matrix cells).

In ``adaptive`` mode the active rung picks the aggregator through
``lax.switch`` over a static table of closures built from the registry —
branchless on-device dispatch, no host involvement, no retrace when the
rung moves.  Every ladder entry is called with the trainer's full keyword
surface (aggregators swallow unknown kwargs via ``**_``), with the fused
epilogue and channel deferral disabled: the deferred-OMA read belongs to
exactly one statically-known aggregator, which an adaptive rung is not
(fed/train.py applies the standalone prepass instead — bit-identical
channel statistics, one extra stack pass only in adaptive mode).

Degraded/fault interplay: the branch closures inherit the trainer's
``degraded`` flag, and the detector upstream freezes state on non-finite
rows — so deep-fade erasures neither masquerade as attacks nor strip the
fault hardening from whichever rung is active.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..registry import AGGREGATORS

#: policy carry: (rung i32, up_streak i32, down_streak i32, budget f32)
PolicyState = tuple


@dataclass(frozen=True)
class PolicyParams:
    """Static hysteresis knobs (FedConfig defense_* fields)."""

    up_n: int = 3          # consecutive suspicious iterations per escalation
    down_m: int = 20       # consecutive clean iterations per de-escalation
    min_flagged: int = 1   # flagged clients that make an iteration suspicious
    n_rungs: int = 3       # ladder length (clamps the rung)
    # leaky escalation budget (duty-cycle resistance, module docstring):
    # +1 per escalation, *(1 - budget_leak) per iteration; budget above
    # floor_thresh pins the rung floor at 1.  floor_thresh <= 0 disables.
    budget_leak: float = 0.005
    floor_thresh: float = 1.5


def init_policy() -> PolicyState:
    return (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.float32(0.0))


def policy_update(pol: PolicyState, n_flagged, p: PolicyParams):
    """One hysteresis step; returns ``(new_state, suspicious bool)``."""
    rung, up, down, budget = pol
    suspicious = n_flagged >= p.min_flagged
    up = jnp.where(suspicious, up + 1, 0)
    down = jnp.where(suspicious, 0, down + 1)
    escalate = up >= p.up_n
    deescalate = (down >= p.down_m) & (rung > 0)
    # escalation-history budget: integrates escalations, leaks per step;
    # above the threshold the floor keeps one rung of caution in place
    # however long the attacker sleeps
    budget = budget * (1.0 - p.budget_leak) + escalate.astype(jnp.float32)
    if isinstance(p.floor_thresh, (int, float)):
        if p.floor_thresh > 0:
            floor = (budget >= p.floor_thresh).astype(jnp.int32)
            floor = jnp.minimum(floor, p.n_rungs - 1)
        else:
            floor = jnp.int32(0)
    else:
        # traced floor_thresh (the experiment-axis batch runner feeds a
        # per-experiment knob): branchless equivalent of the static paths,
        # so a batch may mix enabled and disabled floors in one lowering
        floor = jnp.minimum(
            jnp.where(
                p.floor_thresh > 0,
                (budget >= p.floor_thresh).astype(jnp.int32),
                0,
            ),
            p.n_rungs - 1,
        )
    rung = jnp.clip(
        rung + escalate.astype(jnp.int32) - deescalate.astype(jnp.int32),
        floor,
        p.n_rungs - 1,
    )
    # a consumed streak restarts: each further rung needs fresh evidence
    up = jnp.where(escalate, 0, up)
    down = jnp.where(deescalate, 0, down)
    return (rung, up, down, budget), suspicious


def validate_ladder(names: Sequence[str], base_agg: "str | None") -> None:
    """Fail fast (config-validation time) on a ladder the switch cannot
    realize: unknown names, channel-owning aggregators (gm/signmv transmit
    INSIDE aggregation — there is no received stack for the other rungs to
    share), or — in adaptive mode (``base_agg`` given) — a base rung that
    disagrees with ``cfg.agg`` (the channel dispatch and run title key off
    cfg.agg; the ladder must start there).  Monitor mode passes
    ``base_agg=None``: the rung is only reported, never applied, so any
    configured aggregator may be watched."""
    if len(names) < 2:
        raise ValueError(
            f"defense ladder needs >= 2 rungs to escalate, got {list(names)}"
        )
    for n in names:
        meta = AGGREGATORS.meta(n)  # raises on unknown names
        if meta.get("owns_channel", False):
            raise ValueError(
                f"defense ladder rung {n!r} owns its channel (the AirComp "
                f"transmission happens inside aggregation) — all rungs must "
                f"aggregate the same received stack; use gm2 instead of gm"
            )
    if base_agg is not None and names[0] != base_agg:
        raise ValueError(
            f"defense ladder base rung {names[0]!r} must equal --agg "
            f"{base_agg!r}: rung 0 IS the configured aggregator (set "
            f"--agg {names[0]} or reorder --defense-ladder)"
        )


def make_branch_table(
    names: Sequence[str], *, honest_size: int, **static_kw
) -> List[Callable]:
    """Static table of aggregator closures for ``lax.switch``.

    Each branch takes one operand tuple ``(w_agg, guess, key)`` (the only
    traced per-iteration inputs) and closes over the static keyword
    surface.  All branches return f32 [d] so the switch has one output
    type whatever rung runs.
    """
    branches = []
    for n in names:
        fn = AGGREGATORS.get(n)

        def branch(operand, fn=fn):
            w_agg, guess, key = operand
            return fn(
                w_agg,
                honest_size=honest_size,
                guess=guess,
                key=key,
                **static_kw,
            ).astype(jnp.float32)

        branches.append(branch)
    return branches


def aggregate_switch(rung, branches: List[Callable], w_agg, guess, key):
    """Branchless rung dispatch: one ``lax.switch`` in the traced program."""
    return jax.lax.switch(rung, branches, (w_agg, guess, key))
