"""Named experiment presets — the BASELINE.json scale-up ladder.

Each preset is a dict of :class:`~byzantine_aircomp_tpu.fed.config.FedConfig`
kwargs for one of the north-star configurations (BASELINE.json "configs"),
from the reference's own MNIST MLP K=50 runs (README.md:17-31 of
``/root/reference``) up to the 1000-client CIFAR-10 ResNet-18 target.  Use
via CLI ``--preset <name>`` (explicit flags still override) or
``presets.get(name)`` programmatically.

Memory note for the K=1000 ResNet-18 rungs: the [K, d] client stack is
K x 11.2M floats ≈ 45 GB — more than one chip's HBM, which is exactly why
the sharded trainer splits the stack over the (clients, model) mesh; run
those presets multi-chip (or scale K down single-chip).
"""

from __future__ import annotations

from typing import Dict

from .fed.config import FedConfig

PRESETS: Dict[str, dict] = {
    # reference config 1: ideal-channel baseline (no attack)
    "mnist_mlp_k50_baseline": dict(
        dataset="mnist", model="MLP", honest_size=50, byz_size=0, agg="gm2"
    ),
    # reference config 2: classflip under ideal gm2
    "mnist_mlp_k50_b5_classflip": dict(
        dataset="mnist",
        model="MLP",
        honest_size=45,
        byz_size=5,
        attack="classflip",
        agg="gm2",
    ),
    # reference config 3: classflip over the AirComp channel
    "mnist_mlp_k50_b10_classflip_air": dict(
        dataset="mnist",
        model="MLP",
        honest_size=40,
        byz_size=10,
        attack="classflip",
        agg="gm",
        noise_var=1e-2,
    ),
    # scale-up config 4: EMNIST CNN, K=200 (reference EMNIST widths:
    # fc 2048 -> 62 classes, EMNIST_Air_weight.py:80-82; train-set eval
    # skipped as in the reference, :273-274)
    "emnist_cnn_k200_b40_classflip": dict(
        dataset="emnist",
        model="CNN",
        fc_width=2048,
        honest_size=160,
        byz_size=40,
        attack="classflip",
        agg="gm2",
        eval_train=False,
    ),
    "emnist_cnn_k200_b40_classflip_tmean": dict(
        dataset="emnist",
        model="CNN",
        fc_width=2048,
        honest_size=160,
        byz_size=40,
        attack="classflip",
        agg="trimmed_mean",
        eval_train=False,
    ),
    # the docs/RESULTS.md operating point: mnist_hard's uniform label
    # resampling (p=0.09) pins the Bayes ceiling at 0.919 — the paper
    # figure's convergence level — so robustness differences stay visible
    # instead of saturating at 1.0 on the easy synthetic set
    "mnist_hard_mlp_k50_b5_classflip": dict(
        dataset="mnist_hard",
        model="MLP",
        honest_size=45,
        byz_size=5,
        attack="classflip",
        agg="gm2",
        eval_train=False,
    ),
    "mnist_hard_mlp_k20_b4_weightflip_cclip": dict(
        dataset="mnist_hard",
        model="MLP",
        honest_size=16,
        byz_size=4,
        attack="weightflip",
        agg="cclip",  # adaptive tau default; see docs/RESULTS.md
        eval_train=False,
    ),
    # the non-IID study (docs/RESULTS.md Dirichlet matrix): label-skewed
    # clients, gm2 — the heterogeneity-robust defense — at the matrix's
    # operating point
    "mnist_hard_noniid_k20_b4_classflip": dict(
        dataset="mnist_hard",
        model="MLP",
        honest_size=16,
        byz_size=4,
        attack="classflip",
        agg="gm2",
        partition="dirichlet",
        dirichlet_alpha=0.3,
        eval_train=False,
    ),
    # ... and the literature's remedy for coordinatewise defenses under
    # skew: median + bucketing (Karimireddy 2022); see the
    # bucketing-effect table in docs/RESULTS.md
    "mnist_hard_noniid_k20_b4_weightflip_median_bkt2": dict(
        dataset="mnist_hard",
        model="MLP",
        honest_size=16,
        byz_size=4,
        attack="weightflip",
        agg="median",
        partition="dirichlet",
        dirichlet_alpha=0.3,
        bucket_size=2,
        eval_train=False,
    ),
    # robustness config: the imperfect-world stress test — adversarial
    # clients (classflip) COMPOSED with every non-adversarial fault axis
    # (dropout replay, deep-fade erasure, correlated CSI error, NaN
    # corruption) against gm2, the paper's headline defense.  The run must
    # stay finite every round (receiver finite-guard) and the per-round
    # effective-K path shows how many clients actually landed
    "chaos": dict(
        dataset="mnist_hard",
        model="MLP",
        honest_size=16,
        byz_size=4,
        attack="classflip",
        agg="gm2",
        fault="chaos",
        eval_train=False,
    ),
    # scale-up config 5: CIFAR-10 ResNet-18 at K=1000 (multi-chip regime)
    "cifar10_resnet18_k1000_b100_signflip_krum": dict(
        dataset="cifar10",
        model="ResNet18",
        honest_size=900,
        byz_size=100,
        attack="signflip",
        agg="krum",
        eval_train=False,
    ),
    "cifar10_resnet18_k1000_b100_gradascent_multikrum": dict(
        dataset="cifar10",
        model="ResNet18",
        honest_size=900,
        byz_size=100,
        attack="gradascent",
        agg="multi_krum",
        eval_train=False,
    ),
}


def names():
    return sorted(PRESETS)


def get(name: str, **overrides) -> FedConfig:
    """Build a FedConfig from a preset; ``overrides`` win over the preset."""
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; available: {', '.join(names())}")
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return FedConfig(**kw)
