"""Experiment-axis batching: N same-shape configs, ONE jitted round fn.

Every study so far costs one process and one XLA lowering per config —
``sweep.py`` and the analysis matrices fork a fresh interpreter per cell
and recompile the identical round program dozens of times.  This module
converts the experiment axis into a *batch* axis: N configs that agree on
everything structural (shapes, aggregator choice, execution-path
selection) are stacked into one carry pytree, their divergent scalars
(seeds via per-experiment base keys; learning rate, attack magnitude,
channel SNR, detector/ladder constants as a :class:`BatchableKnobs` dict
of traced ``[N]`` arrays), and ``jax.vmap`` maps the UNMODIFIED
``FedTrainer._round_core`` over the stack under one ``jax.jit``.  One
lowering serves all N cells; a knob change is a device-array update, so
hot-swapping between rounds can never retrace (machine-checked by the
RetraceDetector gate, name ``batch_round_fn``).

Bit-identity: on the seed-only batch (all knobs equal, seeds differ) the
vmapped program reproduces each solo run's trajectory bit-for-bit — the
per-lane computation is the same dot_generals over the same operands, and
the per-round key derivation (``fold_in(base_key, round)``) is identical
because each lane carries its own base key.  tests/test_serve.py pins
this.  A ``backend="map"`` escape hatch lowers through ``jax.lax.map``
(sequential per-lane execution of the solo-shaped element program) for
platforms where a vmapped primitive reassociates.

The contract (what must MATCH across the batch) is enforced by
:func:`validate_batch` and documented in docs/SERVING.md: every
config field that selects a traced-program *structure* — model/dataset
shapes, client counts, aggregator and ladder names, attack identity,
path selection (service/cohort/participation/bucketing/momentum/fedprox),
server-optimizer wiring — must be equal; output-only observability knobs
may differ freely; the knobs in :data:`BATCHABLE_KNOBS` become per-lane
data.

Elastic lanes: the round index is itself per-lane data (an ``[N]``
``int32`` vmapped alongside the carry), so lanes may sit at DIFFERENT
rounds of their own trajectories inside one dispatch.  That is what
makes lane *refill* possible without a retrace: when a tenant drains or
is cancelled, :meth:`BatchRunner.release_lane` frees its slot (and its
quarantine/strike state — a refilled tenant must not inherit the prior
occupant's forensic counters) and :meth:`BatchRunner.install_lane`
splices a new tenant's carry row, base key, knob columns, and own round
counter into the SAME compiled program.  Each lane retires at its own
``cfg.rounds`` horizon; the driver loop runs until every lane is
inactive.  ``serve/elastic.py`` builds the scheduling policy (and the
``shard_map``-over-vmap mesh-tenant backend) on top of these hooks.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as obs_lib
from ..defense import events as defense_events
from ..fed.config import FedConfig
from ..obs import forensics as forensics_lib

#: knobs bound onto the (copied) cfg the round fn reads at trace time
_CFG_KNOBS = (
    "gamma", "weight_decay", "attack_param", "noise_var",
    "churn_arrival", "churn_departure", "straggler_prob",
)
#: cfg knob -> DetectorParams field
_DETECTOR_KNOBS = {
    "defense_alpha": "alpha",
    "defense_drift": "drift",
    "defense_z": "z_thresh",
    "defense_cusum": "cusum_thresh",
    "defense_warmup": "warmup",
}
#: cfg knob -> PolicyParams field
_POLICY_KNOBS = {
    "defense_up": "up_n",
    "defense_down": "down_m",
    "defense_min_flagged": "min_flagged",
    "defense_leak": "budget_leak",
    "defense_floor": "floor_thresh",
}
_INT_KNOBS = frozenset(
    {"defense_warmup", "defense_up", "defense_down", "defense_min_flagged"}
)

#: batchable knobs the STREAMED iteration path Python-gates on (reads
#: concretely at trace time to pick cohort-scan structure): a streamed
#: batch must PIN these — equal across the batch, traced as closure
#: constants, excluded from hot-swap.  ``serve/elastic.py`` enforces it;
#: :func:`static_signature` folds them into a streamed config's digest
#: so tenants that disagree can never be grouped together.
PINNED_STREAM_KNOBS = ("straggler_prob",)

#: every knob that can ride the experiment axis as traced data.  ``seed``
#: is batchable *structurally*: each lane carries its own base key and
#: initial params, no tracer needed.
BATCHABLE_KNOBS = (
    ("seed",)
    + _CFG_KNOBS
    + tuple(_DETECTOR_KNOBS)
    + tuple(_POLICY_KNOBS)
)

#: fields that relocate/duplicate outputs without touching the traced
#: program — free to differ across the batch (mirrors config_hash's
#: unconditional skip list; forensics is NOT here: the in-jit top-M
#: extraction is part of the traced program)
_OUTPUT_ONLY = (
    "checkpoint_dir", "cache_dir", "profile_dir", "profile_rounds",
    "inherit", "mark", "obs_dir", "obs_stdout", "log_file", "quiet",
    "hbm_warn_factor", "metrics", "metrics_port", "alerts",
    "obs_rotate_mb",
    # async-rim knobs: relocate/reorder host I/O without touching the
    # trajectory (mirrors the harness config_hash unconditional skips).
    # rounds_per_dispatch itself is NOT here — R>1 runs route solo
    # (RunRegistry._is_solo) and R forks the hash lineage.
    "async_writer", "dispatch_prefetch",
    # trace is emission-only: it flips span events into id-minting mode,
    # never the traced program
    "trace",
)


def applicable_knobs(cfg: FedConfig) -> List[str]:
    """The traced-knob subset live for this config family: a knob whose
    feature is off (no attack parameter, noiseless channel, defense off,
    single-tenant service off) has no traced read site, so it is neither
    stacked nor hot-swappable."""
    knobs = ["gamma", "weight_decay"]
    if cfg.attack is not None and cfg.attack_param is not None:
        knobs.append("attack_param")
    if cfg.noise_var is not None:
        knobs.append("noise_var")
    if cfg.service == "on":
        knobs += ["churn_arrival", "churn_departure", "straggler_prob"]
    if cfg.defense != "off":
        knobs += list(_DETECTOR_KNOBS) + list(_POLICY_KNOBS)
    return knobs


#: structural-looking fields that are actually host-driver horizons: the
#: per-lane driver loop reads them in Python only, so lanes may differ
#: (a lane retires at its own ``rounds``) — required for elastic refill,
#: where a freed slot is reseated by a tenant mid-way through the
#: group's life
_PER_LANE_HORIZON = ("rounds",)


def _validate_structure(cfgs: Sequence[FedConfig]) -> List[str]:
    """The shared structural contract (everything in
    :func:`validate_batch` except the streamed-cohort carve-out).
    Raises ``ValueError`` naming the first violation; returns the
    applicable traced-knob names on success."""
    if not cfgs:
        raise ValueError("validate_batch: empty batch")
    for cfg in cfgs:
        cfg.validate()
    t = cfgs[0]
    skip = (
        set(BATCHABLE_KNOBS) | set(_OUTPUT_ONLY) | set(_PER_LANE_HORIZON)
    )
    for f in dataclasses.fields(FedConfig):
        if f.name in skip:
            continue
        vals = [getattr(c, f.name) for c in cfgs]
        if any(v != vals[0] for v in vals[1:]):
            raise ValueError(
                f"batch contract: field {f.name!r} must match across the "
                f"batch (it selects traced-program structure), got "
                f"{sorted(set(map(repr, vals)))}"
            )
    for knob in ("attack_param", "noise_var"):
        classes = {getattr(c, knob) is None for c in cfgs}
        if len(classes) > 1:
            raise ValueError(
                f"batch contract: {knob} presence must match across the "
                f"batch (None gates a traced branch); mix of set/None"
            )
    if t.service == "on" and t.rollback != "off":
        raise ValueError(
            "batch contract: service batches require rollback='off' "
            "(warm rollback restores per-run host state outside the "
            "shared batch carry)"
        )
    if t.partition == "dirichlet":
        seeds = {c.seed for c in cfgs}
        if len(seeds) > 1:
            raise ValueError(
                "batch contract: a dirichlet partition derives the data "
                "permutation from the seed; batched lanes share one data "
                "layout, so seeds must match (use contiguous for seed "
                "batches)"
            )
    return applicable_knobs(t)


def validate_batch(cfgs: Sequence[FedConfig]) -> List[str]:
    """The batchable-knob contract of the base (resident-path) runner.
    Raises ``ValueError`` naming the first violation; returns the
    applicable traced-knob names on success.

    Must match across the batch: every FedConfig field that is neither
    batchable (:data:`BATCHABLE_KNOBS`), output-only, nor a host-driver
    horizon (``rounds`` — each lane retires at its own) — shapes,
    aggregator/ladder/attack identity, path selection.  Presence
    classes must match where a knob's *existence* gates traced
    structure: ``attack_param`` / ``noise_var`` are all-None or all-set.
    Additional structural constraints: no streamed cohorts
    (``cohort_size == 0`` — the cohort scan Python-gates on knob values;
    ``serve/elastic.py`` lifts this by pinning the gating knobs),
    ``service == "on"`` requires ``rollback == "off"`` (warm rollback
    restores host state per run and cannot ride a shared batch carry),
    and a ``dirichlet`` partition requires matching seeds (the data
    permutation is seed-derived, and lanes share one data layout).
    """
    knobs = _validate_structure(cfgs)
    if cfgs[0].cohort_size != 0:
        raise ValueError(
            "batch contract: cohort streaming (cohort_size > 0) is not "
            "batchable — the cohort scan selects structure from knob "
            "values; run streamed configs solo"
        )
    return knobs


def static_signature(cfg: FedConfig) -> str:
    """Stable digest of everything :func:`validate_batch` requires to
    match — two configs with equal signatures can share one
    :class:`BatchRunner` (the RunManager's grouping key)."""
    skip = (
        set(BATCHABLE_KNOBS) | set(_OUTPUT_ONLY) | set(_PER_LANE_HORIZON)
    )
    parts = []
    for f in sorted(dataclasses.fields(FedConfig), key=lambda f: f.name):
        if f.name in skip:
            continue
        parts.append(f"{f.name}={getattr(cfg, f.name)!r}")
    parts.append(f"attack_param_set={cfg.attack_param is not None}")
    parts.append(f"noise_var_set={cfg.noise_var is not None}")
    if cfg.partition == "dirichlet":
        parts.append(f"seed={cfg.seed}")
    if cfg.cohort_size > 0:
        # streamed tenants additionally pin the Python-gated knobs: two
        # configs that disagree can never share a lowering
        for knob in PINNED_STREAM_KNOBS:
            parts.append(f"{knob}={getattr(cfg, knob)!r}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def gather_knobs(cfgs: Sequence[FedConfig]) -> Dict[str, jnp.ndarray]:
    """The :class:`BatchableKnobs` pytree: knob name -> ``[N]`` device
    array over the batch.  EVERY applicable knob is stacked — even one
    constant across the batch — so a later hot-swap is a pure data
    update, never a closure-constant change (which would retrace)."""
    knobs = applicable_knobs(cfgs[0])
    out = {}
    for k in knobs:
        dtype = jnp.int32 if k in _INT_KNOBS else jnp.float32
        out[k] = jnp.asarray([getattr(c, k) for c in cfgs], dtype=dtype)
    return out


@contextmanager
def _bound(template, values: Dict[str, Any]):
    """Install per-experiment knob values (typically tracers) into the
    template trainer for the duration of one trace.

    ``FedTrainer._round_core`` reads ``self.cfg`` and
    ``self.defense.detector/policy`` at TRACE time, so swapping a copied
    cfg (plain dataclass -> ``copy.copy`` + setattr) and a
    ``dataclasses.replace``d DefenseSpec routes every knob read through
    the traced values without touching the trainer's real state."""
    old_cfg, old_defense = template.cfg, template.defense
    cfg = copy.copy(old_cfg)
    for knob in _CFG_KNOBS:
        if knob in values:
            setattr(cfg, knob, values[knob])
    defense = old_defense
    if defense is not None:
        det_kw = {
            field: values[knob]
            for knob, field in _DETECTOR_KNOBS.items()
            if knob in values
        }
        pol_kw = {
            field: values[knob]
            for knob, field in _POLICY_KNOBS.items()
            if knob in values
        }
        if det_kw or pol_kw:
            defense = dataclasses.replace(
                defense,
                detector=dataclasses.replace(defense.detector, **det_kw),
                policy=dataclasses.replace(defense.policy, **pol_kw),
            )
    template.cfg, template.defense = cfg, defense
    try:
        yield
    finally:
        template.cfg, template.defense = old_cfg, old_defense


class BatchRunner:
    """N same-shape experiments through one jitted, vmapped round fn.

    Builds N real ``FedTrainer``s (jit wrappers are lazy, so construction
    costs init-state only; the dataset is loaded once and shared), stacks
    their 7-slot carries and base keys, and drives the template trainer's
    ``_round_core`` under ``jit(vmap(...))`` with the round index as a
    traced ``int32`` (the ``_build_multi_round_fn`` fold_in discipline) —
    so rounds, knob swaps, and lane cancellation all reuse ONE lowering.
    """

    def __init__(
        self,
        cfgs: Sequence[FedConfig],
        dataset=None,
        retrace: Optional[obs_lib.RetraceDetector] = None,
        backend: str = "vmap",
        restore_fn: Optional[Callable[[int, Any], None]] = None,
    ) -> None:
        from ..data import datasets as data_lib
        from ..fed.train import FedTrainer

        self.knob_names = self._validate(cfgs)
        self.backend = backend
        self.cfgs = list(cfgs)
        self.n = len(self.cfgs)
        self.dataset = dataset or data_lib.load(self.cfgs[0].dataset)
        build = self._builder(backend)  # raises on an unknown backend
        self.trainers = [
            FedTrainer(c, dataset=self.dataset) for c in self.cfgs
        ]
        if restore_fn is not None:
            # checkpoint resume hook: install restored state into each
            # lane's trainer BEFORE the carries are stacked (the server's
            # crash-recovery path — see harness.restore_trainer)
            for lane, t in enumerate(self.trainers):
                restore_fn(lane, t)
        self.template = self.trainers[0]
        self.knobs = self._gather_knobs()
        self.carry = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[self._carry_of(t) for t in self.trainers],
        )
        self.base_keys = jnp.stack([t._base_key for t in self.trainers])
        self.retrace = retrace or obs_lib.RetraceDetector()
        self.active = [True] * self.n
        #: per-lane round cursor: lane i's NEXT round of its own
        #: trajectory (elastic lanes may sit at different rounds)
        self.lane_rounds = [0] * self.n
        #: lanes reseated via install_lane over this runner's lifetime
        self.refills = 0
        #: lane -> quarantine reason; a poisoned lane (non-finite params/
        #: variance/loss, exception in its eval) is evicted from recording
        #: while the surviving lanes continue in the same lowering
        self.failed: Dict[int, str] = {}
        self._batched_fn = jax.jit(
            self.retrace.wrap("batch_round_fn", build()),
            donate_argnums=self._donate_argnums(),
        )
        # last per-lane metric rows ([N, ...] device arrays, () when off)
        self.last_fault_metrics = ()
        self.last_defense_metrics = ()
        self.last_service_metrics = ()
        self.last_forensic_metrics = ()

    def _validate(self, cfgs: Sequence[FedConfig]) -> List[str]:
        """The admission contract; subclasses widen it (elastic runners
        admit streamed configs with pinned gating knobs)."""
        return validate_batch(cfgs)

    def _builder(self, backend: str) -> Callable[[], Callable]:
        if backend == "vmap":
            return self._build_vmap
        if backend == "map":
            return self._build_map
        raise ValueError(f"backend must be 'vmap' or 'map', got {backend!r}")

    def _donate_argnums(self) -> tuple:
        """Donate the carry into the batched fn (subclasses narrow this
        where donation is unsound, mirroring parallel/popmesh.py's CPU
        shard_map caveat)."""
        return (0,)

    def _gather_knobs(self) -> Dict[str, jnp.ndarray]:
        out = {}
        for k in self.knob_names:
            dtype = jnp.int32 if k in _INT_KNOBS else jnp.float32
            out[k] = jnp.asarray(
                [getattr(c, k) for c in self.cfgs], dtype=dtype
            )
        return out

    @staticmethod
    def _carry_of(t):
        return (
            t.flat_params, t.server_opt_state, t.client_m, t.fault_state,
            t.defense_state, t.attack_iter, t.service_state,
        )

    def _one(self, carry, base_key, knobs, round_idx):
        template = self.template
        with _bound(template, knobs):
            round_key = jax.random.fold_in(base_key, round_idx)
            return template._round_core(
                *carry, round_key, template.x_train, template.y_train
            )

    def _build_vmap(self):
        def batched(carry, base_keys, knobs, round_idx):
            return jax.vmap(
                self._one, in_axes=(0, 0, 0, 0)
            )(carry, base_keys, knobs, round_idx)

        return batched

    def _build_map(self):
        def batched(carry, base_keys, knobs, round_idx):
            def elem(args):
                c, k, kn, r = args
                return self._one(c, k, kn, r)

            return jax.lax.map(elem, (carry, base_keys, knobs, round_idx))

        return batched

    # -------------------------------------------------------- execution

    def run_round(self, round_idx):
        """One batched round; returns the per-lane honest-dispersion
        metric ``[N]`` as a device array (no host sync — the solo
        ``run_round`` discipline).  ``round_idx`` is a scalar (every lane
        at the same round — the uniform-batch fast path and the legacy
        caller surface) or a length-N sequence of per-lane rounds
        (elastic groups whose lanes sit at different points of their own
        trajectories).  Either way the jitted fn sees ONE ``[N]`` int32
        aval, so mixing scalars and lists can never retrace."""
        if np.ndim(round_idx) == 0:
            rounds = jnp.full((self.n,), int(round_idx), jnp.int32)
        else:
            rounds = jnp.asarray(round_idx, jnp.int32)
        out = self._batched_fn(
            self.carry, self.base_keys, self.knobs, rounds
        )
        self.carry = tuple(out[:7])
        (
            variance, self.last_fault_metrics, self.last_defense_metrics,
            self.last_service_metrics, self.last_forensic_metrics,
        ) = out[7:12]
        return variance

    def lane_params(self, lane: int):
        return self.carry[0][lane]

    def lane_state(self, lane: int):
        """One lane's resumable state as host arrays in
        ``harness.extra_state`` leaf order — ``(flat_params,
        extra_leaves)`` ready for ``checkpoint.save``, so a batch-lane
        checkpoint restores through the same path as a solo one.  The
        carry slots after params (server-opt, client momentum, fault,
        defense, attack-iter, service) match the solo tuple's first six
        slots; the rollback-epoch tail is pinned 0 because service
        batches require rollback off (validate_batch)."""
        flat = np.asarray(self.carry[0][lane])
        extras = [
            np.asarray(leaf[lane])
            for leaf in jax.tree.leaves(tuple(self.carry[1:]))
        ]
        if self.cfgs[lane].service == "on":
            extras.append(np.zeros((), np.int32))
        return flat, extras

    def _quarantine(
        self, lane: int, round_idx: int, reason: str, on_quarantine, log
    ) -> None:
        """Evict a poisoned lane: stop recording, freeze its carry row
        finite (an eager per-row ``.at[lane].set`` — same shapes/dtypes,
        so the jitted program never retraces), and notify the control
        plane.  Cotenant lanes are untouched: under vmap every lane's
        computation is independent, so the survivors stay bit-identical
        to a batch that never contained the poisoned tenant."""
        self.active[lane] = False
        self.failed[lane] = reason

        def freeze(leaf):
            if jnp.issubdtype(leaf.dtype, jnp.inexact):
                return leaf.at[lane].set(
                    jnp.nan_to_num(leaf[lane], posinf=0.0, neginf=0.0)
                )
            return leaf

        self.carry = jax.tree.map(freeze, self.carry)
        log(f"[lane {lane}] QUARANTINED at round {round_idx}: {reason}")
        if on_quarantine is not None:
            try:
                on_quarantine(lane, round_idx, reason)
            except Exception:  # a control-plane bug must not kill cotenants
                import traceback

                traceback.print_exc()

    def evaluate(self, lane: int, split: str = "val"):
        """Per-lane eval through the TEMPLATE's jitted eval fn (one
        lowering for every lane; chunk cache shared — lanes share one
        dataset by contract)."""
        t = self.template
        if split not in t._eval_cache:
            ds = t.dataset
            arrs = (
                (ds.x_val, ds.y_val) if split == "val"
                else (ds.x_train, ds.y_train)
            )
            t._eval_cache[split] = t._chunked(*arrs)
        x, y, m = t._eval_cache[split]
        loss, acc = t._eval_fn(self.lane_params(lane), x, y, m)
        return float(loss), float(acc)

    # -------------------------------------------------------- hot swap

    def set_knob(self, lane: int, name: str, value) -> None:
        """Hot-swap one lane's knob: a pure device-array update, so the
        next round reuses the existing lowering (RetraceDetector-gated by
        callers).  Raises ``KeyError`` for knobs that are not traced data
        in this batch's config family."""
        if name not in self.knobs:
            raise KeyError(
                f"knob {name!r} is not traced data for this batch "
                f"(batchable here: {sorted(self.knobs)}); structural "
                f"knobs cannot be hot-swapped without a retrace"
            )
        if not 0 <= lane < self.n:
            raise IndexError(f"lane {lane} out of range [0, {self.n})")
        arr = self.knobs[name]
        self.knobs[name] = arr.at[lane].set(
            jnp.asarray(value, dtype=arr.dtype)
        )

    def cancel(self, lane: int) -> None:
        """Stop recording/evaluating a lane.  The lane's compute still
        rides the batch (masking it out would change nothing — the
        program is shape-static) but it stops producing records, events,
        or evals; when every lane is cancelled the driver loop exits."""
        self.release_lane(lane)

    # -------------------------------------------------- elastic lanes

    def release_lane(self, lane: int) -> None:
        """Free a lane slot for refill: deactivate it AND clear its
        quarantine/strike state, so a tenant reseated into this lane
        never inherits the prior occupant's forensic counters (the
        cancel-then-refill contamination bug)."""
        self.active[lane] = False
        self.failed.pop(lane, None)

    def install_lane(
        self,
        lane: int,
        cfg: FedConfig,
        own_round: int = 0,
        restored=None,
        paths: Optional[Dict[str, list]] = None,
    ) -> None:
        """Reseat a freed lane with a new tenant, reusing the existing
        lowering: build its trainer (optionally restoring a checkpoint —
        the journal's requeue path, so a refilled resume is bit-identical
        to the uninterrupted run), splice its carry row / base key / knob
        columns into the stacked state, and start its own round cursor at
        ``own_round``.  Shapes and dtypes are pinned by the signature
        contract, so the splice is pure data movement — the retrace gate
        stays at one lowering."""
        from ..fed import harness
        from ..fed.train import FedTrainer

        self._validate([self.cfgs[0], cfg])
        t = FedTrainer(cfg, dataset=self.dataset)
        if restored is not None:
            harness.restore_trainer(t, cfg, restored, log_fn=lambda s: None)
        self.cfgs[lane] = cfg
        self.trainers[lane] = t
        self.carry = jax.tree.map(
            lambda leaf, row: leaf.at[lane].set(row),
            self.carry, self._carry_of(t),
        )
        self.base_keys = self.base_keys.at[lane].set(t._base_key)
        for k, arr in self.knobs.items():
            self.knobs[k] = arr.at[lane].set(
                jnp.asarray(getattr(cfg, k), dtype=arr.dtype)
            )
        self.active[lane] = True
        self.failed.pop(lane, None)
        self.lane_rounds[lane] = int(own_round)
        self.refills += 1
        if getattr(self, "_prev_rung", None) is not None:
            self._prev_rung[lane] = (
                int(t.defense_state[1][0]) if t.defense is not None
                else None
            )
        if getattr(self, "paths_list", None) is not None:
            # AFTER the carry splice: a fresh lane's index-0 eval reads
            # the newly installed params
            self.paths_list[lane] = (
                dict(paths) if paths is not None else self._init_paths(lane)
            )

    # -------------------------------------------------------- driver

    def _init_paths(self, lane: int) -> Dict[str, list]:
        cfg = self.cfgs[lane]
        t = self.trainers[lane]
        if cfg.eval_train:
            tr_loss, tr_acc = self.evaluate(lane, "train")
        else:
            tr_loss, tr_acc = (0.0, 0.0)
        va_loss, va_acc = self.evaluate(lane, "val")
        paths: Dict[str, list] = {
            "trainLossPath": [tr_loss],
            "trainAccPath": [tr_acc],
            "valLossPath": [va_loss],
            "valAccPath": [va_acc],
            "variencePath": [],  # sic — reference spelling
            "roundsPerSec": [],
        }
        if t.fault is not None:
            paths["faultDroppedPath"] = []
            paths["faultErasedPath"] = []
            paths["faultCorruptPath"] = []
            paths["effectiveKPath"] = []
        if t.defense is not None:
            for path_key in defense_events.PATH_KEYS.values():
                paths[path_key] = []
        if cfg.service == "on":
            paths["serviceAvailPath"] = []
            paths["serviceAbsentPath"] = []
            paths["serviceLatePath"] = []
            paths["effectiveKPath"] = []
        return paths

    def train(
        self,
        log_fn: Optional[Callable[[str], None]] = None,
        obs_list: Optional[Sequence["obs_lib.Observability"]] = None,
        start_round: int = 0,
        before_round: Optional[Callable[[int], None]] = None,
        after_round: Optional[Callable[[int], None]] = None,
        resume_paths: Optional[Sequence[Optional[Dict[str, list]]]] = None,
        on_quarantine: Optional[Callable[[int, int, str], None]] = None,
        start_rounds: Optional[Sequence[int]] = None,
        on_lane_done: Optional[Callable[[int], None]] = None,
    ) -> List[Dict[str, list]]:
        """Drive every lane to its own ``cfg.rounds``; returns per-lane
        paths dicts mirroring ``FedTrainer.train`` (same keys, same float
        conversions — the bit-identity surface).  ``obs_list`` supplies
        one Observability per lane (None entries allowed);
        ``before_round(step)`` runs at each group-step boundary — the
        control plane applies queued knob swaps, cancellations, and lane
        REFILLS there (``release_lane`` + ``install_lane``) — and
        ``after_round(step)`` after the step's lanes are recorded (the
        control plane checkpoints there, reading ``self.paths_list``).
        A lane that reaches its horizon is retired (``on_lane_done(i)``,
        then its slot is free for refill); the loop exits when no lane is
        active.

        Resume: ``start_rounds[i]`` (or the uniform ``start_round``) with
        ``resume_paths[i]`` holding lane i's checkpointed paths (entries
        through its resume round) continues a crashed batch — the
        per-round ``fold_in`` keys make the suffix bit-identical to the
        uninterrupted run.  Lanes with a None entry start fresh (initial
        eval at index 0).

        Quarantine: a lane whose params/variance go non-finite, whose
        eval returns a non-finite loss, or whose recording raises is
        evicted (``self.failed[lane]`` holds the reason,
        ``on_quarantine(lane, round, reason)`` notifies the control
        plane) while the surviving lanes continue — same lowering, no
        retrace."""
        log = log_fn or (lambda s: None)
        self.obs_list = list(obs_list) if obs_list else [None] * self.n
        paths_list = [
            (
                dict(resume_paths[i])
                if resume_paths is not None and resume_paths[i] is not None
                else self._init_paths(i)
            )
            for i in range(self.n)
        ]
        self.paths_list = paths_list
        self.lane_rounds = [
            int(r) for r in (
                start_rounds if start_rounds is not None
                else [start_round] * self.n
            )
        ]
        self._prev_rung = [
            int(t.defense_state[1][0]) if t.defense is not None else None
            for t in self.trainers
        ]
        # a lane resumed AT its horizon has nothing left to run
        self._retire_done_lanes(on_lane_done)
        step = min(self.lane_rounds)
        while True:
            if before_round is not None:
                before_round(step)
            # a lane REFILLED at/past its horizon (resumed from a
            # final-round checkpoint) retires without running a round
            self._retire_done_lanes(on_lane_done)
            if not any(self.active):
                break
            # each lane runs the next round of ITS OWN trajectory; a
            # uniform group passes one scalar (the legacy surface), a
            # mixed group the per-lane list — same [N] aval either way
            rounds = list(self.lane_rounds)
            uniform = len(set(rounds)) == 1
            arg = rounds[0] if uniform else rounds
            before = self.retrace.count("batch_round_fn")
            t0 = time.perf_counter()
            variance = self.run_round(arg)
            jax.block_until_ready(self.carry[0])
            compiled = self.retrace.count("batch_round_fn") > before
            dt = time.perf_counter() - t0
            var_np = np.asarray(variance)
            # per-lane health: a poisoned tenant (divergent gamma, hostile
            # knob swap) shows up as non-finite params or dispersion; one
            # [N]-reduction per round keeps the check off the hot path
            finite_np = np.asarray(
                jnp.isfinite(self.carry[0]).all(
                    axis=tuple(range(1, self.carry[0].ndim))
                )
            )
            fm_np = (
                np.asarray(self.last_fault_metrics)
                if self.template.fault is not None else None
            )
            dm_np = (
                np.asarray(self.last_defense_metrics)
                if self.template.defense is not None else None
            )
            sm_np = (
                np.asarray(self.last_service_metrics)
                if self.cfgs[0].service == "on" else None
            )
            for i in range(self.n):
                r = rounds[i]
                if not self.active[i]:
                    continue
                if not np.isfinite(var_np[i]):
                    self._quarantine(
                        i, r, "non-finite round variance", on_quarantine, log
                    )
                    continue
                if not finite_np[i]:
                    self._quarantine(
                        i, r, "non-finite parameters", on_quarantine, log
                    )
                    continue
                # traced lanes record their slice of the vmapped round
                # retrospectively (one device program, N tenant spans);
                # emitted BEFORE the round event so the tail renderer
                # can attach the duration to the line it annotates
                lane_obs = self.obs_list[i] or obs_lib.NULL
                lane_obs.span_event(
                    "round", ms=dt * 1e3,
                    round=r, lane=i, compiled=compiled,
                )
                try:
                    self._record_lane(
                        i, r, float(var_np[i]),
                        None if fm_np is None else fm_np[i],
                        None if dm_np is None else dm_np[i],
                        None if sm_np is None else sm_np[i],
                        dt, compiled, paths_list[i], self.obs_list[i],
                        self._prev_rung, log,
                    )
                except Exception as exc:  # one lane's eval must not kill N-1
                    self._quarantine(
                        i, r,
                        f"recording error: {type(exc).__name__}: {exc}",
                        on_quarantine, log,
                    )
                    continue
                va = paths_list[i]["valLossPath"][-1]
                if not np.isfinite(va):
                    self._quarantine(
                        i, r, "non-finite validation loss", on_quarantine, log
                    )
            for i in range(self.n):
                self.lane_rounds[i] = rounds[i] + 1
            # retire lanes at their own horizon BEFORE after_round, so
            # the control plane's checkpoint pass never writes a
            # past-the-horizon checkpoint for a finished tenant (the run
            # is terminal in the journal from on_lane_done on)
            self._retire_done_lanes(on_lane_done)
            if after_round is not None:
                after_round(step)
            step += 1
        return paths_list

    def _retire_done_lanes(self, on_lane_done) -> None:
        """Deactivate every lane at/past its own horizon, notifying the
        control plane (a hook exception must not kill cotenants)."""
        for i in range(self.n):
            if self.active[i] and self.lane_rounds[i] >= self.cfgs[i].rounds:
                self.active[i] = False
                if on_lane_done is not None:
                    try:
                        on_lane_done(i)
                    except Exception:
                        import traceback

                        traceback.print_exc()

    def _record_lane(
        self, i, r, var_f, fault_row, defense_row, service_row, dt,
        compiled, paths, obs, prev_rung, log,
    ) -> None:
        cfg = self.cfgs[i]
        t = self.trainers[i]
        obs = obs or obs_lib.NULL
        if cfg.eval_train:
            tr_loss, tr_acc = self.evaluate(i, "train")
        else:
            tr_loss, tr_acc = (0.0, 0.0)
        va_loss, va_acc = self.evaluate(i, "val")
        paths["trainLossPath"].append(tr_loss)
        paths["trainAccPath"].append(tr_acc)
        paths["valLossPath"].append(va_loss)
        paths["valAccPath"].append(va_acc)
        paths["variencePath"].append(var_f)
        paths["roundsPerSec"].append(1.0 / dt)
        fault_metrics = None
        if fault_row is not None:
            dropped, erased, corrupt, eff_k = (float(v) for v in fault_row)
            paths["faultDroppedPath"].append(dropped)
            paths["faultErasedPath"].append(erased)
            paths["faultCorruptPath"].append(corrupt)
            paths["effectiveKPath"].append(eff_k)
            fault_metrics = {
                "dropped": dropped, "erased": erased, "corrupt": corrupt,
                "effective_k": eff_k,
            }
        service_metrics = None
        if service_row is not None:
            avail_m, absent_m, late_m, eff_k = (
                float(v) for v in service_row
            )
            paths["serviceAvailPath"].append(avail_m)
            paths["serviceAbsentPath"].append(absent_m)
            paths["serviceLatePath"].append(late_m)
            paths["effectiveKPath"].append(eff_k)
            service_metrics = {
                "available": avail_m, "absent": absent_m, "late": late_m,
                "effective_k": eff_k,
            }
            obs.emit("participation", round=r, **service_metrics)
        if defense_row is not None:
            dmetrics = defense_events.round_metrics(defense_row)
            for dkey, path_key in defense_events.PATH_KEYS.items():
                paths[path_key].append(dmetrics[dkey])
            agg_name = defense_events.active_agg(
                t.defense.mode, t.defense.ladder,
                int(dmetrics["rung"]), cfg.agg,
            )
            defense_events.emit_round(
                obs, r, mode=t.defense.mode, agg=agg_name,
                metrics=dmetrics, prev_rung=prev_rung[i],
            )
            prev_rung[i] = int(dmetrics["rung"])
        if t._forensics_on and obs.enabled:
            forensics_lib.emit_round_flags(
                obs, r, np.asarray(self.last_forensic_metrics[i]),
                mode=cfg.forensics,
            )
        obs.round(
            r,
            train_loss=tr_loss, train_acc=tr_acc,
            val_loss=va_loss, val_acc=va_acc,
            variance=var_f, round_secs=dt, rounds_per_sec=1.0 / dt,
            compiled=compiled,
            fault_metrics=fault_metrics, service_metrics=service_metrics,
        )
        log(
            f"[lane {i}][{r + 1}/{cfg.rounds}] "
            f"val: loss={va_loss:.4f} acc={va_acc:.4f}"
        )
