"""The experiment server: a stdlib HTTP control plane over one port.

Rides the :class:`~..obs.exporter.MetricsExporter` routes hook, so a
single socket serves both surfaces — the Prometheus scrape endpoints the
repo already had (``/metrics``, ``/healthz``) and the multi-tenant run
API this module adds:

* ``POST /runs``              — submit a run (body: FedConfig overrides
  as JSON; same coercion rules as the CLI's ``--set``); returns 201 with
  the run's info including its server-assigned ``run_id``
* ``GET  /runs``              — list every run with status/progress
* ``GET  /runs/<id>``         — one run's info
* ``POST /runs/<id>/cancel``  — cancel (queued: immediate; running: the
  lane goes dark at the next round boundary)
* ``POST /runs/<id>/knobs``   — hot-swap batchable knobs between rounds
  (body: ``{"gamma": 0.05, ...}``); a swap is a per-lane device-array
  update and can never retrace the shared round program

Tenancy: every run writes only under ``<obs_root>/<run_id>/`` (events,
checkpoints, caches), and its metrics carry a ``run_id`` label in the
shared registry, so one ``/metrics`` scrape shows
``aircomp_events_total{kind="round",run_id="run-0001"}`` per tenant.
Errors map conventionally: unknown run -> 404, contract/knob/body
violations -> 400 with ``{"error": ...}``.

See docs/SERVING.md for the API walk-through and the batchable-knob
contract (what may differ across runs sharing one compiled trainer).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional, Tuple

from .. import obs as obs_lib
from ..fed.config import config_from_mapping
from ..obs.metrics import HTTP_SECONDS_BUCKETS
from ..obs.trace import parse_traceparent
from .runs import QueueFull, RunManager

_JSON = "application/json"


class ExperimentServer:
    """RunManager + shared metrics registry + one HTTP surface."""

    def __init__(
        self,
        obs_root: str,
        port: int = 0,
        host: str = "0.0.0.0",
        dataset=None,
        backend: str = "vmap",
        batch_window: float = 0.25,
        queue_cap: int = 0,
        run_retries: int = 1,
        run_backoff: float = 2.0,
        wedge_secs: float = 0.0,
        recover: bool = True,
        auth_token: Optional[str] = None,
    ) -> None:
        # optional bearer auth on the MUTATING surface only: submissions,
        # cancels and knob swaps change tenant state, so they 401 without
        # the token; /metrics, /healthz and the read-only GETs stay open
        # for scrapers and dashboards
        self.auth_token = auth_token
        self.registry = obs_lib.MetricsRegistry()
        self.manager = RunManager(
            obs_root,
            registry=self.registry,
            dataset=dataset,
            backend=backend,
            batch_window=batch_window,
            queue_cap=queue_cap,
            run_retries=run_retries,
            run_backoff=run_backoff,
            wedge_secs=wedge_secs,
        )
        if recover:
            # replay the durable journal BEFORE serving: terminal runs
            # are re-adopted as facts, in-flight runs requeue and resume
            # from their last checkpoint (docs/RUNBOOK.md)
            self.manager.recover()
        self.exporter = obs_lib.MetricsExporter(
            self.registry,
            port=port,
            host=host,
            health_fn=self._health,
            routes=self._routes,
        )

    @property
    def port(self) -> Optional[int]:
        return self.exporter.port

    def start(self) -> "ExperimentServer":
        self.manager.start()
        self.exporter.start()
        return self

    def close(self) -> None:
        self.exporter.close()
        self.manager.close()

    def __enter__(self) -> "ExperimentServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ routes

    def _health(self) -> Dict[str, Any]:
        counts: Dict[str, int] = {}
        for info in self.manager.list_runs():
            counts[info["status"]] = counts.get(info["status"], 0) + 1
        reason = self.manager.degraded()
        body: Dict[str, Any] = {"ok": reason is None, "runs": counts}
        if reason is not None:
            # the exporter maps ok=False to HTTP 503 — a wedged run
            # degrades the whole service until requeued or failed
            body["reason"] = reason
        return body

    @staticmethod
    def _json(status: int, payload: Any) -> Tuple[int, str, bytes]:
        return status, _JSON, (json.dumps(payload) + "\n").encode()

    def _authorized(self, headers: Dict[str, str]) -> bool:
        if self.auth_token is None:
            return True
        auth = headers.get("authorization", "")
        supplied = auth[7:] if auth.startswith("Bearer ") else ""
        # constant-time compare — a token check that leaks prefix length
        # through timing is not a token check
        import hmac as _hmac

        return _hmac.compare_digest(supplied, self.auth_token)

    def _routes(
        self, method: str, path: str, body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ) -> Optional[Tuple[int, str, bytes]]:
        """The exporter's extra-route hook; ``None`` falls through to the
        built-in ``/metrics``/``/healthz`` handling."""
        path = path.split("?", 1)[0]
        # normalize ONCE, before the auth gate: the dispatcher drops
        # empty segments, so gating on the raw path would let
        # ``POST //runs`` skip auth yet still dispatch
        parts = [p for p in path.split("/") if p]
        if (
            method == "POST"
            and parts[:1] == ["runs"]
            and not self._authorized(headers or {})
        ):
            return self._json(401, {"error": "unauthorized"})
        # W3C-style trace continuity: a client that stamps its submit /
        # cancel / knob-swap with ``traceparent`` sees its trace id on
        # every event the request produces (only for --trace on tenants;
        # untraced streams stay byte-identical)
        traceparent = parse_traceparent((headers or {}).get("traceparent"))
        t0 = time.perf_counter()
        try:
            out = self._dispatch(method, parts, body, traceparent)
        except KeyError as exc:
            out = self._json(404, {"error": str(exc).strip("'\"")})
        except QueueFull as exc:  # backpressure, not a client error
            out = self._json(429, {"error": str(exc)})
        except ValueError as exc:  # includes json.JSONDecodeError
            out = self._json(400, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 — surface, don't kill the thread
            out = self._json(
                500, {"error": f"{type(exc).__name__}: {exc}"}
            )
        if out is not None:
            # server-measured request latency, by route template (never
            # the raw path — run ids would explode the label space).
            # This is what the soak harness cross-checks its client-side
            # p99 against: a slow server is visible between soaks too.
            self.registry.observe(
                "aircomp_http_request_seconds",
                time.perf_counter() - t0,
                buckets=HTTP_SECONDS_BUCKETS,
                help_text="server-side run-API request latency by route",
                route=self._route_label(method, parts),
            )
        return out

    @staticmethod
    def _route_label(method: str, parts: list) -> str:
        if parts[:1] != ["runs"]:
            return "other"
        if len(parts) == 1:
            return f"{method} /runs"
        if len(parts) == 2:
            return f"{method} /runs/<id>"
        if len(parts) == 3 and parts[2] in ("cancel", "knobs"):
            return f"{method} /runs/<id>/{parts[2]}"
        return "other"

    def _dispatch(
        self, method: str, parts: list, body: bytes,
        traceparent: Optional[Tuple[str, str]] = None,
    ) -> Optional[Tuple[int, str, bytes]]:
        if not parts or parts[0] != "runs":
            return None
        mgr = self.manager
        if len(parts) == 1:
            if method == "POST":
                overrides = json.loads(body.decode() or "{}")
                if not isinstance(overrides, dict):
                    raise ValueError(
                        "POST /runs body must be a JSON object of "
                        "FedConfig overrides"
                    )
                # a client-supplied idempotency key makes submit retries
                # safe: the same key returns the original run (200), a
                # fresh key creates one (201)
                key = overrides.pop("idempotency_key", None)
                if key is not None and not isinstance(key, str):
                    raise ValueError("idempotency_key must be a string")
                run_id, created = mgr.submit_idempotent(
                    config_from_mapping(overrides), key=key,
                    traceparent=traceparent,
                )
                return self._json(201 if created else 200, mgr.get(run_id))
            if method == "GET":
                return self._json(200, {"runs": mgr.list_runs()})
        elif len(parts) == 2 and method == "GET":
            return self._json(200, mgr.get(parts[1]))
        elif len(parts) == 3 and parts[2] == "cancel" and method == "POST":
            return self._json(
                200, mgr.cancel(parts[1], traceparent=traceparent)
            )
        elif len(parts) == 3 and parts[2] == "knobs" and method == "POST":
            swaps = json.loads(body.decode() or "{}")
            if not isinstance(swaps, dict) or not swaps:
                raise ValueError(
                    "POST /runs/<id>/knobs body must be a non-empty JSON "
                    "object {knob: value}"
                )
            info = None
            for knob, value in swaps.items():
                info = mgr.swap(
                    parts[1], knob, value, traceparent=traceparent
                )
            return self._json(200, info)
        return None
