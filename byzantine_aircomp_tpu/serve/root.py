"""Aggregation root: the trusted fold point of the 2-tier topology.

Edges (serve/edge.py) POST canonical wire partials; the root verifies
them (HMAC, nonce monotonicity, epoch currency, finiteness, shape/tag
consistency), folds each complete phase with the SAME
``ops/shardctx.fold_leaves`` left fold in shard order the sequential
engine uses — so tree == mesh == sequential stays bit-identical — and
hands the fold back to polling edges.  Zero-trust posture:

* a forged MAC never reaches the fold: it is rejected before decode,
  journaled (``forged_rejected``) and counted per claimed identity —
  it can NOT quarantine or strike the edge whose identity it claims,
  or any attacker could evict the fleet edge by edge.
* a replayed nonce under a VALID mac is rejected (409) and journaled
  (``replay_rejected``), but does NOT quarantine the edge either: the
  protocol runs over plain HTTP, so any on-path observer can capture
  and re-POST a legitimate submission — containment here would turn
  passive capture into permanent fleet eviction.  The nonce
  high-water mark already makes the replay inert; distinguishing a
  hostile channel from a compromised edge is an operator call
  (docs/RUNBOOK.md).
* an AUTHENTICATED protocol violation — a fresh, validly signed
  envelope carrying a malformed seq or an out-of-range round, which
  only the keyholder could have produced (every verified envelope
  burns its nonce even when later rejected, so a capture cannot be
  replayed to inflate the count) — costs a strike; at
  ``strike_limit`` strikes the edge is quarantined (``strike_limit``).
* a partial that fails decode / finite checks quarantines its edge
  (``bad_payload`` / ``nonfinite_partial``) — the lane-eviction
  pattern from the batch runner applied one level up.  The phase
  schema (tags/shapes/meta) is decided by NO single submitter:
  submissions buffer until every live edge has reported, the majority
  schema wins (a tie resolves to the first edge in shard order — the
  result-consensus rule), and the dissenting minority is quarantined
  (``bad_payload``) — a Byzantine edge that races a bogus schema in
  first cannot evict the honest fleet one epoch at a time.
* a missing partial past ``partial_timeout`` quarantines the silent
  edges and bumps the round's EPOCH: survivors see ``stale_epoch`` on
  their next request, re-read the live set, and re-run the round in
  degraded mode (the effective-K guards take it from there).  Deadlines
  are checked at the top of every route dispatch — edges and harnesses
  poll continuously, so a dedicated timer thread would buy nothing.

The final exchange of every round carries each edge's RESULT arrays
under the ``"same"`` consensus tag: results are functions of merged data
only, so honest edges agree byte-for-byte.  The root byte-majority
votes, stores the winners as the round's results, and quarantines
dissenters (``result_mismatch``) — a compromised edge cannot poison the
published aggregate without out-voting the fleet.

The numeric fold runs under ``jax.jit`` wrapped by the retrace detector;
each distinct (tags, shapes, live-count) phase signature legitimately
lowers once, and ``/results`` reports ``fold_lowerings`` ==
``fold_signatures`` so the chaos harness can assert the root never
recompiles mid-run.  Nonce high-water marks persist to a root journal
(``serve/journal.py``) and are restored before serving, so replay
protection survives a root restart.
"""

from __future__ import annotations

import argparse
import hmac as hmac_lib
import json
import os
import threading
import time
import urllib.parse
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as obs_lib
from ..ops import shardctx
from . import journal as journal_lib
from .edge import TopologyConfig, sign_envelope

_JSON = "application/json"


class Reject(Exception):
    """A verified-bad submission: carries the HTTP status + payload."""

    def __init__(self, status: int, **payload: Any) -> None:
        super().__init__(payload.get("error", "rejected"))
        self.status = status
        self.payload = payload


class RootState:
    """All root bookkeeping behind one lock (HTTP handler threads)."""

    def __init__(
        self,
        cfg: TopologyConfig,
        obs_dir: Optional[str] = None,
        registry=None,
        now_fn=time.time,
        trace: bool = False,
    ) -> None:
        self.cfg = cfg
        self.now = now_fn
        # --trace on: the root mints the topology-wide trace id (edges
        # adopt it from round_info) and emits per-round root_round /
        # root_fold spans on its own stream
        self.trace = trace
        self.trace_id: Optional[str] = (
            obs_lib.trace.new_trace_id() if trace else None
        )
        self._lock = threading.RLock()
        self.registry = (
            registry if registry is not None else obs_lib.MetricsRegistry()
        )
        if obs_dir:
            os.makedirs(obs_dir, exist_ok=True)
            self.sink: Any = obs_lib.MultiSink([
                obs_lib.JsonlSink(os.path.join(obs_dir, "root.events.jsonl")),
                obs_lib.MetricsSink(self.registry),
            ])
            self.journal = journal_lib.RunJournal(
                os.path.join(obs_dir, journal_lib.ROOT_JOURNAL_NAME)
            )
        else:
            self.sink = obs_lib.MetricsSink(self.registry)
            self.journal = None
        self.live = set(range(cfg.edges))
        self.quarantined: Dict[int, str] = {}
        self.nonces: Dict[int, int] = {e: 0 for e in range(cfg.edges)}
        # strikes: authenticated protocol violations (strike_limit
        # enforced); forged/replays: attacker-producible rejections,
        # counted per claimed identity for observability ONLY
        self.strikes: Dict[int, int] = {}
        self.forged: Dict[int, int] = {}
        self.replays: Dict[int, int] = {}
        self.epoch = 0
        # (round, epoch, seq) -> phase dict
        self.phases: Dict[Tuple[int, int, int], Dict[str, Any]] = {}
        # round -> {"ingress", "done", "completed", "results",
        #           "done_first_ts", "epoch"}
        self.rounds: Dict[int, Dict[str, Any]] = {}
        self.detector = obs_lib.RetraceDetector()
        self._fold_jit = None
        self._fold_sigs: set = set()
        self._restore()

    # ----------------------------------------------------------- restore

    def _restore(self) -> None:
        """Replay the root journal: nonce HWMs and standing quarantines
        survive a root restart, so captured submissions stay dead."""
        if self.journal is None:
            return
        states = journal_lib.replay_edges(
            self.journal.path,
            warn=lambda m: print(f"[root] {m}", flush=True),
        )
        for edge, st in states.items():
            if edge in self.nonces:
                self.nonces[edge] = max(self.nonces[edge], st["nonce"])
            if st["quarantined"] and edge in self.live:
                self.live.discard(edge)
                self.quarantined[edge] = st["quarantined"]

    # ------------------------------------------------------- observation

    def _emit(self, kind: str, **fields: Any) -> None:
        self.sink.emit(obs_lib.make_event(kind, **fields))

    def _journal(self, op: str, edge: int, **fields: Any) -> None:
        if self.journal is not None:
            self.journal.append(op, f"edge-{edge}", **fields)

    # ------------------------------------------------------- containment

    def _quarantine(self, edge: int, reason: str, bump: bool = True) -> None:
        """Evict ``edge``; optionally bump the epoch so in-flight phases
        restart over the surviving set (consensus dissent does NOT bump —
        the fold already completed over the majority)."""
        if edge in self.quarantined:
            return
        self.live.discard(edge)
        self.quarantined[edge] = reason
        self._journal("edge_quarantined", edge, reason=reason)
        self._emit("edge_quarantine", edge=edge, reason=reason)
        if bump:
            self.epoch += 1
            # stale-epoch phases can never fold; drop them
            self.phases = {
                key: ph for key, ph in self.phases.items()
                if key[1] >= self.epoch
            }

    def _reject(self, edge: int, reason: str, status: int,
                journal_op: Optional[str] = None, **extra: Any) -> Reject:
        """An attacker-producible rejection (forgery, replay): journaled
        and counted, but never a strike — anything an observer can
        trigger must carry no consequence for the claimed edge."""
        if journal_op:
            self._journal(journal_op, edge, reason=reason, **extra)
        self._emit("edge_reject", edge=edge, reason=reason)
        return Reject(status, error=reason, **extra)

    def _strike(self, edge: int, reason: str, status: int,
                nonce: Optional[int] = None, **extra: Any) -> Reject:
        """An authenticated violation: the envelope carried a fresh,
        valid MAC+nonce, so only the keyholder produced it.  These are
        attributable, so they accrue toward ``cfg.strike_limit``.  The
        burned nonce rides the journal entry so the HWM floor survives
        a restart — a captured violation cannot be replayed to strike
        twice."""
        self.strikes[edge] = self.strikes.get(edge, 0) + 1
        self._journal("strike", edge, reason=reason, nonce=nonce,
                      strikes=self.strikes[edge])
        self._emit("edge_reject", edge=edge, reason=reason)
        exc = Reject(status, error=reason, **extra)
        if self.strikes[edge] >= self.cfg.strike_limit:
            self._quarantine(edge, "strike_limit")
        return exc

    # ------------------------------------------------------ verification

    def _verify(self, body: Any, op: str) -> int:
        """The zero-trust chain; returns the verified edge id or raises
        :class:`Reject`.  Order matters: identity before authenticity,
        authenticity before ANY stateful reaction, replay before decode
        — an unauthenticated byte never changes fold state.  A verified
        nonce is burned IMMEDIATELY, before the epoch/round checks, so
        a later-rejected envelope cannot be captured and replayed (the
        property the strike accounting relies on)."""
        if not isinstance(body, dict) or body.get("op") != op:
            raise Reject(400, error=f"body must be a signed {op!r} envelope")
        edge = body.get("edge")
        if not isinstance(edge, int) or edge not in self.nonces:
            raise Reject(401, error="unknown edge")
        mac = body.get("mac")
        want = sign_envelope(self.cfg.keys[edge], body)
        if not (isinstance(mac, str) and hmac_lib.compare_digest(mac, want)):
            self.forged[edge] = self.forged.get(edge, 0) + 1
            raise self._reject(
                edge, "bad_mac", 401, journal_op="forged_rejected",
                nonce=body.get("nonce"),
            )
        # authenticated from here on
        if edge in self.quarantined:
            raise Reject(410, error=self.quarantined[edge])
        nonce = body.get("nonce")
        if not isinstance(nonce, int) or nonce <= self.nonces[edge]:
            # a VALID mac with a reused nonce: either the channel echoed
            # (an on-path observer replaying a capture) or the edge is
            # duplicated.  The root cannot tell which, and the first is
            # attacker-triggerable, so the replay is rejected and
            # journaled (the HWM keeps it inert across restarts) but the
            # edge is NOT quarantined — otherwise one passive capture
            # per edge would durably evict the whole fleet.
            self.replays[edge] = self.replays.get(edge, 0) + 1
            raise self._reject(
                edge, "replay", 409, journal_op="replay_rejected",
                nonce=nonce,
            )
        self.nonces[edge] = nonce
        if body.get("epoch") != self.epoch:
            raise Reject(409, error="stale_epoch", epoch=self.epoch)
        rnd = body.get("round")
        if not isinstance(rnd, int) or not 0 <= rnd < self.cfg.rounds:
            raise self._strike(edge, "bad_round", 400, nonce=nonce,
                               round=rnd)
        return edge

    # ------------------------------------------------------------- folds

    def _fold(self, key: Tuple[int, int, int], phase: Dict[str, Any]) -> None:
        if not self.trace:
            return self._fold_inner(key, phase)
        t0 = time.perf_counter()
        try:
            return self._fold_inner(key, phase)
        finally:
            rst = self._round(key[0])
            rst["fold_ms"] = (
                rst.get("fold_ms", 0.0) + (time.perf_counter() - t0) * 1e3
            )

    def _fold_inner(
        self, key: Tuple[int, int, int], phase: Dict[str, Any]
    ) -> None:
        order = sorted(phase["subs"])
        tags = phase["tags"]
        subs = phase["subs"]
        if all(t == "same" for t in tags):
            # result consensus: majority bytes win, dissenters are
            # contained without an epoch bump (the fold stands)
            n_leaves = len(subs[order[0]])
            winners: List[np.ndarray] = []
            dissent: set = set()
            for i in range(n_leaves):
                blobs = {e: subs[e][i].tobytes() for e in order}
                votes = Counter(blobs.values())
                best = max(votes.values())
                # majority wins; a tie resolves to the first edge in
                # shard order (deterministic, and with >2/3 honest edges
                # a tie can only happen when every submission disagrees)
                win_edge = next(e for e in order if votes[blobs[e]] == best)
                winners.append(subs[win_edge][i])
                dissent |= {
                    e for e in order if blobs[e] != blobs[win_edge]
                }
            phase["folded"] = winners
            names = (phase.get("meta") or {}).get("names")
            if names and len(names) == len(winners):
                rst = self._round(key[0])
                rst["results"] = {
                    n: w for n, w in zip(names, winners)
                }
            for e in sorted(dissent):
                self._quarantine(e, "result_mismatch", bump=False)
            return
        stacked = tuple(
            np.stack([subs[e][i] for e in order])
            for i in range(len(subs[order[0]]))
        )
        n = len(order)
        if self._fold_jit is None:
            import jax

            self._fold_jit = jax.jit(
                self.detector.wrap("root_fold_fn", self._fold_body),
                static_argnames=("tags", "n"),
            )
        sig = (
            tuple(tags), n,
            tuple((s.shape, str(s.dtype)) for s in stacked),
        )
        self._fold_sigs.add(sig)
        out = self._fold_jit(stacked, tags=tuple(tags), n=n)
        phase["folded"] = [np.asarray(x, order="C") for x in out]

    @staticmethod
    def _fold_body(stacked, *, tags, n):
        return shardctx.fold_partials(stacked, tags, n)

    # ---------------------------------------------------------- deadline

    def _round(self, rnd: int) -> Dict[str, Any]:
        rst = self.rounds.setdefault(rnd, {
            "ingress": 0, "done": set(), "completed": False,
            "results": {}, "done_first_ts": None, "epoch": self.epoch,
        })
        if self.trace and "span_id" not in rst:
            # the round's root_round span opens at first ingress and is
            # emitted retrospectively when the round completes
            rst["span_id"] = obs_lib.trace.new_span_id()
            rst["t0"] = self.now()
            rst["fold_ms"] = 0.0
        return rst

    def deadline_check(self, now: Optional[float] = None) -> None:
        """Quarantine edges that keep a phase (or a round close) waiting
        past ``partial_timeout``.  Called at the top of every dispatch —
        the fleet polls continuously, so wall-clock progress is free."""
        now = self.now() if now is None else now
        with self._lock:
            timeout = self.cfg.partial_timeout
            for key, phase in list(self.phases.items()):
                if phase.get("folded") is not None:
                    continue
                if key[1] != self.epoch:
                    continue
                if now - phase["first_ts"] <= timeout:
                    continue
                for e in sorted(self.live - set(phase["subs"])):
                    self._quarantine(e, "partial_timeout")
            for rnd, rst in self.rounds.items():
                ts = rst.get("done_first_ts")
                if rst["completed"] or ts is None:
                    continue
                if now - ts <= self.cfg.partial_timeout:
                    continue
                for e in sorted(self.live - rst["done"]):
                    self._quarantine(e, "partial_timeout")
                self._maybe_complete(rnd)

    # ------------------------------------------------------------ routes

    def submit_partial(
        self, raw: bytes, traceparent=None
    ) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            try:
                body = json.loads(raw.decode())
            except (json.JSONDecodeError, UnicodeDecodeError) as exc:
                return 400, {"error": f"bad json: {exc}"}
            try:
                edge = self._verify(body, "partial")
                seq = body.get("seq")
                if not isinstance(seq, int) or seq < 0:
                    raise self._strike(edge, "bad_seq", 400,
                                       nonce=body["nonce"], seq=repr(seq))
                try:
                    # malformed leaf dicts raise KeyError (missing
                    # wdtype/data/shape) or TypeError (bad shape/dtype
                    # entries), not only ValueError — all three are the
                    # same authenticated-hostile payload
                    leaves, tags = shardctx.partial_from_wire(body)
                except (ValueError, KeyError, TypeError) as exc:
                    self._quarantine(edge, "bad_payload")
                    raise Reject(422, error=f"bad payload: {exc!r}")
                for x in leaves:
                    if x.dtype.kind == "f" and not np.isfinite(x).all():
                        self._quarantine(edge, "nonfinite_partial")
                        raise Reject(422, error="nonfinite partial")
                rnd = body["round"]
                key = (rnd, self.epoch, seq)
                phase = self.phases.setdefault(key, {
                    "subs": {}, "tags": None, "meta": None,
                    "first_ts": self.now(), "folded": None,
                })
                if phase["folded"] is not None:
                    # the fold stands: a fresh-nonce resubmission can
                    # neither re-open the vote nor refold the phase
                    return 200, {"ok": True, "seq": seq, "folded": True}
                phase["subs"][edge] = {
                    "leaves": leaves,
                    "tags": list(tags),
                    "shapes": [(list(x.shape), x.dtype.str)
                               for x in leaves],
                    "meta": body.get("meta"),
                }
                rst = self._round(rnd)
                rst["ingress"] += len(raw)
                extra: Dict[str, Any] = {}
                if self.trace and traceparent is not None:
                    # the ingress event happened WITHIN the edge's round
                    # span: correlate via the W3C header (the envelope,
                    # never the HMAC-signed body)
                    extra["trace_id"] = traceparent[0]
                    extra["span_id"] = traceparent[1]
                self._emit(
                    "edge_partial", round=rnd, edge=edge, seq=seq,
                    bytes=len(raw), **extra,
                )
                if self.live <= set(phase["subs"]):
                    self._resolve(key, phase, submitter=edge)
                return 200, {"ok": True, "seq": seq}
            except Reject as exc:
                return exc.status, exc.payload

    def _resolve(self, key: Tuple[int, int, int], phase: Dict[str, Any],
                 submitter: int) -> None:
        """Every live edge has reported: decide the phase schema by
        majority vote — NO single submitter is trusted with it — then
        fold.  The minority is quarantined (``bad_payload``), which
        bumps the epoch so survivors re-run the round; a tie resolves
        to the first edge in shard order (the result-consensus rule)."""
        subs = phase["subs"]
        order = sorted(subs)
        schemas = {
            e: json.dumps(
                [subs[e]["tags"], subs[e]["shapes"], subs[e]["meta"]],
                sort_keys=True,
            )
            for e in order
        }
        votes = Counter(schemas.values())
        best = max(votes.values())
        win_edge = next(e for e in order if votes[schemas[e]] == best)
        losers = [e for e in order if schemas[e] != schemas[win_edge]]
        if losers:
            for e in losers:
                self._quarantine(e, "bad_payload")
            if submitter in losers:
                raise Reject(
                    422, error="partial disagrees with phase schema quorum"
                )
            return
        winner = subs[win_edge]
        phase["tags"] = winner["tags"]
        phase["meta"] = winner["meta"]
        phase["subs"] = {e: subs[e]["leaves"] for e in order}
        self._fold(key, phase)

    def get_fold(self, rnd: int, seq: int, epoch: int,
                 edge: Optional[int]) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            if edge is not None and edge in self.quarantined:
                return 410, {"error": self.quarantined[edge]}
            if epoch != self.epoch:
                return 409, {"error": "stale_epoch", "epoch": self.epoch}
            phase = self.phases.get((rnd, epoch, seq))
            if phase is None or phase.get("folded") is None:
                return 202, {"pending": True}
            return 200, shardctx.partial_to_wire(
                phase["folded"], phase["tags"]
            )

    def submit_done(self, raw: bytes) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            try:
                body = json.loads(raw.decode())
            except (json.JSONDecodeError, UnicodeDecodeError) as exc:
                return 400, {"error": f"bad json: {exc}"}
            try:
                edge = self._verify(body, "done")
            except Reject as exc:
                return exc.status, exc.payload
            rnd = body["round"]
            rst = self._round(rnd)
            rst["done"].add(edge)
            if rst["done_first_ts"] is None:
                rst["done_first_ts"] = self.now()
            self._maybe_complete(rnd)
            return 200, {"ok": True, "completed": rst["completed"]}

    def _maybe_complete(self, rnd: int) -> None:
        rst = self.rounds.get(rnd)
        if rst is None or rst["completed"]:
            return
        if not self.live or not self.live <= rst["done"]:
            return
        rst["completed"] = True
        rst["epoch"] = self.epoch
        degraded = len(self.live) < self.cfg.edges
        rst["degraded"] = degraded
        self._emit(
            "edge_round", round=rnd, epoch=self.epoch,
            edges=len(self.live), degraded=degraded,
            ingress_bytes=rst["ingress"],
        )
        if self.trace:
            span_id = rst.get("span_id") or obs_lib.trace.new_span_id()
            ms = max(self.now() - rst.get("t0", self.now()), 0.0) * 1e3
            self._emit(
                "span", name="root_round", ms=round(ms, 3),
                round=rnd, epoch=self.epoch,
                trace_id=self.trace_id, span_id=span_id,
            )
            self._emit(
                "span", name="root_fold",
                ms=round(rst.get("fold_ms", 0.0), 3),
                round=rnd, trace_id=self.trace_id,
                span_id=obs_lib.trace.new_span_id(),
                parent_span_id=span_id,
            )
        for e in sorted(self.live):
            self._journal(
                "partial", e, round=rnd, nonce=self.nonces[e],
            )
            self._journal("round_done", e, round=rnd, epoch=self.epoch)
        # phase payloads for a closed round are dead weight; drop them
        self.phases = {
            key: ph for key, ph in self.phases.items() if key[0] != rnd
        }

    def round_info(self, rnd: int) -> Dict[str, Any]:
        with self._lock:
            rst = self.rounds.get(rnd)
            info = {
                "round": rnd,
                "epoch": self.epoch,
                "live": sorted(self.live),
                "completed": bool(rst and rst["completed"]),
            }
            if self.trace_id is not None:
                # edges adopt this on first poll, so the whole topology
                # shares one trace
                info["trace_id"] = self.trace_id
            return info

    def results(self) -> Dict[str, Any]:
        with self._lock:
            rounds = {}
            for rnd, rst in sorted(self.rounds.items()):
                rounds[str(rnd)] = {
                    "completed": rst["completed"],
                    "epoch": rst["epoch"],
                    "ingress_bytes": rst["ingress"],
                    "degraded": rst.get(
                        "degraded", len(self.live) < self.cfg.edges
                    ),
                    "results": {
                        n: shardctx.encode_leaf(v)
                        for n, v in rst["results"].items()
                    },
                }
            return {
                "epoch": self.epoch,
                "live": sorted(self.live),
                "quarantined": dict(self.quarantined),
                "strikes": dict(self.strikes),
                "forged": dict(self.forged),
                "replays": dict(self.replays),
                "rounds": rounds,
                "fold_lowerings": self.detector.count("root_fold_fn"),
                "fold_signatures": len(self._fold_sigs),
            }

    def all_done(self) -> bool:
        with self._lock:
            return all(
                self.rounds.get(r, {}).get("completed")
                for r in range(self.cfg.rounds)
            )

    def close(self) -> None:
        self.sink.close()
        if self.journal is not None:
            self.journal.close()


class RootServer:
    """One socket: the edge protocol + /metrics + /healthz."""

    def __init__(
        self,
        cfg: TopologyConfig,
        obs_dir: Optional[str] = None,
        port: int = 0,
        host: str = "0.0.0.0",
        trace: bool = False,
    ) -> None:
        self.state = RootState(cfg, obs_dir=obs_dir, trace=trace)
        self.exporter = obs_lib.MetricsExporter(
            self.state.registry,
            port=port,
            host=host,
            health_fn=self._health,
            routes=self._routes,
        )

    @property
    def port(self) -> Optional[int]:
        return self.exporter.port

    def start(self) -> "RootServer":
        self.exporter.start()
        return self

    def close(self) -> None:
        self.exporter.close()
        self.state.close()

    def __enter__(self) -> "RootServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _health(self) -> Dict[str, Any]:
        st = self.state
        return {
            "ok": bool(st.live),
            "live": sorted(st.live),
            "quarantined": dict(st.quarantined),
            "epoch": st.epoch,
        }

    @staticmethod
    def _json(status: int, payload: Any) -> Tuple[int, str, bytes]:
        return status, _JSON, (json.dumps(payload) + "\n").encode()

    def _routes(
        self, method: str, path: str, body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ) -> Optional[Tuple[int, str, bytes]]:
        url = urllib.parse.urlsplit(path)
        parts = [p for p in url.path.split("/") if p]
        if not parts:
            return None
        # wall-clock progress rides every request — see deadline_check
        self.state.deadline_check()
        try:
            if parts[0] == "partials" and method == "POST":
                from ..obs.trace import parse_traceparent

                return self._json(*self.state.submit_partial(
                    body,
                    traceparent=parse_traceparent(
                        (headers or {}).get("traceparent")
                    ),
                ))
            if parts[0] == "done" and method == "POST":
                return self._json(*self.state.submit_done(body))
            if parts[0] == "fold" and len(parts) == 3 and method == "GET":
                q = urllib.parse.parse_qs(url.query)
                edge = q.get("edge", [None])[0]
                return self._json(*self.state.get_fold(
                    int(parts[1]), int(parts[2]),
                    int(q.get("epoch", ["0"])[0]),
                    int(edge) if edge is not None else None,
                ))
            if parts[0] == "rounds" and len(parts) == 2 and method == "GET":
                return self._json(200, self.state.round_info(int(parts[1])))
            if parts[0] == "results" and method == "GET":
                return self._json(200, self.state.results())
        except ValueError as exc:
            return self._json(400, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 — surface, don't kill thread
            return self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
        return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "byzantine_aircomp_tpu root",
        description="aggregation root of the 2-tier topology",
    )
    p.add_argument("--config", required=True,
                   help="topology JSON (shared with the edges)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--obs-dir", default=None,
                   help="events + root journal directory")
    p.add_argument("--linger", type=float, default=5.0,
                   help="seconds to keep serving after all rounds close "
                        "(lets the harness scrape /results)")
    p.add_argument("--trace", choices=("off", "on"), default="off",
                   help="mint a topology trace id and emit per-round "
                        "root_round/root_fold spans (output-only)")
    args = p.parse_args(argv)
    cfg = TopologyConfig.load(args.config)
    server = RootServer(
        cfg, obs_dir=args.obs_dir, port=args.port, host=args.host,
        trace=args.trace == "on",
    ).start()
    # parsed by the chaos harness; keep the trailing space (port parse)
    print(f"edge root on {args.host}:{server.port} ", flush=True)
    try:
        while not server.state.all_done():
            time.sleep(0.1)
            server.state.deadline_check()
            if not server.state.live:
                print("edge root: all edges quarantined", flush=True)
                break
        time.sleep(args.linger)
    except KeyboardInterrupt:
        pass
    finally:
        results = server.state.results()
        server.close()
        print(f"edge root results: {json.dumps(results)}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
