"""Elastic lane scheduling: mesh tenants in shared lanes, lane refill.

Two serving gaps closed here, both on top of the hooks
``serve/batch.py`` grew for this module (per-lane round indices,
``release_lane`` / ``install_lane``):

**Mesh/streamed tenants batch.**  The v1 server routed every
``cohort_size > 0`` (and so every ``pop_shards > 1``) tenant solo,
because the streamed iteration path Python-gates its cohort-scan
structure on one batchable knob (``straggler_prob`` — see
``fed/train.py _iteration_streamed``).  :func:`validate_stream_batch`
lifts the carve-out by PINNING the gating knobs instead: they must be
equal across the batch (``static_signature`` already folds them into a
streamed config's digest, so unequal tenants never group), they trace
as closure constants, and they are excluded from the stacked knob
arrays and from hot-swap.  Everything else about the streamed round —
the cohort scan, the quantile rungs, churn/deadline service state —
vmaps unchanged, so N streamed tenants share ONE lowering exactly like
resident ones.

**The lane axis can shard over the device mesh.**  For mesh tenants
(``pop_shards > 1`` — pod-scale streamed runs) the
``backend="shard_vmap"`` tier wraps the vmapped element program in
``shard_map`` over a 1-D ``lanes`` mesh (the SNIPPETS shard_map-
wrapped-jit pattern; same jaxlib caveats as ``parallel/popmesh.py``:
``check_rep=False`` required, carry donation through ``shard_map``
unsound on the CPU client).  Each device owns ``n/ndev`` lanes of the
same compiled program; inside a lane the sequential-engine trainer is
bit-identical to the mesh engine by the ``ops/shardctx.py`` merge
algebra, so sharding the lane axis changes placement, never math.
When the device count does not divide the batch (or there is one
device), the runner downgrades to plain ``vmap`` — same numbers,
different placement.

**Elastic refill.**  :func:`seat_order` reseats recovered tenants into
their journal-hinted lanes (the mid-refill SIGKILL replay invariant:
the same tenant lands in the same lane), and the RunManager's
between-round refill path uses ``install_lane`` to splice a queued
tenant into a drained/cancelled slot — one lowering per group shape
for the whole group lifetime, refills included.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import obs as obs_lib
from ..fed.config import FedConfig
from . import batch as batch_lib
from .batch import PINNED_STREAM_KNOBS, BatchRunner

#: mesh axis name of the lane dimension (shard_vmap backend)
LANE_AXIS = "lanes"


def pinned_knobs(cfg: FedConfig) -> tuple:
    """The batchable knobs this config family must PIN (equal across the
    batch, not hot-swappable): the streamed path's Python-gated knobs
    for ``cohort_size > 0`` tenants, nothing for resident ones."""
    return PINNED_STREAM_KNOBS if cfg.cohort_size > 0 else ()


def validate_stream_batch(cfgs: Sequence[FedConfig]) -> List[str]:
    """The widened admission contract: everything
    :func:`serve.batch.validate_batch` requires EXCEPT the streamed-
    cohort carve-out, plus pinned-knob equality for streamed batches.
    Returns the applicable traced-knob names minus the pinned ones."""
    knobs = batch_lib._validate_structure(cfgs)
    t = cfgs[0]
    for knob in pinned_knobs(t):
        vals = sorted({float(getattr(c, knob)) for c in cfgs})
        if len(vals) > 1:
            raise ValueError(
                f"stream batch contract: knob {knob!r} gates the cohort "
                f"scan's traced structure and must be PINNED (equal) "
                f"across a streamed batch, got {vals}"
            )
    return [k for k in knobs if k not in pinned_knobs(t)]


class ElasticBatchRunner(BatchRunner):
    """BatchRunner admitting streamed/mesh tenants, optionally sharding
    the lane axis over the device mesh (``backend="shard_vmap"``)."""

    def __init__(
        self,
        cfgs: Sequence[FedConfig],
        dataset=None,
        retrace: Optional[obs_lib.RetraceDetector] = None,
        backend: str = "vmap",
        restore_fn=None,
    ) -> None:
        self._lane_mesh = None
        if backend == "shard_vmap":
            devs = jax.devices()
            if len(devs) > 1 and len(cfgs) % len(devs) == 0:
                self._lane_mesh = Mesh(np.asarray(devs), (LANE_AXIS,))
            else:
                # an indivisible batch (or a single device) downgrades
                # to plain vmap: same numbers, different placement
                backend = "vmap"
        super().__init__(
            cfgs, dataset=dataset, retrace=retrace, backend=backend,
            restore_fn=restore_fn,
        )

    def _validate(self, cfgs: Sequence[FedConfig]) -> List[str]:
        return validate_stream_batch(cfgs)

    def _builder(self, backend: str):
        if backend == "shard_vmap":
            return self._build_shard_vmap
        return super()._builder(backend)

    def _donate_argnums(self) -> tuple:
        # donating buffers through shard_map is unsound on this jaxlib's
        # CPU client (parallel/popmesh.py's _round_donate_argnums)
        if self._lane_mesh is not None and jax.default_backend() == "cpu":
            return ()
        return super()._donate_argnums()

    def _build_shard_vmap(self):
        mesh, spec = self._lane_mesh, P(LANE_AXIS)

        def batched(carry, base_keys, knobs, round_idx):
            return shard_map(
                jax.vmap(self._one, in_axes=(0, 0, 0, 0)),
                mesh=mesh,
                in_specs=(spec, spec, spec, spec),
                out_specs=spec,
                check_rep=False,
            )(carry, base_keys, knobs, round_idx)

        return batched


def runner_for(
    cfgs: Sequence[FedConfig],
    dataset=None,
    retrace: Optional[obs_lib.RetraceDetector] = None,
    backend: str = "vmap",
    restore_fn=None,
) -> BatchRunner:
    """Build the right runner for a signature group: streamed/mesh
    tenants get the elastic runner (mesh tenants upgrade ``vmap`` to
    the lane-sharded ``shard_vmap`` tier), resident tenants the base
    one — callers never pick a class by hand."""
    cfg0 = cfgs[0]
    if cfg0.cohort_size > 0 or cfg0.pop_shards > 1:
        be = backend
        if backend == "vmap" and cfg0.pop_shards > 1:
            be = "shard_vmap"
        return ElasticBatchRunner(
            cfgs, dataset=dataset, retrace=retrace, backend=be,
            restore_fn=restore_fn,
        )
    return BatchRunner(
        cfgs, dataset=dataset, retrace=retrace, backend=backend,
        restore_fn=restore_fn,
    )


def seat_order(runs: Sequence) -> List:
    """Order a group's runs by lane: a run whose journal-replayed
    ``lane_hint`` points at an unclaimed in-range slot is seated THERE
    (deterministic replay: a refilled tenant must land back in the same
    lane after a crash), the rest fill the remaining slots in
    submission order."""
    n = len(runs)
    seats: List[Optional[object]] = [None] * n
    rest = []
    for run in runs:
        hint = getattr(run, "lane_hint", None)
        if hint is not None and 0 <= hint < n and seats[hint] is None:
            seats[hint] = run
        else:
            rest.append(run)
    it = iter(rest)
    return [seat if seat is not None else next(it) for seat in seats]
