"""Multi-tenant experiment serving: many runs, one compiled trainer.

Two rungs (ROADMAP item #4):

* :mod:`.batch`  — the experiment-axis vmap runner: N same-shape configs
  become one jitted round program, with seeds / attack scales / detector
  constants / channel SNR carried as traced per-experiment data
  (:class:`~.batch.BatchableKnobs`) instead of hashed statics.  One
  lowering serves every cell; the seed-only batch is bit-identical to N
  independent solo runs.
* :mod:`.elastic` — elastic lane scheduling on top of the batch runner:
  streamed/mesh tenants batch too (their trace-gating knobs PINNED
  instead of refused), the lane axis can shard over the device mesh
  (``backend="shard_vmap"``), and drained lanes refill from the
  admission queue between rounds with journaled, SIGKILL-replayable
  seat decisions.
* :mod:`.runs` + :mod:`.server` — the resident control plane: a stdlib
  HTTP surface (extending ``obs/exporter.py``) to submit / inspect /
  cancel runs and hot-swap batchable knobs between rounds, with per-run
  obs-dir subtrees, ``run_id``-labelled metrics, and checkpoint
  namespaces so tenants cannot read each other's artifacts.

See docs/SERVING.md for the API and the batchable-knob contract.
"""

from .batch import (  # noqa: F401
    BATCHABLE_KNOBS,
    PINNED_STREAM_KNOBS,
    BatchRunner,
    applicable_knobs,
    gather_knobs,
    static_signature,
    validate_batch,
)
from .elastic import (  # noqa: F401
    ElasticBatchRunner,
    pinned_knobs,
    runner_for,
    seat_order,
    validate_stream_batch,
)
from .runs import RunManager  # noqa: F401
from .server import ExperimentServer  # noqa: F401
