"""Durable run journal: the experiment server's write-ahead log.

The control plane's registry (``serve/runs.RunManager``) is in-memory —
before this module a server crash lost every run: queued configs,
progress, even the knowledge that a run had completed.  The journal makes
the lifecycle durable: one JSONL line per transition, appended through
``utils/io.open_append`` (line-buffered, one ``write()`` per line — a
kill can tear at most the final line, and :func:`replay` skips a torn
tail with a warning instead of raising).

Ops and their extra fields::

    submitted   config (non-default FedConfig fields, PRE-namespace),
                signature, title, solo, idempotency_key?
    running     —           (the scheduler picked the run up)
    checkpoint  round       (a durable per-round checkpoint landed)
    requeued    retries, reason   (watchdog bounded-backoff retry)
    refill      lane, round, group_round, signature   (a drained lane's
                slot reseated from the admission queue mid-group —
                WRITTEN BEFORE the device splice, so a SIGKILL
                mid-refill replays the same tenant into the same lane)
    completed   round, lowerings, final_val_acc?, final_val_loss?
    failed      round, reason
    cancelled   round

The journal records *transitions*; the resumable *state* (params, opt
carries, metric paths) lives in the per-run checkpoints
(``fed/checkpoint.py`` — atomic npz with the paths JSON riding the same
write).  A restarted server folds the journal into per-run states
(:func:`replay`): terminal runs are re-adopted as facts, in-flight runs
are re-queued and resume from their last checkpoint.  See
docs/RUNBOOK.md for the operator walk-through.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils import io as io_lib

#: journal file name under the server's obs root
JOURNAL_NAME = "journal.jsonl"

#: ops that mean the run reached a terminal status
TERMINAL_OPS = ("completed", "failed", "cancelled")


def journal_path(obs_root: str) -> str:
    return os.path.join(obs_root, JOURNAL_NAME)


class RunJournal:
    """Append-only lifecycle log, one JSON object per line.

    Thread-safe (the scheduler, watchdog, and HTTP handler threads all
    append); the file handle opens lazily on first append so constructing
    a journal for a root that never sees a run creates nothing.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._fh = None

    def append(self, op: str, run_id: str, **fields: Any) -> None:
        rec = {"op": op, "run_id": run_id, "ts": time.time(), **fields}
        line = json.dumps(rec)
        with self._lock:
            if self._fh is None:
                self._fh = io_lib.open_append(self.path)
            self._fh.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def replay(
    path: str, warn: Optional[Callable[[str], None]] = None
) -> Dict[str, Dict[str, Any]]:
    """Fold a journal into per-run states, in first-submission order.

    Returns ``run_id -> state`` where state carries ``status`` (the last
    op's terminal name, or ``queued`` for any in-flight run), ``config``
    (the submitted mapping — None if the submitted line itself was the
    torn tail, in which case the run is unrecoverable and reported
    through ``warn``), ``round`` (the last durably checkpointed round),
    ``retries``, and the terminal facts (``lowerings``, ``error``,
    ``final_val_acc``/``final_val_loss``) when present.  Torn or garbage
    lines are skipped via ``warn`` — a crash mid-append must cost one
    line, never the journal.
    """
    states: Dict[str, Dict[str, Any]] = {}
    for rec in io_lib.iter_jsonl(path, warn=warn):
        op = rec.get("op")
        run_id = rec.get("run_id")
        if not op or not isinstance(run_id, str):
            continue
        st = states.setdefault(
            run_id,
            {
                "run_id": run_id,
                "status": "queued",
                "config": None,
                "round": 0,
                "retries": 0,
            },
        )
        if op == "submitted":
            st["config"] = rec.get("config")
            st["signature"] = rec.get("signature")
            st["title"] = rec.get("title")
            st["solo"] = bool(rec.get("solo"))
            if rec.get("idempotency_key"):
                st["idempotency_key"] = rec["idempotency_key"]
        elif op == "running":
            st["status"] = "queued"  # in-flight: requeue on replay
        elif op == "checkpoint":
            st["round"] = max(st["round"], int(rec.get("round", 0)))
        elif op == "requeued":
            st["status"] = "queued"
            st["retries"] = int(rec.get("retries", st["retries"]))
        elif op == "refill":
            # mid-group reseat: in-flight (requeue on replay), remember
            # the lane so recovery seats the same tenant in the same
            # slot; the resume round stays checkpoint-owned
            st["status"] = "queued"
            if rec.get("lane") is not None:
                st["lane"] = int(rec["lane"])
        elif op in TERMINAL_OPS:
            st["status"] = op
            if rec.get("round") is not None:
                st["round"] = int(rec["round"])
            if rec.get("lowerings") is not None:
                st["lowerings"] = int(rec["lowerings"])
            if rec.get("reason"):
                st["error"] = rec["reason"]
            for k in ("final_val_acc", "final_val_loss"):
                if rec.get(k) is not None:
                    st[k] = rec[k]
    for run_id, st in list(states.items()):
        if st["config"] is None:
            if warn is not None:
                warn(
                    f"run {run_id}: journal has no intact 'submitted' "
                    "line (torn tail?); dropping — resubmit it"
                )
            del states[run_id]
    return states


#: root-journal file name under the aggregation root's obs dir
ROOT_JOURNAL_NAME = "root_journal.jsonl"

#: root-journal ops (run_id is ``edge-<id>``): ``partial`` carries the
#: per-round accepted-nonce high-water mark (written at round close, not
#: per exchange — a round is ~100 exchanges and the HWM is all restart
#: recovery needs), ``replay_rejected`` / ``forged_rejected`` record the
#: zero-trust rejections with the offending nonce (a replay rejection
#: also raises the HWM floor, so a captured submission stays dead across
#: restarts without quarantining the edge it names), ``strike`` an
#: authenticated protocol violation counting toward ``strike_limit``,
#: ``edge_quarantined`` the containment decision, and ``round_done`` the
#: fleet-level close.


def replay_edges(
    path: str, warn: Optional[Callable[[str], None]] = None
) -> Dict[int, Dict[str, Any]]:
    """Fold a ROOT journal into per-edge security state.

    Returns ``edge -> {"nonce": hwm, "quarantined": reason | None}``.  A
    restarted root restores the nonce high-water marks BEFORE serving, so
    a replay of a submission captured before the crash is still rejected
    — the idempotency machinery the run journal uses for run adoption,
    reused for replay protection.  Quarantines are permanent across
    restarts: a contained edge stays contained until the operator rotates
    its key and clears the journal (docs/RUNBOOK.md).
    """
    states: Dict[int, Dict[str, Any]] = {}
    for rec in io_lib.iter_jsonl(path, warn=warn):
        run_id = rec.get("run_id")
        if not isinstance(run_id, str) or not run_id.startswith("edge-"):
            continue
        try:
            edge = int(run_id[5:])
        except ValueError:
            continue
        st = states.setdefault(edge, {"nonce": 0, "quarantined": None})
        nonce = rec.get("nonce")
        if isinstance(nonce, int):
            st["nonce"] = max(st["nonce"], nonce)
        if rec.get("op") == "edge_quarantined":
            st["quarantined"] = rec.get("reason", "unknown")
    return states
