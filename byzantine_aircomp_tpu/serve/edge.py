"""Edge aggregator: one shard of the 2-tier edge -> root topology.

The paper's fusion center becomes a tree: N edge processes each own a
contiguous range of cohort chunks (exactly a ``pop_shards`` shard), run
the UNCHANGED streamed aggregator programs from ``ops/aggregators.py``,
and merge through :class:`EdgeShardCtx` — a population-shard context
whose merge points are ordered host callbacks that POST the partial to
the root and return the fold.  Because the traced per-shard compute is
the same code the sequential engine runs and the root folds with the
same ``ops/shardctx.fold_leaves`` in shard order, tree == sequential ==
mesh stays BIT-identical — no re-derivation, no tolerance windows.

Mechanics worth knowing:

* one round fn per process — the whole round (stats pass, 32-step rank
  bisection, trimmed tail, Weiszfeld loop, packed sign vote, result
  consensus) is ONE jitted function; ``jax.experimental.io_callback``
  (ordered) carries each merge across the network from inside
  ``fori_loop``/``while_loop`` bodies.  The RetraceDetector wraps it, so
  an edge that silently re-lowers mid-run fails its exit audit exactly
  like the trainer would.  A degraded round (surviving edges after a
  kill) is a legitimately different program and lowers once more.
* phases are anonymous — every edge executes the same deterministic
  exchange sequence (all branching depends on merged values, which are
  bit-identical across edges), so a per-round ``seq`` counter is the
  whole phase-coordination protocol.
* zero-trust submissions — every POST carries the edge id, a strictly
  increasing nonce, and an HMAC-SHA256 over the canonical JSON of the
  envelope under the edge's pre-shared key.  The root rejects forgeries
  and replays without folding them (serve/root.py).
* epoch restarts — when the root quarantines a dead edge mid-round it
  bumps the round's epoch; survivors see ``stale_epoch``, re-query the
  live set, and re-run the round in degraded mode (the effective-K
  guards inside the streamed aggregators take it from there).

``python -m byzantine_aircomp_tpu edge --config topo.json --shard 2
--root-url http://host:port`` runs one edge to completion; the chaos
harness (analysis/chaos.py) drives 4 of them plus a root on one machine
and kills one mid-round.
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import hmac
import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: marker substrings for exceptions that must cross the XLA callback
#: boundary (io_callback wraps host exceptions in XlaRuntimeError; the
#: message survives, the type does not)
RESTART_MARKER = "EDGE_RESTART_EPOCH"
DEAD_MARKER = "EDGE_QUARANTINED"


class RoundRestart(RuntimeError):
    """The root bumped the round's epoch (an edge died mid-round)."""

    def __init__(self, epoch: int) -> None:
        super().__init__(f"{RESTART_MARKER}:{epoch}")
        self.epoch = epoch


class EdgeQuarantined(RuntimeError):
    """The root quarantined THIS edge; the process must stand down."""

    def __init__(self, reason: str = "") -> None:
        super().__init__(f"{DEAD_MARKER}:{reason}")


# --------------------------------------------------------------------------
# topology config + submission signing (shared with serve/root.py and the
# chaos harness, which crafts replayed/forged submissions from the same
# helpers to prove the root rejects them)
# --------------------------------------------------------------------------


def canonical_bytes(obj: Any) -> bytes:
    """Deterministic JSON bytes — the HMAC input.  ``sort_keys`` plus
    tight separators means both ends serialize the envelope identically."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def sign_envelope(key_hex: str, body: Dict[str, Any]) -> str:
    """HMAC-SHA256 over the canonical envelope (sans ``mac``), hex."""
    payload = {k: v for k, v in body.items() if k != "mac"}
    return hmac.new(
        bytes.fromhex(key_hex), canonical_bytes(payload), hashlib.sha256
    ).hexdigest()


@dataclass
class TopologyConfig:
    """The 2-tier run description both tiers load from one JSON file."""

    edges: int
    k: int
    d: int
    cohort: int
    rounds: int
    aggs: List[str] = field(default_factory=list)
    sign_bits: int = 0
    trim_ratio: float = 0.1
    quantile: str = "exact"
    sketch_bins: int = 512
    gm2_maxiter: int = 1000
    seed: int = 2021
    partial_timeout: float = 5.0
    # authenticated protocol violations (validly signed, fresh-nonce
    # envelopes the root still rejects) before the edge is quarantined;
    # forgeries and replays never count — they are attacker-producible
    strike_limit: int = 3
    keys: Dict[int, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.k % self.cohort:
            raise ValueError(f"k {self.k} % cohort {self.cohort} != 0")
        if self.n_chunks % self.edges:
            raise ValueError(
                f"n_chunks {self.n_chunks} % edges {self.edges} != 0"
            )
        missing = [e for e in range(self.edges) if e not in self.keys]
        if missing:
            raise ValueError(f"no HMAC key for edges {missing}")

    @property
    def n_chunks(self) -> int:
        return self.k // self.cohort

    @property
    def chunks_per_edge(self) -> int:
        return self.n_chunks // self.edges

    @property
    def rows_per_edge(self) -> int:
        return self.chunks_per_edge * self.cohort

    @property
    def result_names(self) -> List[str]:
        names = list(self.aggs)
        if self.sign_bits == 1:
            names.append("signvote")
        return names

    @classmethod
    def load(cls, path: str) -> "TopologyConfig":
        with open(path) as f:
            raw = json.load(f)
        raw["keys"] = {int(e): k for e, k in raw.get("keys", {}).items()}
        return cls(**raw)


def round_stack(seed: int, rnd: int, k: int, d: int):
    """The round's deterministic [k, d] client stack.  Every edge (and
    the flat reference the chaos harness compares against) rebuilds the
    SAME stack from (seed, round), so a partial disagreement can only
    come from the aggregation path — which is the thing under test."""
    import jax

    key = jax.random.fold_in(jax.random.PRNGKey(seed), rnd)
    return jax.random.normal(key, (k, d), dtype="float32")


# --------------------------------------------------------------------------
# the edge-side shard context
# --------------------------------------------------------------------------


class EdgeShardCtx:
    """Shard ``p`` of S whose merges cross the network.

    ``scan_idx_merge`` runs this shard's chunk scan exactly the way
    ``SeqShardCtx.one_shard`` does (same body, same global chunk index
    range ``[p*cpp, (p+1)*cpp)``), then ships the partial carry through
    ``exchange(tags, arrays, meta) -> merged arrays`` — an ORDERED
    ``io_callback``, so exchanges execute in program order even from
    inside ``fori_loop``/``while_loop`` bodies, which is what keeps the
    per-round ``seq`` counter aligned across edges."""

    def __init__(self, shard: int, n_shards: int, exchange) -> None:
        if not 0 <= shard < n_shards:
            raise ValueError(f"shard {shard} outside [0, {n_shards})")
        self.shard = shard
        self.n_shards = n_shards
        self.exchange = exchange

    def varying(self, x):
        return x

    def merge(self, carry, spec, meta: Optional[dict] = None):
        """Merge one partial pytree with the fleet via the root."""
        import jax
        from jax.experimental import io_callback

        from ..ops import shardctx

        flat, treedef = jax.tree.flatten(carry)
        tags = tuple(shardctx.flat_tags(spec, flat))
        shapes = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in flat]
        merged = io_callback(
            functools.partial(self._host_exchange, tags, meta),
            shapes,
            *flat,
            ordered=True,
        )
        return jax.tree.unflatten(treedef, merged)

    def _host_exchange(self, tags, meta, *arrays):
        out = self.exchange(tags, [np.asarray(a) for a in arrays], meta)
        # NOT ascontiguousarray: that helper promotes 0-d to 1-d, and the
        # callback contract is exact-shape (scalars like gm2's denominator
        # and the finite ballot count are legitimate 0-d leaves)
        return [np.asarray(x, order="C") for x in out]

    def scan_idx_merge(self, n_chunks: int, body, init, spec):
        import jax
        import jax.numpy as jnp

        S = self.n_shards
        if n_chunks % S:
            raise ValueError(
                f"n_chunks {n_chunks} not divisible by edges {S}"
            )
        cpp = n_chunks // S
        idxs = self.shard * cpp + jnp.arange(cpp, dtype=jnp.int32)

        def step(carry, c_idx):
            return body(carry, c_idx), None

        carry, _ = jax.lax.scan(step, init, idxs)
        return self.merge(carry, spec)

    def scan_merge(self, rebuild, n_chunks: int, body, init, spec):
        return self.scan_idx_merge(
            n_chunks, lambda carry, c: body(carry, rebuild(c), c), init, spec
        )


# --------------------------------------------------------------------------
# the round program
# --------------------------------------------------------------------------


class EdgeCompute:
    """Builds and caches the edge's jitted round functions.

    One function per degraded-ness: the healthy program and the
    surviving-set program differ (degraded aggregation switches to the
    finite/effective-K formulas), so each lowers once and the retrace
    audit allows exactly those."""

    def __init__(self, cfg: TopologyConfig, shard: int, exchange,
                 detector=None) -> None:
        from ..obs import RetraceDetector

        self.cfg = cfg
        self.shard = shard
        self.ctx = EdgeShardCtx(shard, cfg.edges, exchange)
        self.detector = detector if detector is not None else RetraceDetector()
        self._fns: Dict[bool, Any] = {}

    def fn_name(self, degraded: bool) -> str:
        return "edge_round_fn_degraded" if degraded else "edge_round_fn"

    def round_fn(self, degraded: bool):
        import jax

        if degraded not in self._fns:
            self._fns[degraded] = jax.jit(
                self.detector.wrap(
                    self.fn_name(degraded),
                    functools.partial(self._round, degraded),
                )
            )
        return self._fns[degraded]

    def _round(self, degraded: bool, stack):
        import jax
        import jax.numpy as jnp

        from ..ops import aggregators

        cfg = self.cfg
        d, cohort, n_chunks = cfg.d, cfg.cohort, cfg.n_chunks
        ctx = self.ctx

        def rebuild(c):
            return jax.lax.dynamic_slice(
                stack, (c * cohort, 0), (cohort, d)
            )

        outs: Dict[str, Any] = {}
        sum_all = sum_fin = n_fin = None
        if cfg.aggs:
            # one shared stats pass: mean's sums, gm2's init guess, and
            # the degraded paths' finite-row count, all from one exchange
            sum_all, sum_fin, n_fin = aggregators.stream_stats(
                rebuild, n_chunks, d, ctx
            )
        for name in cfg.aggs:
            outs[name] = aggregators.stream_aggregate(
                name, rebuild,
                k=cfg.k, d=d, n_chunks=n_chunks, degraded=degraded,
                sum_all=sum_all, sum_finite=sum_fin, n_finite=n_fin,
                quantile=cfg.quantile, sketch_bins=cfg.sketch_bins,
                trim_ratio=cfg.trim_ratio, maxiter=cfg.gm2_maxiter,
                ctx=ctx,
            )
        if cfg.sign_bits == 1:
            # the packed one-bit wire: this edge's rows pack to uint32
            # sign words locally; only the per-coordinate plane COUNTS
            # (bounded by rows-per-edge, so uint8/uint16 on the wire)
            # and the finite-row ballot count cross the network
            rows = jax.lax.dynamic_slice(
                stack, (self.shard * cfg.rows_per_edge, 0),
                (cfg.rows_per_edge, d),
            )
            words, k_valid = aggregators.pack_signs(
                rows, jnp.zeros(d, jnp.float32)
            )
            counts = aggregators.packed_sign_votes(words, d)
            m_counts, m_valid = ctx.merge(
                (counts, k_valid), ("sum", "sum"),
                meta={"label": "signvote"},
            )
            outs["signvote"] = (2 * m_counts - m_valid).astype(jnp.int32)
        # result consensus: every edge computed bit-identical finals
        # (they are functions of merged data only); the root verifies
        # byte-equality across the fleet and quarantines dissenters
        names = cfg.result_names
        merged = self.ctx.merge(
            tuple(outs[n] for n in names),
            ("same",) * len(names),
            meta={"label": "results", "names": names},
        )
        return dict(zip(names, merged))


# --------------------------------------------------------------------------
# the HTTP client half (stdlib urllib; the root is serve/root.py)
# --------------------------------------------------------------------------


class EdgeClient:
    """Signed, nonce'd submissions plus fold polling for one edge."""

    def __init__(self, root_url: str, edge: int, key_hex: str,
                 poll_secs: float = 0.02, timeout: float = 30.0) -> None:
        self.root_url = root_url.rstrip("/")
        self.edge = edge
        self.key_hex = key_hex
        self.poll_secs = poll_secs
        self.timeout = timeout
        self._nonce = 0
        self._round = -1
        self._epoch = 0
        self._seq = 0
        # distributed tracing: an outbound W3C header set by the edge
        # loop per round (never part of the HMAC-signed body — the wire
        # schema and its signature are trace-agnostic), plus an HTTP-time
        # accumulator the loop drains into its edge_exchange span
        self.traceparent: Optional[str] = None
        self._exchange_ms = 0.0

    # --------------------------------------------------------- plumbing

    def take_exchange_ms(self) -> float:
        """Drain the accumulated on-the-wire time (ms) since last call."""
        ms, self._exchange_ms = self._exchange_ms, 0.0
        return ms

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Tuple[int, dict]:
        data = json.dumps(body).encode() if body is not None else None
        headers: Dict[str, str] = {}
        if data:
            headers["Content-Type"] = "application/json"
        if self.traceparent is not None:
            headers["traceparent"] = self.traceparent
        req = urllib.request.Request(
            self.root_url + path, data=data, method=method, headers=headers,
        )
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as exc:
            raw = exc.read().decode(errors="replace")
            try:
                return exc.code, json.loads(raw or "{}")
            except json.JSONDecodeError:
                return exc.code, {"error": raw}
        finally:
            self._exchange_ms += (time.monotonic() - t0) * 1e3

    def _raise_for(self, status: int, resp: dict) -> None:
        if status == 410:
            raise EdgeQuarantined(str(resp.get("error", "")))
        if status == 409 and resp.get("error") == "stale_epoch":
            raise RoundRestart(int(resp.get("epoch", self._epoch + 1)))
        raise RuntimeError(
            f"edge {self.edge}: root answered {status}: {resp}"
        )

    def _signed(self, body: Dict[str, Any]) -> Dict[str, Any]:
        self._nonce += 1
        body = {**body, "edge": self.edge, "nonce": self._nonce}
        body["mac"] = sign_envelope(self.key_hex, body)
        return body

    # --------------------------------------------------------- protocol

    def begin_round(self, rnd: int, epoch: int) -> None:
        self._round, self._epoch, self._seq = rnd, epoch, 0

    def round_state(self, rnd: int) -> dict:
        status, resp = self._request("GET", f"/rounds/{rnd}")
        if status != 200:
            self._raise_for(status, resp)
        return resp

    def exchange(self, tags, arrays, meta: Optional[dict] = None):
        """The EdgeShardCtx host callback: POST this shard's partial for
        the current (round, epoch, seq), then poll the fold."""
        from ..ops import shardctx

        seq = self._seq
        self._seq += 1
        body = self._signed({
            "op": "partial",
            "round": self._round,
            "epoch": self._epoch,
            "seq": seq,
            "meta": meta or {},
            **shardctx.partial_to_wire(arrays, tags),
        })
        status, resp = self._request("POST", "/partials", body)
        if status != 200:
            self._raise_for(status, resp)
        path = (
            f"/fold/{self._round}/{seq}"
            f"?epoch={self._epoch}&edge={self.edge}"
        )
        deadline = time.time() + self.timeout
        while True:
            status, resp = self._request("GET", path)
            if status == 200:
                leaves, _ = shardctx.partial_from_wire(resp)
                return leaves
            if status == 202:
                if time.time() > deadline:
                    raise RuntimeError(
                        f"edge {self.edge}: fold of round {self._round} "
                        f"seq {seq} never completed"
                    )
                time.sleep(self.poll_secs)
                continue
            self._raise_for(status, resp)

    def done(self, rnd: int) -> None:
        body = self._signed({
            "op": "done", "round": rnd, "epoch": self._epoch,
        })
        status, resp = self._request("POST", "/done", body)
        if status != 200:
            self._raise_for(status, resp)


# --------------------------------------------------------------------------
# the edge main loop
# --------------------------------------------------------------------------


def _classify(exc: BaseException) -> Optional[str]:
    """Map an exception that crossed the XLA callback boundary back to
    the protocol signal its message carries."""
    msg = str(exc)
    if RESTART_MARKER in msg:
        return "restart"
    if DEAD_MARKER in msg:
        return "dead"
    return None


def run_edge(cfg: TopologyConfig, shard: int, root_url: str,
             obs_dir: Optional[str] = None,
             trace: bool = False) -> Dict[str, Any]:
    """Run one edge through every round; returns a summary dict.

    Exit invariants (the chaos harness asserts them via the return/exit
    code): all rounds completed or this edge was quarantined, and the
    retrace audit passed — each round program lowered at most once."""
    import jax

    from .. import obs as obs_lib

    sink = (
        obs_lib.JsonlSink(f"{obs_dir}/edge{shard}.events.jsonl")
        if obs_dir else obs_lib.MemorySink()
    )
    # the fold-poll deadline must OUTLIVE the root's partial_timeout: a
    # survivor waiting on a phase a dead edge never joins has to still be
    # polling when the root quarantines the deadbeat and answers 409
    client = EdgeClient(
        root_url, shard, cfg.keys[shard],
        timeout=max(30.0, cfg.partial_timeout * 2 + 30.0),
    )
    compute = EdgeCompute(cfg, shard, client.exchange)
    status = "completed"
    rounds_run = 0
    # --trace on: the whole topology shares ONE trace — the root mints
    # the id and publishes it in round_info, every edge adopts it on
    # first poll (minting a private one only if the root predates the
    # field), and each round's submissions carry the edge_round span as
    # traceparent so the root's ingress events correlate back
    trace_id: Optional[str] = None
    try:
        for rnd in range(cfg.rounds):
            stack = round_stack(cfg.seed, rnd, cfg.k, cfg.d)
            round_span = obs_lib.trace.new_span_id() if trace else None
            t0 = time.perf_counter()
            client.take_exchange_ms()
            while True:
                state = client.round_state(rnd)
                if trace and trace_id is None:
                    trace_id = (
                        state.get("trace_id") or obs_lib.trace.new_trace_id()
                    )
                if trace:
                    client.traceparent = obs_lib.trace.format_traceparent(
                        trace_id, round_span
                    )
                live = list(state.get("live", []))
                if shard not in live:
                    raise EdgeQuarantined("not in live set")
                client.begin_round(rnd, int(state.get("epoch", 0)))
                degraded = len(live) < cfg.edges
                try:
                    out = compute.round_fn(degraded)(stack)
                    jax.block_until_ready(out)
                    client.done(rnd)
                    rounds_run += 1
                    if trace:
                        ms = (time.perf_counter() - t0) * 1e3
                        ex_ms = client.take_exchange_ms()
                        sink.emit(obs_lib.make_event(
                            "span", name="edge_round", ms=round(ms, 3),
                            round=rnd, edge=shard,
                            trace_id=trace_id, span_id=round_span,
                        ))
                        sink.emit(obs_lib.make_event(
                            "span", name="edge_exchange",
                            ms=round(ex_ms, 3),
                            round=rnd, edge=shard, trace_id=trace_id,
                            span_id=obs_lib.trace.new_span_id(),
                            parent_span_id=round_span,
                        ))
                    break
                except Exception as exc:  # noqa: BLE001 — see _classify
                    kind = _classify(exc)
                    if kind == "restart":
                        continue
                    raise
    except EdgeQuarantined:
        status = "quarantined"
    except Exception as exc:  # noqa: BLE001
        if _classify(exc) == "dead":
            status = "quarantined"
        else:
            raise
    counts = compute.detector.snapshot()
    steady = all(
        compute.detector.check(name, max_lowerings=1)
        for name in counts
    )
    sink.emit(obs_lib.make_event(
        "retrace", counts=counts, steady_state_ok=steady,
    ))
    sink.close()
    return {
        "edge": shard,
        "status": status,
        "rounds": rounds_run,
        "lowerings": counts,
        "steady_state_ok": steady,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "byzantine_aircomp_tpu edge",
        description="one edge aggregator of the 2-tier topology",
    )
    p.add_argument("--config", required=True,
                   help="topology JSON (shared with the root)")
    p.add_argument("--shard", type=int, required=True,
                   help="this edge's shard index in [0, edges)")
    p.add_argument("--root-url", required=True,
                   help="root base URL, e.g. http://127.0.0.1:8123")
    p.add_argument("--obs-dir", default=None,
                   help="directory for this edge's event stream")
    p.add_argument("--trace", choices=("off", "on"), default="off",
                   help="emit per-round edge spans and propagate the "
                        "topology trace id on every request (output-only)")
    args = p.parse_args(argv)
    # the ordered io_callback logs a full traceback at ERROR for every
    # protocol exception (epoch restarts are routine, not errors)
    import logging

    logging.getLogger("jax._src.callback").setLevel(logging.CRITICAL)
    cfg = TopologyConfig.load(args.config)
    summary = run_edge(
        cfg, args.shard, args.root_url, args.obs_dir,
        trace=args.trace == "on",
    )
    print(f"edge {args.shard}: {json.dumps(summary)}", flush=True)
    if not summary["steady_state_ok"]:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
