"""Run registry + scheduler: the control plane behind the experiment server.

A :class:`RunManager` owns the lifecycle of every submitted run:

* ``submit`` assigns a ``run_id``, rebases the config's output paths onto
  the run's private subtree (``harness.run_namespace`` — the tenancy
  boundary), opens the run's own event stream, and queues it under its
  :func:`~.batch.static_signature`.
* The scheduler (a background thread started by :meth:`start`, or a
  direct :meth:`drain` call from tests) groups queued runs by signature
  and executes each group through ONE shared :class:`~.batch.BatchRunner`
  — that grouping is what turns 64 tenant submissions into a single XLA
  lowering.
* Between rounds (the BatchRunner's ``before_round`` hook) queued knob
  swaps and cancellations land: a swap is a per-lane device-array update
  (``set_knob`` — never a retrace, and the post-group lowering count is
  recorded on every run so the guarantee is auditable per tenant), a
  cancel flips the lane dark (compute still rides the batch; recording
  stops).

Every tenant-visible state change is an audit event in the run's own
stream — ``run_submitted`` / ``knob_swap`` / ``run_cancelled`` (schema
v4) — and, when the manager was given a shared registry, every run's
metrics land under its own ``run_id`` label via
:class:`~..obs.metrics.LabeledRegistry`, so one ``/metrics`` scrape shows
all tenants side by side.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from .. import obs as obs_lib
from ..fed import harness
from ..fed.config import FedConfig
from .batch import BatchRunner, applicable_knobs, static_signature

#: terminal statuses — no further transitions, obs stream closed
_DONE = ("completed", "cancelled", "failed")


class Run:
    """One tenant run: config + lifecycle + its private output subtree.

    Not self-locking — the manager's lock guards every mutation (the
    scheduler thread and HTTP handler threads both touch runs).
    """

    def __init__(self, run_id: str, cfg: FedConfig, signature: str) -> None:
        self.run_id = run_id
        self.cfg = cfg
        self.signature = signature
        self.title = harness.ckpt_title(cfg)
        self.status = "queued"
        self.round = 0  # last round boundary reached while running
        self.lane: Optional[int] = None
        self.error: Optional[str] = None
        self.lowerings: Optional[int] = None
        self.swaps: List[tuple] = []  # pending (knob, value), applied between rounds
        self.applied_swaps: List[dict] = []
        self.cancel_requested = False
        self.paths: Optional[Dict[str, list]] = None
        self.obs: obs_lib.Observability = obs_lib.NULL

    def info(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "run_id": self.run_id,
            "title": self.title,
            "signature": self.signature,
            "status": self.status,
            "round": self.round,
            "rounds": self.cfg.rounds,
            "lane": self.lane,
            "obs_dir": self.cfg.obs_dir,
            "checkpoint_dir": self.cfg.checkpoint_dir,
            "knobs": {
                k: getattr(self.cfg, k)
                for k in ("seed",) + tuple(applicable_knobs(self.cfg))
            },
            "swaps": list(self.applied_swaps),
        }
        if self.lowerings is not None:
            d["lowerings"] = self.lowerings
        if self.error is not None:
            d["error"] = self.error
        if self.paths and self.paths.get("valLossPath"):
            d["val_loss"] = self.paths["valLossPath"][-1]
            d["val_acc"] = self.paths["valAccPath"][-1]
        return d


class RunManager:
    """Thread-safe run registry + signature-grouped batch scheduler."""

    def __init__(
        self,
        obs_root: str,
        registry=None,
        dataset=None,
        backend: str = "vmap",
        batch_window: float = 0.25,
    ) -> None:
        self.obs_root = obs_root
        self.registry = registry
        self._dataset = dataset
        self._backend = backend
        self._batch_window = batch_window
        self._lock = threading.RLock()
        self._runs: Dict[str, Run] = {}
        self._order: List[str] = []
        self._pending: List[str] = []
        self._seq = 0
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._dataset_cache: Dict[str, Any] = {}

    # ---------------------------------------------------------- registry

    def submit(self, cfg: FedConfig) -> str:
        """Register + queue one run; returns its server-assigned id.

        The run's event stream opens HERE so ``run_submitted`` is the
        stream's first event and a crash between submit and execution
        still leaves an audit trail."""
        with self._lock:
            self._seq += 1
            run_id = f"run-{self._seq:04d}"
            cfg = harness.run_namespace(cfg, run_id, self.obs_root)
            run = Run(run_id, cfg, static_signature(cfg))
            sink: obs_lib.EventSink = obs_lib.JsonlSink(
                obs_lib.events_path(cfg.obs_dir, run.title)
            )
            if self.registry is not None:
                labeled = obs_lib.LabeledRegistry(self.registry, run_id=run_id)
                sink = obs_lib.MultiSink(
                    [sink, obs_lib.MetricsSink(labeled)]
                )
            run.obs = obs_lib.Observability(sink)
            run.obs.emit(
                "run_submitted",
                run_id=run_id, title=run.title, signature=run.signature,
            )
            self._runs[run_id] = run
            self._order.append(run_id)
            self._pending.append(run_id)
        self._wake.set()
        return run_id

    def _get(self, run_id: str) -> Run:
        run = self._runs.get(run_id)
        if run is None:
            raise KeyError(f"no such run {run_id!r}")
        return run

    def get(self, run_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._get(run_id).info()

    def list_runs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._runs[rid].info() for rid in self._order]

    def cancel(self, run_id: str) -> Dict[str, Any]:
        """Cancel a run.  Queued runs finalize immediately; running runs
        go dark at the next round boundary (idempotent on done runs)."""
        with self._lock:
            run = self._get(run_id)
            if run.status in _DONE:
                return run.info()
            run.cancel_requested = True
            if run.status == "queued":
                run.status = "cancelled"
                run.obs.emit("run_cancelled", run_id=run_id, round=0)
                run.obs.close()
            return run.info()

    def swap(self, run_id: str, knob: str, value) -> Dict[str, Any]:
        """Hot-swap one batchable knob.  Queued runs take the new value
        into their initial knob stack; running runs get a per-lane
        device-array update at the next round boundary.  Raises
        ``ValueError`` for non-batchable knobs or done runs."""
        with self._lock:
            run = self._get(run_id)
            if run.status in _DONE:
                raise ValueError(
                    f"run {run_id} is {run.status}; knobs can only be "
                    f"swapped on queued/running runs"
                )
            allowed = applicable_knobs(run.cfg)
            if knob not in allowed:
                raise ValueError(
                    f"knob {knob!r} is not hot-swappable for this run "
                    f"(batchable here: {sorted(allowed)}); structural "
                    f"knobs need a new run"
                )
            value = float(value)
            if run.status == "queued":
                # the batch doesn't exist yet — the new value simply
                # becomes the lane's initial knob (gather_knobs reads cfg)
                setattr(run.cfg, knob, value)
                run.applied_swaps.append(
                    {"round": 0, "knob": knob, "value": value}
                )
                run.obs.emit(
                    "knob_swap",
                    run_id=run_id, round=0, knob=knob, value=value,
                )
            else:
                run.swaps.append((knob, value))
            return run.info()

    # --------------------------------------------------------- scheduler

    def start(self) -> "RunManager":
        """Start the background scheduler (the server's mode).  Waits
        ``batch_window`` seconds after a submission before draining so
        concurrent tenants coalesce into one batch."""
        with self._lock:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop,
                    name="aircomp-run-scheduler",
                    daemon=True,
                )
                self._thread.start()
        return self

    def close(self) -> None:
        self._stop = True
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            for rid in self._order:
                run = self._runs[rid]
                if run.status not in _DONE:
                    run.obs.close()

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stop:
                break
            if self._pending:
                time.sleep(self._batch_window)
                try:
                    self.drain()
                except Exception:  # keep the scheduler alive; runs record
                    traceback.print_exc()  # their own failure status

    def drain(self) -> None:
        """Execute every currently-queued run, grouped by signature into
        one BatchRunner per group.  Blocks until done.  Tests call this
        directly for deterministic grouping; the scheduler thread calls
        it after the batch window."""
        while True:
            with self._lock:
                pending = [
                    self._runs[rid]
                    for rid in self._pending
                    if self._runs[rid].status == "queued"
                ]
                self._pending = []
                groups: Dict[str, List[Run]] = {}
                for run in pending:
                    run.status = "running"
                    groups.setdefault(run.signature, []).append(run)
            if not groups:
                return
            for runs in groups.values():
                self._run_group(runs)

    def _dataset_for(self, name: str):
        if self._dataset is not None:
            return self._dataset
        if name not in self._dataset_cache:
            from ..data import datasets as data_lib

            self._dataset_cache[name] = data_lib.load(name)
        return self._dataset_cache[name]

    def _fail(self, runs: List[Run], exc: BaseException) -> None:
        with self._lock:
            for run in runs:
                if run.status not in _DONE:
                    run.status = "failed"
                    run.error = f"{type(exc).__name__}: {exc}"
                run.obs.close()

    def _run_group(self, runs: List[Run]) -> None:
        try:
            dataset = self._dataset_for(runs[0].cfg.dataset)
            batch = BatchRunner(
                [r.cfg for r in runs],
                dataset=dataset,
                backend=self._backend,
            )
        except Exception as exc:
            self._fail(runs, exc)
            return
        with self._lock:
            for lane, run in enumerate(runs):
                run.lane = lane

        def before_round(rnd: int) -> None:
            with self._lock:
                for run in runs:
                    if run.status != "running":
                        continue
                    if run.cancel_requested:
                        batch.cancel(run.lane)
                        run.status = "cancelled"
                        run.obs.emit(
                            "run_cancelled", run_id=run.run_id, round=rnd
                        )
                        run.swaps = []
                        continue
                    for knob, value in run.swaps:
                        batch.set_knob(run.lane, knob, value)
                        setattr(run.cfg, knob, value)
                        run.applied_swaps.append(
                            {"round": rnd, "knob": knob, "value": value}
                        )
                        run.obs.emit(
                            "knob_swap",
                            run_id=run.run_id, round=rnd,
                            knob=knob, value=value,
                        )
                    run.swaps = []
                    run.round = rnd

        try:
            paths_list = batch.train(
                obs_list=[r.obs for r in runs],
                before_round=before_round,
            )
        except Exception as exc:
            self._fail(runs, exc)
            return
        lowerings = batch.retrace.count("batch_round_fn")
        with self._lock:
            for run, paths in zip(runs, paths_list):
                run.paths = paths
                run.lowerings = lowerings
                if run.status == "running":
                    run.status = "completed"
                    run.round = run.cfg.rounds
                run.obs.close()
