"""Run registry + scheduler: the control plane behind the experiment server.

A :class:`RunManager` owns the lifecycle of every submitted run:

* ``submit`` assigns a ``run_id``, rebases the config's output paths onto
  the run's private subtree (``harness.run_namespace`` — the tenancy
  boundary), journals the submission (``serve/journal.py`` — the durable
  write-ahead log a restarted server replays), opens the run's own event
  stream, and queues it under its :func:`~.batch.static_signature`.
* The scheduler (a background thread started by :meth:`start`, or a
  direct :meth:`drain` call from tests) groups queued runs by
  ``static_signature`` and executes each group through ONE shared
  runner (:func:`~.elastic.runner_for`) — that grouping is what turns
  64 tenant submissions into a single XLA lowering.  Streamed and mesh
  tenants batch too (the elastic runner PINS the cohort-scan gating
  knobs instead of refusing them; mesh tenants shard the lane axis
  over the device mesh) — only multi-round dispatch tiers
  (``rounds_per_dispatch > 1``, whose R-round scan cannot join the
  per-round group loop) still run SOLO through ``harness.run``.
* Lane groups are ELASTIC: when a lane drains mid-group (completes its
  own horizon, cancels, or quarantines) the slot is refilled between
  rounds from the admission queue (same signature), the incoming
  tenant resuming from its own checkpoint.  Each refill decision is a
  journal record written BEFORE the device splice, so a SIGKILL
  mid-refill replays the same tenant into the same lane; per-lane
  round indices let every lane run its own horizon, and the group
  retires only when no lane is live.
* Between rounds (the BatchRunner's ``before_round`` hook) queued knob
  swaps and cancellations land; after each round (``after_round``) every
  live lane writes a durable checkpoint — params + opt carries + the
  metric paths recorded so far, one atomic npz — so a killed server
  resumes every in-flight run from its last round boundary with final
  records bit-identical to an uninterrupted run.
* A poisoned lane (non-finite params/variance/loss, exception in eval)
  is quarantined by the BatchRunner health guards: the run fails with
  exactly one ``run_failed`` event naming the reason while its cotenants
  continue in the same lowering.
* A watchdog thread (``wedge_secs > 0``) detects runs that stop making
  progress, cancels and requeues them with bounded retries and
  exponential backoff (``run_retries`` / ``run_backoff``), and reports
  the service degraded (the server's ``/healthz`` flips to 503) while
  any run is wedged.

Every tenant-visible state change is an audit event in the run's own
stream — ``run_submitted`` / ``knob_swap`` / ``run_cancelled`` /
``run_failed`` / ``run_requeued`` / ``journal_replay`` (schema v6) —
and, when the manager was given a shared registry, every run's metrics
land under its own ``run_id`` label via
:class:`~..obs.metrics.LabeledRegistry`, so one ``/metrics`` scrape shows
all tenants side by side.  docs/RUNBOOK.md is the operator guide.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from .. import obs as obs_lib
from ..fed import checkpoint, harness
from ..fed.config import FedConfig, config_from_mapping, config_to_mapping
from ..utils import io as io_lib
from . import elastic as elastic_lib
from . import journal as journal_lib
from .batch import applicable_knobs, static_signature

#: terminal statuses — no further transitions, obs stream closed
_DONE = ("completed", "cancelled", "failed")


class QueueFull(RuntimeError):
    """Submission rejected by the queue cap (HTTP maps this to 429)."""


def _warn(msg: str) -> None:
    print(f"[serve] {msg}", file=sys.stderr)


class Run:
    """One tenant run: config + lifecycle + its private output subtree.

    Not self-locking — the manager's lock guards every mutation (the
    scheduler, watchdog, and HTTP handler threads all touch runs).
    """

    def __init__(self, run_id: str, cfg: FedConfig, signature: str) -> None:
        self.run_id = run_id
        self.cfg = cfg
        self.signature = signature
        self.title = harness.ckpt_title(cfg)
        self.status = "queued"
        self.round = 0  # last round boundary reached while running
        self.lane: Optional[int] = None
        self.lane_hint: Optional[int] = None  # journal-replayed seat
        self.error: Optional[str] = None
        self.lowerings: Optional[int] = None
        self.swaps: List[tuple] = []  # pending (knob, value), applied between rounds
        self.applied_swaps: List[dict] = []
        self.cancel_requested = False
        self.paths: Optional[Dict[str, list]] = None
        self.obs: obs_lib.Observability = obs_lib.NULL
        # crash-safety / supervision state
        self.solo = False  # streamed/mesh config: single-lane harness path
        self.resume_round = 0  # checkpointed round a (re)start resumes from
        self.retries = 0  # watchdog requeues consumed
        self.wedged = False  # watchdog flagged: no progress in wedge_secs
        self.attempt = 0  # execution epoch: stale group closures no-op
        self.last_progress = time.time()
        self.idempotency_key: Optional[str] = None
        self.final: Optional[Dict[str, Any]] = None  # journal-adopted val stats
        self.record_path: Optional[str] = None
        # distributed tracing (--trace on tenants only): the tenant's
        # trace id (adopted from the submit's traceparent header when
        # present, minted otherwise), the pre-minted "run_request" root
        # span every per-run span hangs off, and the client's span id
        # (recorded on the root as remote_parent_span_id — kept out of
        # parent_span_id so local orphan detection stays meaningful)
        self.submitted_at = time.time()
        self.trace_id: Optional[str] = None
        self.root_span_id: Optional[str] = None
        self.remote_parent: Optional[str] = None

    def info(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "run_id": self.run_id,
            "title": self.title,
            "signature": self.signature,
            "status": self.status,
            "round": self.round,
            "rounds": self.cfg.rounds,
            "lane": self.lane,
            "obs_dir": self.cfg.obs_dir,
            "checkpoint_dir": self.cfg.checkpoint_dir,
            "knobs": {
                k: getattr(self.cfg, k)
                for k in ("seed",) + tuple(applicable_knobs(self.cfg))
            },
            "swaps": list(self.applied_swaps),
        }
        if self.solo:
            d["solo"] = True
        if self.resume_round:
            d["resume_round"] = self.resume_round
        if self.retries:
            d["retries"] = self.retries
        if self.wedged:
            d["wedged"] = True
        if self.lowerings is not None:
            d["lowerings"] = self.lowerings
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.error is not None:
            d["error"] = self.error
        if self.record_path is not None:
            d["record"] = self.record_path
        if self.paths and self.paths.get("valLossPath"):
            d["val_loss"] = self.paths["valLossPath"][-1]
            d["val_acc"] = self.paths["valAccPath"][-1]
        elif self.final is not None:
            if self.final.get("val_loss") is not None:
                d["val_loss"] = self.final["val_loss"]
            if self.final.get("val_acc") is not None:
                d["val_acc"] = self.final["val_acc"]
        return d


class RunManager:
    """Thread-safe run registry + signature-grouped batch scheduler."""

    def __init__(
        self,
        obs_root: str,
        registry=None,
        dataset=None,
        backend: str = "vmap",
        batch_window: float = 0.25,
        queue_cap: int = 0,
        run_retries: int = 1,
        run_backoff: float = 2.0,
        wedge_secs: float = 0.0,
    ) -> None:
        self.obs_root = obs_root
        self.registry = registry
        self._dataset = dataset
        self._backend = backend
        self._batch_window = batch_window
        self.queue_cap = queue_cap
        self.run_retries = run_retries
        self.run_backoff = run_backoff
        self.wedge_secs = wedge_secs
        self.journal = journal_lib.RunJournal(journal_lib.journal_path(obs_root))
        self._lock = threading.RLock()
        self._runs: Dict[str, Run] = {}
        self._order: List[str] = []
        self._pending: List[str] = []
        self._idem: Dict[str, str] = {}
        self._requeue_at: Dict[str, float] = {}
        self._seq = 0
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._dataset_cache: Dict[str, Any] = {}
        # scheduler-scope telemetry: lane_group / lane_refill events and
        # the admission-queue gauge are group/service facts, not tenant
        # ones, so they land UNLABELED on the shared registry
        self._sched = (
            obs_lib.Observability(obs_lib.MetricsSink(registry))
            if registry is not None
            else obs_lib.NULL
        )

    # ---------------------------------------------------------- registry

    @staticmethod
    def _is_solo(cfg: FedConfig) -> bool:
        """Only genuinely unbatchable semantics fall outside the batch
        contract now: multi-round dispatch tiers (the group loop is
        per-round; an R-round scan cannot join it) and service-mode warm
        rollback (it restores per-run host state outside the shared
        batch carry).  Streamed cohorts and population meshes batch
        through the elastic runner (``serve/elastic.py``), which pins
        their trace-gating knobs instead of refusing them."""
        if cfg.rounds_per_dispatch > 1:
            return True
        return cfg.service == "on" and cfg.rollback != "off"

    def _queue_depth(self) -> int:
        """Runs awaiting admission to a lane (caller holds the lock)."""
        return sum(1 for r in self._runs.values() if r.status == "queued")

    def _gauge_queue(self) -> None:
        """Refresh the admission-queue-depth gauge (caller holds the
        lock; the registry itself is thread-safe)."""
        if self.registry is not None:
            self.registry.set(
                "aircomp_admission_queue_depth",
                float(self._queue_depth()),
                help_text="runs queued for admission to a lane group",
            )

    def _open_obs(self, run_id: str, cfg: FedConfig, title: str):
        sink: obs_lib.EventSink = obs_lib.JsonlSink(
            obs_lib.events_path(cfg.obs_dir, title)
        )
        if self.registry is not None:
            labeled = obs_lib.LabeledRegistry(self.registry, run_id=run_id)
            msink = obs_lib.MetricsSink(labeled)
            # the watchdog's wedge threshold doubles as the per-run
            # health bar (0 keeps per-sink wedge detection disabled)
            msink.wedge_secs = self.wedge_secs
            sink = obs_lib.MultiSink([sink, msink])
        out = obs_lib.Observability(sink)
        out.traced = getattr(cfg, "trace", "off") == "on"
        return out

    # ----------------------------------------------------------- tracing

    @staticmethod
    def _init_trace(run: Run, traceparent=None) -> None:
        """Mint (or adopt, from a submit's traceparent header) the
        tenant's trace identity and hang it on the run's obs façade so
        every retrospective span (queue_wait, lane_install, per-lane
        rounds, the run_request root) shares one tree.  No-op for
        untraced tenants."""
        if not run.obs.traced:
            return
        if traceparent is not None:
            run.trace_id = traceparent[0]
            run.remote_parent = traceparent[1]
        else:
            run.trace_id = obs_lib.trace.new_trace_id()
        run.root_span_id = obs_lib.trace.new_span_id()
        run.obs.trace_root = (run.trace_id, run.root_span_id)

    @staticmethod
    def _trace_fields(run: Run) -> Dict[str, Any]:
        """Envelope correlation for a tenant's control-plane events:
        the run's trace id plus the root span as the enclosing span.
        Empty for untraced tenants, so their streams stay byte-identical
        to pre-trace builds."""
        if run.trace_id is None:
            return {}
        out: Dict[str, Any] = {"trace_id": run.trace_id}
        if run.root_span_id is not None:
            out["span_id"] = run.root_span_id
        return out

    def _reopen_obs(self, run: Run) -> None:
        """Reopen a handed-over stream (solo finalization) with the
        run's trace identity restored."""
        run.obs = self._open_obs(run.run_id, run.cfg, run.title)
        if run.trace_id is not None and run.obs.traced:
            run.obs.trace_root = (run.trace_id, run.root_span_id)

    def _close_run_obs(self, run: Run) -> None:
        """Every terminal transition funnels here: emit the tenant's
        ``run_request`` root span (traced runs only — submit to terminal
        wall-clock, the id every other per-run span parents to) and
        close the stream, so no trace leaves its root unclosed."""
        if (
            run.trace_id is not None
            and run.obs is not obs_lib.NULL
            and run.obs.traced
        ):
            extra: Dict[str, Any] = {}
            if run.remote_parent is not None:
                extra["remote_parent_span_id"] = run.remote_parent
            run.obs.span_event(
                "run_request",
                ms=(time.time() - run.submitted_at) * 1e3,
                run_id=run.run_id,
                span_id=run.root_span_id,
                status=run.status,
                **extra,
            )
        run.obs.close()
        # detach so a second terminal sweep (e.g. a group-level _fail
        # after a lane already finalized) can never re-emit the root
        run.obs = obs_lib.NULL

    def submit(
        self,
        cfg: FedConfig,
        idempotency_key: Optional[str] = None,
        traceparent: Optional[Tuple[str, str]] = None,
    ) -> str:
        """Register + queue one run; returns its server-assigned id.

        The submission is journaled FIRST (write-ahead: the pre-namespace
        config mapping, so a restarted server can rebuild the exact run
        under the same id) and the run's event stream opens here so
        ``run_submitted`` is the stream's first event — a crash between
        submit and execution still leaves both an audit trail and a
        recoverable queue entry.  Raises :class:`QueueFull` when a
        ``queue_cap`` is set and that many runs are already queued."""
        cfg_map = config_to_mapping(cfg)
        with self._lock:
            if idempotency_key is not None and idempotency_key in self._idem:
                return self._idem[idempotency_key]
            if self.queue_cap > 0:
                queued = sum(
                    1 for r in self._runs.values() if r.status == "queued"
                )
                if queued >= self.queue_cap:
                    raise QueueFull(
                        f"queue full: {queued} runs already queued "
                        f"(cap {self.queue_cap}); retry after the scheduler "
                        "drains"
                    )
            self._seq += 1
            run_id = f"run-{self._seq:04d}"
            cfg = harness.run_namespace(cfg, run_id, self.obs_root)
            run = Run(run_id, cfg, static_signature(cfg))
            run.solo = self._is_solo(cfg)
            run.idempotency_key = idempotency_key
            if idempotency_key is not None:
                self._idem[idempotency_key] = run_id
            self.journal.append(
                "submitted",
                run_id,
                config=cfg_map,
                signature=run.signature,
                title=run.title,
                solo=run.solo,
                idempotency_key=idempotency_key,
            )
            run.obs = self._open_obs(run_id, cfg, run.title)
            self._init_trace(run, traceparent)
            run.obs.emit(
                "run_submitted",
                run_id=run_id, title=run.title, signature=run.signature,
                **self._trace_fields(run),
            )
            self._runs[run_id] = run
            self._order.append(run_id)
            self._pending.append(run_id)
            self._gauge_queue()
        self._wake.set()
        return run_id

    def submit_idempotent(
        self,
        cfg: FedConfig,
        key: Optional[str] = None,
        traceparent: Optional[Tuple[str, str]] = None,
    ) -> Tuple[str, bool]:
        """Submit unless ``key`` was already used; returns ``(run_id,
        created)`` so the HTTP layer can answer 200 instead of 201 on a
        client retry."""
        with self._lock:
            if key is not None and key in self._idem:
                return self._idem[key], False
        return (
            self.submit(cfg, idempotency_key=key, traceparent=traceparent),
            True,
        )

    def _get(self, run_id: str) -> Run:
        run = self._runs.get(run_id)
        if run is None:
            raise KeyError(f"no such run {run_id!r}")
        return run

    def get(self, run_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._get(run_id).info()

    def list_runs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._runs[rid].info() for rid in self._order]

    def cancel(
        self, run_id: str, traceparent: Optional[Tuple[str, str]] = None
    ) -> Dict[str, Any]:
        """Cancel a run.  Queued runs finalize immediately; running batch
        lanes go dark at the next round boundary (idempotent on done
        runs).  A running SOLO lane cannot be interrupted mid-schedule —
        the cancel takes effect only if it is still queued."""
        with self._lock:
            run = self._get(run_id)
            if run.status in _DONE:
                return run.info()
            run.cancel_requested = True
            self._requeue_at.pop(run_id, None)
            if run.status == "queued":
                run.status = "cancelled"
                run.obs.emit(
                    "run_cancelled", run_id=run_id, round=0,
                    **self._remote_fields(run, traceparent),
                )
                self._close_run_obs(run)
                self.journal.append("cancelled", run_id, round=run.round)
                self._gauge_queue()
            return run.info()

    @classmethod
    def _remote_fields(
        cls, run: Run, traceparent: Optional[Tuple[str, str]]
    ) -> Dict[str, Any]:
        """Trace fields for a control-plane event triggered over HTTP:
        the run's own trace identity plus, when the client stamped the
        request with a traceparent, the client's span as
        ``remote_parent_span_id`` (correlation both ways without
        grafting a foreign span into the local tree)."""
        out = cls._trace_fields(run)
        if out and traceparent is not None:
            out["remote_parent_span_id"] = traceparent[1]
            if traceparent[0] != run.trace_id:
                out["remote_trace_id"] = traceparent[0]
        return out

    def swap(
        self, run_id: str, knob: str, value,
        traceparent: Optional[Tuple[str, str]] = None,
    ) -> Dict[str, Any]:
        """Hot-swap one batchable knob.  Queued runs take the new value
        into their initial knob stack; running runs get a per-lane
        device-array update at the next round boundary.  Raises
        ``ValueError`` for non-batchable knobs or done runs."""
        with self._lock:
            run = self._get(run_id)
            if run.status in _DONE:
                raise ValueError(
                    f"run {run_id} is {run.status}; knobs can only be "
                    f"swapped on queued/running runs"
                )
            allowed = set(applicable_knobs(run.cfg)) - set(
                elastic_lib.pinned_knobs(run.cfg)
            )
            if knob not in allowed:
                raise ValueError(
                    f"knob {knob!r} is not hot-swappable for this run "
                    f"(batchable here: {sorted(allowed)}); structural "
                    f"and stream-pinned knobs need a new run"
                )
            value = float(value)
            if run.status == "queued":
                # the batch doesn't exist yet — the new value simply
                # becomes the lane's initial knob (gather_knobs reads cfg)
                setattr(run.cfg, knob, value)
                run.applied_swaps.append(
                    {"round": 0, "knob": knob, "value": value}
                )
                run.obs.emit(
                    "knob_swap",
                    run_id=run_id, round=0, knob=knob, value=value,
                    **self._remote_fields(run, traceparent),
                )
            else:
                run.swaps.append((knob, value))
            return run.info()

    # ---------------------------------------------------------- recovery

    def recover(self, warn=None) -> List[str]:
        """Replay the durable journal: re-adopt terminal runs as facts,
        requeue in-flight runs to resume from their last checkpoint.
        Returns the requeued ids.  Call BEFORE :meth:`start` on a
        restarted server (ExperimentServer does)."""
        warn = warn or _warn
        states = journal_lib.replay(self.journal.path, warn=warn)
        requeued: List[str] = []
        with self._lock:
            for run_id, st in states.items():
                if run_id in self._runs:
                    continue
                try:
                    num = int(run_id.rsplit("-", 1)[1])
                except (IndexError, ValueError):
                    num = 0
                self._seq = max(self._seq, num)
                try:
                    cfg = config_from_mapping(dict(st["config"]))
                except Exception as exc:
                    warn(
                        f"run {run_id}: journaled config no longer valid "
                        f"({exc}); dropping"
                    )
                    continue
                cfg = harness.run_namespace(cfg, run_id, self.obs_root)
                run = Run(run_id, cfg, static_signature(cfg))
                run.solo = self._is_solo(cfg)
                key = st.get("idempotency_key")
                if key:
                    run.idempotency_key = key
                    self._idem[key] = run_id
                status = st["status"]
                if status in _DONE:
                    run.status = status
                    run.round = (
                        cfg.rounds if status == "completed"
                        else int(st.get("round", 0))
                    )
                    run.lowerings = st.get("lowerings")
                    run.error = st.get("error")
                    if status == "completed":
                        # re-adopt the on-disk record so a restarted
                        # server still serves completed runs' artifacts
                        path = harness.cache_path(cfg, cfg.dataset)
                        if os.path.exists(path):
                            run.record_path = path
                    if (
                        st.get("final_val_acc") is not None
                        or st.get("final_val_loss") is not None
                    ):
                        run.final = {
                            "val_acc": st.get("final_val_acc"),
                            "val_loss": st.get("final_val_loss"),
                        }
                else:
                    run.retries = int(st.get("retries", 0))
                    run.resume_round = self._probe_resume(run, warn)
                    run.round = run.resume_round
                    run.status = "queued"
                    if st.get("lane") is not None:
                        # journaled refill seat: recovery must reseat
                        # this tenant into the same lane (seat_order)
                        run.lane_hint = int(st["lane"])
                    run.obs = self._open_obs(run_id, cfg, run.title)
                    # trace ids are not journaled — a re-adopted tenant
                    # starts a fresh trace for its new attempt
                    self._init_trace(run)
                    run.obs.emit(
                        "journal_replay",
                        run_id=run_id,
                        status="resumed" if run.resume_round else "restarted",
                        round=run.resume_round,
                        **self._trace_fields(run),
                    )
                    self._pending.append(run_id)
                    requeued.append(run_id)
                self._runs[run_id] = run
                self._order.append(run_id)
            self._gauge_queue()
        if requeued:
            self._wake.set()
        return requeued

    def _probe_resume(self, run: Run, warn=_warn) -> int:
        """The round this run can durably resume from — 0 when there is
        no usable checkpoint (absent, torn, or missing the paths meta a
        full-record batch resume needs; restarting from scratch replays
        the identical trajectory, it just costs recompute)."""
        try:
            restored = checkpoint.load(run.cfg.checkpoint_dir, run.title)
            if restored is None:
                return 0
            if not run.solo:
                meta = checkpoint.load_meta(run.cfg.checkpoint_dir, run.title)
                if meta is None:
                    return 0
            return int(restored[0])
        except Exception as exc:
            warn(
                f"run {run.run_id}: unreadable checkpoint "
                f"({type(exc).__name__}: {exc}); restarting from round 0"
            )
            return 0

    # --------------------------------------------------------- scheduler

    def start(self) -> "RunManager":
        """Start the background scheduler (the server's mode).  Waits
        ``batch_window`` seconds after a submission before draining so
        concurrent tenants coalesce into one batch.  With
        ``wedge_secs > 0`` a watchdog thread also starts, requeueing
        wedged runs with bounded retries."""
        with self._lock:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop,
                    name="aircomp-run-scheduler",
                    daemon=True,
                )
                self._thread.start()
            if self.wedge_secs > 0 and self._watchdog is None:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop,
                    name="aircomp-run-watchdog",
                    daemon=True,
                )
                self._watchdog.start()
        return self

    def close(self) -> None:
        self._stop = True
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30.0)
            self._thread = None
        watchdog = self._watchdog
        if watchdog is not None:
            watchdog.join(timeout=5.0)
            self._watchdog = None
        with self._lock:
            for rid in self._order:
                run = self._runs[rid]
                if run.status not in _DONE:
                    run.obs.close()
        self._sched.close()
        self.journal.close()

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stop:
                break
            if self._pending:
                time.sleep(self._batch_window)
                try:
                    self.drain()
                except Exception:  # keep the scheduler alive; runs record
                    traceback.print_exc()  # their own failure status

    def drain(self) -> None:
        """Execute every currently-queued run: solo configs one at a
        time, batchable ones grouped by ``signature`` into one elastic
        runner per group (each lane resumes from its OWN checkpoint
        round, so mixed-progress tenants still share a lowering).
        Blocks until done.  Tests call this directly for deterministic
        grouping; the scheduler thread calls it after the batch
        window.  Runs left queued (submitted mid-drain) are picked up
        either by a group's between-round refill or by the next loop
        iteration."""
        while True:
            with self._lock:
                pending = [
                    self._runs[rid]
                    for rid in self._pending
                    if self._runs[rid].status == "queued"
                ]
                self._pending = []
                solos: List[Run] = []
                groups: Dict[str, List[Run]] = {}
                for run in pending:
                    run.status = "running"
                    run.attempt += 1
                    run.last_progress = time.time()
                    self.journal.append("running", run.run_id)
                    if run.solo:
                        solos.append(run)
                    else:
                        groups.setdefault(run.signature, []).append(run)
                self._gauge_queue()
            if not groups and not solos:
                return
            for runs in groups.values():
                self._run_group(runs)
            for run in solos:
                self._run_solo(run)

    def _dataset_for(self, name: str):
        if self._dataset is not None:
            return self._dataset
        if name not in self._dataset_cache:
            from ..data import datasets as data_lib

            self._dataset_cache[name] = data_lib.load(name)
        return self._dataset_cache[name]

    def _fail(self, runs: List[Run], exc: BaseException) -> None:
        with self._lock:
            for run in runs:
                if run.status not in _DONE:
                    run.status = "failed"
                    run.error = f"{type(exc).__name__}: {exc}"
                    run.obs.emit(
                        "run_failed",
                        run_id=run.run_id, round=run.round, reason=run.error,
                        **self._trace_fields(run),
                    )
                    self.journal.append(
                        "failed", run.run_id,
                        round=run.round, reason=run.error,
                    )
                self._close_run_obs(run)

    def _load_lane_resume(
        self, run: Run
    ) -> Tuple[int, Optional[tuple], Optional[Dict[str, list]]]:
        """One lane's durable resume state: ``(round, restored, paths)``
        — or ``(0, None, None)`` when there is no usable checkpoint
        (absent, torn, round-mismatched, or missing the paths meta).
        Per-lane: an elastic group resumes each lane independently, so
        one torn checkpoint restarts ONE lane, never the group (a fresh
        replay is bit-identical by the fold_in key discipline —
        correctness never depends on the checkpoint, only wall-clock
        does)."""
        if run.resume_round <= 0:
            return 0, None, None
        try:
            restored = checkpoint.load(run.cfg.checkpoint_dir, run.title)
            meta = checkpoint.load_meta(run.cfg.checkpoint_dir, run.title)
        except Exception as exc:
            _warn(
                f"run {run.run_id}: checkpoint unreadable at seat time "
                f"({type(exc).__name__}: {exc}); lane restarts fresh"
            )
            return 0, None, None
        if (
            restored is None
            or int(restored[0]) != run.resume_round
            or meta is None
        ):
            return 0, None, None
        return run.resume_round, restored, json.loads(meta)

    def _run_group(self, runs: List[Run]) -> None:
        runs = elastic_lib.seat_order(runs)
        lane_resume = [self._load_lane_resume(run) for run in runs]
        start_rounds = [rr for rr, _, _ in lane_resume]
        restores = [restored for _, restored, _ in lane_resume]
        resume_paths = [paths for _, _, paths in lane_resume]
        try:
            dataset = self._dataset_for(runs[0].cfg.dataset)

            def restore_fn(lane: int, trainer) -> None:
                if restores[lane] is not None:
                    harness.restore_trainer(
                        trainer, runs[lane].cfg, restores[lane], log_fn=_warn
                    )

            batch = elastic_lib.runner_for(
                [r.cfg for r in runs],
                dataset=dataset,
                backend=self._backend,
                restore_fn=(
                    restore_fn
                    if any(r is not None for r in restores)
                    else None
                ),
            )
        except Exception as exc:
            self._fail(runs, exc)
            return
        # seated[lane] is the lane's CURRENT occupant (None = drained
        # slot awaiting refill); group_runs accumulates every run that
        # ever rode this batch, so a group-level exception fails the
        # refilled tenants too
        seated: List[Optional[Run]] = list(runs)
        group_runs: List[Run] = list(runs)
        attempts = {run.run_id: run.attempt for run in runs}
        with self._lock:
            for lane, run in enumerate(runs):
                run.lane = lane
                run.lane_hint = lane
                run.resume_round = start_rounds[lane]
                run.round = start_rounds[lane]
                # admission latency, submit -> lane seat (traced no-op
                # otherwise); feeds aircomp_queue_wait_seconds and the
                # queue_wait_p99 alert
                run.obs.span_event(
                    "queue_wait",
                    ms=(time.time() - run.submitted_at) * 1e3,
                    run_id=run.run_id, lane=lane,
                )

        def _live(run: Run) -> bool:
            """Still this group's run?  A watchdog requeue bumps the
            attempt — the stale group must stop touching it."""
            return (
                run.status == "running"
                and run.attempt == attempts[run.run_id]
            )

        def _release(lane: int) -> None:
            # free the slot AND the lane's forensic state (quarantine
            # freeze / failure reason), so a refilled tenant never
            # inherits the prior occupant's counters
            batch.release_lane(lane)
            seated[lane] = None

        def install(lane: int, run: Run, step: int) -> None:
            """Seat a queued tenant into a drained lane (lock held)."""
            run.status = "running"
            run.attempt += 1
            attempts[run.run_id] = run.attempt
            run.lane = lane
            run.lane_hint = lane
            run.wedged = False
            run.last_progress = time.time()
            run.obs.span_event(
                "queue_wait",
                ms=(time.time() - run.submitted_at) * 1e3,
                run_id=run.run_id, lane=lane,
            )
            t_install = time.perf_counter()
            rr, restored, rpaths = self._load_lane_resume(run)
            # WAL discipline: the refill record lands BEFORE the device
            # splice, so a SIGKILL between the two replays this tenant
            # back into this exact lane (recover() turns the journaled
            # lane into a seat_order hint)
            self.journal.append(
                "refill", run.run_id,
                lane=lane, round=rr, group_round=step,
                signature=run.signature,
            )
            try:
                batch.install_lane(
                    lane, run.cfg, own_round=rr,
                    restored=restored, paths=rpaths,
                )
            except Exception as exc:
                run.status = "failed"
                run.error = f"{type(exc).__name__}: {exc}"
                run.obs.emit(
                    "run_failed",
                    run_id=run.run_id, round=rr, reason=run.error,
                    **self._trace_fields(run),
                )
                self._close_run_obs(run)
                self.journal.append(
                    "failed", run.run_id, round=rr, reason=run.error,
                )
                return
            batch.obs_list[lane] = run.obs
            run.resume_round = rr
            run.round = rr
            seated[lane] = run
            group_runs.append(run)
            run.obs.span_event(
                "lane_install",
                ms=(time.perf_counter() - t_install) * 1e3,
                run_id=run.run_id, lane=lane, round=rr,
            )
            run.obs.emit(
                "lane_refill",
                run_id=run.run_id, lane=lane, round=rr, group_round=step,
                **self._trace_fields(run),
            )
            self._sched.emit(
                "lane_refill",
                run_id=run.run_id, lane=lane, round=rr, group_round=step,
            )

        def refill(step: int) -> None:
            """Between rounds, reseat drained lanes from the admission
            queue (lock held): same-signature queued tenants only, the
            journal-hinted ones reclaiming their exact lane first, the
            rest zipping into the remaining slots in submission
            order."""
            free = [ln for ln in range(batch.n) if seated[ln] is None]
            if not free:
                return
            sig = runs[0].signature
            picks: List[Run] = []
            keep: List[str] = []
            for rid in self._pending:
                cand = self._runs[rid]
                if (
                    len(picks) < len(free)
                    and cand.status == "queued"
                    and not cand.solo
                    and cand.signature == sig
                    and not cand.cancel_requested
                ):
                    picks.append(cand)
                else:
                    keep.append(rid)
            if not picks:
                return
            self._pending = keep
            free_set = set(free)
            hinted: List[Tuple[int, Run]] = []
            rest: List[Run] = []
            for cand in picks:
                h = cand.lane_hint
                if h is not None and h in free_set:
                    hinted.append((h, cand))
                    free_set.discard(h)
                else:
                    rest.append(cand)
            open_lanes = iter(sorted(free_set))
            for lane, cand in hinted + [
                (next(open_lanes), c) for c in rest
            ]:
                install(lane, cand, step)
            self._gauge_queue()

        def emit_lane_group(step: int) -> None:
            # occupancy is the acceptance gauge: live lanes / group
            # width, sampled every round boundary after refill
            live = sum(1 for ln in range(batch.n) if seated[ln] is not None)
            self._sched.emit(
                "lane_group",
                round=step, lanes=batch.n, live=live,
                occupancy=live / batch.n,
                queue_depth=self._queue_depth(),
            )

        def before_round(step: int) -> None:
            with self._lock:
                for lane in range(batch.n):
                    run = seated[lane]
                    if run is None:
                        continue
                    if not _live(run):
                        # terminal elsewhere (quarantined, watchdog-
                        # failed) or re-adopted: free the slot
                        _release(lane)
                        continue
                    if run.wedged:
                        # the watchdog owns this run now (requeue or
                        # terminal failure) — this group just stops
                        # driving the lane, without terminalizing
                        _release(lane)
                        continue
                    rnd = batch.lane_rounds[lane]
                    if run.cancel_requested:
                        _release(lane)
                        run.status = "cancelled"
                        run.obs.emit(
                            "run_cancelled", run_id=run.run_id, round=rnd,
                            **self._trace_fields(run),
                        )
                        self._close_run_obs(run)
                        self.journal.append(
                            "cancelled", run.run_id, round=rnd
                        )
                        run.swaps = []
                        continue
                    for knob, value in run.swaps:
                        batch.set_knob(lane, knob, value)
                        setattr(run.cfg, knob, value)
                        run.applied_swaps.append(
                            {"round": rnd, "knob": knob, "value": value}
                        )
                        run.obs.emit(
                            "knob_swap",
                            run_id=run.run_id, round=rnd,
                            knob=knob, value=value,
                            **self._trace_fields(run),
                        )
                    run.swaps = []
                    run.round = rnd
                    run.last_progress = time.time()
                refill(step)
                emit_lane_group(step)

        def on_quarantine(lane: int, rnd: int, reason: str) -> None:
            with self._lock:
                run = seated[lane]
                if run is None or not _live(run):
                    return
                run.status = "failed"
                run.error = f"quarantined: {reason}"
                run.round = rnd
                run.obs.emit(
                    "run_failed",
                    run_id=run.run_id, round=rnd, reason=run.error,
                    **self._trace_fields(run),
                )
                self._close_run_obs(run)
                self.journal.append(
                    "failed", run.run_id, round=rnd, reason=run.error
                )

        def after_round(step: int) -> None:
            # durable per-round progress: params + opt carries + the
            # metric paths so far, one atomic npz per live lane — the
            # unit a restarted server resumes from.  lane_rounds has
            # already advanced past the round just run, so it IS the
            # boundary a restart resumes from.
            with self._lock:
                for lane in range(batch.n):
                    run = seated[lane]
                    if (
                        run is None
                        or not _live(run)
                        or not batch.active[lane]
                    ):
                        continue
                    rnd = batch.lane_rounds[lane]
                    flat, extras = batch.lane_state(lane)
                    try:
                        checkpoint.save(
                            run.cfg.checkpoint_dir,
                            run.title,
                            rnd,
                            flat,
                            extras,
                            meta=json.dumps(batch.paths_list[lane]),
                        )
                    except Exception as exc:
                        _warn(
                            f"run {run.run_id}: checkpoint write failed "
                            f"({type(exc).__name__}: {exc}); continuing"
                        )
                        continue
                    self.journal.append(
                        "checkpoint", run.run_id, round=rnd
                    )
                    run.round = rnd
                    run.last_progress = time.time()

        def on_lane_done(lane: int) -> None:
            # a lane reached its OWN horizon: finalize the tenant now
            # (record, journal, stream close) so the slot refills at
            # the next round boundary while cotenants keep going
            with self._lock:
                run = seated[lane]
                if run is None or not _live(run) or run.wedged:
                    return
                paths = batch.paths_list[lane]
                run.paths = paths
                run.lowerings = batch.retrace.count("batch_round_fn")
                run.status = "completed"
                run.wedged = False
                run.round = run.cfg.rounds
                record = harness.build_record(
                    run.cfg,
                    paths,
                    dataset_name=dataset.name,
                    dataset_size=len(dataset.x_train),
                    max_feature=int(dataset.x_train[0].size),
                )
                try:
                    run.record_path = io_lib.atomic_pickle(
                        harness.cache_path(run.cfg, dataset.name), record
                    )
                except Exception as exc:
                    _warn(
                        f"run {run.run_id}: record write failed "
                        f"({type(exc).__name__}: {exc})"
                    )
                self.journal.append(
                    "completed",
                    run.run_id,
                    round=run.round,
                    lowerings=run.lowerings,
                    final_val_acc=paths["valAccPath"][-1],
                    final_val_loss=paths["valLossPath"][-1],
                )
                self._close_run_obs(run)
                seated[lane] = None

        try:
            batch.train(
                obs_list=[r.obs for r in runs],
                start_rounds=start_rounds,
                before_round=before_round,
                after_round=after_round,
                resume_paths=resume_paths,
                on_quarantine=on_quarantine,
                on_lane_done=on_lane_done,
            )
        except Exception as exc:
            self._fail(group_runs, exc)
            return
        lowerings = batch.retrace.count("batch_round_fn")
        with self._lock:
            for run in group_runs:
                # lanes finalized early (mid-group retirement) recorded
                # their lowering count as of that round; backfill the
                # group-final count so every tenant reports the shared
                # program's true total
                if run.status == "completed" and run.lowerings is None:
                    run.lowerings = lowerings

    def _run_solo(self, run: Run) -> None:
        """One streamed/mesh tenant through the ordinary harness path —
        a single-lane group.  The harness reopens the run's event stream
        (seq continues from the file), checkpoints every round with the
        metric paths riding the npz (``persist_paths``), and on an
        ``inherit`` resume merges the prefix so the record covers the
        whole schedule."""
        run_id = run.run_id
        with self._lock:
            run.lane = 0
            run.last_progress = time.time()
            run.obs.span_event(
                "queue_wait",
                ms=(time.time() - run.submitted_at) * 1e3,
                run_id=run_id, lane=0,
            )
        # hand the stream over: the harness's own sink appends after ours
        run.obs.close()
        run.obs = obs_lib.NULL
        solo_cfg = dataclasses.replace(run.cfg, inherit=True)

        def on_ckpt(rnd: int) -> None:
            self.journal.append("checkpoint", run_id, round=rnd)
            with self._lock:
                run.round = rnd
                run.last_progress = time.time()

        def _exec():
            return harness.run(
                solo_cfg,
                record_in_file=True,
                persist_paths=True,
                on_checkpoint=on_ckpt,
            )

        try:
            if run.trace_id is not None:
                # the harness's own "run" span (and everything under it)
                # adopts the tenant's trace and parents to the pre-minted
                # run_request root — one tree across the handover
                with obs_lib.trace.activate(run.trace_id, run.root_span_id):
                    record = _exec()
            else:
                record = _exec()
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            with self._lock:
                run.status = "failed"
                run.error = err
                self._reopen_obs(run)
                run.obs.emit(
                    "run_failed", run_id=run_id, round=run.round, reason=err,
                    **self._trace_fields(run),
                )
                self._close_run_obs(run)
            self.journal.append(
                "failed", run_id, round=run.round, reason=err
            )
            return
        lowerings = self._solo_lowerings(run.cfg, run.title)
        with self._lock:
            run.paths = {
                k: v
                for k, v in record.items()
                if isinstance(v, list)
            }
            run.lowerings = lowerings
            run.status = "completed"
            run.round = run.cfg.rounds
            run.record_path = harness.cache_path(run.cfg, record["name"])
            if run.trace_id is not None:
                # reopen the handed-back stream just long enough to seal
                # the trace: the run_request root appends after the
                # harness's own events, closing the tree
                self._reopen_obs(run)
                self._close_run_obs(run)
            else:
                run.obs = obs_lib.NULL
        self.journal.append(
            "completed",
            run_id,
            round=run.cfg.rounds,
            lowerings=lowerings,
            final_val_acc=record["valAccPath"][-1],
            final_val_loss=record["valLossPath"][-1],
        )

    def _solo_lowerings(self, cfg: FedConfig, title: str) -> Optional[int]:
        """The solo round fn's lowering count, read back from the run's
        own retrace event (the harness emits it at run end)."""
        path = obs_lib.events_path(cfg.obs_dir, title)
        count: Optional[int] = None
        for e in io_lib.iter_jsonl(path):
            if e.get("kind") == "retrace":
                counts = e.get("counts") or {}
                if counts.get("round_fn") is not None:
                    count = int(counts["round_fn"])
        return count

    # ---------------------------------------------------------- watchdog

    def degraded(self) -> Optional[str]:
        """A human-readable reason when the service is degraded (wedged
        or backoff-pending runs), else None — the server's /healthz
        flips to 503 on it."""
        with self._lock:
            wedged = [
                rid
                for rid in self._order
                if self._runs[rid].wedged
                and self._runs[rid].status not in _DONE
            ]
            if wedged:
                return f"wedged runs: {', '.join(wedged)}"
            if self._requeue_at:
                return (
                    "requeue pending: "
                    + ", ".join(sorted(self._requeue_at))
                )
        return None

    def _watchdog_loop(self) -> None:
        interval = max(min(self.wedge_secs / 4.0, 0.5), 0.05)
        while not self._stop:
            time.sleep(interval)
            try:
                self._watchdog_sweep(time.time())
            except Exception:
                traceback.print_exc()

    def _watchdog_sweep(self, now: float) -> None:
        """One supervision pass (explicit ``now`` so tests drive it
        deterministically): flag running runs with no progress in
        ``wedge_secs`` as wedged, cancel their lane, and either schedule
        a bounded-backoff requeue (``run_backoff * 2**(retries-1)``
        seconds) or — retries exhausted — fail them for good.  Solo
        lanes are flagged (degrading /healthz) but never requeued while
        their executing thread may still be alive: a second execution
        over the same namespace would race the first."""
        wake = False
        with self._lock:
            for rid in self._order:
                run = self._runs[rid]
                if (
                    run.status != "running"
                    or run.wedged
                    or self.wedge_secs <= 0
                ):
                    continue
                age = now - run.last_progress
                if age <= self.wedge_secs:
                    continue
                run.wedged = True
                run.cancel_requested = True  # lane goes dark if it wakes
                reason = f"wedged: no progress in {age:.1f}s"
                if run.solo:
                    _warn(
                        f"run {rid} {reason} (solo lane — flagged, not "
                        "requeued; /healthz reports degraded)"
                    )
                    continue
                if run.retries < self.run_retries:
                    run.retries += 1
                    delay = self.run_backoff * (2 ** (run.retries - 1))
                    self._requeue_at[rid] = now + delay
                    run.obs.emit(
                        "run_requeued",
                        run_id=rid, round=run.round,
                        retries=run.retries, reason=reason,
                    )
                    self.journal.append(
                        "requeued", rid, retries=run.retries, reason=reason
                    )
                    _warn(
                        f"run {rid} {reason}; requeue "
                        f"{run.retries}/{self.run_retries} in {delay:.1f}s"
                    )
                else:
                    run.status = "failed"
                    run.error = f"{reason}; retries exhausted"
                    run.obs.emit(
                        "run_failed",
                        run_id=rid, round=run.round, reason=run.error,
                    )
                    run.obs.close()
                    self.journal.append(
                        "failed", rid, round=run.round, reason=run.error
                    )
            for rid, due in sorted(self._requeue_at.items()):
                if now < due:
                    continue
                del self._requeue_at[rid]
                run = self._runs[rid]
                if run.status in _DONE:
                    continue
                run.status = "queued"
                run.wedged = False
                run.cancel_requested = False
                run.last_progress = now
                run.resume_round = self._probe_resume(run)
                run.round = run.resume_round
                self._pending.append(rid)
                self._gauge_queue()
                wake = True
        if wake:
            self._wake.set()
