"""``python -m byzantine_aircomp_tpu.sweep`` — defense-vs-attack matrix
(alias for :mod:`byzantine_aircomp_tpu.analysis.sweep`)."""

from .analysis.sweep import main

if __name__ == "__main__":
    main()
