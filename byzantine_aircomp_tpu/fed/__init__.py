from ..registry import OPTIMIZERS
from .config import FedConfig  # noqa: F401
from .train import FedTrainer  # noqa: F401

# The reference's --opt selects the federated optimizer function by name via
# eval (MNIST_Air_weight.py:580); only SGD exists (:226).  Same surface here,
# through the registry.
if "SGD" not in OPTIMIZERS:
    OPTIMIZERS.register("SGD")(FedTrainer)
