"""Run configuration.

Field names and defaults mirror the reference's ``optConfig`` / CLI surface
(``/root/reference/MNIST_Air_weight.py:16-28, :516-544``): K=50 honest clients,
100 rounds x displayInterval 10, batch 50, gamma 1e-2, weight_decay 0,
seed 2021, gm/gm2 maxiter 1000 tol 1e-5 (``:350``).
"""

from __future__ import annotations

import dataclasses
import typing
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class FedConfig:
    # topology
    honest_size: int = 50
    byz_size: int = 0

    # schedule (reference: rounds=100, displayInterval=10)
    rounds: int = 100
    display_interval: int = 10

    # optimizer (reference SGD: w <- w - gamma*(grad + wd*w))
    gamma: float = 1e-2
    weight_decay: float = 0.0
    batch_size: int = 50
    # local SGD steps per client per global iteration.  1 = the reference's
    # FedSGD (MNIST_Air_weight.py:296-303); >1 = the FedAvg regime, each
    # step on a fresh with-replacement batch
    local_steps: int = 1
    # FedProx (Li et al., MLSys 2020): proximal term mu*(w - w_global)
    # added to each LOCAL step's gradient, anchoring client drift under
    # local_steps > 1 (with one local step the anchor distance is 0, so
    # mu has no effect — FedSGD is recovered exactly).  0 disables.
    fedprox_mu: float = 0.0
    # server-side optimizer applied to the pseudo-gradient
    # (global_params - aggregated): "none" = take the aggregate directly
    # (reference semantics, :354-358); "momentum" = FedAvgM; "adam" = FedAdam
    server_opt: str = "none"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # client-side momentum (Karimireddy, He & Jaggi, ICML 2021 "Learning
    # from History"): each client keeps m_i <- beta*m_i + (1-beta)*g_i
    # across global iterations and sends w_global - gamma*m_i.  Momentum
    # averages a client's gradients over ~1/(1-beta) rounds, which breaks
    # time-coupled (inner-product-manipulation style) attacks that rely
    # on small per-round biases, and is the form under which cclip's
    # guarantees are proved.  0 = off (reference behavior).  Requires
    # local_steps == 1 (the FedSGD regime the paper analyzes); adds a
    # [K, d] state buffer carried across rounds (checkpointed, sharded
    # over clients on meshes)
    client_momentum: float = 0.0

    # dispatch
    agg: str = "gm"
    attack: Optional[str] = None
    noise_var: Optional[float] = None
    # non-adversarial fault injection (ops/faults.py): a registered
    # FaultSpec name ("dropout", "deep_fade", "csi", "corrupt", "chaos")
    # or None = the ideal deployment (bit-identical to the pre-fault
    # program — no fault code is traced).  The knobs below OVERRIDE the
    # named spec's defaults when not None; setting any of them without
    # --fault is an error (they would silently do nothing)
    fault: Optional[str] = None
    dropout_prob: Optional[float] = None
    fade_floor: Optional[float] = None
    csi_std: Optional[float] = None
    corrupt_prob: Optional[float] = None
    corrupt_mode: Optional[str] = None
    corrupt_size: Optional[int] = None
    ge_p_gb: Optional[float] = None
    ge_p_bg: Optional[float] = None
    ge_bad_mult: Optional[float] = None

    # online defense (defense/): "off" = no defense code is traced (the
    # program, RNG stream, pickled record and config_hash are bit-identical
    # to a build without the subsystem); "monitor" = in-jit anomaly
    # detector + would-be escalation rung tracked and reported, aggregation
    # untouched (trajectory identical to off); "adaptive" = the rung picks
    # the aggregator from the escalation ladder via an in-jit lax.switch.
    # The knobs below follow the fault-knob contract: any non-default value
    # with defense="off" is an error (it would silently do nothing)
    defense: str = "off"
    # comma-separated escalation ladder, cheapest rung first; adaptive mode
    # requires ladder[0] == agg (rung 0 IS the configured aggregator) and
    # rejects channel-owning rungs (gm/signmv — their AirComp transmission
    # happens inside aggregation, so the rungs can't share one received
    # stack)
    defense_ladder: str = "mean,trimmed_mean,multi_krum"
    # detector: iterations before flags arm, EMA smoothing, CUSUM
    # allowance/threshold (robust sigmas), instantaneous z threshold
    defense_warmup: int = 5
    defense_alpha: float = 0.1
    defense_drift: float = 0.5
    defense_cusum: float = 8.0
    defense_z: float = 4.0
    # hysteresis: escalate after N consecutive suspicious iterations,
    # de-escalate after M consecutive clean ones; an iteration is
    # suspicious when >= min_flagged clients flag
    defense_up: int = 3
    defense_down: int = 20
    defense_min_flagged: int = 1
    # duty-cycle resistance (defense/policy.py): each escalation adds one
    # unit to a leaky budget (decaying by defense_leak per iteration);
    # budget >= defense_floor pins the de-escalation floor at rung 1, so
    # a burst/sleep/burst attacker (ops/attacks.duty_cycle) finds the
    # ladder still raised.  defense_floor = 0 disables (seed hysteresis)
    defense_floor: float = 1.5
    defense_leak: float = 0.005

    # aggregator options (reference options dict, :350)
    agg_maxiter: int = 1000
    agg_tol: float = 1e-5
    gm_p_max: float = 1.0
    # extended-aggregator knobs: multi-Krum selection count (None = honest
    # size), centered-clipping radius and fixed iteration count
    krum_m: Optional[int] = None
    # scalar magnitude for parameterized message attacks (alie z, ipm eps,
    # gaussian sigma); None = the attack's own default
    attack_param: Optional[float] = None
    # centered-clipping radius; None = adaptive (per-step median of the
    # client delta norms, a robust honest-scale estimate for B < K/2).  A
    # fixed radius that is large vs the honest delta scale collapses under
    # weightflip — the adaptive default tracks the actual update magnitude
    clip_tau: Optional[float] = None
    clip_iters: int = 3
    # signmv (one-bit OTA majority vote) step magnitude; None = the
    # coordinatewise median of |w_i - guess| (robust adaptive scale)
    sign_eta: Optional[float] = None
    # sign-channel payload width for the vote aggregators (signmv/bev):
    # 32 = legacy full-precision ballots (byte-identical trajectories);
    # 1 = bit-packed uint32 sign words + popcount reduce (the one-bit OTA
    # wire, ~32x less sign-stack HBM/air traffic — needs an explicit
    # sign_eta since the wire carries no magnitudes); 8/16 =
    # quantize-dequantize emulation for the accuracy-vs-bits matrix.
    # Structural and hashed (skipped at the 32 default for checkpoint-
    # title continuity, like the defense/cohort/service knob blocks)
    sign_bits: int = 32
    # dnc (spectral divide-and-conquer) knobs — the paper's defaults:
    # filtering rounds, coordinate-subsample size, removal multiplier
    # (ceil(c*B) flagged per round)
    dnc_iters: int = 3
    dnc_sub_dim: int = 10000
    dnc_c: float = 1.0
    # "auto" | "xla" | "pallas": geometric-median Weiszfeld step
    # implementation (pallas = fused single-HBM-pass TPU kernel,
    # ops/pallas_kernels.py).  "auto" resolves to pallas on a real TPU
    # backend and xla elsewhere (interpret-mode pallas on CPU is slow);
    # the sharded trainer forces xla on multi-device meshes (GSPMD
    # cannot partition pallas_call)
    agg_impl: str = "auto"
    # "auto" | "on" | "off": single-HBM-pass aggregation epilogue for the
    # sort-family aggregators (median / trimmed_mean) — selection (Pallas
    # peel kernel on TPU, XLA key bisection elsewhere) instead of the full
    # [K, d] sort, with the OMA channel prepass folded into the same stack
    # read.  "auto" = on exactly when the resolved agg impl is pallas and
    # no fault is injected; "on" forces the XLA selection realization on
    # other backends too; degraded/bucketed/bf16 rounds always fall back
    # to the sort path (docs/DESIGN.md fallback matrix)
    fused_epilogue: str = "auto"
    # "f32" | "bf16": storage dtype of the [K, d] client stack handed to
    # the aggregator.  "bf16" halves the aggregator's HBM read traffic —
    # the Weiszfeld solvers re-read the whole stack every iteration, the
    # dominant repeated traffic at the bench config — while all arithmetic
    # stays f32 (type promotion in the XLA paths, explicit in-tile upcast
    # in the pallas kernels) and the aggregate is returned as f32.
    # EXPERIMENT: bf16's 8-bit mantissa is coarse relative to the
    # inter-client weight spread at convergence, so accuracy must be
    # gate-checked per workload (tests cover the synthetic schedule);
    # default stays f32
    stack_dtype: str = "f32"

    # determinism
    seed: int = 2021
    fix_seed: bool = True
    # PRNG implementation for the per-round key stream: "threefry"
    # (default - splittable, identical across backends) or "rbg" /
    # "unsafe_rbg" (hardware RNG path, much cheaper key derivation and
    # sampling on TPU; streams differ from threefry, so use for
    # throughput, not cross-backend reproducibility).  Model init always
    # uses threefry so initial params are impl-independent.
    prng_impl: str = "threefry"

    # model / data
    model: str = "MLP"
    dataset: str = "mnist"
    fc_width: int = 1024
    # ResNet knobs: stem width (64 = standard ResNet-18; smaller keeps the
    # topology for scaled trajectory runs) and per-block activation
    # rematerialization (jax.checkpoint), the HBM-for-FLOPs trade that
    # lifts the vmapped-clients single-chip memory ceiling
    # (docs/PERFORMANCE.md "no longer fits")
    resnet_width: int = 64
    remat: bool = False
    # client data partition: "contiguous" (the reference's equal slices,
    # approximately IID on an unsorted set, :238-239) or "dirichlet"
    # (label-skewed non-IID per Hsu et al. 2019 — the standard stress
    # axis for distance-based Byzantine defenses).  The Dirichlet split
    # is derived from (seed, alpha); smaller alpha = more skew
    partition: str = "contiguous"
    dirichlet_alpha: float = 0.3
    # quantity skew orthogonal to the label skew above: "none" keeps the
    # equal-size cut, "zipf:<s>" re-cuts the (possibly Dirichlet-permuted)
    # contiguous index stream into Zipf(s)-proportioned pieces — client i
    # owns ~ i^-s of the samples, so size skew composes with label skew.
    # s=0 is the exact equal cut (bit-identical boundaries)
    size_skew: str = "none"
    # partial participation (the FedAvg setting; the reference activates
    # every client every iteration): each global iteration runs a
    # STRATIFIED sample of half-up(participation * honest_size) honest and
    # floor(participation * byz_size) Byzantine clients (see
    # participant_counts for the rounding policy), drawn fresh per
    # iteration.  Stratification keeps the Byzantine fraction (and so the
    # aggregators' honest_size contract) exact with static shapes; 1.0
    # (default) is bit-identical to the full-participation program
    participation: float = 1.0
    # bucketing (Karimireddy, He & Jaggi, ICLR 2022): before robust
    # aggregation the server averages random disjoint buckets of s client
    # messages and aggregates the [m/s, d] bucket means instead — the
    # canonical remedy for the attack-free collapse of coordinatewise/
    # selection defenses (median/krum/signmv) under non-IID clients (see
    # docs/RESULTS.md's Dirichlet matrix): bucket means concentrate
    # around the true mean while at most one Byzantine row contaminates
    # each bucket.  1 = off (reference behavior); the participating
    # client count must be divisible by s
    bucket_size: int = 1
    # streaming cohort aggregation: when > 0 the round never materializes
    # the full [K, d] client stack — a lax.scan over cohort_size-client
    # chunks rebuilds each chunk on demand and feeds streaming/mergeable
    # aggregates (ops/aggregators.stream_aggregate), so peak HBM is
    # O(cohort*d) instead of O(K*d).  0 (default) takes the resident code
    # path verbatim: bit-identical records, RNG stream and config_hash.
    # Must divide both honest_size and byz_size so every chunk is purely
    # honest or purely Byzantine (honest chunks trace no attack code);
    # requires a streamable aggregator (mean/median/trimmed_mean/gm2), a
    # row-local or data-level attack, and a fault without the [K, d]
    # stale-replay buffer (see validate below)
    cohort_size: int = 0
    # streamed median/trimmed_mean realization: "exact" = total-order-key
    # bisection (32 counting passes over the cohort scan; identical ranks
    # to the resident selection epilogue, the parity fallback) or
    # "sketch" = mergeable key-space histogram (3 passes; error bounded
    # by the histogram bucket width, docs/DESIGN.md)
    cohort_quantile: str = "exact"
    # histogram resolution of the quantile sketch ([bins, d] i32 carry)
    cohort_sketch_bins: int = 512
    # always-on service rounds: when "on" the round no longer trains a
    # fixed cohort — a registered POPULATION of ``population`` clients
    # (split exactly proportionally into honest/Byzantine id blocks, see
    # population_counts) carries per-client availability state with
    # Markov churn, and every iteration draws a fresh stratified
    # subsample of honest_size + byz_size participants in-jit from the
    # available pool.  Stragglers (straggler_prob) and drawn-but-offline
    # clients miss the round deadline: their rows close as NaN and every
    # aggregator degrades through the effective-K machinery the fault
    # subsystem introduced.  "off" (default) is bit-identical to the
    # pre-service program: no extra key split, empty carry slot,
    # unchanged config_hash
    service: str = "off"
    # registered population size; a positive multiple of node_size so the
    # honest/Byzantine split over stable population ids stays exact
    population: int = 0
    # Markov churn: per-iteration probability that an offline client
    # re-registers (arrival) / an online client departs
    churn_arrival: float = 0.02
    churn_departure: float = 0.01
    # per-iteration probability that an arrived participant misses the
    # round deadline (its row is erased to NaN like a dropout)
    straggler_prob: float = 0.0
    # warm rollback: "on" arms the divergence guard (non-finite train/val
    # loss or variance, a val-loss spike past rollback_loss_factor x the
    # recent median, or — with a defense running and rollback_cusum > 0 —
    # a CUSUM peak past rollback_cusum).  A trip restores the last good
    # in-memory snapshot and resumes with the trim fraction widened by
    # rollback_widen (at most rollback_max restores per run)
    rollback: str = "on"
    rollback_loss_factor: float = 3.0
    rollback_cusum: float = 0.0
    rollback_widen: float = 1.5
    rollback_max: int = 3
    # population-axis sharding for streamed service rounds: split the
    # n_chunks cohort chunks of every round over S shard owners (one
    # device each when a mesh is available, a sequential lax.map over
    # shard ids otherwise) and merge the partial aggregates with the
    # fixed algebra in ops/shardctx.py.  1 (default) traces the legacy
    # single-scan program byte-identically and is skipped from
    # config_hash; > 1 requires --service on with a streamed cohort and
    # forks the hash/title lineage exactly like --cohort-size does
    # (float partial sums reassociate across the shard fold).  NOT in
    # _SERVICE_KNOBS: the hash-skip condition is pop_shards == 1, not
    # service == "off"
    pop_shards: int = 1
    # multi-round dispatch: run R rounds as ONE lax.scan dispatch
    # (fed/train.py _build_multi_round_fn) instead of R round_fn
    # dispatches.  1 (default) drives the legacy per-round loop
    # byte-identically and is skipped from config_hash; > 1 folds the
    # stacked [R, ...] scan outputs into records/events at dispatch exit,
    # moves eval + checkpoint + divergence-guard decisions to R-round
    # boundaries, and forks the hash/title lineage (`_rdN`) exactly like
    # --cohort-size does (the scanned program reassociates float reduces
    # across compilation units).  Fresh round budgets must divide by R;
    # a resumed run may open with one alignment dispatch and close with
    # one tail dispatch (each a distinct scan length -> one extra
    # lowering, accepted and logged by the retrace audit).
    rounds_per_dispatch: int = 1
    # rounds between boundary evals under R>1: 0 (default) evaluates at
    # every dispatch exit; a positive multiple of R evaluates only at
    # those boundaries and replicates the last eval into the skipped
    # rounds' record entries (degraded eval granularity, documented in
    # docs/DESIGN.md)
    eval_interval: int = 0
    # R>1 granularity contract: "exact" refuses feature combinations
    # whose semantics would silently coarsen (service-mode warm rollback
    # guards every round today but can only guard dispatch boundaries
    # under R>1); "degraded" opts into R-boundary rollback/forensics
    # granularity.  R=1 is always exact and bit-identical to the
    # pre-dispatch-tier driver.
    dispatch_mode: str = "exact"
    # double-buffer the dispatch rim: "on" launches dispatch i+1 before
    # folding dispatch i's host outputs so host record/event work
    # overlaps device compute.  Timing-only (roundsPerSec values change;
    # the trajectory, records, and event payloads are bit-identical), so
    # it is skipped from config_hash unconditionally like the obs knobs.
    dispatch_prefetch: str = "off"
    # async host rim (obs/writer.py): move checkpoint serialization,
    # JSONL/event sink appends, and the record pickle onto a bounded
    # single-consumer writer thread.  "auto" (default) enables it iff
    # rounds_per_dispatch > 1; output-only (per-sink seq envelope and
    # the run-end drain keep streams complete and ordered), so skipped
    # from config_hash unconditionally.
    async_writer: str = "auto"

    def participant_counts(self) -> tuple:
        """(honest, Byzantine) rows per iteration — the single source of
        the stratified-draw policy (trainer, sharded divisibility check,
        oracle backend, and validation all use it).

        Rounding policy: honest count rounds half-up; Byzantine count is
        FLOORED.  Python's round() is banker's rounding, which can round an
        exact .5 tie down for honest and up for Byzantine (H=13, B=3,
        f=0.5 -> 6 honest + 2 byz: 25% Byzantine among participants vs
        18.75% in the population).  Flooring f*B means rounding never
        inflates the number of participating attackers beyond f*B; the
        residual fraction shift from honest-side rounding at tiny counts
        is bounded by one client."""
        if self.participation < 1.0:
            # the epsilon guards both roundings against binary-float
            # products landing just under a mathematical integer or .5 tie
            # (0.29 * 100 -> 28.999999999999996: mathematical floor is 29,
            # not 28; same failure class for the honest half-up threshold)
            return (
                int(self.participation * self.honest_size + 0.5 + 1e-9),
                int(self.participation * self.byz_size + 1e-9),
            )
        return self.honest_size, self.byz_size

    def population_counts(self) -> tuple:
        """(honest, Byzantine) population block sizes under --service on.

        Stable population ids [0, pop_h) are honest, [pop_h, population)
        are Byzantine; validate enforces population % node_size == 0 so
        the split is exactly proportional and the stratified draw keeps
        the configured Byzantine fraction over ids, not row indices."""
        per = self.population // self.node_size
        return per * self.honest_size, per * self.byz_size

    # eval
    eval_batch: int = 2000
    eval_train: bool = True  # EMNIST reference skips train-set eval

    # federated optimizer (registry name; reference --opt, only SGD exists)
    opt: str = "SGD"

    # execution layout: None = auto (shard over all devices when >1 and K
    # divides evenly), True/False = force; model_parallel splits the d axis
    sharded: Optional[bool] = None
    model_parallel: Optional[int] = None

    # checkpoint / resume (the reference's --inherit is dead; ours works)
    checkpoint_dir: str = ""
    inherit: bool = False

    # misc
    mark: str = ""
    cache_dir: str = ""
    # when set, the harness drives jax.profiler through obs/profile.py:
    # the trace lands in profile_dir (loadable in Perfetto/XProf) with a
    # StepTraceAnnotation per round and named eval/checkpoint phases on
    # top of the round step's named_scope annotations
    # (client_local_step / message_attack / channel / aggregate)
    profile_dir: str = ""
    # capture window "A:B" (half-open, round indices): trace only rounds
    # [A, B) instead of the whole run; requires profile_dir
    profile_rounds: str = ""

    # observability (obs/): structured telemetry knobs.  All output-only —
    # they relocate/duplicate what the run reports without touching the
    # trajectory, so they are excluded from config_hash (like cache_dir)
    # and never reach run_title.  With all four at defaults no obs code
    # runs and the pickled record/RNG stream are bit-identical to a build
    # without the subsystem.
    # directory for the per-run schema-versioned event stream
    # ({ckpt_title}.events.jsonl, appended on resume)
    obs_dir: str = ""
    # also emit the event stream as JSON lines on stdout
    obs_stdout: bool = False
    # tee every harness log line (and the banner) here, flushed per line
    log_file: str = ""
    # silence the harness's stdout logging (the log_file tee still writes)
    quiet: bool = False
    # warn when the measured device peak_bytes_in_use watermark exceeds
    # the analytic model (obs/hbm.modeled_peak_bytes) by this factor;
    # output-only like the other obs knobs
    hbm_warn_factor: float = 2.0
    # client-level forensics (obs/forensics.py) — output-only like the
    # other obs knobs (excluded from config_hash, never in run_title,
    # record/RNG bit-identical when off).  "off": no forensic code is
    # traced; "top": in-jit top-M extraction + client_flag events for the
    # rows the detector flagged; "full": client_flag events for the whole
    # top-M every round + the host-side flight recorder (ring buffer of
    # the last flight_window rounds of detector carry, dumped on every
    # rollback trip and at run end).  Requires --defense != off (the
    # detector produces the scores being attributed).
    forensics: str = "off"
    # top-M suspicious clients extracted per round (<= node_size)
    forensics_top: int = 8
    # flight-recorder window W: rounds of detector carry kept in the ring
    flight_window: int = 8
    # live telemetry (obs/metrics.py, obs/exporter.py, obs/alerts.py) —
    # output-only like every obs knob: excluded from config_hash, never
    # in run_title, record/RNG bit-identical off vs on.  "on" folds the
    # event stream into an in-process metrics registry (a sink in the
    # ordinary fan-out; the jitted round fn is untouched)
    metrics: str = "off"
    # serve Prometheus /metrics + /healthz on this port (implies
    # --metrics on); 0 disables the exporter
    metrics_port: int = 0
    # SLO alert rules evaluated each round on the registry (implies
    # --metrics on): "off", "default" (the built-in pack), or a path to
    # a JSON rule list — see docs/OBSERVABILITY.md
    alerts: str = "off"
    # rotate the --obs-dir event stream once the live file passes this
    # many MiB (0 = one unbounded file); segments keep one seq envelope
    obs_rotate_mb: float = 0.0
    # distributed tracing (obs/trace.py, obs/span.py) — output-only like
    # every obs knob: excluded from config_hash, never in run_title,
    # record/RNG/event streams bit-identical off vs on (modulo the ids).
    # "on" makes spans mint trace/span ids, nest via the context-local
    # parent stack, and ride traceparent headers across the serving hops
    # so analysis/trace_view.py can assemble cross-process timelines
    trace: str = "off"

    @property
    def node_size(self) -> int:
        return self.honest_size + self.byz_size

    _FAULT_KNOBS = (
        "dropout_prob", "fade_floor", "csi_std", "corrupt_prob",
        "corrupt_mode", "corrupt_size", "ge_p_gb", "ge_p_bg", "ge_bad_mult",
    )

    # defense knobs that require --defense != off (fault-knob contract);
    # harness.config_hash also reads this tuple to keep the hash of every
    # defense-off config identical to pre-defense builds
    _DEFENSE_KNOBS = (
        "defense_ladder", "defense_warmup", "defense_alpha", "defense_drift",
        "defense_cusum", "defense_z", "defense_up", "defense_down",
        "defense_min_flagged", "defense_floor", "defense_leak",
    )

    # cohort knobs that require cohort_size > 0 (fault-knob contract);
    # harness.config_hash also reads this tuple to keep the hash of every
    # cohort-off config identical to pre-streaming builds
    _COHORT_KNOBS = ("cohort_quantile", "cohort_sketch_bins")

    # service knobs that require --service on (fault-knob contract);
    # harness.config_hash also reads this tuple to keep the hash of every
    # service-off config identical to pre-service builds
    _SERVICE_KNOBS = (
        "population", "churn_arrival", "churn_departure", "straggler_prob",
        "rollback", "rollback_loss_factor", "rollback_cusum",
        "rollback_widen", "rollback_max",
    )

    # forensics knobs that require --forensics top|full (fault-knob
    # contract); the forensics trio is output-only, so harness.config_hash
    # skips all three UNCONDITIONALLY (alongside obs_dir/log_file/...)
    # rather than via this tuple
    _FORENSICS_KNOBS = ("forensics_top", "flight_window")

    # dispatch-tier knobs that require rounds_per_dispatch > 1 (fault-knob
    # contract).  harness.config_hash reads this tuple to keep the hash of
    # every R=1 config identical to pre-dispatch-tier builds; the two
    # output-only members (dispatch_prefetch, async_writer) are NOT here —
    # they are validated independently and hash-skipped unconditionally
    # like the obs knobs.
    _DISPATCH_KNOBS = ("eval_interval", "dispatch_mode")

    def defense_ladder_names(self) -> tuple:
        """The escalation ladder as a tuple of aggregator names."""
        return tuple(n for n in self.defense_ladder.split(",") if n)

    def fault_overrides(self) -> dict:
        """The non-None fault knobs, as ``dataclasses.replace`` overrides
        for the named FaultSpec (ops/faults.resolve)."""
        return {
            k: getattr(self, k)
            for k in self._FAULT_KNOBS
            if getattr(self, k) is not None
        }

    def validate(self):
        # reference asserts (MNIST_Air_weight.py:229-230)
        assert self.byz_size == 0 or self.attack is not None, (
            "byz_size > 0 requires an attack"
        )
        assert self.honest_size > 0, "honest_size must be positive"
        assert self.agg_impl in ("auto", "xla", "pallas"), (
            f"agg_impl must be 'auto', 'xla' or 'pallas', got {self.agg_impl!r}"
        )
        assert self.fused_epilogue in ("auto", "on", "off"), (
            f"fused_epilogue must be 'auto', 'on' or 'off', "
            f"got {self.fused_epilogue!r}"
        )
        assert 0.0 < self.participation <= 1.0, (
            f"participation must be in (0, 1], got {self.participation}"
        )
        part_h, part_b = self.participant_counts()
        if self.participation < 1.0:
            assert part_h >= 1, (
                f"participation {self.participation} rounds to zero honest "
                f"participants of {self.honest_size}"
            )
            assert self.byz_size == 0 or part_b >= 1, (
                f"participation {self.participation} would silently drop "
                f"all {self.byz_size} Byzantine clients (rounds to 0); "
                f"raise the fraction or set byz_size=0 explicitly"
            )
        assert self.partition in ("contiguous", "dirichlet"), (
            f"partition must be 'contiguous' or 'dirichlet', "
            f"got {self.partition!r}"
        )
        assert self.dirichlet_alpha > 0, (
            f"dirichlet_alpha must be positive, got {self.dirichlet_alpha}"
        )
        if self.size_skew != "none":
            assert self.size_skew.startswith("zipf:"), (
                f"size_skew must be 'none' or 'zipf:<s>', "
                f"got {self.size_skew!r}"
            )
            try:
                s = float(self.size_skew.split(":", 1)[1])
            except ValueError:
                raise AssertionError(
                    f"size_skew exponent must be a float, "
                    f"got {self.size_skew!r}"
                )
            assert s >= 0, (
                f"size_skew exponent must be >= 0, got {s}"
            )
        assert self.stack_dtype in ("f32", "bf16"), (
            f"stack_dtype must be 'f32' or 'bf16', got {self.stack_dtype!r}"
        )
        assert self.bucket_size >= 1, (
            f"bucket_size must be >= 1, got {self.bucket_size}"
        )
        if self.bucket_size > 1:
            m = part_h + part_b
            assert m % self.bucket_size == 0, (
                f"bucket_size {self.bucket_size} must divide the "
                f"{m} participating clients"
            )
            n_buckets = m // self.bucket_size
            clean = n_buckets - part_b  # worst case: one byz row per bucket
            assert clean >= 2, (
                f"bucketing leaves {n_buckets} buckets of which {part_b} "
                f"may be Byzantine-contaminated — {clean} worst-case clean "
                f"buckets is degenerate; use a smaller bucket_size or "
                f"fewer Byzantine clients"
            )
            assert not (self.agg in ("krum", "Krum", "multi_krum") and clean < 3), (
                f"krum needs >= 3 worst-case clean buckets to score "
                f"neighbors (got {clean}); smaller bucket_size required"
            )
            # gm/signmv transmit INSIDE their aggregation (the AirComp
            # sum is per Weiszfeld step / per vote) — there are no
            # received per-client messages for the server to bucket, so
            # the combination has no physical meaning
            assert self.agg not in ("gm", "signmv"), (
                f"bucketing is undefined for agg={self.agg!r}: its "
                f"over-the-air transmission happens inside aggregation; "
                f"use gm2 (ideal) or a prepass aggregator"
            )
        # aggregators see round(f*H) + round(f*B) rows under partial
        # participation — or m/s bucket means under bucketing — so
        # selection counts are bounded by that, not K
        eff_k = (part_h + part_b) // self.bucket_size
        assert self.krum_m is None or 1 <= self.krum_m <= eff_k, (
            f"krum_m must be in [1, {eff_k}] (participating clients), "
            f"got {self.krum_m}"
        )
        assert (self.clip_tau is None or self.clip_tau > 0) and self.clip_iters >= 1, (
            f"clip_tau must be > 0 (or None = adaptive) and clip_iters >= 1, "
            f"got {self.clip_tau}, {self.clip_iters}"
        )
        assert self.sign_eta is None or self.sign_eta > 0, (
            f"sign_eta must be positive when set, got {self.sign_eta}"
        )
        if self.sign_bits not in (1, 8, 16, 32):
            raise ValueError(
                f"sign_bits must be one of 1, 8, 16, 32 "
                f"(payload width of the sign channel), got {self.sign_bits}"
            )
        if self.sign_bits != 32:
            if self.agg not in ("signmv", "bev"):
                raise ValueError(
                    f"sign_bits={self.sign_bits} narrows the SIGN channel "
                    f"— only the vote aggregators transmit it; "
                    f"agg={self.agg!r} transmits full-precision weights "
                    f"(use --agg signmv or bev, or leave sign_bits at 32)"
                )
        if self.sign_bits == 1:
            if self.bucket_size != 1:
                raise ValueError(
                    "sign_bits=1 packs each client's ballots into uint32 "
                    "words — bucket means over packed words are undefined "
                    "(a mean of sign words is not a sign word); use "
                    "--bucket-size 1"
                )
            if self.sign_eta is None:
                raise ValueError(
                    "sign_bits=1 requires an explicit --sign-eta: the "
                    "one-bit wire carries no delta magnitudes, so the "
                    "adaptive eta (coordinatewise median of |delta|) has "
                    "nothing to estimate from"
                )
        assert (
            self.dnc_iters >= 1 and self.dnc_sub_dim >= 1 and self.dnc_c > 0
        ), (
            f"dnc knobs must be positive, got iters={self.dnc_iters}, "
            f"sub_dim={self.dnc_sub_dim}, c={self.dnc_c}"
        )
        assert self.fedprox_mu >= 0, (
            f"fedprox_mu must be >= 0, got {self.fedprox_mu}"
        )
        assert 0.0 <= self.client_momentum < 1.0, (
            f"client_momentum must be in [0, 1), got {self.client_momentum}"
        )
        assert not (self.client_momentum and self.local_steps != 1), (
            "client_momentum requires local_steps == 1 (the FedSGD regime "
            "the momentum analysis covers); use server_opt for FedAvg"
        )
        assert self.prng_impl in ("threefry", "rbg", "unsafe_rbg"), (
            f"prng_impl must be 'threefry', 'rbg' or 'unsafe_rbg', "
            f"got {self.prng_impl!r}"
        )
        assert self.local_steps >= 1, "local_steps must be >= 1"
        assert self.server_opt in ("none", "momentum", "adam"), (
            f"server_opt must be none|momentum|adam, got {self.server_opt!r}"
        )
        overrides = self.fault_overrides()
        if self.fault is None:
            assert not overrides, (
                f"fault knobs {sorted(overrides)} require fault= to be set "
                f"(they override a named FaultSpec and would otherwise "
                f"silently do nothing)"
            )
        else:
            # resolve + spec-level validation up front so an unknown fault
            # name or out-of-range knob fails here, not at trace time
            from ..ops import faults as fault_lib

            spec = fault_lib.resolve(self.fault, overrides)
            assert self.participation == 1.0, (
                "fault injection requires full participation: the stale-"
                "replay buffer and Gilbert-Elliott state are [K]-indexed "
                "by the full client stack"
            )
            assert spec.corrupt_size <= self.honest_size, (
                f"corrupt_size {spec.corrupt_size} exceeds the "
                f"{self.honest_size} honest clients (corruption models "
                f"crashed honest senders; Byzantine rows are the attack's)"
            )
        if self.profile_rounds:
            assert self.profile_dir, (
                "profile_rounds requires profile_dir (a capture window "
                "without a trace destination would silently do nothing)"
            )
            # fail on a malformed window at startup, not at round A
            from ..obs.profile import parse_rounds

            parse_rounds(self.profile_rounds)
        assert self.hbm_warn_factor > 0, (
            f"hbm_warn_factor must be positive, got {self.hbm_warn_factor}"
        )
        # live-telemetry knobs (all output-only; see docs/OBSERVABILITY.md)
        assert self.metrics in ("off", "on"), (
            f"metrics must be off|on, got {self.metrics!r}"
        )
        assert 0 <= self.metrics_port <= 65535, (
            f"metrics_port must be a port number (0 disables), got "
            f"{self.metrics_port}"
        )
        assert self.alerts, (
            "alerts must be 'off', 'default', or a JSON rules path — got "
            "an empty string"
        )
        if self.alerts not in ("off", "default"):
            # fail on a malformed rules file at startup, not at round 0
            from ..obs.alerts import load_rules

            load_rules(self.alerts)
        assert self.obs_rotate_mb >= 0, (
            f"obs_rotate_mb must be >= 0 (0 disables rotation), got "
            f"{self.obs_rotate_mb}"
        )
        if self.obs_rotate_mb > 0:
            # fault-knob contract: rotation without a file stream would
            # silently do nothing
            assert self.obs_dir, "obs_rotate_mb requires --obs-dir"
        assert self.defense in ("off", "monitor", "adaptive"), (
            f"defense must be off|monitor|adaptive, got {self.defense!r}"
        )
        if self.defense == "off":
            # fault-knob contract: tuning a defense knob without enabling
            # the defense would silently do nothing
            defaults = {
                f.name: f.default for f in dataclasses.fields(self)
            }
            touched = sorted(
                k for k in self._DEFENSE_KNOBS
                if getattr(self, k) != defaults[k]
            )
            assert not touched, (
                f"defense knobs {touched} require --defense monitor|adaptive "
                f"(they configure the detector/ladder and would otherwise "
                f"silently do nothing)"
            )
        else:
            assert self.participation == 1.0, (
                "defense requires full participation: the detector EMA/"
                "CUSUM state is [K]-indexed by the full client stack"
            )
            assert self.defense_warmup >= 1, (
                f"defense_warmup must be >= 1, got {self.defense_warmup}"
            )
            assert 0.0 < self.defense_alpha <= 1.0, (
                f"defense_alpha must be in (0, 1], got {self.defense_alpha}"
            )
            assert (
                self.defense_drift > 0
                and self.defense_z > 0
                and self.defense_cusum > 0
            ), (
                f"defense drift/z/cusum thresholds must be positive, got "
                f"{self.defense_drift}, {self.defense_z}, {self.defense_cusum}"
            )
            assert (
                self.defense_up >= 1
                and self.defense_down >= 1
                and self.defense_min_flagged >= 1
            ), (
                f"defense hysteresis knobs must be >= 1, got "
                f"up={self.defense_up}, down={self.defense_down}, "
                f"min_flagged={self.defense_min_flagged}"
            )
            assert self.defense_floor >= 0.0, (
                f"defense_floor must be >= 0 (0 disables the escalation-"
                f"budget rung floor), got {self.defense_floor}"
            )
            assert 0.0 <= self.defense_leak < 1.0, (
                f"defense_leak must be in [0, 1) (per-iteration budget "
                f"decay), got {self.defense_leak}"
            )
            # ladder resolution fails here, not at trace time; in adaptive
            # mode rung 0 must be the configured aggregator
            from ..defense.policy import validate_ladder

            validate_ladder(
                self.defense_ladder_names(),
                self.agg if self.defense == "adaptive" else None,
            )
        if self.forensics not in ("off", "top", "full"):
            raise ValueError(
                f"forensics must be off|top|full, got {self.forensics!r}"
            )
        if self.forensics == "off":
            # fault-knob contract: tuning a forensics knob without enabling
            # the forensics layer would silently do nothing
            defaults = {f.name: f.default for f in dataclasses.fields(self)}
            touched = sorted(
                k for k in self._FORENSICS_KNOBS
                if getattr(self, k) != defaults[k]
            )
            if touched:
                raise ValueError(
                    f"forensics knobs {touched} require --forensics "
                    f"top|full (they size the top-M extraction / flight "
                    f"recorder and would otherwise silently do nothing)"
                )
        else:
            if self.defense == "off":
                raise ValueError(
                    "--forensics attributes the defense detector's "
                    "per-client scores — it requires --defense "
                    "monitor|adaptive"
                )
            if not 1 <= self.forensics_top <= self.node_size:
                raise ValueError(
                    f"forensics_top must be in [1, node_size="
                    f"{self.node_size}] (the top-k runs over the K drawn "
                    f"rows), got {self.forensics_top}"
                )
            if self.flight_window < 1:
                raise ValueError(
                    f"flight_window must be >= 1, got {self.flight_window}"
                )
        if self.attack is not None:
            # knowledge-tier contract (AttackSpec.meta()): a defense-aware
            # attack observes the carried detector state, which only
            # exists when the defense subsystem is running
            from ..ops import attacks as attack_lib

            if (
                attack_lib.resolve(self.attack).meta()["defense_aware"]
                and self.defense == "off"
            ):
                raise ValueError(
                    f"attack {self.attack!r} is defense-aware (it reads "
                    f"the published detector EMA/CUSUM state inside the "
                    f"round) and requires --defense adaptive|monitor; "
                    f"with --defense off there is no detector state to "
                    f"observe"
                )
        if self.cohort_size < 0:
            raise ValueError(
                f"cohort_size must be >= 0, got {self.cohort_size}"
            )
        if self.cohort_size == 0:
            # fault-knob contract: tuning a cohort knob without enabling
            # the streamed path would silently do nothing
            defaults = {f.name: f.default for f in dataclasses.fields(self)}
            touched = sorted(
                k for k in self._COHORT_KNOBS
                if getattr(self, k) != defaults[k]
            )
            if touched:
                raise ValueError(
                    f"cohort knobs {touched} require --cohort-size > 0 "
                    f"(they configure the streamed quantile rung and would "
                    f"otherwise silently do nothing)"
                )
        else:
            if self.cohort_quantile not in ("exact", "sketch"):
                raise ValueError(
                    f"cohort_quantile must be 'exact' or 'sketch', "
                    f"got {self.cohort_quantile!r}"
                )
            if self.cohort_sketch_bins < 2:
                raise ValueError(
                    f"cohort_sketch_bins must be >= 2, got "
                    f"{self.cohort_sketch_bins}"
                )
            # under partial participation the streamed round walks the
            # PARTICIPANT index space (subsample-then-stream): the drawn
            # part_h + part_b rows are chunked, so the chunking contract
            # is against the participating counts, not the full K
            if part_h % self.cohort_size or part_b % self.cohort_size:
                raise ValueError(
                    f"cohort_size {self.cohort_size} must divide both the "
                    f"{part_h} participating honest and {part_b} "
                    f"participating Byzantine clients (each streamed chunk "
                    f"must be purely honest or purely Byzantine — honest "
                    f"chunks trace no attack code); pick a participation "
                    f"fraction whose stratified counts the cohort divides"
                )
            if self.bucket_size != 1:
                raise ValueError(
                    "bucketing shuffles rows ACROSS cohorts before "
                    "aggregation, which needs the resident stack; use "
                    "--cohort-size 0 with --bucket-size"
                )
            if self.client_momentum != 0.0:
                raise ValueError(
                    "client_momentum carries a resident [K, d] state "
                    "buffer — exactly the allocation the streamed path "
                    "removes"
                )
            if self.stack_dtype != "f32":
                raise ValueError(
                    "the streamed selection rung bisects f32 total-order "
                    "keys; bf16 chunks are not supported (--cohort-size 0 "
                    "for bf16)"
                )
            if self.fused_epilogue == "on":
                raise ValueError(
                    "the fused sort-family epilogue reads the resident "
                    "[K, d] stack in one pass — it cannot apply to a "
                    "streamed round (the cohort scan IS the single pass); "
                    "leave it 'auto'"
                )
            from ..ops import aggregators as agg_lib

            for rung in {self.agg, *(
                self.defense_ladder_names()
                if self.defense == "adaptive" else ()
            )}:
                if not agg_lib.streamable(rung):
                    raise ValueError(
                        f"aggregator {rung!r} has no streaming/mergeable "
                        f"formulation (needs the resident [K, d] stack); "
                        f"streamable: mean, median, trimmed_mean, gm2"
                    )
            if self.attack is not None:
                from ..ops import attacks as attack_lib

                meta = attack_lib.resolve(self.attack).meta()
                if not meta["streamable"]:
                    raise ValueError(
                        f"attack {self.attack!r} is omniscient (reads the "
                        f"honest rows of the resident stack) and cannot "
                        f"run under cohort streaming; row-local/data-level "
                        f"attacks (signflip, gaussian, duty_cycle, "
                        f"classflip, dataflip, gradascent) stream fine — "
                        f"use --cohort-size 0 for the omniscient ones"
                    )
            if self.fault is not None:
                from ..ops import faults as fault_lib

                spec = fault_lib.resolve(self.fault, self.fault_overrides())
                if spec.needs_stale:
                    raise ValueError(
                        f"fault {self.fault!r} keeps a resident [K, d] "
                        f"stale-replay buffer (dropout_prob > 0) — exactly "
                        f"the allocation the streamed path removes; "
                        f"deep_fade/csi/corrupt stream fine"
                    )
        if self.service not in ("off", "on"):
            raise ValueError(
                f"service must be 'off' or 'on', got {self.service!r}"
            )
        if self.service == "off":
            # fault-knob contract: tuning a service knob without enabling
            # the service loop would silently do nothing
            defaults = {f.name: f.default for f in dataclasses.fields(self)}
            touched = sorted(
                k for k in self._SERVICE_KNOBS
                if getattr(self, k) != defaults[k]
            )
            if touched:
                raise ValueError(
                    f"service knobs {touched} require --service on (they "
                    f"configure the population/churn/rollback model and "
                    f"would otherwise silently do nothing)"
                )
        else:
            if self.population < self.node_size or (
                self.population % self.node_size
            ):
                raise ValueError(
                    f"--service on needs --population set to a positive "
                    f"multiple of node_size {self.node_size} (got "
                    f"{self.population}): the honest/Byzantine split over "
                    f"stable population ids must stay exactly proportional"
                )
            if self.participation != 1.0:
                raise ValueError(
                    "--service on replaces the legacy --participation "
                    "draw: the per-iteration subsample IS the "
                    "participation model (K = node_size rows drawn from "
                    "the population); leave participation at 1.0"
                )
            if self.fault is not None:
                raise ValueError(
                    "--service on subsumes fault injection: stragglers "
                    "and churn ARE the dropout model (deadline "
                    "semantics), and the fault carry (stale-replay "
                    "buffer, Gilbert-Elliott state) is [K]-row-indexed, "
                    "which has no stable meaning under per-iteration "
                    "subsampling; use --straggler-prob instead"
                )
            if self.bucket_size != 1:
                raise ValueError(
                    "--service on closes rounds with NaN rows for missed "
                    "deadlines; bucket means would smear a NaN across "
                    "every row of its bucket — use --bucket-size 1"
                )
            if self.client_momentum != 0.0:
                raise ValueError(
                    "client_momentum keeps a [K, d] per-row buffer; under "
                    "per-iteration subsampling it would need a "
                    "[population, d] buffer keyed by stable ids — not "
                    "supported, use server_opt momentum instead"
                )
            if not (0.0 <= self.churn_arrival <= 1.0
                    and 0.0 <= self.churn_departure <= 1.0):
                raise ValueError(
                    f"churn rates are per-iteration probabilities in "
                    f"[0, 1], got arrival={self.churn_arrival}, "
                    f"departure={self.churn_departure}"
                )
            if not 0.0 <= self.straggler_prob < 1.0:
                raise ValueError(
                    f"straggler_prob must be in [0, 1) — at 1.0 every "
                    f"round closes empty — got {self.straggler_prob}"
                )
            if self.rollback not in ("off", "on"):
                raise ValueError(
                    f"rollback must be 'off' or 'on', got {self.rollback!r}"
                )
            if self.rollback_loss_factor <= 1.0:
                raise ValueError(
                    f"rollback_loss_factor must be > 1 (a spike factor "
                    f"over the recent val-loss median), got "
                    f"{self.rollback_loss_factor}"
                )
            if self.rollback_cusum < 0.0:
                raise ValueError(
                    f"rollback_cusum must be >= 0 (0 disables the CUSUM "
                    f"guard), got {self.rollback_cusum}"
                )
            if self.rollback_cusum > 0.0 and self.defense == "off":
                raise ValueError(
                    "rollback_cusum reads the defense CUSUM state — it "
                    "requires --defense monitor|adaptive"
                )
            if self.rollback_widen < 1.0:
                raise ValueError(
                    f"rollback_widen must be >= 1 (the trim fraction only "
                    f"ever widens on rollback), got {self.rollback_widen}"
                )
            if self.rollback_max < 1:
                raise ValueError(
                    f"rollback_max must be >= 1, got {self.rollback_max}"
                )
        if self.pop_shards < 1:
            raise ValueError(
                f"pop_shards must be >= 1, got {self.pop_shards}"
            )
        if self.pop_shards > 1:
            if self.service != "on":
                raise ValueError(
                    "--pop-shards > 1 shards the service population's "
                    "cohort chunks over owners — it requires --service on"
                )
            if self.cohort_size <= 0:
                raise ValueError(
                    "--pop-shards > 1 shards the STREAMED chunk scan; set "
                    "--cohort-size > 0 (the resident path has its own "
                    "client-axis sharding via --sharded)"
                )
            n_chunks = self.node_size // self.cohort_size
            if n_chunks % self.pop_shards:
                raise ValueError(
                    f"pop_shards {self.pop_shards} must divide the "
                    f"per-round chunk count {n_chunks} (node_size "
                    f"{self.node_size} / cohort_size {self.cohort_size}) "
                    f"so every shard owns the same number of cohort chunks"
                )
            if self.forensics != "off":
                raise ValueError(
                    "--forensics needs the round's full top-M merge "
                    "stream, which is not shard-mergeable; use "
                    "--pop-shards 1 for forensic runs"
                )
        if self.rounds_per_dispatch < 1:
            raise ValueError(
                f"rounds_per_dispatch must be >= 1, "
                f"got {self.rounds_per_dispatch}"
            )
        if self.async_writer not in ("auto", "on", "off"):
            raise ValueError(
                f"async_writer must be auto, on, or off, "
                f"got {self.async_writer!r}"
            )
        if self.trace not in ("off", "on"):
            raise ValueError(
                f"trace must be off or on, got {self.trace!r}"
            )
        if self.dispatch_prefetch not in ("off", "on"):
            raise ValueError(
                f"dispatch_prefetch must be off or on, "
                f"got {self.dispatch_prefetch!r}"
            )
        if self.dispatch_mode not in ("exact", "degraded"):
            raise ValueError(
                f"dispatch_mode must be exact or degraded, "
                f"got {self.dispatch_mode!r}"
            )
        if self.rounds_per_dispatch == 1:
            defaults = {f.name: f.default for f in dataclasses.fields(self)}
            touched = sorted(
                k for k in self._DISPATCH_KNOBS
                if getattr(self, k) != defaults[k]
            )
            if self.dispatch_prefetch != "off":
                touched = sorted(touched + ["dispatch_prefetch"])
            if touched:
                raise ValueError(
                    f"dispatch knobs {touched} require "
                    f"--rounds-per-dispatch > 1 (the R=1 driver is the "
                    f"exact per-round loop; there is no dispatch "
                    f"granularity to tune)"
                )
        else:
            if self.rounds % self.rounds_per_dispatch:
                raise ValueError(
                    f"rounds_per_dispatch {self.rounds_per_dispatch} must "
                    f"divide the round budget {self.rounds}: a fresh run "
                    f"schedules only full R-round dispatches (a RESUMED "
                    f"run may open with an alignment dispatch and close "
                    f"with a tail dispatch, but the configured budget "
                    f"itself must split cleanly)"
                )
            if self.eval_interval < 0:
                raise ValueError(
                    f"eval_interval must be >= 0, got {self.eval_interval}"
                )
            if self.eval_interval and (
                self.eval_interval % self.rounds_per_dispatch
            ):
                raise ValueError(
                    f"eval_interval {self.eval_interval} must be 0 (every "
                    f"dispatch boundary) or a multiple of "
                    f"rounds_per_dispatch {self.rounds_per_dispatch}: "
                    f"evals only run between dispatches"
                )
            if (
                self.service == "on"
                and self.rollback == "on"
                and self.dispatch_mode == "exact"
            ):
                raise ValueError(
                    "--rounds-per-dispatch > 1 with --service on arms the "
                    "warm-rollback divergence guard, which can only fire "
                    "at dispatch boundaries under a multi-round scan; "
                    "opt into that coarser granularity with "
                    "--dispatch-mode degraded, or disable the guard with "
                    "--rollback off, or keep --rounds-per-dispatch 1"
                )
        return self


def coerce_field(name: str, raw: str):
    """Coerce a ``key=value`` CLI string by the FedConfig field's annotation.

    The ``--set`` plumbing shared by benchmarks/trajectory.py and
    benchmarks/hbm_compile.py (it lived in trajectory.py, which forced
    hbm_compile into a sys.path-dependent ``from trajectory import ...``).
    Bools accept true/false/1/yes; Optional fields accept "none"/"null".
    """
    hints = typing.get_type_hints(FedConfig)
    if name not in hints:
        raise SystemExit(f"unknown FedConfig field {name!r}")
    tp = hints[name]
    if typing.get_origin(tp) is typing.Union:  # Optional[...]
        if raw.lower() in ("none", "null"):
            return None
        tp = [a for a in typing.get_args(tp) if a is not type(None)][0]
    if tp is bool:
        return raw.lower() in ("1", "true", "yes")
    return tp(raw)


def config_from_mapping(body: dict) -> FedConfig:
    """Build a validated FedConfig from a JSON-ish mapping (the experiment
    server's ``POST /runs`` body).  Strings go through :func:`coerce_field`
    (same rules as ``--set``); JSON numbers are cast by the field
    annotation so ``{"gamma": 1}`` stores a float like the CLI would;
    bools/None pass through.  Raises ``ValueError`` naming the first
    unknown field — a typo'd knob must be a 400, not a silent default.
    """
    hints = typing.get_type_hints(FedConfig)
    kwargs = {}
    for name, value in body.items():
        if name not in hints:
            raise ValueError(f"unknown FedConfig field {name!r}")
        if isinstance(value, str):
            kwargs[name] = coerce_field(name, value)
        elif isinstance(value, bool) or value is None:
            kwargs[name] = value
        elif isinstance(value, (int, float)):
            tp = hints[name]
            if typing.get_origin(tp) is typing.Union:  # Optional[...]
                tp = [a for a in typing.get_args(tp) if a is not type(None)][0]
            kwargs[name] = tp(value) if tp in (int, float) else value
        else:
            kwargs[name] = value
    cfg = FedConfig(**kwargs)
    cfg.validate()
    return cfg


def config_to_mapping(cfg: FedConfig) -> dict:
    """The JSON-safe inverse of :func:`config_from_mapping`: every field
    whose value differs from the dataclass default, as a plain mapping.

    The experiment server's durable journal stores each submission this
    way (the PRE-namespace config — replay re-namespaces under the same
    ``run_id``, reproducing the original paths), so the round trip
    ``config_from_mapping(config_to_mapping(cfg)) == cfg`` must hold for
    any valid config; tests/test_chaos.py pins it."""
    out = {}
    for f in dataclasses.fields(FedConfig):
        default = (
            f.default
            if f.default is not dataclasses.MISSING
            else f.default_factory()  # type: ignore[misc]
        )
        value = getattr(cfg, f.name)
        if value != default:
            out[f.name] = value
    return out
