"""Experiment harness: titles, banner, pickled metric records.

Reproduces the reference's ``run`` observability surface
(``/root/reference/MNIST_Air_weight.py:427-492``) so existing analysis
(draw.ipynb's pickle-loading cells) keeps working against this framework's
output: same title scheme ``{Model}_{opt}_{attack|baseline}_{agg}[_{var}][_{mark}]``
(``:446-455``), same cache-dir convention ``{name}_K{K}_B{B}_`` (``:546-550``),
same record keys including the ``variencePath`` spelling (``:481-489``).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .. import obs as obs_lib
from ..data import datasets as data_lib
from ..utils import env as env_lib
from ..utils import io as io_lib
from . import checkpoint
from .config import FedConfig
from .train import FedTrainer

# module-level log routing (configured per-run by ``configure_log``): the
# optional --log-file tee handle and the --quiet stdout gate.  Module
# globals — not Logger objects threaded everywhere — because ``log`` is
# this package's module-level logging function (reproduce.py and friends
# call ``harness.log`` directly) and every caller must share one routing.
_LOG_FILE = None
_QUIET = False


def configure_log(log_file: str = "", quiet: bool = False):
    """Route :func:`log` (and the banner): tee to ``log_file`` (append,
    flushed per line so a timeout-killed run keeps its tail) and/or
    silence stdout.  Returns a zero-arg restore callable — callers wrap
    the run in try/finally so in-process sequential runs (tests, sweeps)
    never inherit a previous run's routing or leak the file handle."""
    global _LOG_FILE, _QUIET
    prev = (_LOG_FILE, _QUIET)
    _LOG_FILE = io_lib.open_append(log_file) if log_file else None
    _QUIET = quiet

    def restore():
        global _LOG_FILE, _QUIET
        if _LOG_FILE is not None:
            _LOG_FILE.close()
        _LOG_FILE, _QUIET = prev

    return restore


def _emit_line(line: str):
    """One log line to the configured outputs, flushed on every line."""
    if not _QUIET:
        print(line)
        sys.stdout.flush()
    if _LOG_FILE is not None:
        _LOG_FILE.write(line + "\n")
        _LOG_FILE.flush()


def log(*k, **kw):
    """Timestamped logging (reference ``log``, ``:40-44``), routed through
    the configured sink: stdout unless ``--quiet``, plus the ``--log-file``
    tee when set."""
    stamp = time.strftime("[%m-%d %H:%M:%S] ", time.localtime())
    sep = kw.get("sep", " ")
    _emit_line(stamp + sep.join(str(x) for x in k))


_CFG_DEFAULTS = {f.name: f.default for f in dataclasses.fields(FedConfig)}


def _non_default(cfg: FedConfig, name: str) -> bool:
    return getattr(cfg, name) != _CFG_DEFAULTS[name]


def run_title(cfg: FedConfig) -> str:
    attack_name = cfg.attack if cfg.attack is not None else "baseline"
    title = f"{cfg.model}_{cfg.opt}_{attack_name}_{cfg.agg}"
    if cfg.noise_var is not None:
        title += f"_{cfg.noise_var}"
    # framework extensions beyond the reference scheme (:446-455) append
    # only when non-default (checked against the FedConfig dataclass
    # defaults, so the two can't drift), so reference-equivalent runs keep
    # identical titles AND differently-configured runs never collide on
    # checkpoints
    if _non_default(cfg, "local_steps"):
        title += f"_E{cfg.local_steps}"
    if cfg.fedprox_mu:
        title += f"_prox{cfg.fedprox_mu}"
    if cfg.server_opt == "momentum":
        title += f"_momentum{cfg.server_lr}m{cfg.server_momentum}"
    elif cfg.server_opt != "none":
        title += f"_{cfg.server_opt}{cfg.server_lr}"
    if cfg.client_momentum:
        title += f"_cm{cfg.client_momentum}"
    # result-affecting magnitude knobs (non-default only, same rationale)
    if cfg.attack_param is not None:
        title += f"_ap{cfg.attack_param}"
    if cfg.krum_m is not None:
        title += f"_m{cfg.krum_m}"
    if _non_default(cfg, "clip_tau"):
        title += f"_tau{cfg.clip_tau}"
    elif cfg.agg == "cclip":
        # the cclip default changed fixed tau=10 -> adaptive (round 2); pre-
        # round-2 cclip runs carry the bare title for fixed tau=10, so the
        # adaptive default must be spelled out or the two algorithms would
        # alias on checkpoints/pickles and --inherit would silently resume a
        # fixed-tau checkpoint under the adaptive rule
        title += "_tauadaptive"
    if _non_default(cfg, "clip_iters"):
        title += f"_ci{cfg.clip_iters}"
    if cfg.sign_eta is not None:
        title += f"_eta{cfg.sign_eta}"
    if _non_default(cfg, "sign_bits"):
        title += f"_sb{cfg.sign_bits}"
    if _non_default(cfg, "dnc_iters"):
        title += f"_di{cfg.dnc_iters}"
    if _non_default(cfg, "dnc_sub_dim"):
        title += f"_ds{cfg.dnc_sub_dim}"
    if _non_default(cfg, "dnc_c"):
        title += f"_dc{cfg.dnc_c}"
    # implementation knobs that change the TRAJECTORY (not just speed):
    # a non-threefry PRNG stream and a bf16 aggregator stack both produce
    # different results from the default run, so they must not alias with
    # it on checkpoints/pickles (same hazard class as the cclip tau note)
    if cfg.partition == "dirichlet":
        title += f"_dir{cfg.dirichlet_alpha}"
    if cfg.size_skew != "none":
        # quantity-skewed shard sizes change every client's sample stream,
        # so skewed runs must never alias the equal-cut trajectory
        title += f"_skew{cfg.size_skew.replace(':', '')}"
    if cfg.participation < 1.0:
        title += f"_part{cfg.participation}"
    if cfg.bucket_size > 1:
        title += f"_bkt{cfg.bucket_size}"
    if cfg.cohort_size > 0:
        # the streamed round reorders float accumulation (and re-keys the
        # per-cohort channel/batch draws), so it must never alias the
        # resident trajectory on checkpoints/pickles
        title += f"_cohort{cfg.cohort_size}"
        for knob in FedConfig._COHORT_KNOBS:
            if _non_default(cfg, knob):
                title += f"_{knob.replace('cohort_', '')}{getattr(cfg, knob)}"
    if cfg.service == "on":
        # service rounds re-key the participant draw / channel / detector
        # by population id, so they must never alias a static-K trajectory;
        # composes with the _cohort suffix above (subsample-then-stream)
        title += f"_pop{cfg.population}_sub{cfg.node_size}"
        for knob in FedConfig._SERVICE_KNOBS:
            if knob != "population" and _non_default(cfg, knob):
                title += f"_{knob.replace('_', '')}{getattr(cfg, knob)}"
    if cfg.pop_shards > 1:
        # pop-sharding reassociates the float partial-sum fold (cohort
        # idiom: the lineage forks like --cohort-size), so sharded
        # checkpoints never alias the single-scan trajectory
        title += f"_ps{cfg.pop_shards}"
    if cfg.rounds_per_dispatch > 1:
        # the multi-round scan is a separately compiled program (float
        # re-association vs the per-round loop — cohort idiom), and its
        # eval/checkpoint cadence is R-boundary, so dispatch-tier
        # checkpoints never alias the exact per-round trajectory
        title += f"_rd{cfg.rounds_per_dispatch}"
        if _non_default(cfg, "eval_interval"):
            title += f"_ev{cfg.eval_interval}"
        if _non_default(cfg, "dispatch_mode"):
            title += f"_{cfg.dispatch_mode}"
    if _non_default(cfg, "prng_impl"):
        title += f"_prng{cfg.prng_impl}"
    if _non_default(cfg, "stack_dtype"):
        # prefixed like _prng above: a bare _bf16 would collide with
        # --mark bf16 on a default-dtype run
        title += f"_stack{cfg.stack_dtype}"
    if cfg.fault is not None:
        # fault scenario + any overridden knobs: a chaos run and a
        # fault-free run must never alias on checkpoints/pickles, and two
        # chaos runs at different dropout rates must not either
        title += f"_fault{cfg.fault}"
        for knob, val in sorted(cfg.fault_overrides().items()):
            title += f"_{knob.replace('_', '')}{val}"
    if cfg.defense != "off":
        # defense mode + any non-default knobs (fault idiom): an adaptive
        # run rewrites the aggregation trajectory, and even monitor runs
        # must not alias defended checkpoints with undefended ones —
        # validate() keeps every knob at its default when the defense is
        # off, so off-runs keep the exact pre-defense title
        title += f"_def{cfg.defense}"
        for knob in FedConfig._DEFENSE_KNOBS:
            if _non_default(cfg, knob):
                val = str(getattr(cfg, knob)).replace(",", "-")
                title += f"_{knob.replace('_', '')}{val}"
    if cfg.mark:
        title += f"_{cfg.mark}"
    return title


def config_hash(cfg: FedConfig) -> str:
    """Short stable digest of EVERY result-affecting config field.

    ``run_title`` spells out only the knobs the reference scheme (and our
    non-default suffixes) name — seed, honest/byz sizes, dataset,
    batch_size, gamma, widths and the rest of the dataclass never reach the
    title, so e.g. seed-2021 and seed-2022 ResNet cells share
    ``ResNet18_SGD_gradascent_krum`` and would silently resume each other's
    checkpoints.  Hash the full field dict and let :func:`ckpt_title`
    append it where collision actually corrupts results.  Excluded:
    path-like fields (they relocate outputs without changing the
    trajectory), ``inherit`` (the resume switch itself), and ``rounds`` —
    the schedule horizon is exactly the knob ``--inherit`` is meant to
    vary (a rounds=100 run continues a rounds=50 checkpoint; the per-round
    trajectory prefix is identical by the fold_in key discipline).
    """
    import hashlib

    skip = (
        "checkpoint_dir", "cache_dir", "profile_dir", "inherit", "rounds",
        # observability knobs relocate/duplicate outputs without touching
        # the trajectory — hashing them would split checkpoint identity
        # between an observed and an unobserved run of the same config
        "obs_dir", "obs_stdout", "log_file", "quiet",
        "profile_rounds", "hbm_warn_factor",
        # forensics is output-only telemetry (obs/forensics.py): the knobs
        # add events/artifacts without touching the trajectory, so like
        # the obs knobs they are skipped UNCONDITIONALLY
        "forensics", "forensics_top", "flight_window",
        # live telemetry (obs/metrics.py, obs/exporter.py, obs/alerts.py)
        # derives everything from the event stream on the host — same
        # output-only contract, skipped UNCONDITIONALLY
        "metrics", "metrics_port", "alerts", "obs_rotate_mb",
        # the async writer rim relocates WHERE/WHEN bytes hit disk, and
        # dispatch prefetch only reorders host folds against device
        # compute — both leave the trajectory and every record payload
        # bit-identical, so they are output-only knobs like the obs trio
        "async_writer", "dispatch_prefetch",
        # distributed tracing mints ids onto emitted events and headers —
        # pure output metadata, skipped UNCONDITIONALLY so a traced and
        # an untraced run share checkpoints and batch-lane signatures
        "trace",
    )
    if cfg.defense == "off":
        # a defense-off config must hash identically to builds that
        # predate the defense fields (checkpoint/pickle continuity);
        # validate() pins every defense knob to its default when the
        # defense is off, so skipping them drops no information
        skip = skip + ("defense",) + FedConfig._DEFENSE_KNOBS
    if cfg.cohort_size == 0:
        # same continuity contract as the defense block: a cohort-off
        # config must hash identically to builds that predate the
        # streaming fields (validate() pins the cohort knobs to their
        # defaults when cohort_size is 0, so skipping drops nothing)
        skip = skip + ("cohort_size",) + FedConfig._COHORT_KNOBS
    if cfg.service == "off":
        # and again for the service-round fields: a service-off config
        # must hash identically to builds that predate them (validate()
        # pins every service knob to its default when service is off)
        skip = skip + ("service",) + FedConfig._SERVICE_KNOBS
    if cfg.pop_shards == 1:
        # pop-shard continuity: the default single-scan engine must hash
        # identically to builds that predate population sharding.  NOT
        # keyed on service — pop_shards > 1 always forks (the shard fold
        # reassociates float sums), even though it requires --service on
        skip = skip + ("pop_shards",)
    if cfg.size_skew == "none":
        # size-skew continuity: the default equal cut must hash
        # identically to builds that predate the size_skew field
        skip = skip + ("size_skew",)
    if cfg.sign_bits == 32:
        # same continuity contract: a full-width (legacy) sign channel
        # must hash identically to builds that predate the sign_bits
        # field — the 32 default is byte-identical to the old path
        skip = skip + ("sign_bits",)
    if cfg.rounds_per_dispatch == 1:
        # dispatch-tier continuity: an R=1 config must hash identically
        # to builds that predate the multi-round dispatch fields
        # (validate() pins the dispatch knobs to their defaults at R=1,
        # so skipping drops nothing); R>1 forks the lineage — the scan
        # is a separately compiled program with R-boundary eval cadence
        skip = skip + ("rounds_per_dispatch",) + FedConfig._DISPATCH_KNOBS
    items = sorted(
        (f.name, repr(getattr(cfg, f.name)))
        for f in dataclasses.fields(cfg)
        if f.name not in skip
    )
    return hashlib.sha256(repr(items).encode()).hexdigest()[:8]


def ckpt_title(cfg: FedConfig) -> str:
    """Checkpoint key: the human-readable run title plus the config hash,
    so two configs can only share saved state when EVERY result-affecting
    field matches.  Pickled metric records keep the bare ``run_title``
    (reference-compatible paths for draw.ipynb-style analysis)."""
    return f"{run_title(cfg)}_c{config_hash(cfg)}"


def run_namespace(cfg: FedConfig, run_id: str, root: str) -> FedConfig:
    """Rebase every output-only path onto the run's private subtree
    ``<root>/<run_id>/`` — the tenancy boundary of the experiment server.

    Events, checkpoints, caches, and profiles from different runs can
    never collide or interleave because each run writes only under its
    own ``run_id``.  Nothing here touches the trajectory: every
    rewritten field is in :func:`config_hash`'s unconditional skip list,
    so the namespaced config keeps the submitted config's identity.
    """
    ns = os.path.join(root, run_id)
    os.makedirs(ns, exist_ok=True)
    return dataclasses.replace(
        cfg,
        obs_dir=ns,
        checkpoint_dir=os.path.join(ns, "ckpt"),
        cache_dir=os.path.join(ns, "cache"),
        profile_dir=os.path.join(ns, "profile"),
        log_file="",
    )


def cache_path(cfg: FedConfig, dataset_name: str) -> str:
    cache_dir = cfg.cache_dir or f"./{dataset_name.upper()}_Air_weight_tpu/"
    os.makedirs(cache_dir, exist_ok=True)
    prefix = f"{dataset_name}_K{cfg.node_size}_B{cfg.byz_size}_"
    return os.path.join(cache_dir, prefix + run_title(cfg))


def banner(cfg: FedConfig, trainer: FedTrainer, path: str):
    n_params = trainer.dim
    if n_params >= 2**20:
        p_str = f"{n_params / 2**20:.2f}M"
    elif n_params >= 2**10:
        p_str = f"{n_params / 2**10:.2f}K"
    else:
        p_str = str(n_params)
    attack_name = cfg.attack if cfg.attack is not None else "baseline"
    ds = trainer.dataset
    _emit_line(f"[submit task ] {path}")
    _emit_line("[running info]")
    _emit_line(f"[network info]   name={cfg.model} parameters number={p_str}")
    _emit_line(
        f"[optimization]   name={cfg.opt} aggregation={cfg.agg} attack={attack_name}"
    )
    _emit_line(
        f"[dataset info] name={ds.name} source={ds.source} "
        f"trainSize={len(ds.x_train)} validationSize={len(ds.x_val)}"
    )
    _emit_line(
        f"[optimizer   ] gamma={cfg.gamma} weight_decay={cfg.weight_decay} "
        f"batchSize={cfg.batch_size}"
    )
    _emit_line(
        f"[node number ]   honestSize={cfg.honest_size}, byzantineSize={cfg.byz_size}"
    )
    _emit_line(
        f"[running time]   rounds={cfg.rounds}, displayInterval={cfg.display_interval}"
    )
    import jax

    _emit_line(
        f"[jax set     ]  backend={jax.default_backend()} devices={len(jax.devices())} "
        f"SEED={cfg.seed}, fixSeed={cfg.fix_seed}"
    )
    _emit_line("-------------------------------------------")


def _make_trainer(cfg: FedConfig, trainer_cls):
    """Pick the execution layout: sharded over the device mesh when it buys
    parallelism (cfg.sharded=None is auto), single-program otherwise.  Layout
    is orthogonal to the federated optimizer, so the sharded wrapper applies
    to the base trainer; custom registered optimizers run as themselves."""
    import jax

    from .train import FedTrainer

    n_dev = len(jax.devices())
    if cfg.pop_shards > 1 and trainer_cls is FedTrainer:
        # population-axis sharding (streamed service rounds) is its own
        # layout: the mesh engine when the devices exist, the sequential
        # reference engine otherwise (sharded=False forces sequential —
        # useful for parity baselines on a multi-device host)
        if cfg.sharded is not False and n_dev >= cfg.pop_shards:
            from ..parallel import PopShardedFedTrainer

            log(f"Population-sharded execution over {cfg.pop_shards} devices")
            return PopShardedFedTrainer(cfg)
        log(f"Population shards x{cfg.pop_shards} (sequential engine)")
        return FedTrainer(cfg)
    if trainer_cls is FedTrainer:
        from ..parallel import ShardedFedTrainer, mesh as mesh_lib

        n_model = cfg.model_parallel or 1
        n_clients_axis = n_dev // n_model if n_dev % n_model == 0 else 0
        auto = n_dev > 1 and n_clients_axis and cfg.node_size % n_clients_axis == 0
        use_sharded = auto if cfg.sharded is None else cfg.sharded
        if use_sharded:
            mesh = mesh_lib.make_mesh(model_parallel=cfg.model_parallel)
            log(f"Sharded execution over mesh {dict(mesh.shape)}")
            return ShardedFedTrainer(cfg, mesh=mesh)
    return trainer_cls(cfg)


def extra_state(t, cfg: FedConfig):
    """Everything beyond flat params that must survive a resume, as one
    pytree: server-optimizer state, the client-momentum buffer, the
    fault-injection carry (stale-update buffer + Gilbert-Elliott channel
    state), the defense carry (detector baselines + policy rung/streaks),
    the attack-onset iteration counter, and the service carry (population
    availability + widened trim scale) with the rollback epoch.  The leaf
    ORDER is the checkpoint contract — the experiment server's per-lane
    checkpoints (``serve/batch.BatchRunner.lane_state``) emit the same
    layout so batch-lane and solo checkpoints are interchangeable."""
    return (
        getattr(t, "server_opt_state", ()),
        getattr(t, "client_m", ()),
        getattr(t, "fault_state", ()),
        getattr(t, "defense_state", ()),
        getattr(t, "attack_iter", ()),
        getattr(t, "service_state", ()),
        (
            jnp.int32(getattr(t, "_rollback_epoch", 0))
            if cfg.service == "on" else ()
        ),
    )


def restore_trainer(trainer, cfg: FedConfig, restored, log_fn=None) -> int:
    """Install a ``checkpoint.load`` result into a freshly built trainer;
    returns the round to resume from.  Restores through the trainer's own
    leaf shardings (a plain asarray would drop the mesh placement on
    sharded runs) and tolerates a leaf-count mismatch by keeping the
    extra state fresh — params alone still resume the trajectory of the
    reference layout."""
    import jax

    log_fn = log_fn or log
    start_round, flat, extra_leaves = restored
    trainer.flat_params = jax.device_put(flat, trainer.flat_params.sharding)
    own_state = extra_state(trainer, cfg)
    own_leaves = jax.tree.leaves(own_state)
    if len(extra_leaves) == len(own_leaves) and extra_leaves:
        (
            server_state, client_m, fault_state, defense_state,
            attack_iter, service_state, rollback_epoch,
        ) = jax.tree.unflatten(
            jax.tree.structure(own_state),
            [
                jax.device_put(l, own.sharding)
                for l, own in zip(extra_leaves, own_leaves)
            ],
        )
        trainer.server_opt_state = server_state
        if not isinstance(client_m, tuple):  # () when disabled
            trainer.client_m = client_m
        if jax.tree.leaves(fault_state):  # ()-only when disabled
            trainer.fault_state = fault_state
        if jax.tree.leaves(defense_state):
            trainer.defense_state = defense_state
        if not isinstance(attack_iter, tuple):  # scalar when on
            trainer.attack_iter = attack_iter
        if jax.tree.leaves(service_state):
            trainer.service_state = service_state
        if not isinstance(rollback_epoch, tuple):
            # epoch == rollbacks-so-far by construction (the trainer
            # bumps them together), so one saved scalar restores both
            # the key salt and the budget
            trainer._rollback_epoch = int(rollback_epoch)
            trainer._rollbacks_done = int(rollback_epoch)
    elif len(extra_leaves) != len(own_leaves):
        log_fn(
            "WARNING: checkpoint extra state "
            f"({len(extra_leaves)} leaves) does not match this "
            f"config ({len(own_leaves)}); starting server-opt/"
            "client-momentum state fresh"
        )
    return start_round


#: paths whose index 0 is the pre-training eval — on a resume the restored
#: run re-evaluates the checkpointed params as ITS index 0, a bit-exact
#: duplicate of the prefix's last entry, so the merge drops it
_EVAL_PATH_KEYS = ("trainLossPath", "trainAccPath", "valLossPath", "valAccPath")


def merge_paths(prefix: Dict[str, list], current: Dict[str, list]) -> Dict[str, list]:
    """Concatenate a checkpointed paths prefix with a resumed run's paths
    so the merged record is indistinguishable from an uninterrupted run
    (floats round-trip bit-exactly through the JSON the checkpoint meta
    stores; only the timing-derived ``roundsPerSec`` entries differ)."""
    merged: Dict[str, list] = {}
    for key, cur in current.items():
        pre = prefix.get(key) or []
        if pre and key in _EVAL_PATH_KEYS:
            merged[key] = list(pre) + list(cur[1:])
        else:
            merged[key] = list(pre) + list(cur)
    return merged


def build_record(
    cfg: FedConfig,
    paths: Dict[str, list],
    *,
    dataset_name: str,
    dataset_size: int,
    max_feature: int,
) -> Dict:
    """The reference-format pickled record from a finished run's paths.
    One constructor for every execution path — the solo harness and the
    experiment server's batch lanes build records through this, so the
    server-path record is bit-identical to a solo run of the same config."""
    record = {
        # dataset config block (reference dataSetConfig, :536-541)
        "name": dataset_name,
        "dataSet": dataset_name,
        "dataSetSize": dataset_size,
        "maxFeature": max_feature,
        # config block with callables already as names (reference :474-479)
        "honestSize": cfg.honest_size,
        "byzantineSize": cfg.byz_size,
        "rounds": cfg.rounds,
        "displayInterval": cfg.display_interval,
        "weight_decay": cfg.weight_decay,
        "fixSeed": cfg.fix_seed,
        "SEED": cfg.seed,
        "batchSize": cfg.batch_size,
        "gamma": cfg.gamma,
        "aggregate": cfg.agg,
        "attack": cfg.attack,
        "noise_var": cfg.noise_var,
        "model": cfg.model,
        # metric paths (reference :481-489)
        "trainLossPath": paths["trainLossPath"],
        "trainAccPath": paths["trainAccPath"],
        "valLossPath": paths["valLossPath"],
        "valAccPath": paths["valAccPath"],
        "variencePath": paths["variencePath"],
        # framework extras
        "roundsPerSec": paths["roundsPerSec"],
    }
    if cfg.fault is not None:
        record["fault"] = cfg.fault
        record["faultOverrides"] = cfg.fault_overrides()
        record["faultDroppedPath"] = paths["faultDroppedPath"]
        record["faultErasedPath"] = paths["faultErasedPath"]
        record["faultCorruptPath"] = paths["faultCorruptPath"]
        record["effectiveKPath"] = paths["effectiveKPath"]
    if cfg.defense != "off":
        from ..defense import events as defense_events

        record["defense"] = cfg.defense
        record["defenseLadder"] = list(cfg.defense_ladder_names())
        for path_key in defense_events.PATH_KEYS.values():
            record[path_key] = paths[path_key]
    if cfg.service == "on":
        record["service"] = cfg.service
        record["population"] = cfg.population
        record["serviceAvailPath"] = paths["serviceAvailPath"]
        record["serviceAbsentPath"] = paths["serviceAbsentPath"]
        record["serviceLatePath"] = paths["serviceLatePath"]
        record["effectiveKPath"] = paths["effectiveKPath"]
    return record


def run(
    cfg: FedConfig,
    record_in_file: bool = True,
    persist_paths: bool = False,
    on_checkpoint=None,
) -> Dict:
    """Build a trainer, run the full schedule, pickle the record.

    Mirrors reference ``run`` (``:427-492``): when no attack is given the
    Byzantine count is zeroed (``:430-431``).  With ``--obs-dir`` /
    ``--obs-stdout`` set, a schema-versioned event stream (run_start /
    span / round / retrace / run_end) is emitted ALONGSIDE — never
    instead of — the reference-compatible pickled record.

    ``persist_paths`` (the experiment server's solo-lane mode) stores the
    metrics recorded so far inside every checkpoint's atomic write and,
    on an ``--inherit`` resume, merges that prefix back so the final
    record covers the WHOLE schedule — bit-identical to an uninterrupted
    run — instead of only the resumed suffix.  ``on_checkpoint(round)``
    fires after each durable checkpoint (the server journals progress
    through it)."""
    if cfg.attack is None:
        cfg.byz_size = 0
    cfg.validate()

    restore_log = configure_log(cfg.log_file, cfg.quiet)
    # fd-level stderr filter: XLA's per-compile machine-feature wall of
    # text (ending in a SIGILL warning) collapses to one summary line;
    # the full text survives only under --log-file
    restore_stderr = env_lib.condense_stderr_warnings(cfg.log_file)
    # async host rim (obs/writer.py): one bounded single-consumer thread
    # owns event appends, checkpoint serialization and the record pickle
    # when --async-writer resolves on (auto: iff rounds_per_dispatch > 1)
    writer = obs_lib.WriterThread() if obs_lib.resolve_async(cfg) else None
    obs = obs_lib.from_config(cfg, ckpt_title(cfg), writer=writer)
    if cfg.metrics_port > 0:
        # scrape endpoint up BEFORE training so /metrics answers while
        # the first round is still compiling; obs.close() (the finally
        # below) shuts it down on run end and crash alike
        obs.exporter = obs_lib.MetricsExporter(
            obs.registry,
            port=cfg.metrics_port,
            health_fn=obs.metrics_sink.health,
        ).start()
        log(
            f"Serving /metrics and /healthz on port {obs.exporter.port}"
        )
    try:
        if obs.traced:
            # the trace root: every harness span (setup/round/eval/
            # checkpoint) nests under this one "run" span, and when an
            # ambient context is already active (the server's solo lane
            # activates the tenant's trace before delegating here) the
            # run adopts that trace_id — HTTP submit and training stream
            # share one trace
            with obs.span("run", title=ckpt_title(cfg)):
                return _run_inner(
                    cfg, record_in_file, obs,
                    persist_paths=persist_paths,
                    on_checkpoint=on_checkpoint,
                    writer=writer,
                )
        return _run_inner(
            cfg, record_in_file, obs,
            persist_paths=persist_paths, on_checkpoint=on_checkpoint,
            writer=writer,
        )
    finally:
        # run-end drain contract: every enqueued append/checkpoint/pickle
        # lands before the sinks close, so crash and clean exit both
        # leave complete, seq-ordered streams (AsyncSink.close drains
        # again — idempotent — before closing its inner sink)
        if writer is not None:
            writer.drain()
        obs.close()
        if writer is not None:
            writer.close()
        restore_stderr()
        restore_log()


def _run_inner(
    cfg: FedConfig,
    record_in_file: bool,
    obs,
    persist_paths: bool = False,
    on_checkpoint=None,
    writer=None,
) -> Dict:
    from ..obs import hbm as hbm_lib
    from ..obs import profile as profile_lib
    from ..registry import OPTIMIZERS

    trainer_cls = OPTIMIZERS.get(cfg.opt)
    with obs.span("setup", stage="trainer_init"):
        # dataset load + device upload + trainer construction (jit setup
        # is lazy — compile time lands on the first round's span)
        trainer = _make_trainer(cfg, trainer_cls)
    path = cache_path(cfg, trainer.dataset.name)
    banner(cfg, trainer, path)

    # checkpoint / resume (the reference's --inherit was dead; :22,:500)
    start_round = 0
    checkpoint_fn = None
    resume_prefix = None
    # keyed on ckpt_title (run_title + config hash): run_title alone omits
    # seed/sizes/dataset/gamma/widths, so distinct cells could silently
    # resume each other's state from a shared checkpoint dir
    title = ckpt_title(cfg)
    if cfg.checkpoint_dir:
        import jax

        def checkpoint_fn(r, t):
            meta = None
            if persist_paths and getattr(t, "_last_paths", None) is not None:
                import json as _json

                meta = _json.dumps(t._last_paths)
            flat = t.flat_params
            leaves = jax.tree.leaves(extra_state(t, cfg))
            if writer is None:
                checkpoint.save(
                    cfg.checkpoint_dir, title, r, flat, leaves, meta=meta,
                )
                if on_checkpoint is not None:
                    on_checkpoint(r)
                return
            # async rim: serialize OFF the round loop.  The state must be
            # snapshotted host-side NOW — every carry slot is donated to
            # the next dispatch, so by the time the writer runs, the
            # device buffers behind a lazy view may have been reused
            # (same hazard as the trainer's rollback snapshot).  The save
            # and its journal callback ride as ONE task so a checkpoint
            # can never be journaled before its bytes are durable.
            flat = np.array(flat, copy=True)
            leaves = [np.array(leaf, copy=True) for leaf in leaves]

            def _save_task():
                checkpoint.save(
                    cfg.checkpoint_dir, title, r, flat, leaves, meta=meta,
                )
                if on_checkpoint is not None:
                    on_checkpoint(r)

            # traced runs attribute the off-thread save to the round span
            # that submitted it (a writer_task span; no-op when untraced)
            writer.submit_traced(
                _save_task, "checkpoint", sink=obs.sink, round=r
            )

        if cfg.inherit:
            # a torn npz (killed mid-write before the atomic rename ever
            # existed, or corrupted at rest) must degrade to a round-0
            # restart — the trajectory replays identically, only
            # wall-clock is lost (chaos kill_midckpt_rd4 drives this on
            # the solo-routed dispatch path)
            try:
                restored = checkpoint.load(cfg.checkpoint_dir, title)
            except Exception as exc:
                log(
                    f"Unreadable checkpoint ({type(exc).__name__}: {exc}); "
                    f"restarting from round 0"
                )
                restored = None
            if restored is not None:
                if persist_paths:
                    # grab the paths prefix BEFORE the resumed run's own
                    # checkpoints overwrite the file
                    import json as _json

                    meta = checkpoint.load_meta(cfg.checkpoint_dir, title)
                    resume_prefix = None if meta is None else _json.loads(meta)
                start_round = restore_trainer(trainer, cfg, restored)
                log(f"Resumed from checkpoint at round {start_round}")

    import jax

    service_fields = {}
    if cfg.service == "on":
        service_fields = dict(
            service=cfg.service,
            population=cfg.population,
            churn_arrival=cfg.churn_arrival,
            churn_departure=cfg.churn_departure,
            straggler_prob=cfg.straggler_prob,
            rollback=cfg.rollback,
        )
    if cfg.forensics != "off":
        # output-only, but the audit pipeline (analysis/audit.py) reads
        # these to interpret the client_flag stream it finds alongside
        service_fields = dict(
            service_fields,
            forensics=cfg.forensics,
            forensics_top=cfg.forensics_top,
        )
    obs.emit(
        "run_start",
        title=run_title(cfg),
        ckpt_title=title,
        backend=jax.default_backend(),
        rounds=cfg.rounds,
        start_round=start_round,
        k=cfg.node_size,
        byz=cfg.byz_size,
        # the authoritative byzantine id set, read straight off the
        # trainer's mask: the audit pipeline must not re-derive it from a
        # layout assumption (last-byz-slots) that Dirichlet/skewed
        # partitions are free to break.  Service mode keeps the
        # population-range derivation (client_flag ids are population ids
        # there, and the id space is too large to list)
        byz_ids=(
            None if cfg.service == "on"
            or getattr(trainer, "byz_mask", None) is None
            else [int(i) for i in np.flatnonzero(np.asarray(trainer.byz_mask))]
        ),
        dim=trainer.dim,
        agg=cfg.agg,
        attack=cfg.attack,
        fault=cfg.fault,
        defense=cfg.defense,
        seed=cfg.seed,
        **service_fields,
        # the same static accounting benchmarks/agg_kernels.py reports, so
        # the trainer and the microbench can never disagree on HBM math
        hbm=hbm_lib.aggregator_hbm_model(
            cfg.agg,
            cfg.node_size,
            trainer.dim,
            impl=getattr(trainer, "_agg_impl", cfg.agg_impl),
            fused=bool(getattr(trainer, "_fused_epilogue", False)),
            channel=cfg.noise_var is not None,
            trim=cfg.byz_size,
        ),
    )
    log("Optimization begin")
    t0 = time.perf_counter()
    profiler = profile_lib.from_config(cfg)
    if profiler.enabled:
        window = f" (rounds {cfg.profile_rounds})" if cfg.profile_rounds else ""
        log(f"Profiling to {cfg.profile_dir}{window}")
    profiler.start()  # whole-run mode; window mode opens at round A
    try:
        paths = trainer.train(
            log_fn=log, checkpoint_fn=checkpoint_fn, start_round=start_round,
            obs=obs, profiler=profiler,
        )
    finally:
        profiler.close()
    if resume_prefix and start_round > 0:
        # the resumed run's index-0 eval re-evaluates the restored params —
        # bit-identical to the prefix's last entry — so the merged paths
        # read as one uninterrupted schedule
        paths = merge_paths(resume_prefix, paths)
    if profiler.captured:
        obs.emit(
            "profile",
            dir=cfg.profile_dir,
            rounds=cfg.profile_rounds or "all",
        )
    elapsed = time.perf_counter() - t0
    # rounds/sec only when it means something: a 0-round schedule or a
    # resume-at-end run divides 0 (or a few microseconds of no-op loop) —
    # the old banner printed 0.00 or a nonsense multi-thousand rate
    rounds_run = max(cfg.rounds - start_round, 0)
    if rounds_run and elapsed > 1e-6:
        rps = rounds_run / elapsed
        log(f"Optimization done in {elapsed:.1f}s ({rps:.2f} rounds/sec)")
    else:
        rps = None
        log(f"Optimization done in {elapsed:.1f}s (no rounds run)")

    # retrace audit: the steady-state round fn must have lowered at most
    # once this run (compile on the first executed round, cache hits after)
    retrace = getattr(trainer, "retrace", None)
    if retrace is not None:
        steady_ok = retrace.check("round_fn", max_lowerings=1, warn_fn=log)
        if cfg.rounds_per_dispatch > 1:
            # the dispatch tier drives multi_round_fn instead; a fresh
            # aligned run lowers it exactly once (an unaligned resume
            # legitimately adds an alignment/tail scan length, which
            # this audit then flags on the log for the operator to read)
            steady_ok = (
                retrace.check(
                    "multi_round_fn", max_lowerings=1, warn_fn=log
                )
                and steady_ok
            )
        obs.emit("retrace", counts=retrace.snapshot(), steady_state_ok=steady_ok)
    # forensics full: the run-end flight dump (the window's final state is
    # the on-demand complement of the per-rollback dumps the trainer wrote)
    flight = getattr(trainer, "flight_recorder", None)
    if flight is not None:
        flight.dump(max(cfg.rounds - 1, 0), "run_end", obs=obs)
    # memory summary: measured watermark vs the analytic peak model.  Only
    # device-sourced watermarks are cross-checked — a host RSS includes the
    # interpreter/compiler and would trip the model on every CPU run.
    memory = None
    if obs.enabled:
        memory = dict(profile_lib.device_memory())
        ds = trainer.dataset
        data_bytes = sum(
            getattr(a, "nbytes", 0)
            for a in (
                getattr(ds, "x_train", None), getattr(ds, "y_train", None),
                getattr(ds, "x_val", None), getattr(ds, "y_val", None),
            )
        )
        if cfg.cohort_size > 0:
            # streamed rounds never hold the [K, d] stack: the watermark is
            # judged against the O(cohort*d + K) streamed model, with the
            # surviving per-client state (defense [K] f32 detector rows,
            # fault GE bools) accounted per-feature
            state_pc = 0
            if cfg.defense != "off":
                state_pc += 3 * 4  # detector ema/dev/cusum [K] f32
            if cfg.fault is not None:
                state_pc += 1  # Gilbert-Elliott bad-state bools [K]
            if cfg.service == "on":
                # population-resident rows, expressed per participant:
                # avail bools over N_pop, and the detector rows grow from
                # [K] to [population] (the 12 bytes counted above cover
                # one of the `per` population clients per slot)
                per = cfg.population // cfg.node_size
                state_pc += per  # avail [population] bool
                if cfg.defense != "off":
                    state_pc += (per - 1) * 3 * 4
            modeled = hbm_lib.streamed_peak_bytes(
                cfg.node_size, trainer.dim, cfg.cohort_size,
                data_bytes=data_bytes,
                state_bytes_per_client=state_pc,
                pop_shards=cfg.pop_shards,
            )
            memory["hbm_model"] = (
                "streamed_per_host" if cfg.pop_shards > 1 else "streamed"
            )
        else:
            modeled = hbm_lib.modeled_peak_bytes(
                cfg.node_size, trainer.dim, data_bytes=data_bytes
            )
            memory["hbm_model"] = "resident"
        memory["modeled_peak_bytes"] = modeled
        memory["warn_factor"] = cfg.hbm_warn_factor
        if cfg.pop_shards > 1:
            # mesh runs: the model above is the PER-HOST budget, so the
            # cross-check target is each owner's own watermark, not the
            # first device's (which `device_memory` returns) and not a
            # mesh-wide total.  Emit every owner's row and judge the
            # worst one; host_rss rows are reported but never judged.
            mesh_devs = getattr(
                getattr(trainer, "pop_mesh", None), "devices", None
            )
            per_host = profile_lib.per_device_memory(
                None if mesh_devs is None else list(mesh_devs.flat)
            )
            memory["per_host"] = per_host
            judged = [
                r["peak_bytes_in_use"]
                for r in per_host
                if str(r.get("source", "")).startswith("device")
            ]
            if judged:
                memory["peak_bytes_in_use"] = max(judged)
                memory["source"] = next(
                    r["source"]
                    for r in per_host
                    if str(r.get("source", "")).startswith("device")
                )
        exceeds = (
            str(memory.get("source", "")).startswith("device")
            and memory["peak_bytes_in_use"] > cfg.hbm_warn_factor * modeled
        )
        memory["exceeds_model"] = bool(exceeds)
        if exceeds:
            log(
                "WARNING: measured device peak "
                f"{memory['peak_bytes_in_use']} bytes exceeds "
                f"{cfg.hbm_warn_factor:g}x the modeled peak {modeled} bytes "
                f"(obs/hbm {memory['hbm_model']} model) — an allocation "
                "the model does not account for is resident"
            )
    obs.emit(
        "run_end",
        elapsed_secs=round(elapsed, 3),
        rounds_run=rounds_run,
        rounds_per_sec=None if rps is None else round(rps, 4),
        final_val_acc=paths["valAccPath"][-1],
        final_val_loss=paths["valLossPath"][-1],
        memory=memory,
    )
    # live telemetry epilogue: one last rule evaluation (the retrace and
    # HBM-watermark gauges only exist after the run_end fold above), the
    # alert summary on the log, and the registry dump as an event — the
    # artifact `obs/alerts.py --gate` and dashboards read post-hoc
    last_round = max(cfg.rounds - 1, 0)
    alert_summary = None
    if obs.alert_engine is not None:
        alert_summary = obs.alert_engine.finalize(last_round, obs.sink)
        if alert_summary["total_fired"]:
            fired = {
                name: info["fired"]
                for name, info in alert_summary["rules"].items()
                if info["fired"]
            }
            log(
                f"ALERTS: {alert_summary['total_fired']} fired "
                f"(worst severity {alert_summary['worst']}): {fired}"
            )
        else:
            log("ALERTS: none fired")
    if obs.registry is not None:
        obs.emit(
            "metrics_snapshot",
            round=last_round,
            metrics=obs.registry.snapshot(),
            alerts=alert_summary,
        )

    record = build_record(
        cfg,
        paths,
        dataset_name=trainer.dataset.name,
        dataset_size=len(trainer.dataset.x_train),
        max_feature=int(trainer.dataset.x_train[0].size),
    )
    if record_in_file:
        if writer is not None:
            # the pickle rides the writer (ordering: after every pending
            # checkpoint), then drains so the record is durable before
            # run() returns — callers (chaos harness, the server's solo
            # lane) read the file immediately
            writer.submit_traced(
                lambda: io_lib.atomic_pickle(path, record),
                "record_pickle",
                sink=obs.sink,
            )
            writer.drain()
        else:
            io_lib.atomic_pickle(path, record)
    return record
