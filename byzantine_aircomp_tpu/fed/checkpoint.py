"""Checkpoint / resume.

The reference's ``--inherit`` flag is dead (parsed at
``/root/reference/MNIST_Air_weight.py:22``, read at ``:500``, never used) and
only end-of-run *metrics* are pickled — model weights are discarded
(``:472``).  This framework makes resume real: the flat parameter vector plus
round index are written every round, and ``--inherit`` restores them.

Format: a plain ``.npz`` per run title (atomic-rename write) — the fast
single-host path.  ``utils.checkpoint`` provides the orbax-based variant for
structured params pytrees and multi-host sharded saves.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..utils import io as io_lib


def checkpoint_file(ckpt_dir: str, title: str) -> str:
    return os.path.join(ckpt_dir, title + ".ckpt.npz")


def save(
    ckpt_dir: str,
    title: str,
    round_idx: int,
    flat_params,
    opt_leaves=(),
    meta: Optional[str] = None,
) -> str:
    """Write params (+ optional extra state leaves, in pytree-leaf order)
    atomically.  ``opt_leaves`` carries everything beyond the params that a
    resume needs — server-optimizer state, fault/defense carries, and under
    ``--service on`` the population availability, widen scale and rollback
    epoch (see ``harness.extra_state``); this module stays leaf-order
    agnostic.  ``meta`` is an opaque string (the experiment server stores
    the run's metric paths as JSON) that rides the SAME atomic write — a
    crash can never leave params and paths at different rounds."""
    path = checkpoint_file(ckpt_dir, title)
    # materialize host copies BEFORE acquiring the fd: a device error here
    # must not leak the tmp file
    flat_host = np.asarray(flat_params)
    extras = {f"opt_{i}": np.asarray(leaf) for i, leaf in enumerate(opt_leaves)}
    if meta is not None:
        extras["meta_json"] = np.asarray(meta)
    return io_lib.atomic_write(
        path,
        lambda f: np.savez(f, round_idx=round_idx, flat_params=flat_host, **extras),
    )


def load(
    ckpt_dir: str, title: str
) -> Optional[Tuple[int, np.ndarray, list]]:
    """Returns (round_idx, flat_params, opt_leaves) or None."""
    path = checkpoint_file(ckpt_dir, title)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        n_opt = sum(1 for k in z.files if k.startswith("opt_"))
        opt_leaves = [z[f"opt_{i}"] for i in range(n_opt)]
        return int(z["round_idx"]), z["flat_params"], opt_leaves


def load_meta(ckpt_dir: str, title: str) -> Optional[str]:
    """The opaque ``meta`` string saved alongside the checkpoint, or None
    when the file (or the key — pre-meta checkpoints) is absent."""
    path = checkpoint_file(ckpt_dir, title)
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        if "meta_json" not in z.files:
            return None
        return str(z["meta_json"])
