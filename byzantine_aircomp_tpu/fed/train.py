"""The federated training engine — the hot path.

Re-design of the reference's ``SGD`` round loop
(``/root/reference/MNIST_Air_weight.py:226-372``).  The reference
time-multiplexes K clients through one shared model in a Python ``for`` loop
(``:291``), snapshotting/restoring ``state_dict`` around every client and
copying each client's weights to the CPU (``:304``).  Here the whole global
iteration is ONE pure function:

    flat_params [d] --vmap over K clients--> weight stack [K, d]
      --message attack--> --channel--> --robust aggregate--> flat_params'

and ``display_interval`` iterations are rolled into a single jitted
``lax.scan``, so a full "round" (10 global iterations in the reference
config) is one XLA program with no host round-trips.  Per-client gradients
are taken w.r.t. the *flat* parameter vector directly, so the [K, d] stack
is produced by the vmapped grad with no per-parameter Python plumbing.

Semantics mirrored exactly (see SURVEY.md §3.2):
 * one local SGD step per client per iteration: w <- w - gamma*(g + wd*w)
   (``:302-303``)
 * data-level attacks inside the client step, selected by a static per-client
   Byzantine mask (last ``byz_size`` clients, ``:291-341``)
 * message attack on the stacked [K, d] (``:346-347``)
 * channel dispatch: OMA pre-pass for every aggregator except ``gm`` when
   noise_var is set (``:351-352``)
 * aggregator guess seeded with the pre-iteration global params (``:349-350``)
 * per-round honest-client dispersion metric (``:360-361``)
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import defense as defense_lib
from .. import obs as obs_lib
from ..obs import forensics as forensics_lib
from ..data import datasets as data_lib
from ..ops import aggregators as agg_lib
from ..ops import attacks as attack_lib
from ..ops import channel as channel_lib
from ..ops import faults as fault_lib
from ..ops import flatten as flatten_lib
from ..ops import shardctx as shardctx_lib
from ..registry import DATASETS, MODELS
from .config import FedConfig


def cross_entropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def honest_variance(w_stack: jnp.ndarray, honest_size: int) -> jnp.ndarray:
    """Mean over honest clients of ||w_i - mean_honest||^2
    (reference ``getVarience``, ``:127-129``)."""
    w_h = w_stack[:honest_size]
    centered = w_h - jnp.mean(w_h, axis=0, keepdims=True)
    return jnp.mean(jnp.sum(centered**2, axis=1))


@dataclass
class RoundMetrics:
    train_loss: float
    train_acc: float
    val_loss: float
    val_acc: float
    variance: Optional[float] = None


class FedTrainer:
    """Builds and drives the jitted federated round program.

    Single-device by default; the sharded multi-chip variant lives in
    ``..parallel`` and reuses the same pure round function.
    """

    def __init__(
        self,
        cfg: FedConfig,
        dataset: Optional[data_lib.Dataset] = None,
    ):
        self.cfg = cfg.validate()
        self.dataset = dataset or data_lib.load(
            cfg.dataset
        )
        self.attack = attack_lib.resolve(cfg.attack)
        self.fault = fault_lib.resolve(cfg.fault, cfg.fault_overrides())
        self.agg_fn = agg_lib.resolve(cfg.agg)
        # online defense (defense/): None when --defense off, so the default
        # configuration traces no scoring code and carries no detector state
        self.defense = defense_lib.from_config(cfg)
        # delayed attack ("name@R", AttackSpec.onset_round): Byzantine rows
        # behave honestly until the carried iteration counter reaches the
        # onset.  The threshold is in GLOBAL ITERATIONS (rounds *
        # display_interval) compared against a carried i32, so multi-round
        # scans and checkpoint-resumed runs agree with the per-round path
        if self.attack is not None and self.attack.onset_round is not None:
            self._attack_onset = self.attack.onset_round * cfg.display_interval
        else:
            self._attack_onset = None
        self.num_classes = self.dataset.num_classes

        model_kw = dict(num_classes=self.num_classes)
        if cfg.model == "CNN":
            model_kw["fc_width"] = cfg.fc_width
        # factories swallow unknown kwargs (**_), so the ResNet knobs can
        # ride along unconditionally without touching MLP/CNN
        model_kw["width"] = cfg.resnet_width
        model_kw["remat"] = cfg.remat
        self.model = MODELS.get(cfg.model)(**model_kw)

        # init params (reference modelFactory + setup_seed(2021), :98-104).
        # The impl is pinned so a global jax_default_prng_impl override
        # cannot change initial params.
        sample = jnp.zeros((1,) + self.dataset.input_shape, jnp.float32)
        init_key = jax.random.key(cfg.seed, impl="threefry2x32")
        params = self.model.init(init_key, sample)
        self.spec = flatten_lib.make_flat_spec(params)
        self.flat_params = flatten_lib.flatten(params, self.spec)
        self.dim = self.spec.total

        # device-resident data.  Images are stored FLATTENED to [N, features]:
        # XLA lowers a [K,B]-indexed gather over a 2D operand ~60x faster than
        # the same gather over [N,28,28] (slice unit = one contiguous row).
        # Spatial models (CNN/ResNet) get the [K,B,H,W,...] view restored
        # after the gather; flat models (MLP) consume the 2D rows directly —
        # a [.., 28, 28] array wastes TPU lane tiling (28 of 128 lanes).
        self._sample_shape = self.dataset.input_shape
        self._spatial_input = getattr(type(self.model), "SPATIAL_INPUT", True)
        # client partition: the reference's contiguous equal slices
        # (approximately IID on an unsorted set, :238-239) or the
        # label-Dirichlet non-IID split.  Dirichlet shards are made
        # contiguous by permuting the train arrays ONCE host-side, so the
        # on-device uniform-within-[offset, offset+size) sampler and the
        # 2D u8 gather below are identical for both partitions
        y_host = np.asarray(self.dataset.y_train)
        if cfg.partition == "dirichlet":
            perm, sharding = data_lib.dirichlet_shards(
                y_host, cfg.node_size, cfg.dirichlet_alpha, seed=cfg.seed
            )
            y_host = y_host[perm]
        else:
            perm = None
            sharding = data_lib.contiguous_shards(
                len(y_host), cfg.node_size
            )
        # quantity skew composes with label skew by re-cutting whatever
        # index stream the partition above laid out (identity or the
        # Dirichlet-permuted order) into Zipf-proportioned contiguous
        # pieces; zipf:0 reproduces the equal cut bit-identically
        skew_s = data_lib.parse_size_skew(cfg.size_skew)
        if skew_s is not None:
            sharding = data_lib.zipf_shards(
                len(y_host), cfg.node_size, skew_s
            )
        raw = self.dataset.x_train_raw
        if raw is not None and perm is not None:
            raw = raw[perm]
        if raw is not None:
            # keep the train set uint8 in HBM (4x less random-gather traffic
            # than f32) and normalize after the gather; per-feature flat
            # mean/std vectors broadcast correctly for both scalar (MNIST)
            # and per-channel (CIFAR) statistics
            self.x_train = jnp.asarray(raw).reshape(len(raw), -1)
            mean, std = self.dataset.stats
            shape = self.dataset.input_shape
            m = np.broadcast_to(np.asarray(mean, np.float32), shape).reshape(-1)
            s = np.broadcast_to(np.asarray(std, np.float32), shape).reshape(-1)
            # ((u8/255) - mean)/std folded into one multiply-add per element
            self._norm_scale = jnp.asarray(1.0 / (255.0 * s))
            self._norm_bias = jnp.asarray(-m / s)
        else:
            x_host = np.asarray(self.dataset.x_train)
            if perm is not None:
                x_host = x_host[perm]
            self.x_train = jnp.asarray(x_host).reshape(len(x_host), -1)
            self._norm_scale = None
            self._norm_bias = None
        self.y_train = jnp.asarray(y_host)
        self._num_features = self.x_train.shape[1]
        self.offsets = jnp.asarray(sharding.offsets)
        self.sizes = jnp.asarray(sharding.sizes)

        # static per-client Byzantine mask: LAST byz_size clients (:291)
        mask = np.zeros(cfg.node_size, bool)
        if cfg.byz_size:
            mask[-cfg.byz_size :] = True
        self.byz_mask = jnp.asarray(mask)

        # partial participation: per-iteration stratified sample sizes.
        # Participants are drawn inside the jitted iteration (fresh keys);
        # only the COUNTS are static, so the [m, d] stack keeps one shape
        self._part_h, self._part_b = cfg.participant_counts()
        if cfg.participation < 1.0:
            pmask = np.zeros(self._part_h + self._part_b, bool)
            if self._part_b:
                pmask[-self._part_b :] = True
            self._part_mask = jnp.asarray(pmask)
        else:
            self._part_mask = self.byz_mask

        # effective Weiszfeld impl; the sharded trainer overrides this before
        # the round fn is first traced (GSPMD cannot partition pallas_call).
        # "auto": the fused pallas step wins ~18% end-to-end on a real TPU
        # (single HBM pass over [K, d] per Weiszfeld iteration), but pallas
        # interpret mode on CPU is orders slower than XLA
        self._agg_impl = cfg.agg_impl
        if self._agg_impl == "auto":
            self._agg_impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        self._stack_dtype = (
            jnp.bfloat16 if cfg.stack_dtype == "bf16" else jnp.float32
        )
        # fused aggregation epilogue (single-HBM-pass sort-family selection
        # + in-read OMA; ops/aggregators.py dispatch).  "auto" enables it
        # exactly where it is the proven win: the pallas impl on TPU with no
        # fault injection (faults run degraded aggregators, which always
        # fall back).  "on" forces it elsewhere too — off-TPU the epilogue
        # resolves to the XLA key-bisection selection, which beats the full
        # sort on CPU as well.  The sharded trainer forces this off before
        # the first trace, like _agg_impl (see parallel/sharded.py).
        self._fused_epilogue = cfg.fused_epilogue == "on" or (
            cfg.fused_epilogue == "auto"
            and self._agg_impl == "pallas"
            and self.fault is None
            and cfg.service == "off"
        )
        if self.defense is not None and self.defense.mode == "adaptive":
            # the deferred-OMA read belongs to exactly ONE statically-known
            # aggregator; an adaptive rung is not static, so every rung
            # consumes the same standalone channel prepass instead
            self._fused_epilogue = False

        # packed one-bit sign channel (ops/aggregators.pack_signs): the
        # trainer pre-packs the [K, W] uint32 sign words in the aggregate
        # scope so XLA fuses the pack into the stack read and the f32
        # [K, d] sign stack never materializes in HBM.  Same residency
        # contract as _fused_epilogue: one statically-known vote consumer,
        # resident stack, no fault/service degradation.  An adaptive
        # defense switches rungs dynamically, so the aggregators pack
        # internally there instead (packed=None, sign_bits still 1) —
        # correct, just without the fused-pack guarantee
        self._sign_packed = (
            cfg.sign_bits == 1
            and cfg.agg in ("signmv", "bev")
            and cfg.bucket_size == 1
            and self.fault is None
            and cfg.service == "off"
            and not (
                self.defense is not None and self.defense.mode == "adaptive"
            )
        )

        # server optimizer over the pseudo-gradient (FedAvgM / FedAdam);
        # "none" = take the aggregate directly (reference :354-358)
        if cfg.server_opt == "momentum":
            self._server_tx = optax.sgd(cfg.server_lr, momentum=cfg.server_momentum)
        elif cfg.server_opt == "adam":
            self._server_tx = optax.adam(cfg.server_lr)
        else:
            self._server_tx = None
        self.server_opt_state = (
            self._server_tx.init(self.flat_params) if self._server_tx else ()
        )

        # per-client momentum buffer (Karimireddy 2021; cfg.client_momentum
        # doc): [K, d] carried across global iterations.  () when off, so
        # the default program's carry is cost-free.  The sharded trainer
        # re-lays this out over the clients axis after the constructor
        self.client_m = (
            jnp.zeros((cfg.node_size, self.dim), jnp.float32)
            if cfg.client_momentum
            else ()
        )

        # fault-injection state (ops/faults.py): the stale-replay buffer
        # and Gilbert-Elliott channel-state bools, carried across rounds
        # like client_m.  () when faults are off, so the default program's
        # carry (and its donation) is cost-free.  The sharded trainer
        # re-lays the [K, d] buffer out over the clients axis afterwards.
        self.fault_state = (
            fault_lib.init_state(self.fault, cfg.node_size, self.flat_params)
            if self.fault is not None
            else ()
        )
        # per-round [dropped, erased, corrupt, effective_k] from the last
        # executed round ((), i.e. absent, when faults are off)
        self.last_fault_metrics = ()

        # service-round state (cfg.service doc): per-population-id
        # availability bools plus the rollback trim-widening scalar,
        # carried across rounds like the fault state; () when off so the
        # default program's carry slot is cost-free.  The pop->data-shard
        # residue map gives every stable population id a data shard (the
        # population oversubscribes the node_size shards round-robin
        # within each stratum, so honest ids never read Byzantine shards)
        if cfg.service == "on":
            self._pop_h, self._pop_b = cfg.population_counts()
            pop_shard = np.empty(cfg.population, np.int32)
            pop_shard[: self._pop_h] = np.arange(self._pop_h) % cfg.honest_size
            if cfg.byz_size:
                pop_shard[self._pop_h :] = cfg.honest_size + (
                    np.arange(self._pop_b) % cfg.byz_size
                )
            self._pop_shard = jnp.asarray(pop_shard)
            self.service_state = (
                jnp.ones((cfg.population,), bool),  # everyone starts online
                jnp.float32(1.0),                   # rollback trim widening
            )
        else:
            self.service_state = ()
        # per-round [available, absent, late, min_effective_k] from the
        # last executed round (() when the service loop is off)
        self.last_service_metrics = ()
        # warm-rollback bookkeeping (train()): the epoch salts the round
        # keys AFTER a restore so the replayed rounds draw fresh batches/
        # noise (0 = never rolled back = the pristine key stream)
        self._rollback_epoch = 0
        self._rollbacks_done = 0

        # defense carry (defense/__init__.init_state): detector EMA/CUSUM
        # baselines + policy rung/streaks, [K]-indexed like the fault state
        # and carried the same way; () when the defense is off.  The sharded
        # trainer re-lays the [K] leaves out (replicated) afterwards.
        # Under --service on the detector rows are keyed by STABLE
        # population ids (scores survive non-participation), so the state
        # is [population]-sized and the iteration gathers/scatters the
        # drawn rows.
        self.defense_state = defense_lib.init_state(
            self.defense,
            cfg.population if cfg.service == "on" else cfg.node_size,
        )
        # per-round [rung, flagged, suspicious, score, cusum, transitions]
        # from the last executed round (() when the defense is off)
        self.last_defense_metrics = ()
        # client-level forensics (obs/forensics.py; cfg.forensics doc):
        # output-only — the top-M matrix rides the per-iteration scan
        # OUTPUTS (not the carry), adds no RNG and no checkpointed state,
        # so off/on trajectories are bit-identical.  validate() pins
        # forensics != off to defense != off.
        self._forensics_on = cfg.forensics != "off"
        # round-level [forensics_top, NUM_COLS] top-M matrix from the last
        # executed round (() when forensics is off)
        self.last_forensic_metrics = ()
        # host-side flight recorder (full mode only): ring buffer of the
        # last flight_window rounds of detector carry, dumped on each
        # rollback trip (train()) and at run end (harness)
        self.flight_recorder = (
            forensics_lib.FlightRecorder(
                cfg.flight_window,
                cfg.obs_dir or cfg.checkpoint_dir or ".",
            )
            if cfg.forensics == "full"
            else None
        )
        # attack-onset iteration counter: i32 in the carry with "@R" syntax,
        # () otherwise so the default program's carry stays cost-free
        self.attack_iter = (
            jnp.int32(0) if self._attack_onset is not None else ()
        )

        # per-round key stream; model init above stays threefry so initial
        # params are identical whatever impl drives the round RNG.  Typed
        # keys (jax.random.key) carry their impl — a raw PRNGKey array of a
        # non-default impl would be misinterpreted by downstream consumers.
        # "threefry" pins threefry2x32 explicitly (impl=None would follow
        # the PROCESS-default jax_default_prng_impl, breaking the replay
        # guarantee under a global override)
        impl = "threefry2x32" if cfg.prng_impl == "threefry" else cfg.prng_impl
        self._base_key = jax.random.key(cfg.seed, impl=impl)

        # population-shard context (ops/shardctx.py): LOCAL keeps the
        # legacy single-scan streamed trace; pop_shards > 1 runs the
        # sequential reference engine here, and the mesh trainer
        # (parallel/popmesh.py) overrides _make_pop_ctx/_pop_shard_region
        # with the shard_map collective engine
        self._pop_ctx = self._make_pop_ctx()

        copts = self._jit_compiler_options()
        # retrace detector (obs/retrace.py): counts lowerings of the jitted
        # hot paths.  The counter wrapper sits UNDER jit and is pure Python
        # bookkeeping — the traced program, RNG stream and outputs are
        # bit-identical; steady-state enforcement is the harness's/CI's
        self.retrace = obs_lib.RetraceDetector()
        # args 3-6 are the fault / defense / attack-onset / service states —
        # empty pytrees when the corresponding feature is off, so their
        # donation slots contribute no buffers to the default program
        donate = self._round_donate_argnums()
        self._round_fn = jax.jit(
            self.retrace.wrap("round_fn", self._build_round_fn()),
            donate_argnums=donate,
            compiler_options=copts,
        )
        self._multi_round_fn = jax.jit(
            self.retrace.wrap("multi_round_fn", self._build_multi_round_fn()),
            donate_argnums=donate,
            compiler_options=copts,
        )
        self._eval_fn = jax.jit(
            self.retrace.wrap("eval_fn", self._build_eval_fn()),
            compiler_options=copts,
        )
        self._eval_cache: Dict[str, Any] = {}

    def _jit_compiler_options(self):
        """Per-executable XLA option overrides; None on the single-device
        path.  ``ShardedFedTrainer`` relaxes the CPU collective rendezvous
        timeouts here (the XLA_FLAGS parser in this jaxlib build does not
        register those debug options, so they must ride CompileOptions)."""
        return None

    def _round_donate_argnums(self):
        """Donation slots for the 7 round-carry args.  The pop-mesh
        trainer (``parallel/popmesh.py``) narrows this on the CPU client,
        where donating replicated multi-device buffers through a
        ``shard_map`` program is unsound in this jaxlib."""
        return (0, 1, 2, 3, 4, 5, 6)

    # sharding hooks — identity on a single device; the parallel layer
    # (``..parallel.sharded``) overrides these with with_sharding_constraint
    # so the SAME pure round function drives the multi-chip path.
    def _constrain_stack(self, w_stack):
        return w_stack

    def _constrain_params(self, flat_params):
        return flat_params

    # population-shard hooks (streamed service rounds; ops/shardctx.py).
    # The base trainer runs the chunk region inline — a plain call under
    # LOCAL (pop_shards == 1) or the sequential reference engine;
    # ``parallel.popmesh.PopShardedFedTrainer`` overrides both to wrap the
    # SAME region body in shard_map over a population mesh axis.
    def _make_pop_ctx(self):
        if self.cfg.pop_shards > 1:
            return shardctx_lib.SeqShardCtx(self.cfg.pop_shards)
        return shardctx_lib.LOCAL

    def _pop_shard_region(self, fn, region_in):
        return fn(self._pop_ctx, region_in)

    # ------------------------------------------------------------------
    # pure functions

    def _per_client_grad(self, flat_params, x_k, y_k, is_byz):
        """Gradient of the mean CE loss w.r.t. the FLAT param vector for one
        client's batch, with data-level attack applied under the mask."""
        cfg = self.cfg
        if self.attack is not None and self.attack.data_fn is not None:
            x_att, y_att = self.attack.apply_data(x_k, y_k, self.num_classes)
            x_k = jnp.where(is_byz, x_att, x_k)
            y_k = jnp.where(is_byz, y_att, y_k)

        def loss(fp):
            params = flatten_lib.unflatten(fp, self.spec)
            logits = self.model.apply(params, x_k)
            return jnp.mean(cross_entropy(logits, y_k))

        return jax.grad(loss)(flat_params)

    def _per_client_weights(self, flat_params, x_k, y_k, is_byz):
        """Client weights after ``local_steps`` local SGD steps (FedAvg
        regime), each on its own batch: x_k [E, B, ...], y_k [E, B].
        Generalizes the reference's single step; gradient-scale attacks apply
        at every local step.  With ``fedprox_mu > 0`` each step's gradient
        carries the FedProx proximal pull ``mu*(w - w_round_start)``."""
        cfg = self.cfg
        gscale = 1.0
        if self.attack is not None and self.attack.grad_scale != 1.0:
            gscale = jnp.where(is_byz, self.attack.grad_scale, 1.0)

        def step(w, xy):
            x_e, y_e = xy
            g = self._per_client_grad(w, x_e, y_e, is_byz) * gscale
            if cfg.fedprox_mu:
                g = g + cfg.fedprox_mu * (w - flat_params)
            return w - cfg.gamma * (g + cfg.weight_decay * w), None

        w_final, _ = jax.lax.scan(step, flat_params, (x_k, y_k))
        return w_final

    def _per_client_momentum_step(self, flat_params, x_k, y_k, is_byz, m_prev):
        """One momentum-SGD client step (cfg.client_momentum doc; requires
        local_steps == 1 so x_k is [1, B, ...]): m <- beta*m + (1-beta)*g,
        sent weights = w_global - gamma*m.  Returns (weights, new momentum).
        Gradient-scale attacks poison g and therefore the momentum — the
        attacked state is the client's own, as in the paper's threat model."""
        cfg = self.cfg
        gscale = 1.0
        if self.attack is not None and self.attack.grad_scale != 1.0:
            gscale = jnp.where(is_byz, self.attack.grad_scale, 1.0)
        g = self._per_client_grad(flat_params, x_k[0], y_k[0], is_byz) * gscale
        g = g + cfg.weight_decay * flat_params
        beta = cfg.client_momentum
        m_new = beta * m_prev + (1.0 - beta) * g
        return flat_params - cfg.gamma * m_new, m_new

    def _client_stack(self, flat_params, x, y, part_mask):
        """[m, d] sent-weight stack from the per-client local steps — the
        client-parallel seam.  vmap over clients; ``ShardedFedTrainer``
        overrides this with an explicit shard_map over the 'clients' mesh
        axis (GSPMD left alone can repartition a vmapped CONV to
        channel-parallel, all-gathering the client batch every local step)."""
        return jax.vmap(
            self._per_client_weights, in_axes=(None, 0, 0, 0)
        )(flat_params, x, y, part_mask)

    def _defense_branches(self, agg_honest: int, trim_ratio=None):
        """Static ``lax.switch`` branch table for the adaptive ladder.

        Built at TRACE time (not in ``__init__``) so the sharded trainer's
        post-constructor ``_agg_impl`` override reaches the closures.  Every
        rung gets the trainer's full keyword surface (aggregators swallow
        unknown kwargs) with the fused epilogue off — see the mode gate in
        ``__init__``.  ``trim_ratio`` may be a TRACED scalar (the service
        loop's rollback-widened fraction): the closures capture it at trace
        time and only the degraded trimmed_mean path — which computes its
        trim budget dynamically — ever consumes it."""
        cfg = self.cfg
        extra = {} if trim_ratio is None else {"trim_ratio": trim_ratio}
        return defense_lib.make_branch_table(
            self.defense.ladder,
            honest_size=agg_honest,
            noise_var=cfg.noise_var,
            maxiter=cfg.agg_maxiter,
            tol=cfg.agg_tol,
            p_max=cfg.gm_p_max,
            impl=self._agg_impl,
            fused_epilogue=False,
            oma_key=None,
            m=cfg.krum_m,
            clip_tau=cfg.clip_tau,
            clip_iters=cfg.clip_iters,
            sign_eta=cfg.sign_eta,
            # a bev rung packs internally (no pre-packed words here: the
            # pack belongs to exactly ONE statically-known consumer, and
            # an adaptive rung is not static — same rule as oma_key)
            sign_bits=cfg.sign_bits,
            dnc_iters=cfg.dnc_iters,
            dnc_sub_dim=cfg.dnc_sub_dim,
            dnc_c=cfg.dnc_c,
            degraded=self.fault is not None or cfg.service == "on",
            **extra,
        )

    def _client_stack_momentum(self, flat_params, x, y, part_mask, m_prev):
        """Momentum variant of ``_client_stack``: returns (stack, new [m, d]
        momentum rows)."""
        return jax.vmap(
            self._per_client_momentum_step, in_axes=(None, 0, 0, 0, 0)
        )(flat_params, x, y, part_mask, m_prev)

    def _service_draw(self, key, avail):
        """Stratified service subsample over stable population ids.

        Draws honest_size honest ids from [0, pop_h) and byz_size
        Byzantine ids from [pop_h, population) — uniformly among the
        AVAILABLE ids of each stratum (priority = U(0,1) + 2*offline, so
        every available id outranks every offline one and ties within a
        class are a uniform shuffle).  When a stratum has fewer available
        clients than its quota the server still schedules a full slate
        (static shapes) and tops up with offline ids; those rows carry
        ``arrived=False`` and the deadline stage erases them, so the
        shortfall shows up as effective-K degradation, not a shape change.

        Returns ``(pop_ids [K] i32, arrived [K] bool)`` with honest rows
        first — the stack layout every downstream stage (attack mask,
        honest variance, aggregator honest_size) already assumes."""
        cfg = self.cfg
        kh, kb = jax.random.split(key)
        pop_h = self._pop_h
        prio_h = jax.random.uniform(kh, (pop_h,)) + jnp.where(
            avail[:pop_h], 0.0, 2.0
        )
        ids_h = jnp.argsort(prio_h)[: cfg.honest_size]
        if cfg.byz_size:
            prio_b = jax.random.uniform(kb, (self._pop_b,)) + jnp.where(
                avail[pop_h:], 0.0, 2.0
            )
            pop_ids = jnp.concatenate([
                ids_h, pop_h + jnp.argsort(prio_b)[: cfg.byz_size],
            ])
        else:
            pop_ids = ids_h
        return pop_ids.astype(jnp.int32), avail[pop_ids]

    @staticmethod
    def _masked_honest_variance(w_h):
        """Honest dispersion over the FINITE honest rows — the service
        loop's variant of :func:`honest_variance` (deadline-missed rows are
        NaN; including them would NaN the metric and false-trip the
        rollback guard)."""
        fin = agg_lib._finite_rows(w_h)
        n = jnp.maximum(jnp.sum(fin).astype(jnp.float32), 1.0)
        w0 = jnp.where(fin[:, None], w_h.astype(jnp.float32), 0.0)
        mean = jnp.sum(w0, axis=0) / n
        return jnp.sum(w0 * w0) / n - jnp.sum(mean * mean)

    def _iteration(self, carry, key, x_train, y_train, want_variance):
        """One global iteration: local steps -> attack -> channel -> agg.

        The train arrays arrive as explicit ARGUMENTS (threaded through the
        jitted round fn) rather than closure captures: captured arrays embed
        into the serialized computation, which breaks remote-compile setups
        at dataset scale and bloats every compile.

        ``want_variance`` (traced bool) gates the honest-dispersion metric
        behind a ``lax.cond``: the reference computes ``getVarience`` ONCE per
        round on the last iteration's stack (``:360-361``), so the other
        ``display_interval - 1`` iterations skip the extra [honest, d]
        passes entirely.

        With ``cfg.fault`` set the carry gains the fault state and the
        iteration emits ``(variance, [dropped, erased, corrupt,
        effective_k])``; every fault stage is gated at TRACE time on
        ``self.fault``, so the fault-free program (structure, RNG stream,
        outputs) is bit-identical to the pre-fault one.

        ``cfg.cohort_size > 0`` swaps in the cohort-streamed body
        (:meth:`_iteration_streamed`) at trace time; at 0 this resident
        body is traced verbatim, so the default program is bit-identical
        to builds that predate streaming."""
        if self.cfg.cohort_size > 0:
            return self._iteration_streamed(
                carry, key, x_train, y_train, want_variance
            )
        cfg = self.cfg
        (
            flat_params, opt_state, client_m, fault_state, defense_state,
            attack_iter, service_state,
        ) = carry
        m_h, m_b = self._part_h, self._part_b
        # delayed attack: one traced bool gates EVERY Byzantine behavior
        # (data, gradient and message level) until the onset iteration
        part_mask = self._part_mask
        attack_on = None
        if self._attack_onset is not None:
            attack_on = attack_iter >= self._attack_onset
            part_mask = part_mask & attack_on
        # extra keys exist only on the programs that need them, so the
        # default configuration consumes the exact default RNG stream
        # (checkpoint/replay compatible)
        n_extra = (
            int(cfg.participation < 1.0)
            + int(cfg.bucket_size > 1)
            + int(self.fault is not None)
            + int(cfg.service == "on")
        )
        keys = jax.random.split(key, 4 + n_extra)
        k_batch, k_chan, k_agg, k_msg = keys[:4]
        next_extra = 4
        if cfg.participation < 1.0:
            # stratified participant draw: m_h of the honest, m_b of the
            # Byzantine, fresh every iteration
            k_part = keys[next_extra]
            next_extra += 1
            kh, kb = jax.random.split(k_part)
            part = jax.random.permutation(kh, cfg.honest_size)[:m_h]
            if m_b:
                part = jnp.concatenate([
                    part,
                    cfg.honest_size
                    + jax.random.permutation(kb, cfg.byz_size)[:m_b],
                ])
            offsets = self.offsets[part]
            sizes = self.sizes[part]
        else:
            offsets, sizes = self.offsets, self.sizes
        if cfg.bucket_size > 1:
            k_bucket = keys[next_extra]
            next_extra += 1
        if self.fault is not None:
            k_drop, k_trans = jax.random.split(keys[next_extra])
            next_extra += 1
        pop_ids = arrived = widen = None
        if cfg.service == "on":
            with jax.named_scope("service_draw"):
                # participation stage: draw this iteration's K-row slate
                # from the available population, then advance the Markov
                # churn — the draw sees the PRE-churn availability, so
                # the reported 'available' count matches what the server
                # scheduled against
                k_churn, k_draw, k_dead = jax.random.split(
                    keys[next_extra], 3
                )
                avail, widen = service_state
                n_avail = jnp.sum(avail).astype(jnp.float32)
                pop_ids, arrived = self._service_draw(k_draw, avail)
                k_arr, k_dep = jax.random.split(k_churn)
                avail = jnp.where(
                    avail,
                    ~jax.random.bernoulli(
                        k_dep, cfg.churn_departure, avail.shape
                    ),
                    jax.random.bernoulli(
                        k_arr, cfg.churn_arrival, avail.shape
                    ),
                )
                service_state = (avail, widen)
                # stable id -> data shard: a drawn client reads its own
                # shard wherever the draw placed it in the stack
                shard = self._pop_shard[pop_ids]
                offsets = self.offsets[shard]
                sizes = self.sizes[shard]

        with jax.named_scope("client_local_step"):
            # E local steps per client, each on a fresh with-replacement
            # batch.  E=1 is the reference's FedSGD (:296-303): the length-1
            # scan in _per_client_weights computes exactly
            # w <- fp - gamma*(g*scale + wd*fp), and the [K, E*B] index
            # stream equals the single-step stream (same key, same count)
            idx = data_lib.sample_client_batch_indices(
                k_batch, offsets, sizes,
                cfg.local_steps * cfg.batch_size,
            )
            x = x_train[idx]  # [m, E*B, features] on-device 2D gather
            if self._norm_scale is not None:
                # u8 rows -> normalized floats: same map as the host
                # path (datasets._normalize) up to float re-association,
                # as one multiply-add post-gather on device
                x = x.astype(jnp.float32) * self._norm_scale + self._norm_bias
            shape = (m_h + m_b, cfg.local_steps, cfg.batch_size)
            x = x.reshape(
                shape + (self._sample_shape if self._spatial_input else (-1,))
            )
            y = y_train[idx].reshape(shape)
            if cfg.client_momentum:
                m_prev = (
                    client_m[part] if cfg.participation < 1.0 else client_m
                )
                w_stack, m_rows = self._client_stack_momentum(
                    flat_params, x, y, part_mask, m_prev
                )
                client_m = (
                    client_m.at[part].set(m_rows)
                    if cfg.participation < 1.0
                    else m_rows
                )
                client_m = self._constrain_stack(client_m)
            else:
                w_stack = self._client_stack(
                    flat_params, x, y, part_mask
                )
            w_stack = self._constrain_stack(w_stack)

        n_dropped = n_erased = n_corrupt = None
        if self.fault is not None:
            with jax.named_scope("fault_dropout"):
                # PRE-attack: the stale buffer records what clients SENT,
                # never what an omniscient message attack rewrote (and a
                # corrupted NaN emission can never poison future replays)
                stale, ge_bad = fault_state
                w_stack, stale, n_dropped = fault_lib.apply_dropout(
                    self.fault, k_drop, w_stack, stale
                )
                if self.fault.needs_stale:
                    stale = self._constrain_stack(stale)
                    w_stack = self._constrain_stack(w_stack)

        with jax.named_scope("message_attack"):
            # called even when m_b == 0: apply_message validates
            # attack_param BEFORE its no-op early-out, so a bogus knob
            # fails loudly (ops/attacks.py) instead of being ignored
            if self.attack is not None:
                d_view = None
                if self.attack.defense_aware:
                    # defense-aware tier: the attacker reads the detector
                    # state the server PUBLISHED after the previous
                    # iteration (the attack runs before this iteration's
                    # defense_score, so the carry still holds it).  Under
                    # --service the [population] baselines are gathered to
                    # the drawn slate so row i describes stack row i —
                    # the same alignment detector_update writes back.
                    det_a, pol_a = defense_state
                    step_a, ema_a, dev_a, cus_a = det_a
                    if cfg.service == "on":
                        ema_a = ema_a[pop_ids]
                        dev_a = dev_a[pop_ids]
                        cus_a = cus_a[pop_ids]
                    d_view = attack_lib.DefenseView(
                        step=step_a,
                        ema=ema_a,
                        dev=dev_a,
                        cusum=cus_a,
                        rung=pol_a[0],
                        detector=self.defense.detector,
                        policy=self.defense.policy,
                        guess=flat_params,
                    )
                w_att = self.attack.apply_message(
                    w_stack, m_b, k_msg, param=cfg.attack_param,
                    defense=d_view,
                )
                w_stack = (
                    w_att if attack_on is None
                    else jnp.where(attack_on, w_att, w_stack)
                )

        if self.fault is not None:
            with jax.named_scope("fault_transmission"):
                # POST-attack: corruption and channel impairments hit the
                # transmitted stack, Byzantine rows included
                w_stack, ge_bad, n_erased, n_corrupt = (
                    fault_lib.apply_transmission(
                        self.fault, k_trans, w_stack, ge_bad
                    )
                )
                w_stack = self._constrain_stack(w_stack)
            fault_state = (stale, ge_bad)

        with jax.named_scope("channel"):
            # k_chan is consumed (or deliberately unused) on every branch,
            # so toggling fusion never shifts the round's RNG stream
            oma_key = None
            if cfg.noise_var is not None and agg_lib.needs_oma_prepass(cfg.agg):
                if cfg.service == "on":
                    # per-STABLE-ID links: a client's fade is a function of
                    # its population id, not of where this iteration's
                    # draw happened to place it in the stack.  The
                    # deferred-OMA read is row-index-keyed, so the service
                    # path always takes the standalone pass (the fused
                    # epilogue is off under service anyway — degraded
                    # aggregation has no single-read epilogue)
                    w_stack = channel_lib.oma_by_id(
                        k_chan, w_stack, pop_ids, cfg.noise_var
                    )
                elif (
                    self._fused_epilogue
                    and agg_lib.supports_fused_epilogue(cfg.agg)
                    and cfg.bucket_size == 1
                    and self._stack_dtype == jnp.float32
                ):
                    # defer the channel: the aggregator folds the OMA
                    # corruption into its single stack read (bucketing must
                    # see the post-channel stack, and a bf16 stack would
                    # change what the channel noise lands on — both keep
                    # the standalone pass)
                    oma_key = k_chan
                else:
                    w_stack = channel_lib.oma(k_chan, w_stack, cfg.noise_var)

        n_absent = n_late = None
        if cfg.service == "on":
            with jax.named_scope("deadline"):
                # the round closes NOW: drawn-but-offline rows and
                # straggler rows are erased to NaN ("nothing received"),
                # and everything downstream — detector freeze, degraded
                # aggregation, effective-K telemetry — sees exactly the
                # fault subsystem's erasure convention
                w_stack, n_absent, n_late = fault_lib.apply_deadline(
                    k_dead, w_stack, arrived, cfg.straggler_prob
                )

        defense_metrics = ()
        forensic = ()
        rung = None
        if self.defense is not None:
            with jax.named_scope("defense_score"):
                # score the received [K, d] stack (post-attack, post-fault,
                # post-standalone-channel; under monitor + fused deferral
                # the OMA noise lands inside the aggregator's read, so the
                # detector sees the noiseless received stack — monitor
                # never acts on the rung, so that is purely observational).
                # The detector freezes state on non-finite rows, so deep-
                # fade erasures neither trip flags nor corrupt baselines.
                det, pol = defense_state
                # component-returning variant: identical score/finite
                # expressions, and with forensics off the unused component
                # columns are dead code (the traced program is unchanged)
                score, finite, score_parts = (
                    defense_lib.client_score_components(w_stack, flat_params)
                )
                if cfg.service == "on":
                    # population-keyed detector: gather the drawn ids'
                    # rows, update them under their own first-observation
                    # markers (dev == 0 <=> never updated — the seed
                    # writes dev >= eps), scatter back.  Ids absent from
                    # the draw keep their baselines verbatim, so scores
                    # survive non-participation.
                    step, ema, dev, cus = det
                    ema_g, dev_g, cus_g = (
                        ema[pop_ids], dev[pop_ids], cus[pop_ids]
                    )
                    first = dev_g == 0.0
                    (_, ema_r, dev_r, cus_r), flags = (
                        defense_lib.detector_update(
                            (step, ema_g, dev_g, cus_g),
                            score, finite, self.defense.detector,
                            first=first,
                        )
                    )
                    det = (
                        step + 1,
                        ema.at[pop_ids].set(ema_r),
                        dev.at[pop_ids].set(dev_r),
                        cus.at[pop_ids].set(cus_r),
                    )
                    # forensic identities/baselines for the drawn rows:
                    # stable population ids, pre-update ema/dev (the z the
                    # detector thresholded), post-update CUSUM
                    f_ids, ema_pre, dev_pre, cus_post = (
                        pop_ids, ema_g, dev_g, cus_r
                    )
                else:
                    f_ids = jnp.arange(cfg.node_size)
                    ema_pre, dev_pre = det[1], det[2]
                    det, flags = defense_lib.detector_update(
                        det, score, finite, self.defense.detector
                    )
                    cus_post = det[3]
                n_flagged = jnp.sum(flags)
                pol, suspicious = defense_lib.policy_update(
                    pol, n_flagged, self.defense.policy
                )
                rung = pol[0]
                defense_state = (det, pol)
                # per-iteration observations; _round_core reduces them to
                # the [6] round vector (defense/events.METRIC_KEYS)
                defense_metrics = jnp.stack([
                    rung.astype(jnp.float32),
                    n_flagged.astype(jnp.float32),
                    suspicious.astype(jnp.float32),
                    jnp.max(score),
                    jnp.max(det[3]),
                ])
            if self._forensics_on:
                with jax.named_scope("forensics_top_m"):
                    # fixed-shape top-M flag provenance ([M, NUM_COLS]);
                    # rides the scan OUTPUTS, not the carry
                    forensic = forensics_lib.with_rung(
                        forensics_lib.top_m(
                            forensics_lib.candidate_rows(
                                f_ids, score, score_parts, ema_pre,
                                dev_pre, cus_post, flags,
                                self.defense.detector,
                            ),
                            cfg.forensics_top,
                        ),
                        rung,
                    )

        agg_honest = m_h
        w_for_agg = w_stack
        if cfg.bucket_size > 1:
            with jax.named_scope("bucketing"):
                # Karimireddy 2022: aggregate [m/s, d] random-bucket means.
                # A non-finite row poisons its bucket's mean, which the
                # aggregators' non-finite-row exclusion then drops — an
                # overflowed attack costs its bucket, nothing more.  The
                # aggregator's honest count becomes the WORST-CASE clean
                # bucket count (every Byzantine row in a distinct bucket).
                # segment_sum reads the stack ONCE and writes [m/s, d] —
                # w_stack[perm] would materialize a second full [m, d]
                # copy (~tens of GB at the ResNet rung)
                s = cfg.bucket_size
                m = m_h + m_b
                perm = jax.random.permutation(k_bucket, m)
                bucket_ids = jnp.zeros(m, jnp.int32).at[perm].set(
                    jnp.arange(m, dtype=jnp.int32) // s
                )
                w_for_agg = (
                    jax.ops.segment_sum(
                        w_stack, bucket_ids, num_segments=m // s
                    )
                    / s
                )
                agg_honest = m // s - m_b

        with jax.named_scope("aggregate"):
            # --stack-dtype bf16: hand the aggregator a bf16 view of the
            # stack (halves its per-Weiszfeld-iteration HBM reads);
            # arithmetic stays f32 via promotion / in-kernel upcast, and
            # the aggregate is cast back so the params carry stays f32
            w_agg = w_for_agg.astype(self._stack_dtype)
            # packed one-bit wire: pack the sign words HERE, adjacent to
            # the stack read, so XLA fuses the elementwise sign/shift
            # chain into the stack producer — the f32 [K, d] sign stack
            # never exists in HBM on this path (gate doc in __init__)
            packed = (
                agg_lib.pack_signs(w_agg, flat_params)
                if self._sign_packed
                else None
            )
            # service rounds: the rollback-widened trim fraction rides the
            # carry as a traced scalar — only the degraded trimmed_mean
            # path (dynamic trim budget) consumes it; every other
            # aggregator swallows it via **_
            service_kw = {}
            if cfg.service == "on":
                service_kw["trim_ratio"] = jnp.minimum(
                    jnp.float32(0.1) * widen, 0.45
                )
            if self.defense is not None and self.defense.mode == "adaptive":
                # branchless rung dispatch (defense/policy.py): ONE
                # lax.switch over the static ladder table, every branch
                # reading the same post-channel stack.  Rung 0 is the
                # configured aggregator (cfg.validate enforces it), so an
                # attack-free run aggregates exactly as --defense off does
                aggregated = defense_lib.aggregate_switch(
                    rung,
                    self._defense_branches(
                        agg_honest, **service_kw
                    ),
                    w_agg, flat_params, k_agg,
                )
            else:
                aggregated = self.agg_fn(
                    w_agg,
                    honest_size=agg_honest,
                    key=k_agg,
                    noise_var=cfg.noise_var,
                    guess=flat_params,
                    maxiter=cfg.agg_maxiter,
                    tol=cfg.agg_tol,
                    p_max=cfg.gm_p_max,
                    impl=self._agg_impl,
                    # single-read selection epilogue + deferred channel
                    # (ops/aggregators.py dispatch; **_ on other aggregators)
                    fused_epilogue=self._fused_epilogue,
                    oma_key=oma_key,
                    m=cfg.krum_m,
                    clip_tau=cfg.clip_tau,
                    clip_iters=cfg.clip_iters,
                    sign_eta=cfg.sign_eta,
                    # packed one-bit sign channel (pack_signs above);
                    # sign_bits=32 is the legacy byte-identical path
                    sign_bits=cfg.sign_bits,
                    packed=packed,
                    dnc_iters=cfg.dnc_iters,
                    dnc_sub_dim=cfg.dnc_sub_dim,
                    dnc_c=cfg.dnc_c,
                    # graceful degradation (ops/aggregators.py): under
                    # faults and service deadlines the static rules adapt
                    # to the per-round effective K; False traces the
                    # literal pre-fault aggregator code
                    degraded=self.fault is not None or cfg.service == "on",
                    **service_kw,
                )
            aggregated = aggregated.astype(jnp.float32)
            if self.fault is not None or cfg.service == "on":
                # receiver-side finite-guard — the last line of defense the
                # fault contract promises: whatever non-finite value leaks
                # through aggregation (e.g. zero clients delivered anything
                # finite this round), the global model holds position
                # instead of being NaNed for the rest of training
                aggregated = jnp.where(
                    jnp.isfinite(aggregated), aggregated, flat_params
                )
            if self._server_tx is not None:
                # FedOpt: the aggregate defines a pseudo-gradient
                delta = flat_params - aggregated
                updates, opt_state = self._server_tx.update(
                    delta, opt_state, flat_params
                )
                new_flat = optax.apply_updates(flat_params, updates)
            else:
                new_flat = aggregated  # reference semantics (:354-358)
            new_flat = self._constrain_params(new_flat)
        variance = jax.lax.cond(
            want_variance,
            (
                # deadline-missed honest rows are NaN — the service metric
                # is the dispersion of what the round actually received
                (lambda w: self._masked_honest_variance(w[:m_h]))
                if cfg.service == "on"
                else (lambda w: honest_variance(w, m_h))
            ),
            lambda w: jnp.float32(0.0),
            w_stack,
        )
        if self._attack_onset is not None:
            attack_iter = attack_iter + 1
        carry_out = (
            new_flat, opt_state, client_m, fault_state, defense_state,
            attack_iter, service_state,
        )
        if self.fault is not None:
            # effective K = finite rows the receiver actually aggregates
            # over (post-fault, pre-bucketing); the other three are this
            # iteration's fault event counts
            eff_k = jnp.sum(agg_lib._finite_rows(w_stack)).astype(jnp.float32)
            fault_metrics = jnp.stack(
                [n_dropped, n_erased, n_corrupt, eff_k]
            )
        else:
            fault_metrics = ()
        if cfg.service == "on":
            eff_k = jnp.sum(agg_lib._finite_rows(w_stack)).astype(jnp.float32)
            service_metrics = jnp.stack([n_avail, n_absent, n_late, eff_k])
        else:
            service_metrics = ()
        return carry_out, (
            variance, fault_metrics, defense_metrics, service_metrics,
            forensic,
        )

    def _iteration_streamed(self, carry, key, x_train, y_train, want_variance):
        """Cohort-streamed global iteration: K >> HBM.

        Never materializes the [K, d] stack.  ``rebuild_full(c)`` recomputes
        one ``cohort_size``-client chunk end to end (local steps -> message
        attack -> fault transmission -> channel) as a pure function of the
        cohort index; ONE observation ``lax.scan`` over the chunks collects
        the streaming accumulators (masked sums / finite count), the honest
        dispersion moments, the fault counters + Gilbert-Elliott write-backs
        and the per-chunk defense detector updates; the aggregate itself
        comes from ``ops/aggregators.stream_aggregate``, whose passes
        re-invoke ``rebuild`` — trading recompute for an O(cohort*d) peak
        (obs/hbm.streamed_peak_bytes) instead of O(K*d).

        Contracts (enforced by ``FedConfig.validate``): full participation,
        no bucketing, no client momentum, f32 stack, cohort_size divides
        both honest_size and byz_size (every chunk purely honest or purely
        Byzantine), streamable aggregator/ladder, row-local attack, no
        stale-replay fault.  The round-level key split matches the resident
        path exactly (same count, same order — checkpoint key streams are
        invariant), and the batch-index draw reuses the resident key and
        shape, so with channel/fault off the rebuilt rows are bit-identical
        to the resident stack's.  Per-chunk channel/fault/attack-noise
        draws come from ``channel.cohort_key`` fold-ins — those
        REALIZATIONS differ from the resident path (a fresh draw every
        round either way), which is why ``--cohort-size`` forks the
        run_title/config_hash lineage.

        Defense note: ``client_scores`` medians/centroids run per cohort
        rather than over the full K — a documented approximation (honest
        cohorts are i.i.d. slices, so the cohort median estimates the same
        honest baseline); detector state is still per-client and exact.
        """
        cfg = self.cfg
        (
            flat_params, opt_state, client_m, fault_state, defense_state,
            attack_iter, service_state,
        ) = carry
        m_h, m_b = self._part_h, self._part_b  # participating counts
        cohort = cfg.cohort_size
        n_h_chunks = m_h // cohort
        n_chunks = n_h_chunks + m_b // cohort
        d = self.dim
        k_total = m_h + m_b

        attack_on = None
        if self._attack_onset is not None:
            attack_on = attack_iter >= self._attack_onset

        # identical round-level split to the resident path (replay/ckpt
        # compatible); chunk sub-streams below are cohort_key fold-ins
        n_extra = (
            int(cfg.participation < 1.0)
            + int(self.fault is not None)
            + int(cfg.service == "on")
        )
        keys = jax.random.split(key, 4 + n_extra)
        k_batch, k_chan, k_agg, k_msg = keys[:4]
        del k_agg  # mean/median/trimmed_mean/gm2 never consume it
        next_extra = 4
        offsets, sizes = self.offsets, self.sizes
        if cfg.participation < 1.0:
            # subsample-then-stream: the resident stratified draw verbatim
            # (same key slot, same permutation calls), then the cohort
            # scan walks the [m] PARTICIPANT index space — cfg.validate
            # pins cohort_size to divide both participating counts, so
            # chunk purity still holds (byz participants land last)
            k_part = keys[next_extra]
            next_extra += 1
            kh, kb = jax.random.split(k_part)
            part = jax.random.permutation(kh, cfg.honest_size)[:m_h]
            if m_b:
                part = jnp.concatenate([
                    part,
                    cfg.honest_size
                    + jax.random.permutation(kb, cfg.byz_size)[:m_b],
                ])
            offsets = self.offsets[part]
            sizes = self.sizes[part]
        stale = ge_bad = ()
        if self.fault is not None:
            _k_drop, k_trans = jax.random.split(keys[next_extra])
            next_extra += 1
            stale, ge_bad = fault_state  # stale is () (needs_stale rejected)
        pop_ids = widen = missed = None
        n_avail = n_absent = n_late = None
        if cfg.service == "on":
            with jax.named_scope("service_draw"):
                # same draw/churn/deadline semantics as the resident path;
                # the [K]-resident pop_ids/missed masks are i32/bool rows
                # (O(K), not O(K*d)) so keeping them resident costs
                # nothing against the streamed peak
                k_churn, k_draw, k_dead = jax.random.split(
                    keys[next_extra], 3
                )
                avail, widen = service_state
                n_avail = jnp.sum(avail).astype(jnp.float32)
                pop_ids, arrived = self._service_draw(k_draw, avail)
                k_arr, k_dep = jax.random.split(k_churn)
                avail = jnp.where(
                    avail,
                    ~jax.random.bernoulli(
                        k_dep, cfg.churn_departure, avail.shape
                    ),
                    jax.random.bernoulli(
                        k_arr, cfg.churn_arrival, avail.shape
                    ),
                )
                service_state = (avail, widen)
                shard = self._pop_shard[pop_ids]
                offsets = self.offsets[shard]
                sizes = self.sizes[shard]
                if cfg.straggler_prob > 0.0:
                    late = jnp.logical_and(
                        arrived,
                        jax.random.bernoulli(
                            k_dead, cfg.straggler_prob, (k_total,)
                        ),
                    )
                else:
                    late = jnp.zeros((k_total,), bool)
                missed = jnp.logical_or(late, jnp.logical_not(arrived))
                n_absent = jnp.sum(
                    jnp.logical_not(arrived)
                ).astype(jnp.float32)
                n_late = jnp.sum(late).astype(jnp.float32)
        byz_mask = self._part_mask
        steps_b = cfg.local_steps * cfg.batch_size
        # ONE [K, E*B] index draw under the resident path's exact key and
        # shape — i32 indices are O(K*batch), not O(K*d), so keeping them
        # resident costs nothing against the streamed peak and makes every
        # chunk's batches (hence, with channel/fault off, the chunk rows
        # themselves) bit-identical to the resident stack's rows
        idx_all = data_lib.sample_client_batch_indices(
            k_batch, offsets, sizes, steps_b
        )

        needs_ge = self.fault is not None and self.fault.needs_ge

        # ---- population-shard region.  Everything that touches chunk
        # CONTENTS (rebuild, the observation scan, the detector updates,
        # the aggregation passes) lives in ``core`` below, a pure function
        # of the ``region_in`` dict: every traced value enters through the
        # dict (trainer constants -- byz mask, normalization vectors --
        # are closure-captured and lifted as replicated), so the SAME body
        # runs three ways via ``_pop_shard_region``: a plain call under
        # ops/shardctx.LOCAL (pop_shards == 1, the legacy byte-identical
        # trace), the sequential reference engine (SeqShardCtx,
        # pop_shards > 1 on one device), or shard_map over the population
        # mesh axis (parallel/popmesh.py), where each device scans its own
        # chunk range and the partials merge by the shardctx tag algebra
        # (docs/DESIGN.md "Pod-scale service rounds").
        region_in = dict(
            flat_params=flat_params,
            idx_all=idx_all,
            x_train=x_train,
            y_train=y_train,
            k_msg=k_msg,
            k_chan=k_chan,
        )
        if attack_on is not None:
            region_in["attack_on"] = attack_on
        if self.fault is not None:
            region_in["k_trans"] = k_trans
            region_in["ge_bad"] = ge_bad
        if cfg.service == "on":
            region_in["pop_ids"] = pop_ids
            region_in["missed"] = missed
            region_in["widen"] = widen
        if self.defense is not None:
            region_in["defense_state"] = defense_state

        def core(ctx, rin):
            # bind every traced value locally so nothing below closes over
            # a tracer from outside the (possibly shard_map-wrapped)
            # region boundary
            flat_params = rin["flat_params"]
            idx_all = rin["idx_all"]
            x_train = rin["x_train"]
            y_train = rin["y_train"]
            k_msg = rin["k_msg"]
            k_chan = rin["k_chan"]
            attack_on = rin.get("attack_on")
            k_trans = rin.get("k_trans")
            ge_bad = rin.get("ge_bad", ())
            pop_ids = rin.get("pop_ids")
            missed = rin.get("missed")
            widen = rin.get("widen")
            defense_state_in = rin.get("defense_state")
            sharded = ctx.n_shards > 1

            def rebuild_full(c_idx):
                """([cohort, d] chunk, new GE slice, n_erased, n_corrupt) for
                one cohort — pure in c_idx, so every aggregator pass that
                re-invokes it sees identical chunks."""
                off = c_idx * cohort
                mask_c = jax.lax.dynamic_slice_in_dim(byz_mask, off, cohort)
                if attack_on is not None:
                    mask_c = mask_c & attack_on
                idx = jax.lax.dynamic_slice_in_dim(idx_all, off, cohort, axis=0)
                x = x_train[idx]
                if self._norm_scale is not None:
                    x = x.astype(jnp.float32) * self._norm_scale + self._norm_bias
                shape = (cohort, cfg.local_steps, cfg.batch_size)
                x = x.reshape(
                    shape + (self._sample_shape if self._spatial_input else (-1,))
                )
                y = y_train[idx].reshape(shape)
                chunk = self._constrain_stack(
                    self._client_stack(flat_params, x, y, mask_c)
                )

                if self.attack is not None and self.attack.message_fn is not None:
                    # cohort purity: byz chunks are the LAST ones, so byz_size =
                    # cohort attacks the whole chunk and the scalar gate keeps
                    # honest chunks untouched (row-local attacks only —
                    # cfg.validate rejects the omniscient ones)
                    is_byz_chunk = c_idx >= n_h_chunks
                    d_view = None
                    if self.attack.defense_aware:
                        # chunk-local slice of the PREVIOUS iteration's
                        # published detector rows.  MUST read the iteration-
                        # start snapshot, not ``defense_state``: that variable
                        # is rebound (step+1, new rung) after the observation
                        # scan but BEFORE the aggregation pass re-invokes this
                        # closure, and a post-update view would make the two
                        # passes rebuild different chunks (and break resident
                        # parity at the attack's schedule boundaries)
                        det_s, pol_s = defense_state_in
                        step_s, ema_s, dev_s, cus_s = det_s
                        if cfg.service == "on":
                            ids_v = jax.lax.dynamic_slice_in_dim(
                                pop_ids, off, cohort
                            )
                            ema_v, dev_v, cus_v = (
                                ema_s[ids_v], dev_s[ids_v], cus_s[ids_v]
                            )
                        else:
                            ema_v, dev_v, cus_v = (
                                jax.lax.dynamic_slice_in_dim(r, off, cohort)
                                for r in (ema_s, dev_s, cus_s)
                            )
                        d_view = attack_lib.DefenseView(
                            step=step_s,
                            ema=ema_v,
                            dev=dev_v,
                            cusum=cus_v,
                            rung=pol_s[0],
                            detector=self.defense.detector,
                            policy=self.defense.policy,
                            guess=flat_params,
                        )
                    w_att = self.attack.apply_message(
                        chunk, cohort, channel_lib.cohort_key(k_msg, c_idx),
                        param=cfg.attack_param, defense=d_view,
                    )
                    gate = (
                        is_byz_chunk if attack_on is None
                        else jnp.logical_and(is_byz_chunk, attack_on)
                    )
                    chunk = jnp.where(gate, w_att, chunk)

                ge_c = ()
                n_erased = n_corrupt = jnp.float32(0.0)
                if self.fault is not None:
                    ge_in = (
                        jax.lax.dynamic_slice_in_dim(ge_bad, off, cohort)
                        if self.fault.needs_ge
                        else ()
                    )
                    chunk, ge_c, n_erased, n_corrupt = (
                        fault_lib.apply_transmission(
                            self.fault, channel_lib.cohort_key(k_trans, c_idx),
                            chunk, ge_in, row_offset=off,
                        )
                    )

                if cfg.noise_var is not None and agg_lib.needs_oma_prepass(
                    cfg.agg
                ):
                    if cfg.service == "on":
                        # per-STABLE-ID links under the ROUND key (not the
                        # cohort fold-in): fold_in(k_chan, id) is invariant to
                        # which chunk the draw placed a client in, so the
                        # streamed realization matches the resident path's
                        # bit for bit
                        ids_c = jax.lax.dynamic_slice_in_dim(
                            pop_ids, off, cohort
                        )
                        chunk = channel_lib.oma_by_id(
                            k_chan, chunk, ids_c, cfg.noise_var
                        )
                    else:
                        chunk = channel_lib.oma(
                            channel_lib.cohort_key(k_chan, c_idx), chunk,
                            cfg.noise_var,
                        )
                chunk = self._constrain_stack(chunk)
                if cfg.service == "on":
                    # deadline erasure LAST (as in the resident path), sliced
                    # from the resident [K] mask so every rebuild pass sees
                    # identical chunks
                    miss_c = jax.lax.dynamic_slice_in_dim(missed, off, cohort)
                    chunk = jnp.where(
                        miss_c[:, None], jnp.asarray(jnp.nan, chunk.dtype), chunk
                    )
                return chunk, ge_c, n_erased, n_corrupt

            def rebuild(c_idx):
                return rebuild_full(c_idx)[0]

            # ---- single observation pass over the chunks (per shard)
            if self.defense is not None:
                det, pol = defense_state_in
                det_rows0 = (det[1], det[2], det[3])
                if sharded:
                    # extra touched-row mask: the scan scatters True at
                    # this shard's drawn rows so the post-scan merge can
                    # select each shard's disjoint row updates
                    det_rows0 = det_rows0 + (jnp.zeros(det[1].shape, bool),)
            else:
                det_rows0 = ()
            obs_init = (
                jnp.zeros(d, jnp.float32),   # sum over all rows
                jnp.zeros(d, jnp.float32),   # sum over finite rows
                jnp.int32(0),                # finite-row count
                jnp.zeros(d, jnp.float32),   # honest-row sum (dispersion)
                jnp.float32(0.0),            # honest sum of squared norms
                jnp.float32(0.0) if cfg.service == "on" else (),  # honest fin
                ge_bad if needs_ge else (),
                jnp.float32(0.0),            # erased
                jnp.float32(0.0),            # corrupt
                det_rows0,
                jnp.int32(0) if self.defense is not None else (),
                jnp.float32(0.0) if self.defense is not None else (),
                # running top-M forensic candidates ([M, NUM_COLS], score
                # column seeded -inf so real rows displace the sentinels)
                forensics_lib.stream_init(cfg.forensics_top)
                if self._forensics_on else (),
            )

            def obs_body(carry_o, c_idx):
                (
                    s_all, s_fin, n_fin, s_h, ssq_h, n_h_fin, ge_acc, n_er,
                    n_co, det_rows, n_flag, max_sc, topm,
                ) = carry_o
                chunk, ge_c, er, co = rebuild_full(c_idx)
                fin = agg_lib._finite_rows(chunk)
                c32 = chunk.astype(jnp.float32)
                c_fin = jnp.where(fin[:, None], c32, 0.0)
                s_all = s_all + jnp.sum(c32, axis=0)
                s_fin = s_fin + jnp.sum(c_fin, axis=0)
                n_fin = n_fin + jnp.sum(fin)
                is_h = (c_idx < n_h_chunks).astype(jnp.float32)
                if cfg.service == "on":
                    # deadline-missed honest rows are NaN: the dispersion
                    # moments run over what the round actually received
                    s_h = s_h + is_h * jnp.sum(c_fin, axis=0)
                    ssq_h = ssq_h + is_h * jnp.sum(c_fin * c_fin)
                    n_h_fin = n_h_fin + is_h * jnp.sum(fin).astype(jnp.float32)
                else:
                    s_h = s_h + is_h * jnp.sum(c32, axis=0)
                    ssq_h = ssq_h + is_h * jnp.sum(c32 * c32)
                if self.fault is not None:
                    n_er, n_co = n_er + er, n_co + co
                    if needs_ge:
                        ge_acc = jax.lax.dynamic_update_slice_in_dim(
                            ge_acc, ge_c, c_idx * cohort, axis=0
                        )
                if self.defense is not None:
                    # per-client detector rows, updated slice-by-slice under
                    # the shared scalar step (incremented ONCE after the scan)
                    if sharded:
                        ema, dev, cus, touched = det_rows
                    else:
                        ema, dev, cus = det_rows
                    off = c_idx * cohort
                    # component-returning variant (defense/scores.py): same
                    # score/finite values; the component columns are dead code
                    # when forensics is off
                    score, score_fin, score_parts = (
                        defense_lib.client_score_components(chunk, flat_params)
                    )
                    if cfg.service == "on":
                        # population-keyed rows: gather this chunk's drawn ids,
                        # update under their own first-observation markers
                        # (dev == 0 <=> never updated), scatter back — same
                        # contract as the resident service path
                        rows_c = jax.lax.dynamic_slice_in_dim(
                            pop_ids, off, cohort
                        )
                        det_c = (det[0], ema[rows_c], dev[rows_c], cus[rows_c])
                        (_, ema_c, dev_c, cus_c), flags = (
                            defense_lib.detector_update(
                                det_c, score, score_fin, self.defense.detector,
                                first=det_c[2] == 0.0,
                            )
                        )
                        det_rows = (
                            ema.at[rows_c].set(ema_c),
                            dev.at[rows_c].set(dev_c),
                            cus.at[rows_c].set(cus_c),
                        )
                        if sharded:
                            det_rows = det_rows + (
                                touched.at[rows_c].set(True),
                            )
                    else:
                        det_c = (
                            det[0],
                            jax.lax.dynamic_slice_in_dim(ema, off, cohort),
                            jax.lax.dynamic_slice_in_dim(dev, off, cohort),
                            jax.lax.dynamic_slice_in_dim(cus, off, cohort),
                        )
                        (_, ema_c, dev_c, cus_c), flags = (
                            defense_lib.detector_update(
                                det_c, score, score_fin, self.defense.detector
                            )
                        )
                        det_rows = (
                            jax.lax.dynamic_update_slice_in_dim(
                                ema, ema_c, off, axis=0
                            ),
                            jax.lax.dynamic_update_slice_in_dim(
                                dev, dev_c, off, axis=0
                            ),
                            jax.lax.dynamic_update_slice_in_dim(
                                cus, cus_c, off, axis=0
                            ),
                        )
                    n_flag = n_flag + jnp.sum(flags)
                    max_sc = jnp.maximum(max_sc, jnp.max(score))
                    if self._forensics_on:
                        # per-cohort top-M merge: this chunk's candidates
                        # (stable ids under service, participant rows
                        # otherwise; pre-update ema/dev, post-update CUSUM)
                        # against the carried top-M — fixed [M, NUM_COLS]
                        ids_f = (
                            rows_c if cfg.service == "on"
                            else off + jnp.arange(cohort, dtype=jnp.int32)
                        )
                        topm = forensics_lib.merge_top_m(
                            topm,
                            forensics_lib.candidate_rows(
                                ids_f, score, score_parts, det_c[1], det_c[2],
                                cus_c, flags, self.defense.detector,
                            ),
                            cfg.forensics_top,
                        )
                return (
                    s_all, s_fin, n_fin, s_h, ssq_h, n_h_fin, ge_acc, n_er,
                    n_co, det_rows, n_flag, max_sc, topm,
                )

            # per-leaf merge tags (ops/shardctx.py): integer sums and
            # extrema are placement-exact; float sums fold in canonical
            # shard order; detector rows stack for the disjoint-row merge
            # below.  LOCAL ignores the spec and lowers to the legacy
            # single lax.scan.
            obs_spec = (
                "sum", "sum", "sum", "sum", "sum",
                "sum" if cfg.service == "on" else (),
                "stack" if needs_ge else (),
                "sum", "sum",
                ("stack",) * (4 if sharded else 3)
                if self.defense is not None else (),
                "sum" if self.defense is not None else (),
                "max" if self.defense is not None else (),
                "stack" if self._forensics_on else (),
            )
            with jax.named_scope("stream_observe"):
                (
                    s_all, s_fin, n_fin, s_h, ssq_h, n_h_fin, ge_new, n_er,
                    n_co, det_rows, n_flag, max_sc, topm,
                ) = ctx.scan_idx_merge(n_chunks, obs_body, obs_init, obs_spec)

            defense_state_new = ()
            defense_metrics = ()
            forensic = ()
            rung = None
            if self.defense is not None:
                if sharded:
                    # disjoint-row merge of the stacked [S, population]
                    # detector partials: the stratified draw is WITHOUT
                    # replacement, so every drawn id lives in exactly one
                    # chunk — shard p's touched rows never overlap shard
                    # q's, and untouched rows keep their round-start value
                    ema_s, dev_s, cus_s, touched_s = det_rows
                    ema_m, dev_m, cus_m = det[1], det[2], det[3]
                    for p_i in range(ctx.n_shards):
                        t_p = touched_s[p_i]
                        ema_m = jnp.where(t_p, ema_s[p_i], ema_m)
                        dev_m = jnp.where(t_p, dev_s[p_i], dev_m)
                        cus_m = jnp.where(t_p, cus_s[p_i], cus_m)
                    det_rows = (ema_m, dev_m, cus_m)
                det = (det[0] + 1, det_rows[0], det_rows[1], det_rows[2])
                pol, suspicious = defense_lib.policy_update(
                    pol, n_flag, self.defense.policy
                )
                rung = pol[0]
                defense_state_new = (det, pol)
                defense_metrics = jnp.stack([
                    rung.astype(jnp.float32),
                    n_flag.astype(jnp.float32),
                    suspicious.astype(jnp.float32),
                    max_sc,
                    jnp.max(det[3]),
                ])
                if self._forensics_on:
                    # rung at flag time, stamped once the policy has updated
                    forensic = forensics_lib.with_rung(topm, rung)

            with jax.named_scope("stream_aggregate"):
                kw = dict(
                    k=k_total, d=d, n_chunks=n_chunks,
                    degraded=self.fault is not None or cfg.service == "on",
                    sum_all=s_all, sum_finite=s_fin, n_finite=n_fin,
                    guess=flat_params, maxiter=cfg.agg_maxiter,
                    tol=cfg.agg_tol, quantile=cfg.cohort_quantile,
                    sketch_bins=cfg.cohort_sketch_bins, ctx=ctx,
                )
                if cfg.service == "on":
                    # rollback-widened trim fraction — only the streamed
                    # trimmed_mean's dynamic trim budget consumes it
                    kw["trim_ratio"] = jnp.minimum(
                        jnp.float32(0.1) * widen, 0.45
                    )
                if self.defense is not None and self.defense.mode == "adaptive":
                    # streamed rung dispatch: one lax.switch over nullary
                    # streamed closures (cfg.validate pins every rung to a
                    # streamable aggregator)
                    branches = tuple(
                        (lambda nm: lambda: agg_lib.stream_aggregate(
                            nm, rebuild, **kw
                        ))(nm)
                        for nm in self.defense.ladder
                    )
                    aggregated = jax.lax.switch(rung, branches)
                else:
                    aggregated = agg_lib.stream_aggregate(cfg.agg, rebuild, **kw)
                aggregated = aggregated.astype(jnp.float32)
            return (
                aggregated, n_fin, s_h, ssq_h, n_h_fin,
                ge_new if needs_ge else (), n_er, n_co,
                defense_state_new, defense_metrics, forensic,
            )

        (
            aggregated, n_fin, s_h, ssq_h, n_h_fin, ge_new, n_er, n_co,
            defense_state_new, defense_metrics, forensic,
        ) = self._pop_shard_region(core, region_in)
        if self.fault is not None:
            fault_state = (stale, ge_new if needs_ge else ge_bad)
        if self.defense is not None:
            defense_state = defense_state_new

        with jax.named_scope("stream_aggregate"):
            if self.fault is not None or cfg.service == "on":
                # same receiver-side finite-guard as the resident path
                aggregated = jnp.where(
                    jnp.isfinite(aggregated), aggregated, flat_params
                )
            if self._server_tx is not None:
                delta = flat_params - aggregated
                updates, opt_state = self._server_tx.update(
                    delta, opt_state, flat_params
                )
                new_flat = optax.apply_updates(flat_params, updates)
            else:
                new_flat = aggregated
            new_flat = self._constrain_params(new_flat)

        # streamed honest dispersion from the observation-pass moments:
        # (1/H) sum ||w_i||^2 - ||mean_h||^2 == mean_i ||w_i - mean_h||^2
        n_h = (
            jnp.maximum(n_h_fin, 1.0) if cfg.service == "on"
            else jnp.float32(m_h)
        )
        mean_h = s_h / n_h
        variance = jnp.where(
            want_variance,
            ssq_h / n_h - jnp.sum(mean_h * mean_h),
            jnp.float32(0.0),
        )
        if self._attack_onset is not None:
            attack_iter = attack_iter + 1
        carry_out = (
            new_flat, opt_state, client_m, fault_state, defense_state,
            attack_iter, service_state,
        )
        if self.fault is not None:
            # dropout is structurally absent under streaming (needs_stale
            # rejected), so the dropped count is a literal 0
            fault_metrics = jnp.stack([
                jnp.float32(0.0), n_er, n_co, n_fin.astype(jnp.float32),
            ])
        else:
            fault_metrics = ()
        if cfg.service == "on":
            service_metrics = jnp.stack([
                n_avail, n_absent, n_late, n_fin.astype(jnp.float32),
            ])
        else:
            service_metrics = ()
        return carry_out, (
            variance, fault_metrics, defense_metrics, service_metrics,
            forensic,
        )

    def _round_core(
        self, flat_params, opt_state, client_m, fault_state, defense_state,
        attack_iter, service_state, round_key, x_train, y_train
    ):
        """One round (display_interval scanned iterations) as a pure fn.

        Returns ``(params, opt_state, client_m, fault_state, defense_state,
        attack_iter, service_state, variance, fault_metrics,
        defense_metrics, service_metrics)`` where fault_metrics is the
        round's reduced [dropped, erased, corrupt, effective_k] (event
        counts summed over the interval, effective K at its per-iteration
        MINIMUM — the worst moment is what resilience claims are about),
        defense_metrics is the [6] vector of ``defense/events.METRIC_KEYS``
        and service_metrics is the reduced [available, absent, late,
        effective_k] participation vector (availability at round end,
        deadline-event counts summed, effective K at its minimum) — each is
        ``()`` when its feature is off, keeping that program's output
        structure free.  A trailing ``forensic_metrics`` element carries
        the round's [forensics_top, NUM_COLS] top-M flag-provenance matrix
        (obs/forensics.py), ``()`` when forensics is off."""
        interval = self.cfg.display_interval
        keys = jax.random.split(round_key, interval)
        want = jnp.arange(interval) == interval - 1

        def it(carry, kf):
            key, want_var = kf
            return self._iteration(carry, key, x_train, y_train, want_var)

        (
            final, opt_final, m_final, f_final, d_final, a_final, s_final,
        ), (
            variances, fms, dms, sms, fos
        ) = jax.lax.scan(
            it,
            (flat_params, opt_state, client_m, fault_state, defense_state,
             attack_iter, service_state),
            (keys, want),
        )
        if self.fault is not None:
            fault_metrics = jnp.concatenate(
                [jnp.sum(fms[:, :3], axis=0), jnp.min(fms[:, 3:], axis=0)]
            )
        else:
            fault_metrics = ()
        if self.defense is not None:
            # [interval, 5] per-iteration observations -> the [6] round
            # vector.  Transitions count every rung move including the
            # round boundary (pre-round rung from the INCOMING policy
            # state), so a round that opens with an escalation reports it
            rung_in = defense_state[1][0].astype(jnp.float32)
            rung_path = jnp.concatenate([rung_in[None], dms[:, 0]])
            defense_metrics = jnp.stack([
                dms[-1, 0],                              # rung at round end
                jnp.max(dms[:, 1]),                      # max flagged
                jnp.sum(dms[:, 2]),                      # suspicious iters
                jnp.max(dms[:, 3]),                      # max score
                jnp.max(dms[:, 4]),                      # max cusum
                jnp.sum(jnp.abs(jnp.diff(rung_path))),   # transitions
            ])
        else:
            defense_metrics = ()
        if self.cfg.service == "on":
            # availability is a level (report the round's last value);
            # absences/lates are events (sum); effective K at its minimum,
            # same worst-moment convention as the fault reduce
            service_metrics = jnp.stack([
                sms[-1, 0], jnp.sum(sms[:, 1]), jnp.sum(sms[:, 2]),
                jnp.min(sms[:, 3]),
            ])
        else:
            service_metrics = ()
        if self._forensics_on:
            # [interval, M, NUM_COLS] -> the round-level [M, NUM_COLS]
            # top-M (a client's peak iteration wins; host-side emission
            # dedupes repeats)
            forensic = forensics_lib.merge_interval(
                fos, self.cfg.forensics_top
            )
        else:
            forensic = ()
        return (
            final, opt_final, m_final, f_final, d_final, a_final, s_final,
            variances[-1], fault_metrics, defense_metrics, service_metrics,
            forensic,
        )

    def _build_round_fn(self):
        return self._round_core

    def _build_multi_round_fn(self):
        """n rounds in ONE device program: an outer scan over round keys.

        The scan consumes a precomputed ``[n]`` array of per-round keys
        (:meth:`_round_keys`) — the same ``fold_in(PRNGKey(seed), round)``
        derivation as :meth:`run_round`, including the host-side
        rollback-epoch salt — so ``run_rounds(r0, n)`` consumes the
        identical RNG stream as n successive ``run_round`` calls and
        removes only the per-round host dispatch (a few ms each on a
        tunneled chip).  Deriving keys on the host keeps epoch salting out
        of the traced program: a warm-rollback re-run changes only the key
        VALUES, never the scan's shape, so the one-lowering contract
        holds across restores.  Trajectories agree with the per-round
        loop up to the float re-association of a separately compiled XLA
        program (ulp-level per step; see
        tests/test_training.py::test_run_rounds_matches_run_round_loop)."""

        def multi_fn(
            flat_params, opt_state, client_m, fault_state, defense_state,
            attack_iter, service_state, round_keys, x_train, y_train,
        ):
            def body(carry, round_key):
                fp, os, cm, fs, ds, ai, ss = carry
                fp, os, cm, fs, ds, ai, ss, var, fm, dm, sm, fo = (
                    self._round_core(
                        fp, os, cm, fs, ds, ai, ss,
                        round_key, x_train, y_train,
                    )
                )
                return (fp, os, cm, fs, ds, ai, ss), (var, fm, dm, sm, fo)

            (
                final, opt_final, m_final, f_final, d_final, a_final,
                s_final,
            ), (
                variances, fms, dms, sms, fos
            ) = jax.lax.scan(
                body,
                (flat_params, opt_state, client_m, fault_state,
                 defense_state, attack_iter, service_state),
                round_keys,
            )
            return (
                final, opt_final, m_final, f_final, d_final, a_final,
                s_final, variances, fms, dms, sms, fos,
            )

        return multi_fn

    def _build_eval_fn(self):
        eval_b = self.cfg.eval_batch

        def eval_fn(flat_params, x_chunks, y_chunks, m_chunks):
            params = flatten_lib.unflatten(flat_params, self.spec)

            def chunk(carry, args):
                xc, yc, mc = args
                logits = self.model.apply(params, xc)
                losses = cross_entropy(logits, yc) * mc
                correct = (jnp.argmax(logits, axis=1) == yc) * mc
                return carry, (jnp.sum(losses), jnp.sum(correct))

            _, (losses, corrects) = jax.lax.scan(
                chunk, 0, (x_chunks, y_chunks, m_chunks)
            )
            total = jnp.sum(m_chunks)
            return jnp.sum(losses) / total, jnp.sum(corrects) / total

        return eval_fn

    # ------------------------------------------------------------------
    # host-side driver

    def _chunked(self, x: np.ndarray, y: np.ndarray):
        b = self.cfg.eval_batch
        n = len(x)
        n_pad = (-n) % b
        xp = np.concatenate([x, np.zeros((n_pad,) + x.shape[1:], x.dtype)])
        yp = np.concatenate([y, np.zeros((n_pad,), y.dtype)])
        mp = np.concatenate([np.ones(n, np.float32), np.zeros(n_pad, np.float32)])
        shape = (-1, b)
        return (
            jnp.asarray(xp.reshape(shape + x.shape[1:])),
            jnp.asarray(yp.reshape(shape)),
            jnp.asarray(mp.reshape(shape)),
        )

    def evaluate(self, split: str = "val"):
        """Full-dataset loss/accuracy (reference ``calculateAccuracy``,
        ``:106-125``), chunked so CNN activations fit on chip."""
        if split not in self._eval_cache:
            ds = self.dataset
            arrs = (ds.x_val, ds.y_val) if split == "val" else (ds.x_train, ds.y_train)
            self._eval_cache[split] = self._chunked(*arrs)
        x, y, m = self._eval_cache[split]
        loss, acc = self._eval_fn(self.flat_params, x, y, m)
        return float(loss), float(acc)

    def run_round(self, round_idx: int) -> jax.Array:
        """Execute one round (display_interval global iterations); returns the
        honest-dispersion metric of the round's last iteration as a DEVICE
        scalar.  No host sync happens here — a ``float()`` conversion per
        round would serialize dispatch on the device round-trip latency
        (~3x the round's compute on a tunneled chip); callers convert when
        they actually consume the value."""
        round_key = jax.random.fold_in(self._base_key, round_idx)
        if self._rollback_epoch:
            # warm rollback: re-running a round after a restore must NOT
            # replay the exact draws that diverged — salt the round key
            # with the rollback epoch (host-side int, so the jitted
            # program is untouched and epoch 0 keys are bit-identical to
            # the pre-rollback stream)
            round_key = jax.random.fold_in(round_key, self._rollback_epoch)
        (
            self.flat_params, self.server_opt_state, self.client_m,
            self.fault_state, self.defense_state, self.attack_iter,
            self.service_state, variance, self.last_fault_metrics,
            self.last_defense_metrics, self.last_service_metrics,
            self.last_forensic_metrics,
        ) = self._round_fn(
            self.flat_params, self.server_opt_state, self.client_m,
            self.fault_state, self.defense_state, self.attack_iter,
            self.service_state, round_key, self.x_train, self.y_train,
        )
        return variance

    def _round_keys(self, start_round: int, num_rounds: int) -> jax.Array:
        """The ``[num_rounds]`` per-round key array a multi-round dispatch
        scans over: ``fold_in(seed, round)``, epoch-salted exactly like
        :meth:`run_round` when a warm rollback has fired.  Host-side by
        design — the salt changes key values, not the traced program."""
        rounds = jnp.arange(
            start_round, start_round + num_rounds, dtype=jnp.int32
        )
        keys = jax.vmap(
            lambda r: jax.random.fold_in(self._base_key, r)
        )(rounds)
        if self._rollback_epoch:
            epoch = self._rollback_epoch
            keys = jax.vmap(
                lambda k: jax.random.fold_in(k, epoch)
            )(keys)
        return keys

    def run_rounds_stacked(self, start_round: int, num_rounds: int):
        """Execute ``num_rounds`` rounds as ONE dispatched program (outer
        ``lax.scan`` over per-round keys); returns the stacked per-round
        outputs ``(variances, fault_ms, defense_ms, service_ms,
        forensic_ms)`` as device arrays of leading dim ``num_rounds``
        (``()`` for each subsystem that is off).  No host sync happens
        here — the multi-round driver folds these into records/events at
        dispatch exit, benchmarks only force the final params."""
        (
            self.flat_params, self.server_opt_state, self.client_m,
            self.fault_state, self.defense_state, self.attack_iter,
            self.service_state, variances, fms, dms, sms, fos,
        ) = self._multi_round_fn(
            self.flat_params, self.server_opt_state, self.client_m,
            self.fault_state, self.defense_state, self.attack_iter,
            self.service_state, self._round_keys(start_round, num_rounds),
            self.x_train, self.y_train,
        )
        # [num_rounds, 4] / [num_rounds, 6] stacked rows (the LAST round's
        # row is what run_round would have reported); () when off
        self.last_fault_metrics = (
            fms[-1] if self.fault is not None else ()
        )
        self.last_defense_metrics = (
            dms[-1] if self.defense is not None else ()
        )
        self.last_service_metrics = (
            sms[-1] if self.cfg.service == "on" else ()
        )
        self.last_forensic_metrics = (
            fos[-1] if self._forensics_on else ()
        )
        return variances, fms, dms, sms, fos

    def run_rounds(self, start_round: int, num_rounds: int) -> jax.Array:
        """Execute ``num_rounds`` rounds as ONE dispatched program; returns
        the per-round honest-dispersion metrics [num_rounds] as a device
        array.  Same RNG stream and semantics as calling :meth:`run_round`
        in a loop (numerically equal up to separate-compilation float
        re-association) — use this when nothing (eval, logging,
        checkpointing) needs the params between rounds, e.g. benchmarking."""
        return self.run_rounds_stacked(start_round, num_rounds)[0]

    def train(
        self,
        log_fn: Optional[Callable[[str], None]] = None,
        checkpoint_fn: Optional[Callable[[int, "FedTrainer"], None]] = None,
        start_round: int = 0,
        obs: Optional["obs_lib.Observability"] = None,
        profiler: Optional["obs_lib.Profiler"] = None,
    ) -> Dict[str, List[float]]:
        """Full training run; returns reference-schema metric paths
        (``trainLossPath`` etc., pickled record keys at ``:481-489``).
        ``start_round > 0`` resumes a checkpointed run: per-round keys are
        derived by ``fold_in(seed, round)``, so the remaining rounds replay
        identically to an uninterrupted run.  ``obs`` (default: the null
        sink) receives span timings — compile-round vs steady-state rounds
        are distinguished by the retrace counter, not by position — and a
        schema-versioned per-round event mirroring the floats appended to
        the reference paths.  The observed program is the SAME program: no
        extra device syncs are introduced (the round span closes over the
        existing ``block_until_ready``) and eval/checkpoint spans only read
        the host clock.  ``profiler`` (default: the null profiler) names
        each round as a ``StepTraceAnnotation`` and the eval/checkpoint
        phases as ``TraceAnnotation`` regions in the device trace, and in
        window mode (``--profile-rounds A:B``) owns the trace lifecycle
        through the ``round_start``/``round_end`` hooks; while no trace is
        active every hook is a no-op returning a shared null context."""
        cfg = self.cfg
        log = log_fn or (lambda s: None)
        obs = obs or obs_lib.NULL
        profiler = profiler or obs_lib.NULL_PROFILER

        def eval_pair():
            if cfg.eval_train:
                tr = self.evaluate("train")
            else:
                tr = (0.0, 0.0)  # EMNIST reference stubs train eval (:273-274)
            va = self.evaluate("val")
            return tr, va

        with obs.span("eval", stage="initial", round=start_round), \
                profiler.phase("eval"):
            (tr_loss, tr_acc), (va_loss, va_acc) = eval_pair()
        paths = {
            "trainLossPath": [tr_loss],
            "trainAccPath": [tr_acc],
            "valLossPath": [va_loss],
            "valAccPath": [va_acc],
            "variencePath": [],  # sic — reference spelling, draw.ipynb consumes it
            "roundsPerSec": [],
        }
        if self.fault is not None:
            # per-round fault observability: event counts summed over the
            # round's iterations, plus the round's MINIMUM effective K
            # (finite rows actually aggregated) — the resilience metric
            # the fault-matrix sweep and the acceptance criteria read
            paths["faultDroppedPath"] = []
            paths["faultErasedPath"] = []
            paths["faultCorruptPath"] = []
            paths["effectiveKPath"] = []
        prev_rung = None
        if self.defense is not None:
            # per-round defense observability (defense/events.PATH_KEYS):
            # rung, flagged clients, suspicious iterations, score/CUSUM
            # maxima and intra-round transitions
            for path_key in defense_lib.events.PATH_KEYS.values():
                paths[path_key] = []
            prev_rung = int(self.defense_state[1][0])
        if cfg.service == "on":
            # per-round participation telemetry under deadline semantics:
            # availability level at round end, deadline-event counts, and
            # the round's minimum effective K (fault mode is mutually
            # exclusive with service, so effectiveKPath has one owner)
            paths["serviceAvailPath"] = []
            paths["serviceAbsentPath"] = []
            paths["serviceLatePath"] = []
            paths["effectiveKPath"] = []
        # live reference for checkpoint hooks: paths is appended in place,
        # so a checkpoint_fn can persist the metrics recorded so far (the
        # experiment server's crash-resume rides this — harness.run with
        # persist_paths saves them inside the checkpoint's atomic write)
        self._last_paths = paths
        log(
            f"[0/{cfg.rounds}](interval: {cfg.display_interval}) "
            f"train: loss={tr_loss:.4f} acc={tr_acc:.4f} "
            f"val: loss={va_loss:.4f} acc={va_acc:.4f}"
        )

        if cfg.rounds_per_dispatch > 1:
            # dispatch tier: R rounds per device program, host rim folded
            # at dispatch exits.  The R=1 loop below stays byte-identical
            # to the pre-dispatch-tier driver — that bit-identity IS the
            # exact-mode contract (tests/test_training.py pins it).
            return self._train_multi(
                paths, (tr_loss, tr_acc, va_loss, va_acc), eval_pair,
                prev_rung, log, checkpoint_fn, start_round, obs, profiler,
            )

        # warm rollback (service rounds): keep a host-side copy of the last
        # GOOD end-of-round state; when the divergence guard trips, restore
        # it, widen the trim fraction and re-run the round under an
        # epoch-salted key instead of dying or replaying the same draws
        rollback_armed = cfg.service == "on" and cfg.rollback == "on"
        snapshot = None
        recent_val: List[float] = []

        def _state_tuple():
            return (
                self.flat_params, self.server_opt_state, self.client_m,
                self.fault_state, self.defense_state, self.attack_iter,
                self.service_state,
            )

        r = start_round
        while r < cfg.rounds:
            profiler.round_start(r)  # window mode: open trace entering [A, B)
            lowerings_before = self.retrace.count("round_fn")
            t0 = time.perf_counter()
            with obs.span("round", round=r) as sp, profiler.step(r):
                variance = self.run_round(r)
                jax.block_until_ready(self.flat_params)
                # True exactly when this call traced/compiled (round 0 of a
                # fresh jit, or a steady-state retrace — which the harness
                # audit flags) so span timings separate compile from
                # steady-state without a second warmup pass
                compiled = self.retrace.count("round_fn") > lowerings_before
                sp["compiled"] = compiled
            dt = time.perf_counter() - t0
            with obs.span("eval", stage="round", round=r + 1), \
                    profiler.phase("eval"):
                (tr_loss, tr_acc), (va_loss, va_acc) = eval_pair()
            if rollback_armed:
                # guard BEFORE the record appends: a tripped round
                # contributes nothing to the paths/event stream except the
                # rollback event itself
                var_f = float(variance)
                reason = None
                if not (
                    math.isfinite(tr_loss) and math.isfinite(va_loss)
                    and math.isfinite(var_f)
                ):
                    reason = "non_finite"
                elif (
                    self.defense is not None
                    and cfg.rollback_cusum > 0.0
                    and float(np.asarray(self.last_defense_metrics)[4])
                    >= cfg.rollback_cusum
                ):
                    reason = "cusum_spike"
                elif len(recent_val) >= 3:
                    med = sorted(recent_val)[len(recent_val) // 2]
                    if va_loss > cfg.rollback_loss_factor * max(med, 1e-3):
                        reason = "loss_spike"
                if (
                    reason is not None
                    and snapshot is not None
                    and self._rollbacks_done < cfg.rollback_max
                ):
                    if self.flight_recorder is not None:
                        # capture the DIVERGED round's detector carry
                        # before the restore wipes it — this is the state
                        # the flight dump exists to preserve
                        det_s, pol_s = self.defense_state
                        self.flight_recorder.record(
                            r,
                            detector_state=det_s,
                            policy_state=pol_s,
                            defense_metrics=self.last_defense_metrics,
                            forensic_rows=np.asarray(
                                self.last_forensic_metrics
                            ),
                            summary={
                                "val_loss": va_loss,
                                "diverged": True,
                                "reason": reason,
                            },
                        )
                    host_state, shardings, snap_round = snapshot
                    (
                        self.flat_params, self.server_opt_state,
                        self.client_m, self.fault_state, self.defense_state,
                        self.attack_iter, self.service_state,
                    ) = jax.tree.map(jax.device_put, host_state, shardings)
                    avail, widen = self.service_state
                    self.service_state = (
                        avail, widen * jnp.float32(cfg.rollback_widen)
                    )
                    self._rollbacks_done += 1
                    # epoch-salting the round keys (run_round) breaks the
                    # replay of the diverging draws; same shapes/dtypes, so
                    # the jitted program does not retrace
                    self._rollback_epoch = self._rollbacks_done
                    obs.emit(
                        "rollback", round=r, restored_round=snap_round,
                        reason=reason, epoch=self._rollback_epoch,
                        widen=float(widen) * cfg.rollback_widen,
                    )
                    if self.flight_recorder is not None:
                        # exactly one flight dump per guard trip, adjacent
                        # to the rollback event it explains
                        self.flight_recorder.dump(r, reason, obs=obs)
                    log(
                        f"[rollback {self._rollbacks_done}"
                        f"/{cfg.rollback_max}] round {r + 1} diverged "
                        f"({reason}); restored round {snap_round}, trim "
                        f"widened x{cfg.rollback_widen:.2f}"
                    )
                    profiler.round_end(r)
                    continue
            paths["trainLossPath"].append(tr_loss)
            paths["trainAccPath"].append(tr_acc)
            paths["valLossPath"].append(va_loss)
            paths["valAccPath"].append(va_acc)
            paths["variencePath"].append(float(variance))
            paths["roundsPerSec"].append(1.0 / dt)
            var_str = (
                f" var={cfg.noise_var:.2e}" if cfg.noise_var is not None else ""
            )
            fault_metrics = None
            if self.fault is not None:
                dropped, erased, corrupt, eff_k = (
                    float(v) for v in np.asarray(self.last_fault_metrics)
                )
                paths["faultDroppedPath"].append(dropped)
                paths["faultErasedPath"].append(erased)
                paths["faultCorruptPath"].append(corrupt)
                paths["effectiveKPath"].append(eff_k)
                fault_metrics = {
                    "dropped": dropped,
                    "erased": erased,
                    "corrupt": corrupt,
                    "effective_k": eff_k,
                }
                var_str += (
                    f" effK={eff_k:.0f} drop={dropped:.0f} "
                    f"erase={erased:.0f} corrupt={corrupt:.0f}"
                )
            service_metrics = None
            if cfg.service == "on":
                avail_m, absent_m, late_m, eff_k = (
                    float(v) for v in np.asarray(self.last_service_metrics)
                )
                paths["serviceAvailPath"].append(avail_m)
                paths["serviceAbsentPath"].append(absent_m)
                paths["serviceLatePath"].append(late_m)
                paths["effectiveKPath"].append(eff_k)
                service_metrics = {
                    "available": avail_m,
                    "absent": absent_m,
                    "late": late_m,
                    "effective_k": eff_k,
                }
                obs.emit("participation", round=r, **service_metrics)
                var_str += (
                    f" avail={avail_m:.0f} effK={eff_k:.0f} "
                    f"late={late_m:.0f}"
                )
            if self.defense is not None:
                dmetrics = defense_lib.events.round_metrics(
                    self.last_defense_metrics
                )
                for dkey, path_key in defense_lib.events.PATH_KEYS.items():
                    paths[path_key].append(dmetrics[dkey])
                agg_name = defense_lib.events.active_agg(
                    self.defense.mode, self.defense.ladder,
                    int(dmetrics["rung"]), cfg.agg,
                )
                defense_lib.events.emit_round(
                    obs, r, mode=self.defense.mode, agg=agg_name,
                    metrics=dmetrics, prev_rung=prev_rung,
                )
                prev_rung = int(dmetrics["rung"])
                var_str += (
                    f" rung={int(dmetrics['rung'])}({agg_name}) "
                    f"flag={dmetrics['flagged']:.0f}"
                )
            if self._forensics_on and (
                obs.enabled or self.flight_recorder is not None
            ):
                # flag provenance: the round's top-M matrix -> client_flag
                # events (deduped, "top" mode keeps only flagged rows) and
                # the flight-recorder ring.  Host-side reads only, after
                # the round's block_until_ready barrier.
                forensic_rows = np.asarray(self.last_forensic_metrics)
                if obs.enabled:
                    forensics_lib.emit_round_flags(
                        obs, r, forensic_rows, mode=cfg.forensics
                    )
                if self.flight_recorder is not None:
                    det_s, pol_s = self.defense_state
                    self.flight_recorder.record(
                        r,
                        detector_state=det_s,
                        policy_state=pol_s,
                        defense_metrics=self.last_defense_metrics,
                        forensic_rows=forensic_rows,
                        summary={
                            "val_loss": va_loss,
                            "val_acc": va_acc,
                            "variance": float(variance),
                        },
                    )
            obs.round(
                r,
                train_loss=tr_loss,
                train_acc=tr_acc,
                val_loss=va_loss,
                val_acc=va_acc,
                variance=float(variance),
                round_secs=dt,
                rounds_per_sec=1.0 / dt,
                compiled=compiled,
                fault_metrics=fault_metrics,
                service_metrics=service_metrics,
                # per-round watermark (device allocator stats, or host RSS
                # on backends without memory_stats) — host-side reads only,
                # after the existing block_until_ready barrier
                memory=obs_lib.device_memory() if obs.enabled else None,
            )
            log(
                f"[{r + 1}/{cfg.rounds}](interval: {cfg.display_interval}) "
                f"train: loss={tr_loss:.4f} acc={tr_acc:.4f} "
                f"val: loss={va_loss:.4f} acc={va_acc:.4f}{var_str}"
            )
            if rollback_armed:
                recent_val.append(va_loss)
                if len(recent_val) > 8:
                    recent_val.pop(0)
                # snapshot BEFORE checkpoint_fn: a corrupting checkpoint
                # hook (tests force divergence through it) must not be able
                # to poison the restore point.  copy=True is load-bearing:
                # np.asarray of a CPU jax array can be a zero-copy VIEW of
                # the device buffer, and every carry slot is DONATED to the
                # next round's call — the allocator reuses the memory under
                # the view and the "snapshot" silently rots (observed as
                # garbage restores under the pop-mesh engine, whose extra
                # collective buffers change the reuse pattern)
                state = _state_tuple()
                snapshot = (
                    jax.tree.map(lambda x: np.array(x, copy=True), state),
                    jax.tree.map(lambda x: x.sharding, state),
                    r + 1,
                )
            if checkpoint_fn is not None:
                with obs.span("checkpoint", round=r + 1), \
                        profiler.phase("checkpoint"):
                    checkpoint_fn(r + 1, self)
            profiler.round_end(r)  # window mode: close trace leaving [A, B)
            r += 1
        return paths

    def _train_multi(
        self,
        paths: Dict[str, List[float]],
        evals: tuple,
        eval_pair: Callable,
        prev_rung: Optional[int],
        log: Callable[[str], None],
        checkpoint_fn: Optional[Callable[[int, "FedTrainer"], None]],
        start_round: int,
        obs: "obs_lib.Observability",
        profiler: "obs_lib.Profiler",
    ) -> Dict[str, List[float]]:
        """The R>1 dispatch-tier driver: ``ceil(rounds/R)`` multi-round
        scans, with the host rim (record appends, event emission, eval,
        divergence guard, checkpoints) folded at dispatch exits.

        Granularity contract (docs/DESIGN.md "Exact vs degraded"):

        * eval runs at dispatch boundaries (every boundary by default;
          every ``eval_interval`` rounds when set) and the boundary values
          are replicated into the dispatch's per-round record entries —
          per-round eval does not exist because the params between scanned
          rounds never reach the host;
        * the warm-rollback divergence guard (``--dispatch-mode degraded``
          opt-in) fires at dispatch exits and restores the previous
          BOUNDARY snapshot, re-running the whole dispatch under
          epoch-salted keys;
        * checkpoints land at sync boundaries, so resume granularity is R
          rounds;
        * per-round metric rows (variance, fault/defense/service/forensic
          columns) keep EXACT per-round fidelity — they come out of the
          scan stacked ``[n, ...]`` and are bit-equal to the
          :meth:`run_rounds` oracle;
        * ``roundsPerSec`` entries report the amortized per-round rate
          ``n / dt`` of the dispatch that produced them.

        With ``--dispatch-prefetch on``, a boundary with no sync work (no
        eval due, no guard, no flight recorder) defers its host fold until
        the NEXT dispatch has launched, so record/event work overlaps
        device compute (the stacked scan outputs are fresh buffers — only
        the 7 carry slots are donated — so they survive the next launch).
        A resumed run may open with one alignment dispatch and close with
        one tail dispatch; each distinct scan length is one extra lowering
        of ``multi_round_fn``, which the harness retrace audit expects."""
        cfg = self.cfg
        tr_loss, tr_acc, va_loss, va_acc = evals
        R = cfg.rounds_per_dispatch
        eval_every = cfg.eval_interval or R
        prefetch = cfg.dispatch_prefetch == "on"
        rollback_armed = cfg.service == "on" and cfg.rollback == "on"
        snapshot = None
        recent_val: List[float] = []

        def _state_tuple():
            return (
                self.flat_params, self.server_opt_state, self.client_m,
                self.fault_state, self.defense_state, self.attack_iter,
                self.service_state,
            )

        def fold_dispatch(r0, n, t0, dt, compiled, outs):
            """Fold one dispatch's stacked [n, ...] outputs into the
            per-round record paths and event stream.  ``dt`` is None for
            a deferred (prefetched) fold — measured here instead, after
            the blocking host conversion of the stacked outputs."""
            nonlocal prev_rung
            variances, fms, dms, sms, fos = outs
            var_np = np.asarray(variances)
            fault_np = (
                np.asarray(fms) if self.fault is not None else None
            )
            defense_np = (
                np.asarray(dms) if self.defense is not None else None
            )
            service_np = (
                np.asarray(sms) if cfg.service == "on" else None
            )
            forensic_np = (
                np.asarray(fos) if self._forensics_on else None
            )
            if dt is None:
                dt = time.perf_counter() - t0
            rps = n / dt
            memory = obs_lib.device_memory() if obs.enabled else None
            var_str = ""
            for i in range(n):
                rr = r0 + i
                paths["trainLossPath"].append(tr_loss)
                paths["trainAccPath"].append(tr_acc)
                paths["valLossPath"].append(va_loss)
                paths["valAccPath"].append(va_acc)
                paths["variencePath"].append(float(var_np[i]))
                # amortized per-round rate of the dispatch (satellite
                # contract: rounds_per_sec_floor alerting and the harness
                # rounds/sec summary both stay meaningful under R>1)
                paths["roundsPerSec"].append(rps)
                var_str = (
                    f" var={cfg.noise_var:.2e}"
                    if cfg.noise_var is not None else ""
                )
                fault_metrics = None
                if fault_np is not None:
                    dropped, erased, corrupt, eff_k = (
                        float(v) for v in fault_np[i]
                    )
                    paths["faultDroppedPath"].append(dropped)
                    paths["faultErasedPath"].append(erased)
                    paths["faultCorruptPath"].append(corrupt)
                    paths["effectiveKPath"].append(eff_k)
                    fault_metrics = {
                        "dropped": dropped,
                        "erased": erased,
                        "corrupt": corrupt,
                        "effective_k": eff_k,
                    }
                    var_str += (
                        f" effK={eff_k:.0f} drop={dropped:.0f} "
                        f"erase={erased:.0f} corrupt={corrupt:.0f}"
                    )
                service_metrics = None
                if service_np is not None:
                    avail_m, absent_m, late_m, eff_k = (
                        float(v) for v in service_np[i]
                    )
                    paths["serviceAvailPath"].append(avail_m)
                    paths["serviceAbsentPath"].append(absent_m)
                    paths["serviceLatePath"].append(late_m)
                    paths["effectiveKPath"].append(eff_k)
                    service_metrics = {
                        "available": avail_m,
                        "absent": absent_m,
                        "late": late_m,
                        "effective_k": eff_k,
                    }
                    obs.emit("participation", round=rr, **service_metrics)
                    var_str += (
                        f" avail={avail_m:.0f} effK={eff_k:.0f} "
                        f"late={late_m:.0f}"
                    )
                if defense_np is not None:
                    dmetrics = defense_lib.events.round_metrics(
                        defense_np[i]
                    )
                    for dkey, path_key in (
                        defense_lib.events.PATH_KEYS.items()
                    ):
                        paths[path_key].append(dmetrics[dkey])
                    agg_name = defense_lib.events.active_agg(
                        self.defense.mode, self.defense.ladder,
                        int(dmetrics["rung"]), cfg.agg,
                    )
                    defense_lib.events.emit_round(
                        obs, rr, mode=self.defense.mode, agg=agg_name,
                        metrics=dmetrics, prev_rung=prev_rung,
                    )
                    prev_rung = int(dmetrics["rung"])
                    var_str += (
                        f" rung={int(dmetrics['rung'])}({agg_name}) "
                        f"flag={dmetrics['flagged']:.0f}"
                    )
                if forensic_np is not None and obs.enabled:
                    forensics_lib.emit_round_flags(
                        obs, rr, forensic_np[i], mode=cfg.forensics
                    )
                obs.round(
                    rr,
                    train_loss=tr_loss,
                    train_acc=tr_acc,
                    val_loss=va_loss,
                    val_acc=va_acc,
                    variance=float(var_np[i]),
                    round_secs=dt / n,
                    rounds_per_sec=rps,
                    compiled=compiled,
                    fault_metrics=fault_metrics,
                    service_metrics=service_metrics,
                    memory=memory,
                )
            if forensic_np is not None and self.flight_recorder is not None:
                # R-boundary forensics granularity: ONE flight-recorder
                # entry per dispatch, carrying the exit-round detector
                # carry and the last stacked forensic rows (the per-round
                # carries never reach the host under a scan)
                det_s, pol_s = self.defense_state
                self.flight_recorder.record(
                    r0 + n - 1,
                    detector_state=det_s,
                    policy_state=pol_s,
                    defense_metrics=self.last_defense_metrics,
                    forensic_rows=forensic_np[-1],
                    summary={
                        "val_loss": va_loss,
                        "val_acc": va_acc,
                        "variance": float(var_np[-1]),
                    },
                )
            log(
                f"[{r0 + n}/{cfg.rounds}]"
                f"(interval: {cfg.display_interval}, dispatch: {n}) "
                f"train: loss={tr_loss:.4f} acc={tr_acc:.4f} "
                f"val: loss={va_loss:.4f} acc={va_acc:.4f}{var_str}"
            )

        r = start_round
        pending = None  # deferred fold: (r0, n, t0, compiled, outs)
        while r < cfg.rounds:
            # alignment dispatch on an unaligned resume, tail dispatch on
            # an unaligned end — each a distinct scan length (extra
            # lowering), every steady dispatch exactly R rounds
            rem = r % R
            n = min(R - rem if rem else R, cfg.rounds - r)
            end = r + n
            profiler.round_start(r)
            lowerings_before = self.retrace.count("multi_round_fn")
            t0 = time.perf_counter()
            with obs.span("dispatch", round=r, rounds=n) as sp, \
                    profiler.step(r):
                outs = self.run_rounds_stacked(r, n)
                compiled = (
                    self.retrace.count("multi_round_fn") > lowerings_before
                )
                sp["compiled"] = compiled
            if pending is not None:
                # double buffer: fold the PREVIOUS dispatch's host rim
                # while the device runs this one
                p_r0, p_n, p_t0, p_compiled, p_outs = pending
                fold_dispatch(p_r0, p_n, p_t0, None, p_compiled, p_outs)
                pending = None
            # the armed guard needs a fresh boundary eval to judge (the
            # R=1 loop evaluates every round for the same reason)
            do_eval = (
                (end % eval_every == 0)
                or end >= cfg.rounds
                or rollback_armed
            )
            sync = (
                not prefetch
                or do_eval
                or rollback_armed
                or self.flight_recorder is not None
                or end >= cfg.rounds
            )
            if not sync:
                pending = (r, n, t0, compiled, outs)
                profiler.round_end(r)
                r = end
                continue
            jax.block_until_ready(self.flat_params)
            dt = time.perf_counter() - t0
            if do_eval:
                with obs.span("eval", stage="round", round=end), \
                        profiler.phase("eval"):
                    (tr_loss, tr_acc), (va_loss, va_acc) = eval_pair()
            if rollback_armed:
                # R-boundary divergence guard (degraded granularity, the
                # --dispatch-mode degraded opt-in): judged on the
                # dispatch's EXIT round; a trip discards the whole
                # dispatch and re-runs it from the previous boundary
                # snapshot under epoch-salted keys
                var_f = float(np.asarray(outs[0])[-1])
                reason = None
                if not (
                    math.isfinite(tr_loss) and math.isfinite(va_loss)
                    and math.isfinite(var_f)
                ):
                    reason = "non_finite"
                elif (
                    self.defense is not None
                    and cfg.rollback_cusum > 0.0
                    and float(np.asarray(self.last_defense_metrics)[4])
                    >= cfg.rollback_cusum
                ):
                    reason = "cusum_spike"
                elif len(recent_val) >= 3:
                    med = sorted(recent_val)[len(recent_val) // 2]
                    if va_loss > cfg.rollback_loss_factor * max(med, 1e-3):
                        reason = "loss_spike"
                if (
                    reason is not None
                    and snapshot is not None
                    and self._rollbacks_done < cfg.rollback_max
                ):
                    if self.flight_recorder is not None:
                        det_s, pol_s = self.defense_state
                        self.flight_recorder.record(
                            end - 1,
                            detector_state=det_s,
                            policy_state=pol_s,
                            defense_metrics=self.last_defense_metrics,
                            forensic_rows=np.asarray(
                                self.last_forensic_metrics
                            ),
                            summary={
                                "val_loss": va_loss,
                                "diverged": True,
                                "reason": reason,
                            },
                        )
                    host_state, shardings, snap_round = snapshot
                    (
                        self.flat_params, self.server_opt_state,
                        self.client_m, self.fault_state,
                        self.defense_state, self.attack_iter,
                        self.service_state,
                    ) = jax.tree.map(jax.device_put, host_state, shardings)
                    avail, widen = self.service_state
                    self.service_state = (
                        avail, widen * jnp.float32(cfg.rollback_widen)
                    )
                    self._rollbacks_done += 1
                    self._rollback_epoch = self._rollbacks_done
                    obs.emit(
                        "rollback", round=end - 1,
                        restored_round=snap_round, reason=reason,
                        epoch=self._rollback_epoch,
                        widen=float(widen) * cfg.rollback_widen,
                    )
                    if self.flight_recorder is not None:
                        self.flight_recorder.dump(end - 1, reason, obs=obs)
                    log(
                        f"[rollback {self._rollbacks_done}"
                        f"/{cfg.rollback_max}] dispatch ending round {end} "
                        f"diverged ({reason}); restored round "
                        f"{snap_round}, trim widened "
                        f"x{cfg.rollback_widen:.2f}"
                    )
                    profiler.round_end(r)
                    continue
            fold_dispatch(r, n, t0, dt, compiled, outs)
            if rollback_armed:
                recent_val.append(va_loss)
                if len(recent_val) > 8:
                    recent_val.pop(0)
                # same donation hazard as the R=1 loop: copy=True or the
                # snapshot rots when the next dispatch reuses the buffers
                state = _state_tuple()
                snapshot = (
                    jax.tree.map(lambda x: np.array(x, copy=True), state),
                    jax.tree.map(lambda x: x.sharding, state),
                    end,
                )
            if checkpoint_fn is not None:
                with obs.span("checkpoint", round=end), \
                        profiler.phase("checkpoint"):
                    checkpoint_fn(end, self)
            profiler.round_end(r)
            r = end
        if pending is not None:
            # unreachable (run end is always a sync boundary), kept as a
            # belt so a future cadence change cannot silently drop a fold
            p_r0, p_n, p_t0, p_compiled, p_outs = pending
            fold_dispatch(p_r0, p_n, p_t0, None, p_compiled, p_outs)
        return paths

    @property
    def params(self):
        return flatten_lib.unflatten(self.flat_params, self.spec)
