"""Weight initialization matching the reference.

The reference initializes every Conv/Linear with xavier-normal scaled by the
relu gain (sqrt(2)) and constant bias 0.01
(``/root/reference/MNIST_Air_weight.py:92-95``).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax.nn import initializers as jinit

RELU_GAIN = math.sqrt(2.0)


def xavier_normal_relu(gain: float = RELU_GAIN):
    """Xavier-normal with gain: std = gain * sqrt(2 / (fan_in + fan_out)).

    Equivalent to ``variance_scaling`` with scale = gain^2, fan_avg, normal —
    matching ``nn.init.xavier_normal_(w, gain=calculate_gain('relu'))``.
    """
    return jinit.variance_scaling(
        scale=gain * gain, mode="fan_avg", distribution="normal"
    )


def bias_001(key, shape, dtype=jnp.float32):
    """Constant 0.01 bias (reference ``nn.init.constant_(m.bias, 0.01)``)."""
    return jnp.full(shape, 0.01, dtype)
