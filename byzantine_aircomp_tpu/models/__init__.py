from .cnn import CNN, make_cnn  # noqa: F401
from .mlp import MLP, make_mlp  # noqa: F401
from .resnet import ResNet18, make_resnet18  # noqa: F401
