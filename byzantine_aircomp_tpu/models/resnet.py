"""ResNet-18 (CIFAR-10 variant) for the BASELINE.json scale-up config.

Not present in the reference (its ``CNN`` is the largest model,
``/root/reference/MNIST_Air_weight.py:63-90``); BASELINE.json's config 5
targets "CIFAR-10 ResNet-18, K=1000, B=100".  Design choices for federated
TPU training:

* **GroupNorm instead of BatchNorm** — BN's running statistics don't commute
  with weight-space aggregation across clients (each client would carry its
  own stats, and robust aggregators like Krum would mix them incoherently);
  GroupNorm is stateless and is the standard substitution in federated
  vision models.
* CIFAR stem: 3x3 conv, no max-pool (standard ResNet-18-CIFAR).
* NHWC layout, bfloat16-friendly compute path via the ``dtype`` attribute.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..registry import MODELS
from .initializers import bias_001, xavier_normal_relu


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = partial(
            nn.Conv,
            kernel_size=(3, 3),
            use_bias=False,
            kernel_init=xavier_normal_relu(),
            dtype=self.dtype,
        )
        norm = partial(nn.GroupNorm, num_groups=8, dtype=self.dtype)

        residual = x
        y = conv(self.features, strides=(self.strides, self.strides))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.features)(y)
        y = norm()(y)

        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features,
                kernel_size=(1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
                kernel_init=xavier_normal_relu(),
                dtype=self.dtype,
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    dtype: Any = jnp.float32
    # stem width; stage i uses width * 2**i (64 = the standard ResNet-18).
    # Smaller widths keep the topology for CPU-scaled trajectory runs
    # (docs/RESULTS.md states the scaling wherever they appear).
    width: int = 64
    # rematerialize each residual block's activations in the backward pass
    # (jax.checkpoint via nn.remat): the federated trainer vmaps the local
    # step over K clients, so activation memory scales K-fold and is THE
    # single-chip ceiling at ResNet scale (docs/PERFORMANCE.md) — remat
    # trades one extra forward per block for an O(depth) cut in saved
    # activations, the classic HBM-for-FLOPs exchange.
    remat: bool = False

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        if self.width % 8:
            raise ValueError(
                f"ResNet18 width must be a multiple of 8 (GroupNorm groups), "
                f"got {self.width}"
            )
        # nn.remat returns a renamed class (CheckpointBasicBlock) and flax
        # derives both the param-tree keys and the init RNG folds from
        # module names — so blocks carry EXPLICIT names matching the
        # non-remat auto-naming, keeping init bit-identical and
        # checkpoints interchangeable whether remat is on or off
        block_cls = nn.remat(BasicBlock) if self.remat else BasicBlock
        x = nn.Conv(
            self.width,
            kernel_size=(3, 3),
            use_bias=False,
            kernel_init=xavier_normal_relu(),
            dtype=self.dtype,
        )(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        n_block = 0
        for i, block_count in enumerate(self.stage_sizes):
            features = self.width * 2**i
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(
                    features, strides=strides, dtype=self.dtype,
                    name=f"BasicBlock_{n_block}",
                )(x)
                n_block += 1
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(
            self.num_classes,
            kernel_init=xavier_normal_relu(),
            bias_init=bias_001,
        )(x.astype(jnp.float32))


@MODELS.register("ResNet18", aliases=("resnet18",))
def make_resnet18(
    num_classes: int = 10, dtype=jnp.float32, width: int = 64,
    remat: bool = False, **_,
):
    return ResNet18(
        num_classes=num_classes, dtype=dtype, width=width, remat=remat
    )
