"""CNN matching the reference architecture.

Reference ``CNN`` (``/root/reference/MNIST_Air_weight.py:63-90``):
conv(1->32, 5x5, pad 2) + ReLU + maxpool2  ->  conv(32->64, 5x5, pad 2) +
ReLU + maxpool2  ->  fc(64*7*7 -> fc_width) + ReLU  ->  fc(fc_width -> C).
MNIST: fc_width=1024, C=10 (3,274,634 params).  EMNIST byclass: fc_width=2048,
C=62 (``EMNIST_Air_weight.py:80-82``).

Layout is NHWC (TPU-native) rather than the reference's NCHW; XLA maps the
5x5 convs onto the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..registry import MODELS
from .initializers import bias_001, xavier_normal_relu


class CNN(nn.Module):
    num_classes: int = 10
    fc_width: int = 1024

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]  # [B, H, W] -> [B, H, W, 1]
        conv = lambda feat: nn.Conv(
            feat,
            kernel_size=(5, 5),
            padding=2,
            kernel_init=xavier_normal_relu(),
            bias_init=bias_001,
            dtype=jnp.float32,
        )
        x = conv(32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = conv(64)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(
            self.fc_width,
            kernel_init=xavier_normal_relu(),
            bias_init=bias_001,
        )(x)
        x = nn.relu(x)
        return nn.Dense(
            self.num_classes,
            kernel_init=xavier_normal_relu(),
            bias_init=bias_001,
        )(x)


@MODELS.register("CNN", aliases=("cnn",))
def make_cnn(num_classes: int = 10, fc_width: int = 1024, **_):
    # EMNIST variant widens fc1 to 2048 (EMNIST_Air_weight.py:80-82)
    return CNN(num_classes=num_classes, fc_width=fc_width)
