"""MLP: despite the name, a single linear softmax-regression layer.

Mirrors the reference ``MLP`` (``/root/reference/MNIST_Air_weight.py:53-61``):
input flattened to [batch, H*W*C], one ``Linear(input_size, num_classes)``.
7,850 params for MNIST (784 -> 10), 48,670 for EMNIST byclass (784 -> 62).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..registry import MODELS
from .initializers import bias_001, xavier_normal_relu


class MLP(nn.Module):
    # flattens its input anyway, so the trainer may feed [B, features]
    # directly and skip the [B, H, W] re-tiling (TPU lane-dim waste)
    SPATIAL_INPUT = False

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(
            self.num_classes,
            kernel_init=xavier_normal_relu(),
            bias_init=bias_001,
            dtype=jnp.float32,
        )(x)


@MODELS.register("MLP", aliases=("mlp",))
def make_mlp(num_classes: int = 10, **_):
    return MLP(num_classes=num_classes)
