"""Name -> callable registries.

The reference dispatches CLI strings to functions with ``eval`` (see
``/root/reference/MNIST_Air_weight.py:433`` and ``:580``).  We keep the same
public names (``gm``, ``gm2``, ``mean``, ``trimmed_mean``, ``median``, ``Krum``,
``classflip``, ``dataflip``, ``weightflip`` ...) but resolve them through
explicit registries so the CLI surface is identical without executing
arbitrary strings.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional


class Registry:
    """A simple name -> object registry with decorator support.

    ``register`` accepts arbitrary static metadata keywords
    (``needs_honest_size``, ``supports_fused_epilogue``, ``owns_channel``,
    ``extra_args``, ...) stored per entry and shared by aliases, so gates
    that used to string-match names (the fused-epilogue dispatch, the
    channel prepass rule, the defense escalation-ladder validation) read
    one source of truth via :meth:`meta`.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Callable] = {}
        self._meta: Dict[str, dict] = {}

    def register(
        self,
        name: Optional[str] = None,
        *,
        aliases: Iterable[str] = (),
        **meta,
    ):
        def wrap(fn: Callable) -> Callable:
            key = name or fn.__name__
            if key in self._entries:
                raise KeyError(f"duplicate {self.kind} registration: {key!r}")
            self._entries[key] = fn
            self._meta[key] = meta
            for alias in aliases:
                if alias in self._entries:
                    raise KeyError(f"duplicate {self.kind} alias: {alias!r}")
                self._entries[alias] = fn
                self._meta[alias] = meta
            return fn

        return wrap

    def get(self, name: str) -> Callable:
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(
                f"unknown {self.kind} {name!r}; known: {known}"
            ) from None

    def meta(self, name: str) -> dict:
        """Static metadata attached at registration ({} when none given).
        Raises like :meth:`get` on unknown names so a typo can't read as
        an all-defaults entry."""
        self.get(name)
        return self._meta.get(name, {})

    def names(self):
        return sorted(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries


AGGREGATORS = Registry("aggregator")
ATTACKS = Registry("attack")
FAULTS = Registry("fault")
MODELS = Registry("model")
DATASETS = Registry("dataset")
OPTIMIZERS = Registry("optimizer")
