"""byzantine_aircomp_tpu — TPU-native Byzantine-resilient over-the-air
federated learning.

A ground-up JAX/XLA/Pallas re-design of the capability set of
goldenBill/Byzantine_AirComp (arXiv:2105.10883): K federated clients taking
local SGD steps, a simulated Rayleigh-fading AirComp wireless channel, and
robust server aggregation (geometric median, trimmed mean, median, Krum) —
with the K-client loop vmapped and sharded over a TPU device mesh instead of
time-multiplexed in Python.
"""

__version__ = "0.1.0"

from .registry import AGGREGATORS, ATTACKS, DATASETS, MODELS, OPTIMIZERS  # noqa: F401

# Importing these packages registers the built-in aggregators/attacks/models/
# datasets as a side effect — without this, `import byzantine_aircomp_tpu`
# would expose empty registries.
from . import data, fed, models, ops  # noqa: E402,F401
