"""Param-pytree <-> flat-vector plumbing.

TPU-native replacement for the reference's list-based flatten/unflatten
(``/root/reference/MNIST_Air_weight.py:206-218``): instead of per-parameter
Python loops we precompute a static :class:`FlatSpec` once per model and use
fused ``concatenate``/``dynamic_slice`` ops, so flatten/unflatten trace into a
handful of XLA reshapes that fuse away entirely under ``jit``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class FlatSpec:
    """Static description of a params pytree's flattened layout."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    dtypes: Tuple[Any, ...]
    total: int


def make_flat_spec(params) -> FlatSpec:
    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    dtypes = tuple(l.dtype for l in leaves)
    return FlatSpec(treedef, shapes, sizes, offsets, dtypes, int(sum(sizes)))


def _check_spec(leaves, treedef, spec: FlatSpec):
    if treedef != spec.treedef or tuple(tuple(l.shape) for l in leaves) != spec.shapes:
        raise ValueError(
            "params pytree does not match FlatSpec: "
            f"got treedef {treedef} with shapes {[tuple(l.shape) for l in leaves]}, "
            f"spec has {spec.treedef} with shapes {list(spec.shapes)}"
        )


def flatten(params, spec: FlatSpec) -> jnp.ndarray:
    """Pytree -> [d] float32 vector (reference ``flatten_list`` row)."""
    leaves, treedef = jax.tree.flatten(params)
    _check_spec(leaves, treedef, spec)
    return jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])


def unflatten(vector: jnp.ndarray, spec: FlatSpec):
    """[d] vector -> pytree (reference ``unflatten_vector``)."""
    leaves = [
        jax.lax.dynamic_slice_in_dim(vector, off, size).reshape(shape).astype(dt)
        for off, size, shape, dt in zip(spec.offsets, spec.sizes, spec.shapes, spec.dtypes)
    ]
    return jax.tree.unflatten(spec.treedef, leaves)


def flatten_stack(params_stacked, spec: FlatSpec) -> jnp.ndarray:
    """Client-stacked pytree (leading K axis on every leaf) -> [K, d] matrix.

    Replaces the reference's ``flatten_list`` over a Python list of per-client
    parameter lists (``MNIST_Air_weight.py:206-209``); here the K axis is a
    real array axis so the result is produced by K-preserving reshapes only.
    """
    leaves, treedef = jax.tree.flatten(params_stacked)
    k = leaves[0].shape[0]
    _check_spec([l[0] for l in leaves], treedef, spec)
    return jnp.concatenate(
        [l.reshape(k, -1).astype(jnp.float32) for l in leaves], axis=1
    )


def unflatten_stack(matrix: jnp.ndarray, spec: FlatSpec):
    """[K, d] -> pytree with leading K axis on every leaf."""
    k = matrix.shape[0]
    leaves = [
        jax.lax.dynamic_slice_in_dim(matrix, off, size, axis=1)
        .reshape((k,) + shape)
        .astype(dt)
        for off, size, shape, dt in zip(spec.offsets, spec.sizes, spec.shapes, spec.dtypes)
    ]
    return jax.tree.unflatten(spec.treedef, leaves)
