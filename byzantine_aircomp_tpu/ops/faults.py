"""Non-adversarial fault injection — the imperfect-world counterpart of
:mod:`.attacks`.

The paper's threat model is adversarial clients over an otherwise ideal
PHY; a deployed over-the-air FL system also fails NON-adversarially (BEV-SGD,
arXiv:2110.09660; zero-trust OTA-FL, arXiv:2503.18284): stragglers deliver
stale updates, deep fades erase clients mid-round, CSI is estimated with
error, and a crashed client emits NaN into an analog superposition sum.  A
:class:`FaultSpec` mirrors :class:`.attacks.AttackSpec` — a frozen, registered
bundle of pure per-round transforms — with four orthogonal axes:

* **dropout/straggler** (``dropout_prob``): each round a client fails to
  deliver with probability p; the server replays that client's last
  DELIVERED update from a carried [K, d] buffer (initialized to the global
  init, so a round-0 dropout replays "no progress", not garbage).
* **deep-fade erasure** (``fade_floor``): clients whose ``|h|^2`` falls below
  the truncation threshold are in outage — their rows become NaN ("nothing
  received") and the aggregators' finite-row exclusion drops them.
* **CSI estimation error** (``csi_std``): zero-forcing equalization against
  an estimate ``|h_hat| = |h| * exp(eps)`` scales the delivered row by
  ``exp(-eps)``.  Errors are CORRELATED in time via a Gilbert-Elliott
  good/bad channel state per client (a [K] bool carried through the scan):
  in the bad state the error std widens by ``ge_bad_mult``.
* **payload corruption** (``corrupt_prob``/``corrupt_mode``/``corrupt_size``):
  up to ``corrupt_size`` of the FIRST (honest — Byzantine rows are the last
  ``byz_size``) clients emit NaN / Inf / saturated floats with probability p
  per round, modeling a crashed or overflowed sender rather than an attacker.

Faults COMPOSE with attacks: dropout replay happens before the message
attack (the stale buffer holds what clients sent, never what an omniscient
attacker rewrote), corruption and channel impairments after it (they hit the
transmitted stack, Byzantine rows included).  All state is jit-carried so the
multi-round scan compiles once; with ``FedConfig.fault`` unset none of this
code is traced and the round program is bit-identical to the fault-free one.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..registry import FAULTS
from . import channel

CORRUPT_MODES = ("nan", "inf", "saturate")
# "saturate" emits the largest-magnitude finite f32 — a clipped/overflowed
# sender.  Finite, so it exercises the aggregators' ROBUSTNESS (distance
# filters), not their finite-row exclusion.
SATURATE_VALUE = 3.0e38


@dataclass(frozen=True)
class FaultSpec:
    """A named non-adversarial failure mode (see module docstring).

    All axes default OFF so any single registered fault stays orthogonal;
    ``resolve`` overlays per-run config overrides with ``dataclasses.replace``,
    which is how compound scenarios (the ``chaos`` preset) are built.
    """

    name: str
    # dropout/straggler
    dropout_prob: float = 0.0
    # deep-fade erasure: outage threshold on |h|^2 (0 = off)
    fade_floor: float = 0.0
    # CSI estimation error (log-magnitude std; 0 = perfect CSI)
    csi_std: float = 0.0
    # Gilbert-Elliott correlation of the CSI error: P(good->bad),
    # P(bad->good), and the bad-state std multiplier
    ge_p_gb: float = 0.0
    ge_p_bg: float = 1.0
    ge_bad_mult: float = 5.0
    # payload corruption
    corrupt_prob: float = 0.0
    corrupt_mode: str = "nan"
    corrupt_size: int = 0

    @property
    def needs_stale(self) -> bool:
        """Dropout carries the [K, d] last-delivered buffer."""
        return self.dropout_prob > 0.0

    @property
    def needs_ge(self) -> bool:
        """CSI error carries the [K] Gilbert-Elliott bad-state bools."""
        return self.csi_std > 0.0

    @property
    def has_transmission(self) -> bool:
        """Any post-attack (corruption / channel) impairment active."""
        return (
            self.corrupt_prob > 0.0
            or self.fade_floor > 0.0
            or self.csi_std > 0.0
        )

    def validate(self) -> "FaultSpec":
        for f in ("dropout_prob", "corrupt_prob", "ge_p_gb", "ge_p_bg"):
            v = getattr(self, f)
            assert 0.0 <= v <= 1.0, f"{f} must be in [0, 1], got {v}"
        assert self.fade_floor >= 0.0, (
            f"fade_floor must be >= 0, got {self.fade_floor}"
        )
        assert self.csi_std >= 0.0, (
            f"csi_std must be >= 0, got {self.csi_std}"
        )
        assert self.ge_bad_mult >= 1.0, (
            f"ge_bad_mult must be >= 1 (the bad state widens the error), "
            f"got {self.ge_bad_mult}"
        )
        assert self.corrupt_mode in CORRUPT_MODES, (
            f"corrupt_mode must be one of {CORRUPT_MODES}, "
            f"got {self.corrupt_mode!r}"
        )
        assert self.corrupt_size >= 0, (
            f"corrupt_size must be >= 0, got {self.corrupt_size}"
        )
        assert not (self.corrupt_prob > 0.0) or self.corrupt_size >= 1, (
            "corrupt_prob > 0 needs corrupt_size >= 1 faulty clients"
        )
        return self


# ----------------------------------------------------------------------
# registered failure scenarios (magnitudes are the documented defaults;
# every knob is overridable per-run via FedConfig)

FAULTS.register("dropout")(FaultSpec("dropout", dropout_prob=0.1))
FAULTS.register("deep_fade")(FaultSpec("deep_fade", fade_floor=0.05))
FAULTS.register("csi")(
    FaultSpec("csi", csi_std=0.2, ge_p_gb=0.1, ge_p_bg=0.5)
)
FAULTS.register("corrupt")(
    FaultSpec("corrupt", corrupt_prob=0.05, corrupt_mode="nan", corrupt_size=1)
)
FAULTS.register("chaos")(
    FaultSpec(
        "chaos",
        dropout_prob=0.1,
        fade_floor=0.05,
        csi_std=0.2,
        ge_p_gb=0.1,
        ge_p_bg=0.5,
        corrupt_prob=0.05,
        corrupt_mode="nan",
        corrupt_size=1,
    )
)


def resolve(
    name: Optional[str], overrides: Optional[dict] = None
) -> Optional[FaultSpec]:
    """Look up a fault by name and overlay non-None config overrides;
    None means a fault-free (ideal) deployment."""
    if name is None:
        assert not overrides, (
            f"fault knob overrides {sorted(overrides)} require --fault"
        )
        return None
    spec = FAULTS.get(name)
    if overrides:
        spec = dataclasses.replace(spec, **overrides)
    return spec.validate()


# ----------------------------------------------------------------------
# carried fault state

FaultState = Tuple  # (stale [K, d] | (), ge_bad [K] bool | ())


def init_state(spec: FaultSpec, k: int, flat_params: jnp.ndarray) -> FaultState:
    """Initial scan-carried fault state for K clients.

    The stale buffer starts as K copies of the initial global params: a
    client that drops out before ever delivering replays "I am still at the
    global init", which is the semantically correct zero-progress update.
    Unused axes carry ``()`` so the fault-free parts of the program stay
    cost-free (same idiom as the trainer's ``client_m``).
    """
    stale = (
        jnp.zeros((k, flat_params.shape[0]), jnp.float32) + flat_params[None, :]
        if spec.needs_stale
        else ()
    )
    ge_bad = jnp.zeros((k,), bool) if spec.needs_ge else ()
    return (stale, ge_bad)


def apply_dropout(
    spec: FaultSpec, key: jax.Array, w_stack: jnp.ndarray, stale
):
    """Straggler/dropout replay, PRE-attack.

    Returns ``(delivered, new_stale, n_dropped)``: dropped rows are replaced
    by that client's last delivered update, and the buffer advances to the
    delivered stack — so a client dropped for several consecutive rounds
    keeps replaying its last success, and the buffer never absorbs an
    attacked or corrupted row (it is updated before those stages run).
    """
    if not spec.needs_stale:
        return w_stack, stale, jnp.float32(0.0)
    k = w_stack.shape[0]
    dropped = jax.random.bernoulli(key, spec.dropout_prob, (k,))
    delivered = jnp.where(dropped[:, None], stale, w_stack)
    return delivered, delivered, jnp.sum(dropped).astype(jnp.float32)


def apply_transmission(
    spec: FaultSpec, key: jax.Array, w_stack: jnp.ndarray, ge_bad,
    row_offset=0,
):
    """Post-attack transmission impairments: payload corruption, then the
    channel (CSI error + deep-fade erasure).

    Returns ``(w_stack, new_ge_bad, n_erased, n_corrupt)``.  Corruption hits
    the FIRST ``corrupt_size`` rows (the honest side — a crashed sender is a
    fault, not an attacker); channel impairments hit every row.

    ``row_offset`` is the global client index of row 0 — nonzero only under
    cohort streaming, where ``w_stack`` is one [cohort, d] chunk and
    corruption eligibility must be judged against GLOBAL client positions
    (the trainer passes the matching ``ge_bad`` slice and a per-cohort
    ``fold_in`` key; everything else here is already row-local).  May be a
    traced scalar.
    """
    k = w_stack.shape[0]
    k_corrupt, k_fade, k_csi, k_ge = jax.random.split(key, 4)
    n_corrupt = jnp.float32(0.0)
    n_erased = jnp.float32(0.0)

    if spec.corrupt_prob > 0.0:
        eligible = row_offset + jnp.arange(k) < spec.corrupt_size
        crashed = jnp.logical_and(
            eligible, jax.random.bernoulli(k_corrupt, spec.corrupt_prob, (k,))
        )
        bad = {
            "nan": jnp.nan, "inf": jnp.inf, "saturate": SATURATE_VALUE,
        }[spec.corrupt_mode]
        w_stack = jnp.where(
            crashed[:, None], jnp.asarray(bad, w_stack.dtype), w_stack
        )
        n_corrupt = jnp.sum(crashed).astype(jnp.float32)

    if spec.fade_floor > 0.0 or spec.csi_std > 0.0:
        h_r, h_i = channel.rayleigh_fade(k_fade, k)
        h_sq = h_r**2 + h_i**2
        if spec.csi_std > 0.0:
            k_recover, k_degrade = jax.random.split(k_ge)
            ge_bad = jnp.where(
                ge_bad,
                ~jax.random.bernoulli(k_recover, spec.ge_p_bg, (k,)),
                jax.random.bernoulli(k_degrade, spec.ge_p_gb, (k,)),
            )
            std = spec.csi_std * jnp.where(ge_bad, spec.ge_bad_mult, 1.0)
            scale = channel.csi_error_scale(k_csi, k, std)
            w_stack = w_stack * scale[:, None].astype(w_stack.dtype)
        if spec.fade_floor > 0.0:
            erased = channel.deep_fade_mask(h_sq, spec.fade_floor)
            w_stack = jnp.where(
                erased[:, None], jnp.asarray(jnp.nan, w_stack.dtype), w_stack
            )
            n_erased = jnp.sum(erased).astype(jnp.float32)

    return w_stack, ge_bad, n_erased, n_corrupt


def apply_deadline(
    key: jax.Array, w_stack: jnp.ndarray, arrived, straggler_prob: float
):
    """Service-round deadline close: the round ends NOW with whatever
    effective-K made it.

    ``arrived`` is the [k] bool availability of the drawn participants at
    draw time (a departed client was still drawn — the server scheduled
    it — but its update never lands).  On top of that, each arrived row
    independently misses the deadline with ``straggler_prob`` (static; 0
    traces no bernoulli).  Missed rows are erased to NaN — the same
    "nothing received" convention the fault channel uses, so the degraded
    aggregators and effective-K accounting downstream apply unchanged.

    Returns ``(w_stack, n_absent, n_late)`` with f32 scalar counts:
    absent = drawn-but-offline, late = arrived but past deadline.
    """
    k = w_stack.shape[0]
    if not isinstance(straggler_prob, (int, float)):
        # traced probability (the experiment-axis batch runner feeds a
        # per-experiment knob): always trace the bernoulli — at p == 0.0
        # it draws uniform < 0.0 == all-False, numerically identical to
        # the static zero branch below
        late = jnp.logical_and(
            arrived, jax.random.bernoulli(key, straggler_prob, (k,))
        )
    elif straggler_prob > 0.0:
        late = jnp.logical_and(
            arrived, jax.random.bernoulli(key, straggler_prob, (k,))
        )
    else:
        late = jnp.zeros((k,), bool)
    missed = jnp.logical_or(late, jnp.logical_not(arrived))
    w_stack = jnp.where(
        missed[:, None], jnp.asarray(jnp.nan, w_stack.dtype), w_stack
    )
    n_absent = jnp.sum(jnp.logical_not(arrived)).astype(jnp.float32)
    n_late = jnp.sum(late).astype(jnp.float32)
    return w_stack, n_absent, n_late
