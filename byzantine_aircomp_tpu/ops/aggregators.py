"""Robust aggregation rules over the [K, d] client-weight stack.

TPU-native re-design of the reference aggregators
(``/root/reference/MNIST_Air_weight.py:131-204``):

* ``gm2`` — ideal geometric median (Weiszfeld).  The reference runs a Python
  ``for`` loop with a data-dependent early exit (``:173-183``); here it is a
  ``lax.while_loop`` so the whole iteration compiles into one XLA program and
  the [K, d] stack never leaves HBM.
* ``gm`` — AirComp geometric median: every Weiszfeld step computes its two
  sums (sum_i w_i/d_i and sum_i 1/d_i) *over the simulated air* via
  :func:`..channel.oma2` (``:145-159``).  The PRNG key is carried through the
  while-loop and split per iteration, since the iteration count is dynamic.
* ``mean`` / ``median`` / ``trimmed_mean`` — coordinatewise reductions
  (``:186-195``).  ``median`` follows torch's convention of returning the
  *lower* middle order statistic for even K (torch ``median(dim=0)``), which
  differs from ``jnp.median``'s midpoint average.
* ``krum`` / ``multi_krum`` — the K x K pairwise squared-distance matrix is
  computed as a Gram matrix (one [K,d] x [d,K] matmul, MXU-friendly at
  K=1000) instead of the reference's broadcasted [K,K,d] subtraction
  (``:199``), which would materialize K^2 * d elements.

Every aggregator is a pure function ``(wmatrix, **opts) -> [d]`` (krum
returns one row, like the reference).  All are jit/vmap/shard_map friendly.
"""

from __future__ import annotations

import math
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from ..registry import AGGREGATORS
from . import channel
from . import pallas_kernels
from . import shardctx
from .pallas_kernels import DIST_CLAMP, GM_THRESHOLD_FACTOR


def _centroid(wmatrix):
    return jnp.mean(wmatrix, axis=0)


def _finite_rows(wmatrix):
    """Per-row all-finite mask [K].  The iterative aggregators (gm/gm2/cclip)
    EXCLUDE non-finite rows — an overflowed Byzantine row is a point at
    infinity, whose Weiszfeld weight is 0 — instead of letting a single
    Inf/NaN coordinate poison every arithmetic pass (0*Inf, Inf-Inf)."""
    return jnp.all(jnp.isfinite(wmatrix), axis=1)


def _mask_rows(wmatrix, finite):
    """Non-finite rows selected to 0.  On the XLA paths this is built
    per-consumer so the select fuses into the reduction — no sanitized
    [K, d] copy persists at large d; only the fused-pallas path (small d by
    ``supports_fused``) materializes it once."""
    return jnp.where(finite[:, None], wmatrix, 0.0)


def _finite_centroid(wmatrix, finite):
    # the max(.., 1) only keeps THIS division defined; a stack with zero
    # finite rows is unsupported (the subsequent num/den step divides by
    # den = 0 and the aggregate is NaN regardless — config guarantees
    # honest rows exist, and honest rows are finite).  The f32 cast keeps
    # the ACCUMULATION f32 under --stack-dtype bf16 (fused into the reduce)
    return jnp.sum(
        _mask_rows(wmatrix, finite).astype(jnp.float32), axis=0
    ) / jnp.maximum(jnp.sum(finite), 1.0)


@AGGREGATORS.register("mean", streamable=True, extra_args=())
def mean(wmatrix: jnp.ndarray, *, degraded: bool = False, **_) -> jnp.ndarray:
    """Column mean (reference ``mean``, ``:186-187``).

    The f32 upcast keeps the ACCUMULATION f32 whatever the stack dtype
    (--stack-dtype bf16); XLA fuses the convert into the reduce, so a
    bf16 stack still pays only bf16 HBM reads.

    ``degraded`` (the fault-injection contract — see docs/DESIGN.md "Fault
    model"): average only the finite rows, so one NaN-emitting crashed
    client erases itself instead of the whole aggregate.  With zero finite
    rows the result is NaN and the trainer's receiver finite-guard keeps
    the previous global params."""
    if degraded:
        finite = _finite_rows(wmatrix)
        return jnp.where(
            jnp.sum(finite) > 0, _finite_centroid(wmatrix, finite), jnp.nan
        )
    return jnp.mean(wmatrix.astype(jnp.float32), axis=0)


# ---------------------------------------------------------------------------
# fused aggregation epilogue: selection instead of sort, optional channel fuse
#
# The sort-family aggregators (median / trimmed_mean) pay a full XLA bitonic
# sort over the [K, d] stack — >= 3 stack-sized HBM round trips — plus a
# standalone OMA channel pass before them.  With ``fused_epilogue`` the
# dispatch below replaces that with (a) the single-HBM-pass Pallas peel
# kernels when ``impl="pallas"`` fits the VMEM regime, or (b) an XLA
# order-statistic selection (32-step bisection over IEEE-754 total-order
# int32 keys) that beats the sort everywhere else; either way the OMA
# corruption (``oma_key``) folds into the same stack read instead of a
# separate pass.  Fallback matrix in docs/DESIGN.md: degraded mode, non-f32
# stacks, out-of-VMEM K, or an empty kept band all take the sort path
# (applying ``channel.oma`` first when the channel was deferred), which is
# bit-identical to the pre-fusion two-pass pipeline.


def _nth_smallest_keys(keys: jnp.ndarray, n) -> jnp.ndarray:
    """Per-column n-th smallest (0-indexed) int32 total-order key.

    32-step bisection on the key VALUE domain: each step counts
    ``keys <= mid`` per column, so the work is 32 cheap comparison passes
    instead of a full K-length sort — on CPU/GPU this is the fast
    realization of the selection epilogue (ties, +-Inf and positive NaN
    rank exactly as in ``jnp.sort``; see pallas_kernels.total_order_keys).
    """
    cols = keys.shape[1]
    lo = jnp.full((cols,), -(2**31), jnp.int32)
    hi = jnp.full((cols,), 2**31 - 1, jnp.int32)

    def step(_, lohi):
        lo, hi = lohi
        # overflow-free floor((lo + hi) / 2) in int32
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        cnt = jnp.sum(keys <= mid[None, :], axis=0)
        above = cnt <= n  # not enough at-or-below mid -> answer is above
        return jnp.where(above, mid + 1, lo), jnp.where(above, hi, mid)

    lo, _ = jax.lax.fori_loop(0, 32, step, (lo, hi))
    return lo


def _select_median(wmatrix: jnp.ndarray) -> jnp.ndarray:
    k = wmatrix.shape[0]
    keys = pallas_kernels.total_order_keys(wmatrix)
    return pallas_kernels.total_order_vals(
        _nth_smallest_keys(keys, (k - 1) // 2)
    )


def _select_trimmed_mean(wmatrix: jnp.ndarray, b: int) -> jnp.ndarray:
    """b-trimmed column mean without sorting: locate the kept band's
    boundary order statistics by key bisection, sum the strict interior in
    one masked pass, and add back the boundary values times their kept
    multiplicity (exact under ties at the trim boundary)."""
    k = wmatrix.shape[0]
    w32 = wmatrix.astype(jnp.float32)
    keys = pallas_kernels.total_order_keys(w32)
    lo_k = _nth_smallest_keys(keys, b)          # rank b (lowest kept)
    hi_k = _nth_smallest_keys(keys, k - b - 1)  # rank K-b-1 (highest kept)
    interior = (keys > lo_k[None, :]) & (keys < hi_k[None, :])
    total = jnp.sum(jnp.where(interior, w32, 0.0), axis=0)
    # kept ranks are [b, K-b-1]; entries equal to a boundary key occupy the
    # contiguous rank run [#(< key), #(<= key) - 1] — clip it to the band
    last = k - b - 1

    def kept_copies(boundary):
        n_lt = jnp.sum(keys < boundary[None, :], axis=0)
        n_le = jnp.sum(keys <= boundary[None, :], axis=0)
        run = jnp.minimum(n_le - 1, last) - jnp.maximum(n_lt, b) + 1
        return jnp.maximum(run, 0).astype(jnp.float32)

    def boundary_sum(boundary, copies):
        # 0 * Inf / 0 * NaN guard: contribute only where copies exist
        v = pallas_kernels.total_order_vals(boundary)
        return jnp.where(copies > 0, copies * v, 0.0)

    total = total + boundary_sum(lo_k, kept_copies(lo_k))
    total = total + jnp.where(
        lo_k == hi_k, 0.0, boundary_sum(hi_k, kept_copies(hi_k))
    )
    return total / jnp.float32(k - 2 * b)


def _sort_fused_ok(k: int, channel: bool) -> bool:
    """Trace-time pallas-vs-bisection gate for the selection epilogue, with
    the rejection SURFACED: when a requested pallas realization misses the
    VMEM budget, the spelled-out byte math (``pallas_kernels
    .sort_fused_reason``) goes to the warning stream — which the harness
    condenses into the run log — so the fallback matrix row is
    attributable without re-deriving the K ceiling by hand."""
    reason = pallas_kernels.sort_fused_reason(k, channel)
    if reason is not None:
        warnings.warn(
            "fused selection epilogue: pallas rejected, using the XLA "
            f"key-bisection fallback — {reason}",
            stacklevel=3,
        )
        return False
    return True


def supports_fused_epilogue(name: str) -> bool:
    """Aggregators whose epilogue the fused dispatch below accelerates (and
    into whose stack read the OMA prepass may be folded).  gm already owns
    its channel in-kernel (``aircomp_weiszfeld_step``).  Read from the
    registration metadata — one source of truth shared with the defense
    escalation ladder's branch table — not a name list."""
    return bool(AGGREGATORS.meta(name).get("supports_fused_epilogue", False))


@AGGREGATORS.register(
    "median",
    supports_fused_epilogue=True,
    streamable=True,
    extra_args=("impl", "fused_epilogue", "oma_key", "noise_var"),
)
def median(
    wmatrix: jnp.ndarray,
    *,
    degraded: bool = False,
    impl: str = "xla",
    fused_epilogue: bool = False,
    oma_key: Optional[jax.Array] = None,
    noise_var: Optional[float] = None,
    **_,
) -> jnp.ndarray:
    """Coordinatewise median, torch semantics (lower-middle for even K).

    Reference ``median`` (``:194-195``) uses ``torch.median(dim=0)`` which
    returns the ``(K-1)//2``-th order statistic, not the midpoint average.

    ``fused_epilogue``: replace the full sort with single-read selection —
    the Pallas peel kernel (``impl="pallas"``, K fits VMEM) or the XLA key
    bisection — optionally folding the deferred OMA prepass (``oma_key``)
    into the same stack read.  Off (the default) this body is byte-for-byte
    the pre-fusion aggregator.

    ``degraded``: the median of the n finite rows — non-finite rows sort to
    +Inf and the order statistic index becomes the DYNAMIC ``(n-1)//2``, so
    the rule adapts to the per-round effective K instead of drifting toward
    the +Inf tail.  n = 0 returns +Inf (trainer finite-guard territory).
    Degraded rounds always take the sort path (the dynamic index defeats
    static peel/bisection bounds).
    """
    k = wmatrix.shape[0]
    if fused_epilogue and not degraded and wmatrix.dtype == jnp.float32:
        if impl == "pallas" and _sort_fused_ok(k, oma_key is not None):
            ch = (
                channel.oma_terms(oma_key, k, wmatrix.shape[1], noise_var)
                if oma_key is not None
                else None
            )
            return pallas_kernels.fused_median(wmatrix, channel=ch)
        if oma_key is not None:
            wmatrix = channel.oma(oma_key, wmatrix, noise_var)
        return _select_median(wmatrix)
    if oma_key is not None:
        # fallback owed the deferred channel pass — bit-identical to the
        # standalone prepass in fed/train.py under the same key
        wmatrix = channel.oma(oma_key, wmatrix, noise_var)
    if degraded:
        finite = _finite_rows(wmatrix)
        n = jnp.sum(finite)
        srt = jnp.sort(
            jnp.where(finite[:, None], wmatrix, jnp.inf), axis=0
        )
        idx = jnp.maximum(n - 1, 0) // 2
        return jax.lax.dynamic_index_in_dim(srt, idx, axis=0, keepdims=False)
    srt = jnp.sort(wmatrix, axis=0)
    return srt[(k - 1) // 2]


@AGGREGATORS.register(
    "trimmed_mean",
    supports_fused_epilogue=True,
    streamable=True,
    extra_args=(
        "trim_ratio", "beta", "impl", "fused_epilogue", "oma_key", "noise_var",
    ),
)
def trimmed_mean(
    wmatrix: jnp.ndarray, *, trim_ratio: float = 0.1,
    beta: Optional[int] = None, degraded: bool = False,
    impl: str = "xla", fused_epilogue: bool = False,
    oma_key: Optional[jax.Array] = None,
    noise_var: Optional[float] = None, **_
) -> jnp.ndarray:
    """Coordinatewise beta-trimmed mean.

    beta = floor(K * trim_ratio) rows are dropped at each extreme per
    coordinate, matching the reference's chained double-``topk``
    (``:189-192``) which keeps the middle K - 2*beta order statistics.

    ``fused_epilogue`` / ``oma_key``: single-read selection epilogue with
    optional in-read OMA — same dispatch and fallback matrix as
    :func:`median`; requires a non-empty kept band (K - 2b >= 1).

    ``degraded``: the trim budget adapts to the per-round effective K —
    b = floor(n * trim_ratio) over the n finite rows (an explicit ``beta``
    is clamped to (n-1)//2 so the kept middle band is never empty); the
    static-shape sort keeps non-finite rows at +Inf and a dynamic rank mask
    selects the kept band.  n = 0 returns NaN (trainer finite-guard).
    Degraded rounds always take the sort path (dynamic trim budget).
    """
    k = wmatrix.shape[0]
    if fused_epilogue and not degraded and wmatrix.dtype == jnp.float32:
        b = int(k * trim_ratio) if beta is None else int(beta)
        if 0 <= b and k - 2 * b >= 1:
            if impl == "pallas" and _sort_fused_ok(k, oma_key is not None):
                ch = (
                    channel.oma_terms(oma_key, k, wmatrix.shape[1], noise_var)
                    if oma_key is not None
                    else None
                )
                return pallas_kernels.fused_trimmed_mean(wmatrix, b, channel=ch)
            if oma_key is not None:
                wmatrix = channel.oma(oma_key, wmatrix, noise_var)
            return _select_trimmed_mean(wmatrix, b)
    if oma_key is not None:
        # fallback owed the deferred channel pass (see median)
        wmatrix = channel.oma(oma_key, wmatrix, noise_var)
    if degraded:
        finite = _finite_rows(wmatrix)
        n = jnp.sum(finite)
        if beta is None:
            b = (n * trim_ratio).astype(jnp.int32)
        else:
            b = jnp.minimum(int(beta), jnp.maximum(n - 1, 0) // 2)
        srt = jnp.sort(jnp.where(finite[:, None], wmatrix, jnp.inf), axis=0)
        ranks = jnp.arange(k)[:, None]
        keep = jnp.logical_and(ranks >= b, ranks < n - b)
        total = jnp.sum(
            jnp.where(keep, srt, 0.0).astype(jnp.float32), axis=0
        )
        kept_n = jnp.maximum(n - 2 * b, 1)
        return jnp.where(n > 0, total / kept_n, jnp.nan)
    b = int(k * trim_ratio) if beta is None else int(beta)
    srt = jnp.sort(wmatrix, axis=0)
    kept = jax.lax.slice_in_dim(srt, b, k - b, axis=0)
    # f32 mean whatever the stack dtype (sort order is dtype-invariant;
    # only the accumulation needs the upcast)
    return jnp.mean(kept.astype(jnp.float32), axis=0)


def pairwise_sq_dists(wmatrix: jnp.ndarray) -> jnp.ndarray:
    """[K, K] squared euclidean distances via the Gram matrix.

    ||w_i - w_j||^2 = ||w_i||^2 + ||w_j||^2 - 2 <w_i, w_j>; one MXU matmul
    instead of the reference's [K, K, d] broadcast (``:199``).  Clamped at 0
    against float cancellation.  Non-finite rows (e.g. an overflowed gaussian
    attack) produce Inf - Inf = NaN in the Gram form; those distances are
    mapped to +Inf.  The diagonal is the exact value 0 for well-formed rows
    and +Inf for poisoned ones (non-finite entries OR an f32-overflowing
    squared norm — both make ``sq`` non-finite), so a poisoned row scores
    Inf for ANY k_sel and can never win the selection.
    """
    # sq must match the Gram term's f32 accumulation: with a bf16 stack, a
    # bf16 sq would put ~0.4% relative error on ||w||^2 while gram is f32 —
    # near convergence (||w_i - w_j||^2 << ||w||^2) the cancellation below
    # would then be pure quantization noise and Krum selection scrambles
    sq = jnp.einsum(
        "kd,kd->k", wmatrix, wmatrix, preferred_element_type=jnp.float32
    )
    gram = jnp.dot(wmatrix, wmatrix.T, preferred_element_type=jnp.float32)
    dist = sq[:, None] + sq[None, :] - 2.0 * gram
    # a NaN distance can only come from a non-finite row (Inf - Inf in the
    # Gram form); "infinitely far" is the right semantics — NaN would sort
    # as the SMALLEST distance under top_k(-dist) and as the BEST score
    # under top_k(-scores), making Krum select the poisoned row
    dist = jnp.where(jnp.isnan(dist), jnp.inf, dist)
    dist = jnp.maximum(dist, 0.0)
    k = wmatrix.shape[0]
    # diagonal: exact 0 for well-formed rows, +Inf for poisoned ones.  A 0
    # diagonal on a poisoned row would let it win selection in the
    # degenerate k_sel=1 case (honest_size=2): its sorted row is
    # [0, Inf, ...] and its score 0.  The poisoned test is sq's finiteness,
    # NOT the entries': a row of finite ~1e20 entries overflows its f32
    # squared norm to Inf and behaves exactly like an Inf row in the Gram
    # form (numpy_ref._krum_scores mirrors both).
    diag = jnp.where(jnp.isfinite(sq), 0.0, jnp.inf)
    return jnp.where(jnp.eye(k, dtype=bool), diag[:, None], dist)


def krum_scores(wmatrix: jnp.ndarray, honest_size: int) -> jnp.ndarray:
    """Per-client Krum score: sum of the (honest_size - 1) smallest entries of
    its distance row (self-distance 0 included, as in the reference
    ``:200-202``).

    The small side is summed DIRECTLY via ``top_k(-dist)`` (float negation
    is exact; top_k also guards k_sel's range at trace time).  Do not
    "optimize" this into the complement form ``rowsum - sum(top_k largest)``
    even though it selects fewer elements when k_sel > K/2: under Byzantine
    attack the largest squared distances dominate the rowsum by many orders
    of magnitude, and the f32 subtraction cancels away the small honest
    distances that decide the argmin (caught in development; guarded by
    test_krum_scores_outlier_stack_matches_oracle)."""
    dist = pairwise_sq_dists(wmatrix)
    k_sel = honest_size - 2 + 1
    neg_top, _ = jax.lax.top_k(-dist, k_sel)
    return -jnp.sum(neg_top, axis=1)


def krum_scores_degraded(
    wmatrix: jnp.ndarray, honest_size: int
) -> jnp.ndarray:
    """Krum scores whose neighbor count adapts to the per-round effective K.

    With n finite rows the neighbor sum runs over the
    ``c = clip(min(honest_size - 1, n - 1), 1, K)`` nearest rows — the
    static ``top_k(k_sel)`` of :func:`krum_scores` would demand more
    neighbors than exist when n shrinks below honest_size and every score
    would be +Inf.  Static shapes are kept by sorting the full distance row
    and masking ranks >= c (a DYNAMIC cutoff).  Non-finite rows score +Inf:
    their sorted rows are all-Inf, and a rank mask alone would sum them to
    0 — the best possible score — handing the aggregate to the crashed row.
    """
    k = wmatrix.shape[0]
    dist = pairwise_sq_dists(wmatrix)
    finite = _finite_rows(wmatrix)
    n = jnp.sum(finite)
    c = jnp.clip(jnp.minimum(honest_size - 1, n - 1), 1, k)
    srt = jnp.sort(dist, axis=1)
    ranks = jnp.arange(k)[None, :]
    in_budget = jnp.logical_and(ranks < c, jnp.isfinite(srt))
    scores = jnp.sum(jnp.where(in_budget, srt, 0.0), axis=1)
    return jnp.where(finite, scores, jnp.inf)


@AGGREGATORS.register(
    "krum",
    aliases=("Krum",),
    needs_honest_size=True,
    krum_like=True,
    extra_args=(),
)
def krum(
    wmatrix: jnp.ndarray, *, honest_size: int, degraded: bool = False, **_
) -> jnp.ndarray:
    """Single-Krum: return the client vector minimizing the Krum score
    (reference ``Krum``, ``:197-204``).

    ``degraded``: scores via :func:`krum_scores_degraded`, so selection
    keeps working when faults shrink the finite row count below
    honest_size.  With ZERO finite rows every score is +Inf, argmin picks
    row 0 (non-finite) and the trainer finite-guard rejects it."""
    if degraded:
        scores = krum_scores_degraded(wmatrix, honest_size)
    else:
        scores = krum_scores(wmatrix, honest_size)
    return wmatrix[jnp.argmin(scores)]


@AGGREGATORS.register(
    "multi_krum", needs_honest_size=True, krum_like=True, extra_args=("m",)
)
def multi_krum(
    wmatrix: jnp.ndarray, *, honest_size: int, m: Optional[int] = None,
    degraded: bool = False, **_
) -> jnp.ndarray:
    """Multi-Krum: average the m lowest-scoring clients.

    Not present in the reference (it ships single-Krum only, ``:197-204``);
    included per the scale-up configs in BASELINE.json.  Default
    m = honest_size.

    The mean is taken as a [K]-weight matvec (1/m on the selected rows)
    instead of ``mean(wmatrix[idx])``: the gather would materialize an
    [m, d] copy — ~40 GB at the ResNet-18 rung (m=900, d=11.2M, f32) —
    while the matvec reads the stack once and writes only [d].

    ``degraded``: adaptive-neighbor scores plus a selection that averages
    only the FINITE rows among the m winners — when fewer than m finite
    rows exist, +Inf-scored (non-finite) rows necessarily land in the
    static top_k and must not contribute.  Zero finite selected rows
    returns NaN (trainer finite-guard).
    """
    m_sel = honest_size if m is None else int(m)
    if degraded:
        scores = krum_scores_degraded(wmatrix, honest_size)
        _, idx = jax.lax.top_k(-scores, m_sel)
        keep = _finite_rows(wmatrix)[idx]
        count = jnp.sum(keep)
        weights = jnp.zeros(wmatrix.shape[0], jnp.float32).at[idx].set(
            keep.astype(jnp.float32) / jnp.maximum(count, 1)
        )

        def wmean(cols):
            masked = jnp.where(weights[:, None] > 0, cols, 0.0)
            return jnp.dot(weights, masked, preferred_element_type=jnp.float32)

        k, d = wmatrix.shape
        out = (
            wmean(wmatrix)
            if k * d <= _DENSE_MAX_ELEMS
            else _blocked_columns(wmatrix, wmean)
        )
        return jnp.where(count > 0, out, jnp.nan)
    scores = krum_scores(wmatrix, honest_size)
    _, idx = jax.lax.top_k(-scores, m_sel)
    k, d = wmatrix.shape
    if k * d <= _DENSE_MAX_ELEMS:
        return selected_rows_mean(wmatrix, idx, m_sel)
    # large-d regime: the where-select inside the contraction would
    # materialize a [K, d] temp if XLA does not fuse it into the dot —
    # bound peak extra memory at O(K * block) instead
    return _blocked_columns(
        wmatrix, lambda cols: selected_rows_mean(cols, idx, m_sel)
    )


@AGGREGATORS.register(
    "dnc",
    needs_honest_size=True,
    extra_args=("dnc_iters", "dnc_sub_dim", "dnc_c", "key"),
)
def dnc(
    wmatrix: jnp.ndarray,
    *,
    honest_size: int,
    key: Optional[jax.Array] = None,
    dnc_iters: int = 3,
    dnc_sub_dim: int = 10000,
    dnc_c: float = 1.0,
    **_,
) -> jnp.ndarray:
    """Divide-and-Conquer (Shejwalkar & Houmansadr, NDSS 2021) — the
    defense proposed alongside the ``minmax``/``minsum`` attacks this
    framework ships.  Not in the reference.

    Each of ``dnc_iters`` rounds samples ``dnc_sub_dim`` coordinates,
    centers the [K, r] submatrix, finds its top right-singular vector by
    power iteration (a fixed-length ``fori_loop`` — jit-static), scores
    every client by its squared projection, and flags the ceil(c*B)
    highest scorers.  The aggregate is the mean of clients flagged in NO
    round.  Coordinate subsampling keeps the spectral step O(K * r) per
    power step whatever d is — at ResNet scale only the sampled columns
    are ever gathered.

    Hardening beyond the paper: non-finite rows are force-excluded from
    the surviving set up front and scored -Inf, so the removal budget is
    spent on live rows (an overflowed Byzantine row must not shield its
    finite accomplices by winning top_k every round); if the surviving set
    is empty (pathological — the paper assumes K >> c*B*iters) the masked
    mean degrades to the finite-row centroid rather than NaN.
    """
    k, d = wmatrix.shape
    b = k - honest_size
    n_remove = math.ceil(dnc_c * b)
    if n_remove * dnc_iters >= k:
        raise ValueError(
            f"dnc removes ceil(c*B)={n_remove} clients per round x "
            f"{dnc_iters} rounds but K={k}; need K > removals (K >> is the "
            f"paper's regime) — lower dnc_c/dnc_iters or raise K"
        )
    if key is None:
        key = jax.random.key(0, impl="threefry2x32")
    finite = _finite_rows(wmatrix)
    r = min(d, int(dnc_sub_dim))
    keep = finite

    for it in range(dnc_iters):  # static, small
        k_cols, k_v = jax.random.split(jax.random.fold_in(key, it))
        # with-replacement column draw: O(r) memory, vs a full [d]
        # sort-based permutation (prohibitive in-loop at d ~ 11M); for
        # r << d the distinction is statistically immaterial (the paper's
        # subsampling is itself a variance/cost tradeoff)
        cols = jax.random.randint(k_cols, (r,), 0, d)
        # f32 from here on, whatever the stack dtype: the centering sum
        # and the spectral scores must not accumulate in bf16
        sub = jnp.where(
            finite[:, None], wmatrix[:, cols], 0.0
        ).astype(jnp.float32)  # [K, r]
        centered = sub - jnp.sum(sub, axis=0) / jnp.maximum(
            jnp.sum(finite), 1.0
        )
        centered = jnp.where(finite[:, None], centered, 0.0)

        def power_step(_, v):
            u = centered @ v  # [K]
            v2 = centered.T @ u  # [r]
            return v2 / jnp.maximum(jnp.linalg.norm(v2), 1e-12)

        v0 = jax.random.normal(k_v, (r,), jnp.float32)
        v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-12)
        v = jax.lax.fori_loop(0, 10, power_step, v0)

        scores = (centered @ v) ** 2
        # non-finite rows are already force-excluded (keep starts at
        # `finite`); score them -Inf so the ceil(c*B) removal budget is
        # spent on LIVE rows — +Inf would make an overflowed Byzantine row
        # win top_k every round and shield its finite accomplices
        scores = jnp.where(finite, scores, -jnp.inf)
        if n_remove:
            _, out_idx = jax.lax.top_k(scores, n_remove)
            keep = jnp.logical_and(
                keep, jnp.ones(k, bool).at[out_idx].set(False)
            )

    count = jnp.sum(keep)
    mean_kept = _finite_centroid(wmatrix, keep)
    return jnp.where(count > 0, mean_kept, _finite_centroid(wmatrix, finite))


# ---------------------------------------------------------------------------
# packed one-bit sign channel (signmv / bev ballots)
#
# The sign aggregators' wire payload is ONE ballot per coordinate, but the
# unpacked path still moves it as f32 lanes — the 32x bandwidth win that
# motivates one-bit OTA is unrealized.  The helpers below define the packed
# wire format and its two reduce realizations:
#
# * wire format: [K, W = ceil(d/32)] uint32 words, LSB-first — coordinate
#   ``c`` lives at bit ``c % 32`` of word ``c // 32``.  Bit 1 = ballot +1
#   (delta >= 0, i.e. the IEEE sign bit of the delta with +0.0 voting +1);
#   bit 0 = ballot -1.  A row with ANY non-finite coordinate is invalid:
#   its words are packed all-zero and it is excluded from ``k_valid``, so
#   it casts zero ballots — the unpacked vote's 0-ballot rule for
#   non-finite deltas, coarsened to row granularity (DESIGN.md).
# * reduce: per-coordinate set-bit counts over K; the signed ballot sum is
#   recovered as ``votes = 2*counts - k_valid`` (each set bit is +1, each
#   clear bit of a valid row is -1).  Integer counts, so the Pallas kernel
#   and the XLA bit-plane fallback are bit-identical by construction.
#
# One-bit is the ONLY packed width: an exact 3-state {-1, 0, +1} encoding
# needs >= log2(3) bits/coordinate and cannot reach the 32x bar, so zero
# deltas round up to +1 on the packed wire (sign_bits=1) while the
# unpacked paths keep sign(0) = 0.  Tests pin the convention; trajectories
# over real float deltas (no exact ties against the previous params) are
# unaffected.

SIGNPACK_WORD_BITS = pallas_kernels.SIGNPACK_BITS  # 32, LSB-first


def packed_words(d: int) -> int:
    """uint32 sign words per client for a d-coordinate delta."""
    return -(-d // SIGNPACK_WORD_BITS)


def pack_signs(wmatrix: jnp.ndarray, guess: jnp.ndarray):
    """[K, d] stack + pre-round params -> ``(words [K, W] uint32, k_valid)``.

    Pure elementwise + lane reduce over the stack read, so on the trainer's
    resident path XLA fuses it into the stack producer and the f32 sign
    stack never exists in HBM — the packed words ARE the materialization.
    ``k_valid`` (int32 scalar) counts the all-finite rows; invalid rows
    are packed all-zero (zero ballots, see the wire-format comment)."""
    k, d = wmatrix.shape
    w_cnt = packed_words(d)
    delta = wmatrix.astype(jnp.float32) - guess[None, :].astype(jnp.float32)
    finite = _finite_rows(delta)  # [K]
    ballot_up = jnp.logical_and(finite[:, None], delta >= 0.0)  # [K, d]
    pad = w_cnt * SIGNPACK_WORD_BITS - d
    bits = jnp.pad(ballot_up, ((0, 0), (0, pad))).reshape(
        k, w_cnt, SIGNPACK_WORD_BITS
    )
    weights = jnp.uint32(1) << jnp.arange(
        SIGNPACK_WORD_BITS, dtype=jnp.uint32
    )
    words = jnp.sum(
        jnp.where(bits, weights[None, None, :], jnp.uint32(0)), axis=-1
    )
    return words, jnp.sum(finite).astype(jnp.int32)


def _packed_vote_counts_xla(words: jnp.ndarray, d: int) -> jnp.ndarray:
    """XLA bit-plane realization of the packed reduce: counts [d] int32.

    ``[K, W] >> j & 1`` for j in [0, 32) -> [K, W, 32] bit planes, summed
    over K to [W, 32]; the row-major flatten is exactly the LSB-first
    coordinate order ``c = w*32 + j``.  Integer arithmetic throughout, so
    bit-identical to ``pallas_kernels.packed_vote_counts``."""
    shifts = jnp.arange(SIGNPACK_WORD_BITS, dtype=jnp.uint32)
    planes = (words[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    counts = jnp.sum(planes.astype(jnp.int32), axis=0)  # [W, 32]
    return counts.reshape(-1)[:d]


def packed_sign_votes(
    words: jnp.ndarray, d: int, *, impl: str = "xla"
) -> jnp.ndarray:
    """Per-coordinate set-bit counts of the packed sign words, [d] int32.

    ``impl="pallas"`` takes the single-pass popcount kernel when K fits the
    VMEM budget; the rejection is SURFACED like :func:`_sort_fused_ok` —
    the spelled-out byte math goes to the warning stream so an ``xla``
    fallback row in the matrix is attributable from the run log alone."""
    k = words.shape[0]
    if impl == "pallas":
        reason = pallas_kernels.signpack_fused_reason(k)
        if reason is None:
            return pallas_kernels.packed_vote_counts(words, d)
        warnings.warn(
            "packed sign vote: pallas rejected, using the XLA bit-plane "
            f"fallback — {reason}",
            stacklevel=3,
        )
    return _packed_vote_counts_xla(words, d)


def _quantize_deltas(
    wmatrix: jnp.ndarray, guess: jnp.ndarray, bits: int
) -> jnp.ndarray:
    """b-bit symmetric uniform quantize-dequantize EMULATION of the delta
    channel (sign_bits = 8 or 16): per-client scale ``s_i = max |delta_i|``
    over finite coordinates, levels ``Q = 2^(b-1) - 1``, so the wire would
    carry ``k * d * b / 8`` bytes (obs/hbm.py models it).  Returns the
    reconstructed stack ``guess + dq``; rows with any non-finite
    coordinate pass through UNCHANGED so the downstream vote's non-finite
    handling is identical to the unpacked path, and an all-zero delta row
    (s_i = 0) dequantizes to exactly zero."""
    delta = wmatrix.astype(jnp.float32) - guess[None, :].astype(jnp.float32)
    finite = _finite_rows(delta)  # [K]
    q_max = jnp.float32(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(jnp.where(finite[:, None], delta, 0.0)),
                    axis=1, keepdims=True)  # [K, 1], 0 on invalid rows
    q = jnp.clip(
        jnp.round(delta / jnp.maximum(scale, 1e-30) * q_max), -q_max, q_max
    )
    dq = jnp.where(scale > 0.0, q * scale / q_max, 0.0)
    return jnp.where(
        finite[:, None], guess[None, :].astype(jnp.float32) + dq, wmatrix
    )


def _packed_sign_step(wmatrix, guess, packed, noise, sign_eta, impl, name):
    """Shared sign_bits=1 tail for signmv/bev: pack (unless the trainer
    already did), popcount-reduce, recover signed votes, step ``sign_eta``
    in the voted direction.  ``sign_eta`` is mandatory on this path — the
    one-bit channel carries no magnitudes for the adaptive eta median."""
    if sign_eta is None:
        raise ValueError(
            f"{name} at sign_bits=1 needs an explicit sign_eta: the "
            "one-bit channel carries no magnitudes for the adaptive "
            "eta median"
        )
    d = wmatrix.shape[1]
    if packed is None:
        packed = pack_signs(wmatrix, guess)
    words, k_valid = packed
    counts = packed_sign_votes(words, d, impl=impl)
    votes = (2 * counts - k_valid).astype(jnp.float32) + noise
    return guess + jnp.float32(sign_eta) * jnp.sign(votes)


@AGGREGATORS.register(
    "signmv",
    owns_channel=True,
    extra_args=(
        "guess", "key", "noise_var", "sign_eta", "sign_bits", "packed",
        "impl",
    ),
)
def sign_majority_vote(
    wmatrix: jnp.ndarray,
    *,
    guess: Optional[jnp.ndarray] = None,
    key: Optional[jax.Array] = None,
    noise_var: Optional[float] = None,
    sign_eta: Optional[float] = None,
    sign_bits: int = 32,
    packed=None,
    impl: str = "xla",
    **_,
) -> jnp.ndarray:
    """One-bit over-the-air aggregation: sign-SGD with majority vote.

    Not in the reference (whose aggregators all transmit full-precision
    weights, ``:131-204``); included as the one-bit AirComp defense from the
    OTA literature (Zhu et al. 2023, "One-Bit Byzantine-Tolerant Distributed
    Learning via Over-the-Air Computation"; majority-vote robustness per
    Bernstein et al. 2019).  Each client transmits only the SIGN of its
    model delta w_i - guess — one BPSK symbol per coordinate — and the
    receiver observes their over-the-air SUM (plus AWGN when ``noise_var``
    is set), which IS the majority vote; parameters then move a fixed
    magnitude in the voted direction:

        new = guess + eta * sign( sum_i sign(w_i - guess) + n )

    Per coordinate, B Byzantine clients can flip the vote only when the
    honest margin is < 2B+1 ballots, and can never influence the step
    magnitude — eta is ``sign_eta`` when given, else the coordinatewise
    median of |w_i - guess| (a robust scale estimate for B < K/2).  Tied or
    noise-drowned coordinates (sign(0) = 0) do not move.  A non-finite
    delta (overflowed/NaN Byzantine row) casts a 0 ballot and counts as
    infinitely large for the eta median, so it can neither poison the vote
    (sign(NaN) = NaN would contaminate the sum) nor the scale.  Above the
    dense memory budget the coordinatewise tail runs over column blocks
    (the [K, d] delta and sorted |delta| temporaries are ~45 GB each at
    the ResNet-18 rung).

    ``sign_bits`` selects the channel payload width: 32 (default) is this
    legacy full-precision-ballot path, byte-identical with the new kwargs
    left at their defaults; 1 takes the bit-packed wire
    (:func:`pack_signs` / :func:`packed_sign_votes` — ``packed`` lets the
    trainer hand in pre-packed words so the f32 sign stack never
    materializes); 8/16 run the same vote on a quantize-dequantize
    emulated stack (:func:`_quantize_deltas`).
    """
    if guess is None:
        raise ValueError("signmv needs the pre-round params as `guess`")
    k, d = wmatrix.shape
    if noise_var is not None:
        if key is None:
            raise ValueError("signmv with noise_var needs a PRNG `key`")
        scale = jnp.sqrt(jnp.asarray(noise_var, jnp.float32) / 2.0)
        noise = scale * jax.random.normal(key, (d,), jnp.float32)
    else:
        noise = jnp.zeros((d,), jnp.float32)
    if sign_bits == 1:
        return _packed_sign_step(
            wmatrix, guess, packed, noise, sign_eta, impl, "signmv"
        )
    if sign_bits in (8, 16):
        wmatrix = _quantize_deltas(wmatrix, guess, sign_bits)

    def tail(cols, g, n):
        delta = cols - g[None, :]
        finite = jnp.isfinite(delta)
        votes = jnp.sum(jnp.where(finite, jnp.sign(delta), 0.0), axis=0) + n
        if sign_eta is None:
            eta = median(jnp.where(finite, jnp.abs(delta), jnp.inf))
            # a coordinate where >= ceil(K/2) deltas are non-finite medians
            # to Inf, and Inf * sign(0) on a tied vote would poison the
            # params with NaN; outside the B < K/2 contract degrade to a
            # no-op step there instead
            eta = jnp.where(jnp.isfinite(eta), eta, 0.0)
        else:
            eta = jnp.float32(sign_eta)
        return g + eta * jnp.sign(votes)

    if k * d <= _DENSE_MAX_ELEMS:
        return tail(wmatrix, guess, noise)
    return _blocked_columns((wmatrix, guess, noise), tail)


@AGGREGATORS.register(
    "bev",
    extra_args=("guess", "sign_eta", "sign_bits", "packed", "impl"),
)
def best_effort_voting(
    wmatrix: jnp.ndarray,
    *,
    guess: Optional[jnp.ndarray] = None,
    sign_eta: Optional[float] = None,
    sign_bits: int = 32,
    packed=None,
    impl: str = "xla",
    **_,
) -> jnp.ndarray:
    """Best-effort voting (BEV-SGD, Jin et al. 2021, arXiv:2110.09660) as
    a receiver-side rung.  BEV-SGD's insight: have every client transmit
    its one-bit gradient sign at FULL (best-effort) power instead of
    channel-inverted power, so a Byzantine client cannot buy extra vote
    weight by power scaling — robustness comes from the per-coordinate
    majority over equally-weighted ballots.  Here the vote runs on the
    already-received full-precision stack (so it composes as an
    escalation-ladder rung: every rung must read the same received
    stack, unlike ``signmv`` whose one-bit BPSK transmission owns the
    channel and is rejected by ``validate_ladder``):

        new = guess + eta * sign( sum_i sign(w_i - guess) )

    Each finite row casts exactly one ballot per coordinate whatever its
    magnitude — a weightflip row a thousand honest scales out still moves
    the vote by one ballot, so B < K/2 bounds the damage per coordinate
    to tied-vote coordinates.  ``eta`` is ``sign_eta`` when given, else
    the coordinatewise median of |w_i - guess| over finite rows (the
    robust step-scale estimate ``signmv`` uses); non-finite rows cast a 0
    ballot and count as +Inf for the eta median, and an Inf median
    (>= K/2 non-finite deltas — outside the contract) degrades that
    coordinate to a no-op step rather than poisoning the params.

    ``sign_bits`` / ``packed`` make bev the second consumer of the packed
    one-bit reduce: at ``sign_bits=1`` the ballots are the same uint32
    sign words ``signmv`` transmits (:func:`pack_signs`), reduced by the
    same popcount kernel — minus the receiver noise, since bev is a
    receiver-side rung.  32 is the legacy path, byte-identical; 8/16
    quantize-dequantize emulation as in ``signmv``."""
    if guess is None:
        raise ValueError("bev needs the pre-round params as `guess`")
    k, d = wmatrix.shape
    if sign_bits == 1:
        return _packed_sign_step(
            wmatrix, guess, packed, jnp.float32(0.0), sign_eta, impl, "bev"
        )
    if sign_bits in (8, 16):
        wmatrix = _quantize_deltas(wmatrix, guess, sign_bits)

    def tail(cols, g):
        delta = cols - g[None, :]
        finite = jnp.isfinite(delta)
        votes = jnp.sum(jnp.where(finite, jnp.sign(delta), 0.0), axis=0)
        if sign_eta is None:
            eta = median(jnp.where(finite, jnp.abs(delta), jnp.inf))
            eta = jnp.where(jnp.isfinite(eta), eta, 0.0)
        else:
            eta = jnp.float32(sign_eta)
        return g + eta * jnp.sign(votes)

    if k * d <= _DENSE_MAX_ELEMS:
        return tail(wmatrix, guess)
    return _blocked_columns((wmatrix, guess), tail)


@AGGREGATORS.register(
    "cclip", extra_args=("guess", "clip_tau", "clip_iters")
)
def centered_clip(
    wmatrix: jnp.ndarray,
    *,
    guess: Optional[jnp.ndarray] = None,
    clip_tau: Optional[float] = None,
    clip_iters: int = 3,
    **_,
) -> jnp.ndarray:
    """Centered clipping (Karimireddy, He & Jaggi, ICML 2021) — not in the
    reference; included as the standard momentum-style defense.  Starting
    from the pre-round global params (the ``guess`` every aggregator already
    receives, reference ``:349-350``), each of the ``clip_iters`` fixed
    steps moves the center by the mean of the client deltas clipped to
    radius tau:

        v <- v + mean_i( (w_i - v) * min(1, tau / ||w_i - v||) )

    ``clip_tau=None`` (the default) resolves tau PER STEP to the median of
    the client delta norms — a robust honest-scale estimate for B < K/2, so
    the radius tracks the actual update magnitude instead of relying on a
    hand-tuned constant (a fixed tau large vs the honest delta scale, e.g.
    the textbook tau=10 against one-local-SGD-step deltas of norm ~1e-2,
    admits enough of a weightflip row per step to collapse training).
    Non-finite rows count as +Inf for that median and are excluded from the
    vote (their delta selected to 0; tau/Inf*Inf would otherwise inject
    NaN); an Inf median (contract violation) degrades to a no-op step.

    A single Byzantine row can displace the center by at most tau/K per
    step, whatever its magnitude.  The fixed small iteration count keeps the
    program static (no data-dependent while_loop needed at this cost)."""
    finite = _finite_rows(wmatrix)
    # f32 regardless of the stack dtype: the carry must stay type-stable
    v = (_finite_centroid(wmatrix, finite) if guess is None else guess
         ).astype(jnp.float32)

    def step(v, _):
        delta = jnp.where(finite[:, None], wmatrix - v[None, :], 0.0)
        norms = jnp.maximum(jnp.linalg.norm(delta, axis=1), 1e-12)
        if clip_tau is None:
            tau = median(jnp.where(finite, norms, jnp.inf)[:, None])[0]
            tau = jnp.where(jnp.isfinite(tau), tau, 0.0)
        else:
            tau = jnp.float32(clip_tau)
        scale = jnp.minimum(1.0, tau / norms)
        return v + jnp.mean(delta * scale[:, None], axis=0), None

    v, _ = jax.lax.scan(step, v, None, length=clip_iters)
    return v


@AGGREGATORS.register("bulyan", needs_honest_size=True, extra_args=())
def bulyan(
    wmatrix: jnp.ndarray, *, honest_size: int, degraded: bool = False, **_
) -> jnp.ndarray:
    """Bulyan (El Mhamdi et al., ICML 2018) — not in the reference (which
    ships single-Krum only, ``:197-204``); included as the standard stronger
    defense against coordinate-wise omniscient attacks (``alie``/``ipm``).

    Batch formulation (jit-friendly): select the theta = K - 2B lowest
    Krum-scoring clients, then per coordinate average the beta = theta - 2B
    values closest to the selected set's median.  Requires K > 4B (theta and
    beta both nonempty; B = K - honest_size), checked statically at trace
    time.

    ``degraded``: Bulyan's theta/beta sizing is deeply static (two nested
    selections), so the graceful-degradation rule is IMPUTATION — non-finite
    rows are replaced with the finite-row centroid before the normal static
    pipeline runs.  An imputed row is maximally inoffensive (it sits at the
    crowd's center, biasing no coordinate median), which the matrix tests
    check against the exact adaptive alternatives.  Zero finite rows
    returns NaN (trainer finite-guard).
    """
    k, d = wmatrix.shape
    b = k - honest_size
    theta, beta = bulyan_sizes(k, b)
    if degraded:
        finite = _finite_rows(wmatrix)
        cent = _finite_centroid(wmatrix, finite).astype(wmatrix.dtype)
        wmatrix = jnp.where(finite[:, None], wmatrix, cent[None, :])
        wmatrix = jnp.where(jnp.sum(finite) > 0, wmatrix, jnp.nan)
    scores = krum_scores(wmatrix, honest_size)
    _, idx = jax.lax.top_k(-scores, theta)
    if theta * d <= _DENSE_MAX_ELEMS:
        return bulyan_tail(wmatrix[idx], beta)
    # large-d regime (ResNet-18: theta*d is tens of GB): never materialize
    # the [theta, d] selection — gather + tail per column block under a scan
    return _blocked_columns(wmatrix, lambda cols: bulyan_tail(cols[idx], beta))


def bulyan_sizes(k: int, b: int):
    """(theta, beta) for Bulyan at K clients / B Byzantine; raises unless
    K > 4B so both the selection and the trimmed set are nonempty."""
    theta = k - 2 * b
    beta = theta - 2 * b
    if beta < 1:
        raise ValueError(
            f"bulyan needs K > 4B for a nonempty trimmed set "
            f"(K={k}, B={b} -> theta={theta}, beta={beta})"
        )
    return theta, beta


# one-shot budget for the dense selection paths, in elements of the largest
# temporary the op would materialize: bulyan gates on theta*d (the [theta, d]
# selection plus its same-sized distance transpose and [d, beta] top_k
# outputs), multi_krum on k*d (the masked stack feeding the contraction, in
# case XLA does not fuse the where into the dot).  At 1<<25 both stay a few
# hundred MB.  Above it (the K=100+ ResNet-18 regime, where the stack alone
# is multiple GB) the blocked column path bounds peak extra memory at
# O(K * block).
_DENSE_MAX_ELEMS = 1 << 25


def selected_rows_mean(
    wmatrix: jnp.ndarray, idx: jnp.ndarray, m_sel: int
) -> jnp.ndarray:
    """Mean of the ``idx`` rows as a [K]-weight matvec (1/m on selected
    rows), with the unpicked rows selected (not multiplied) to 0 first so a
    rejected row containing Inf cannot poison the sum as 0*Inf = NaN.

    GSPMD-friendly — the ring collectives share this helper so the dense and
    sharded selection semantics cannot drift.  ``m_sel=1`` with a length-1
    ``idx`` extracts a single row (the single-Krum winner) without the
    dynamic ``wmatrix[argmin]`` gather that makes GSPMD all-gather the
    whole stack."""
    # f32 weights whatever the stack dtype: bf16(1/m) * m != 1 would
    # systematically rescale the aggregate (~0.2% at m=3), a deterministic
    # drift that compounds round over round
    weights = jnp.zeros(wmatrix.shape[0], jnp.float32).at[idx].set(1.0 / m_sel)
    masked = jnp.where(weights[:, None] > 0, wmatrix, 0.0)
    return jnp.dot(weights, masked, preferred_element_type=jnp.float32)


def _blocked_columns(arrays, fn, max_block_elems: int = 1 << 26):
    """Apply a columnwise reduction ``fn(*column_blocks) -> [block]`` over
    column blocks of one or more arrays whose LAST axis is d (the [K, d]
    stack, and optionally [d] vectors like the aggregation guess or a
    receiver-noise draw, sliced jointly), under a scan, concatenating the
    results to [d]: peak extra memory O(K * block) instead of whatever
    temporaries ``fn`` would materialize at full d.  The remainder columns
    (d % block) are processed with one static slice so no padded copy of
    the stack is made."""
    if not isinstance(arrays, (tuple, list)):
        arrays = (arrays,)
    k, d = arrays[0].shape[0], arrays[0].shape[-1]
    block = max(128, (min(d, max_block_elems // k) // 128) * 128)
    n_blocks, rem = divmod(d, block)

    def step(_, i):
        cols = tuple(
            jax.lax.dynamic_slice_in_dim(a, i * block, block, axis=a.ndim - 1)
            for a in arrays
        )
        return _, fn(*cols)

    parts = []
    if n_blocks:
        _, out = jax.lax.scan(
            step, None, jnp.arange(n_blocks, dtype=jnp.int32)
        )
        parts.append(out.reshape(-1))
    if rem:
        parts.append(fn(*[a[..., d - rem :] for a in arrays]))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def bulyan_tail(sel: jnp.ndarray, beta: int) -> jnp.ndarray:
    """Coordinatewise Bulyan aggregation over the selected [theta, d] rows:
    average the beta values closest to the (lower-middle) median.  Pure
    coordinatewise ops — partitions over a d-sharded ``sel`` untouched."""
    med = median(sel)  # torch lower-middle semantics, same as our median agg
    dist_t = jnp.abs(sel - med[None, :]).T  # [d, theta]
    _, cols = jax.lax.top_k(-dist_t, beta)  # beta closest to median per coord
    vals = jnp.take_along_axis(sel.T, cols, axis=1)  # [d, beta]
    # f32 accumulation even under --stack-dtype bf16 (the stack_dtype
    # contract: storage may be bf16, arithmetic stays f32)
    return jnp.mean(vals.astype(jnp.float32), axis=1)


def _weiszfeld_dists(wmatrix, guess):
    d = jnp.linalg.norm(wmatrix - guess[None, :], axis=1)
    return jnp.maximum(DIST_CLAMP, d)


@AGGREGATORS.register(
    "gm2", streamable=True, extra_args=("guess", "maxiter", "tol", "impl")
)
def gm2(
    wmatrix: jnp.ndarray,
    *,
    guess: Optional[jnp.ndarray] = None,
    maxiter: int = 1000,
    tol: float = 1e-5,
    impl: str = "xla",
    **_,
) -> jnp.ndarray:
    """Ideal geometric median by Weiszfeld iteration (reference ``gm2``,
    ``:162-184``): guess <- sum_i(w_i/d_i) / sum_i(1/d_i) with d_i clamped at
    1e-4, stopping when the guess moves <= tol or after maxiter steps.

    The data-dependent early exit is a ``lax.while_loop`` so the whole solve
    stays on device (SURVEY.md "hard parts" (a)).  ``impl="pallas"`` runs each
    step as the fused single-HBM-pass kernel
    (:func:`.pallas_kernels.weiszfeld_step`) when the model fits the fused
    regime; XLA's two-pass lowering otherwise.

    Non-finite rows are EXCLUDED (weight 0): the XLA path selects their
    contributions to 0 per iteration (the select fuses into the reduction —
    no persistent sanitized copy at large d); the fused pallas kernel masks
    them in-tile (VPU ops on resident data, no extra HBM traffic).
    """
    finite = _finite_rows(wmatrix)
    # f32 regardless of the stack dtype: the while carry must stay type-stable
    init_guess = (_finite_centroid(wmatrix, finite) if guess is None
                  else guess).astype(jnp.float32)
    use_pallas = impl == "pallas" and pallas_kernels.supports_fused(
        wmatrix.shape[1]
    )

    def cond(state):
        i, _, movement = state
        return jnp.logical_and(i < maxiter, movement > tol)

    def body(state):
        i, g, _ = state
        if use_pallas:
            num, den = pallas_kernels.weiszfeld_step(wmatrix, g)
        else:
            dist = _weiszfeld_dists(wmatrix, g)
            inv = jnp.where(finite, 1.0 / dist, 0.0)
            num = jnp.sum(
                jnp.where(finite[:, None], wmatrix * inv[:, None], 0.0), axis=0
            )
            den = jnp.sum(inv)
        g_next = num / den
        movement = jnp.linalg.norm(g - g_next)
        return i + 1, g_next, movement

    _, final, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init_guess, jnp.float32(jnp.inf))
    )
    return final


@AGGREGATORS.register(
    "gm",
    owns_channel=True,
    extra_args=(
        "guess", "key", "noise_var", "maxiter", "tol", "p_max", "impl",
    ),
)
def gm(
    wmatrix: jnp.ndarray,
    *,
    key: jax.Array,
    noise_var: Optional[float] = None,
    guess: Optional[jnp.ndarray] = None,
    maxiter: int = 1000,
    tol: float = 1e-5,
    p_max: float = 1.0,
    impl: str = "xla",
    **_,
) -> jnp.ndarray:
    """AirComp geometric median (reference ``gm``, ``:131-160``).

    Each Weiszfeld step transmits per-client messages
    ``concat([w_i/d_i, scaler/d_i])`` (scaler = RMS of the current guess)
    through the over-the-air sum :func:`..channel.oma2` with P_max and
    threshold ``500 * scaler^2`` (``:146-152``), then updates
    ``guess <- noisy_num / noisy_denom * scaler`` (``:153-155``).  Because the
    iteration count is dynamic, the PRNG key rides in the while-loop carry and
    is split once per iteration.

    ``impl="pallas"`` fuses distance + power control + air sums into one
    HBM pass (:func:`.pallas_kernels.aircomp_weiszfeld_step`); fades and
    receiver noise are drawn with the SAME key derivation as the XLA path
    (``oma2``'s ``split(sub) -> (key_h, key_n)``), so both impls consume an
    identical RNG stream.

    Non-finite rows are EXCLUDED (they transmit nothing): the XLA path
    zeroes their messages via the masked inverse distance; the fused pallas
    kernel masks them in-tile.
    """
    finite = _finite_rows(wmatrix)
    # f32 regardless of the stack dtype: the while carry must stay type-stable
    init_guess = (_finite_centroid(wmatrix, finite) if guess is None
                  else guess).astype(jnp.float32)
    k_clients, d = wmatrix.shape
    use_pallas = impl == "pallas" and pallas_kernels.supports_fused(d)

    def cond(state):
        i, _, movement, _ = state
        return jnp.logical_and(i < maxiter, movement > tol)

    def body(state):
        i, g, _, k = state
        k, sub = jax.random.split(k)
        scaler = jnp.sqrt(jnp.mean(g**2))
        if use_pallas:
            key_h, key_n = jax.random.split(sub)
            h_r, h_i = channel.rayleigh_fade(key_h, k_clients)
            num, den = pallas_kernels.aircomp_weiszfeld_step(
                wmatrix, g, h_r**2 + h_i**2, scaler, p_max=p_max
            )
            if noise_var is not None:
                scale = jnp.sqrt(jnp.asarray(noise_var, jnp.float32) / 2.0)
                n = scale * jax.random.normal(key_n, (d + 1,), dtype=jnp.float32)
                num = num + n[:-1]
                den = den + n[-1]
            g_next = num / den * scaler
        else:
            dist = _weiszfeld_dists(wmatrix, g)
            inv = jnp.where(finite, 1.0 / dist, 0.0)[:, None]
            message = jnp.concatenate(
                [jnp.where(finite[:, None], wmatrix * inv, 0.0), scaler * inv],
                axis=1,
            )
            noisy = channel.oma2(
                sub,
                message,
                p_max=p_max,
                noise_var=noise_var,
                threshold=GM_THRESHOLD_FACTOR * scaler**2,
            )
            g_next = noisy[:-1] / noisy[-1] * scaler
        movement = jnp.linalg.norm(g - g_next)
        return i + 1, g_next, movement, k

    _, final, _, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init_guess, jnp.float32(jnp.inf), key)
    )
    return final


# ---------------------------------------------------------------------------
# streaming cohort aggregation: K >> HBM via chunked client scans
#
# ``stream_aggregate`` realizes the streamable aggregators without ever
# materializing the [K, d] stack.  The trainer hands it ``rebuild(c_idx) ->
# [cohort, d]`` — a pure function that recomputes one cohort's post-
# attack/fault/channel chunk from the round inputs — and every algorithm
# below is one or more ``lax.scan`` passes over the cohort index, carrying
# only O(cohort*d + d) state:
#
# * mean         — running (masked) sums, normally supplied by the
#                  trainer's single observation pass: 0 extra passes;
#                  exact up to the float reassociation of chunk-partial
#                  sums vs the resident column mean.
# * gm2          — Weiszfeld where each step's two reductions
#                  (sum w_i/d_i, sum 1/d_i) accumulate across one chunk
#                  pass; identical DIST_CLAMP / finite-masking / stopping
#                  semantics to the resident solver, so for a fixed guess
#                  sequence the iterates differ only by reassociation.
# * median /     — "exact": 32-step total-order-key bisection
#   trimmed_mean   (_nth_smallest_keys) where each step's per-column count
#                  is one chunk pass — the located RANK KEYS are identical
#                  to the resident selection epilogue's, so median values
#                  match bit-for-bit (trimmed_mean adds one boundary/
#                  interior pass whose sums reassociate).
#                  "sketch": a mergeable key-space histogram — a min/max
#                  pass, then a [bins, d] histogram pass whose counts
#                  merge by ADDITION across cohorts (the property that
#                  makes it a valid streamed/distributed quantile
#                  summary), then the rank's bucket via a cumulative sum;
#                  trimmed_mean runs the same correction pass anchored at
#                  the sketch's bucket-edge boundary estimates.  Error
#                  bound: a located boundary key lies within one histogram
#                  bucket (~key_span/bins in total-order-key space) above
#                  the true order statistic's key.
#
# Compute trades for memory: P passes re-run the cohort rebuild (client
# local steps included) P times.  docs/DESIGN.md "Streamed rounds" has the
# carry layouts and the per-aggregator mergeability argument.
#
# Every pass below runs through a population-shard context
# (``ops/shardctx.py``): the default ``shardctx.LOCAL`` scans all chunks in
# one ``lax.scan`` (byte-identical to the pre-sharding programs), while the
# sequential and mesh engines scan per-shard chunk ranges and merge the
# partial carries under the declared spec tags — integer counts by plain
# addition (exact under any placement: a mesh ``psum`` IS the sequential
# fold), float sums by a fixed left fold in shard order (both engines),
# min/max leaves by their associative reductions.  docs/DESIGN.md
# "Pod-scale service rounds" carries the per-aggregator merge algebra.


def streamable(name: str) -> bool:
    """Whether the aggregator has a streaming/mergeable realization below
    (cohort-streamed rounds, --cohort-size > 0).  Registration metadata —
    one source of truth for config validation and the defense ladder."""
    return bool(AGGREGATORS.meta(name).get("streamable", False))


def _chunk_scan(rebuild, n_chunks: int, body, init):
    """``lax.scan`` over cohort indices: ``body(carry, chunk, c_idx) ->
    carry`` sees each rebuilt [cohort, d] chunk exactly once.  XLA reuses
    one chunk buffer across steps (the scan's only inter-step state is
    ``carry``), so peak memory is one chunk plus the carry."""

    def step(carry, c_idx):
        return body(carry, rebuild(c_idx), c_idx), None

    carry, _ = jax.lax.scan(
        step, init, jnp.arange(n_chunks, dtype=jnp.int32)
    )
    return carry


def stream_stats(rebuild, n_chunks: int, d: int, ctx=shardctx.LOCAL):
    """One pass: (sum over ALL rows [d], sum over finite rows [d],
    finite-row count) — the accumulators mean/gm2 need, exposed so the
    trainer's observation pass (which walks the chunks anyway) can supply
    them to :func:`stream_aggregate` without an extra rebuild pass."""

    def acc(carry, chunk, _):
        s_all, s_fin, n_fin = carry
        fin = _finite_rows(chunk)
        c32 = chunk.astype(jnp.float32)
        return (
            s_all + jnp.sum(c32, axis=0),
            s_fin + jnp.sum(jnp.where(fin[:, None], c32, 0.0), axis=0),
            n_fin + jnp.sum(fin),
        )

    return ctx.scan_merge(
        rebuild, n_chunks, acc,
        (jnp.zeros(d, jnp.float32), jnp.zeros(d, jnp.float32), jnp.int32(0)),
        ("sum", "sum", "sum"),
    )


def _stream_count_le(rebuild, n_chunks: int, degraded: bool,
                     ctx=shardctx.LOCAL):
    """count_le(mids [r, d] i32) -> [r, d] counts of total-order keys <=
    mid per column (finite rows only when degraded) — the one-pass
    counting primitive under the streamed key bisection.  The i32 counts
    merge by plain addition across population shards (a mesh ``psum``),
    so every bisection step — and hence the located rank keys — is
    BIT-EQUAL under any shard placement."""

    def count_le(mids):
        r, d = mids.shape

        def acc(cnt, chunk, _):
            keys = pallas_kernels.total_order_keys(
                chunk.astype(jnp.float32)
            )
            le = keys[None, :, :] <= mids[:, None, :]  # [r, cohort, d]
            if degraded:
                le = jnp.logical_and(le, _finite_rows(chunk)[None, :, None])
            return cnt + jnp.sum(le, axis=1, dtype=jnp.int32)

        return ctx.scan_merge(
            rebuild, n_chunks, acc, jnp.zeros((r, d), jnp.int32), "sum"
        )

    return count_le


def _stream_bisect_keys(count_le, ns, r: int, d: int):
    """Streamed :func:`_nth_smallest_keys`: 32 bisection steps, each one
    chunk-counting pass, locating the ``ns`` (0-indexed, [r] — static or
    traced) order-statistic keys per column simultaneously."""
    lo = jnp.full((r, d), -(2**31), jnp.int32)
    hi = jnp.full((r, d), 2**31 - 1, jnp.int32)
    targets = jnp.reshape(jnp.asarray(ns, jnp.int32), (r, 1))

    def step(_, lohi):
        lo, hi = lohi
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        cnt = count_le(mid)
        above = cnt <= targets
        return jnp.where(above, mid + 1, lo), jnp.where(above, hi, mid)

    lo, _ = jax.lax.fori_loop(0, 32, step, (lo, hi))
    return lo


def _stream_sketch_keys(rebuild, n_chunks: int, d: int, ns, r: int,
                        bins: int, degraded: bool, ctx=shardctx.LOCAL):
    """Mergeable quantile sketch over total-order keys: one min/max pass,
    one [bins, d] histogram pass (per-cohort histograms merge by
    addition), then the requested ranks' bucket UPPER EDGES via the
    histogram's cumulative sum — so each estimate is >= the true order
    statistic by at most one bucket width in key space."""
    kmin0 = jnp.full((d,), 2**31 - 1, jnp.int32)
    kmax0 = jnp.full((d,), -(2**31), jnp.int32)

    def chunk_keys(chunk):
        keys = pallas_kernels.total_order_keys(chunk.astype(jnp.float32))
        if degraded:
            fin = _finite_rows(chunk)[:, None]
            return keys, fin
        return keys, None

    def minmax(carry, chunk, _):
        kmin, kmax = carry
        keys, fin = chunk_keys(chunk)
        if fin is not None:
            lo_keys = jnp.where(fin, keys, 2**31 - 1)
            hi_keys = jnp.where(fin, keys, -(2**31))
        else:
            lo_keys = hi_keys = keys
        return (
            jnp.minimum(kmin, jnp.min(lo_keys, axis=0)),
            jnp.maximum(kmax, jnp.max(hi_keys, axis=0)),
        )

    kmin, kmax = ctx.scan_merge(
        rebuild, n_chunks, minmax, (kmin0, kmax0), ("min", "max")
    )
    # bucket geometry in f32 (an int32 span overflows); the <= 2^-24
    # relative rounding is orders below the bucket width for bins << 2^24
    kminf = kmin.astype(jnp.float32)
    span = jnp.maximum(kmax.astype(jnp.float32) - kminf, 1.0)
    col = jnp.arange(d, dtype=jnp.int32)

    def hist_pass(hist, chunk, _):
        keys, fin = chunk_keys(chunk)
        t = (keys.astype(jnp.float32) - kminf[None, :]) / span[None, :]
        idx = jnp.clip((t * bins).astype(jnp.int32), 0, bins - 1)
        ones = (
            fin.astype(jnp.int32)[:, 0]
            if fin is not None
            else jnp.ones(keys.shape[0], jnp.int32)
        )
        return hist.at[idx, jnp.broadcast_to(col, idx.shape)].add(
            ones[:, None]
        )

    # per-shard [bins, d] histograms merge by i32 addition — the property
    # that makes the sketch a valid streamed AND distributed summary
    hist = ctx.scan_merge(
        rebuild, n_chunks, hist_pass, jnp.zeros((bins, d), jnp.int32), "sum"
    )
    cum = jnp.cumsum(hist, axis=0)  # [bins, d]
    targets = jnp.reshape(jnp.asarray(ns, jnp.int32), (r, 1))
    # first bucket whose cumulative count exceeds the rank
    bucket = jnp.argmax(
        cum[None, :, :] > targets[:, None, :], axis=1
    ).astype(jnp.float32)  # [r, d]
    est = kminf[None, :] + (bucket + 1.0) * (span[None, :] / bins)
    return jnp.minimum(est.astype(jnp.int32), kmax[None, :])


def _stream_trimmed_tail(rebuild, n_chunks: int, lo_k, hi_k, n, b,
                         degraded: bool, ctx=shardctx.LOCAL):
    """Final trimmed-mean pass given the kept band's boundary keys [d]:
    strict-interior sum plus boundary values times their kept multiplicity
    (the resident :func:`_select_trimmed_mean` rank-run formula), with the
    denominator taken as the ACTUAL kept count so the same tail serves the
    exact rung (where it equals k - 2b) and the sketch rung (where the
    estimated boundaries may keep a slightly different band)."""
    d = lo_k.shape[0]
    zero_i = jnp.zeros(d, jnp.int32)
    init = (jnp.zeros(d, jnp.float32), zero_i, zero_i, zero_i, zero_i)

    def acc(carry, chunk, _):
        total, lt_lo, le_lo, lt_hi, le_hi = carry
        w32 = chunk.astype(jnp.float32)
        keys = pallas_kernels.total_order_keys(w32)
        live = (
            _finite_rows(chunk)[:, None]
            if degraded
            else jnp.ones(keys.shape, bool)
        )

        def count(cmp):
            return jnp.sum(
                jnp.logical_and(cmp, live), axis=0, dtype=jnp.int32
            )

        interior = jnp.logical_and(
            jnp.logical_and(keys > lo_k[None, :], keys < hi_k[None, :]),
            live,
        )
        return (
            total + jnp.sum(jnp.where(interior, w32, 0.0), axis=0),
            lt_lo + count(keys < lo_k[None, :]),
            le_lo + count(keys <= lo_k[None, :]),
            lt_hi + count(keys < hi_k[None, :]),
            le_hi + count(keys <= hi_k[None, :]),
        )

    total, lt_lo, le_lo, lt_hi, le_hi = ctx.scan_merge(
        rebuild, n_chunks, acc, init, ("sum",) * 5
    )
    last = n - b - 1  # highest kept rank

    def kept_copies(n_lt, n_le):
        run = jnp.minimum(n_le - 1, last) - jnp.maximum(n_lt, b) + 1
        return jnp.maximum(run, 0)

    def boundary_sum(boundary, copies):
        v = pallas_kernels.total_order_vals(boundary)
        return jnp.where(copies > 0, copies.astype(jnp.float32) * v, 0.0)

    copies_lo = kept_copies(lt_lo, le_lo)
    copies_hi = jnp.where(lo_k == hi_k, 0, kept_copies(lt_hi, le_hi))
    interior_cnt = jnp.maximum(lt_hi - le_lo, 0)
    total = total + boundary_sum(lo_k, copies_lo)
    total = total + boundary_sum(hi_k, copies_hi)
    kept = interior_cnt + copies_lo + copies_hi
    return total / jnp.maximum(kept, 1).astype(jnp.float32)


def _stream_quantile_keys(rebuild, n_chunks, d, ns, r, *, quantile,
                          sketch_bins, degraded, ctx=shardctx.LOCAL):
    if quantile == "sketch":
        return _stream_sketch_keys(
            rebuild, n_chunks, d, ns, r, sketch_bins, degraded, ctx
        )
    count_le = _stream_count_le(rebuild, n_chunks, degraded, ctx)
    return _stream_bisect_keys(count_le, ns, r, d)


def stream_mean(rebuild, *, k, d, n_chunks, degraded=False, sum_all=None,
                sum_finite=None, n_finite=None, ctx=shardctx.LOCAL, **_):
    """Streamed :func:`mean`: exact up to chunk-sum reassociation.  The
    running sums normally arrive precomputed from the trainer's
    observation pass (0 extra rebuild passes)."""
    if sum_all is None or sum_finite is None or n_finite is None:
        sum_all, sum_finite, n_finite = stream_stats(rebuild, n_chunks, d, ctx)
    if degraded:
        return jnp.where(
            n_finite > 0,
            sum_finite / jnp.maximum(n_finite, 1).astype(jnp.float32),
            jnp.nan,
        )
    return sum_all / jnp.float32(k)


def stream_gm2(rebuild, *, k, d, n_chunks, guess=None, maxiter=1000,
               tol=1e-5, degraded=False, sum_all=None, sum_finite=None,
               n_finite=None, ctx=shardctx.LOCAL, **_):
    """Streamed :func:`gm2`: each Weiszfeld step's num/den reductions
    accumulate over one chunk pass with the resident solver's exact
    DIST_CLAMP / finite-mask / movement-stop semantics.  Under a shard
    context the per-shard (num, den) partials merge by the canonical
    shard-order fold, so every engine walks the SAME guess sequence and
    the while_loop's trip count agrees on every device — the collectives
    inside the loop body stay aligned."""
    if guess is None:
        if sum_finite is None or n_finite is None:
            _, sum_finite, n_finite = stream_stats(rebuild, n_chunks, d, ctx)
        init_guess = sum_finite / jnp.maximum(n_finite, 1).astype(
            jnp.float32
        )
    else:
        init_guess = guess.astype(jnp.float32)

    def cond(state):
        i, _, movement = state
        return jnp.logical_and(i < maxiter, movement > tol)

    def body(state):
        i, g, _ = state

        def acc(carry, chunk, _):
            num, den = carry
            fin = _finite_rows(chunk)
            c32 = chunk.astype(jnp.float32)
            dist = _weiszfeld_dists(c32, g)
            inv = jnp.where(fin, 1.0 / dist, 0.0)
            num = num + jnp.sum(
                jnp.where(fin[:, None], c32 * inv[:, None], 0.0), axis=0
            )
            return num, den + jnp.sum(inv)

        num, den = ctx.scan_merge(
            rebuild, n_chunks, acc,
            (jnp.zeros(d, jnp.float32), jnp.float32(0.0)),
            ("sum", "sum"),
        )
        g_next = num / den
        movement = jnp.linalg.norm(g - g_next)
        return i + 1, g_next, movement

    _, final, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init_guess, jnp.float32(jnp.inf))
    )
    return final


def stream_median(rebuild, *, k, d, n_chunks, degraded=False,
                  n_finite=None, quantile="exact", sketch_bins=512,
                  ctx=shardctx.LOCAL, **_):
    """Streamed :func:`median` (torch lower-middle semantics): locate the
    ``(n-1)//2`` rank key by bisection (exact — bit-equal to the resident
    selection) or sketch, and bit-roundtrip it back to the value.  Every
    quantity here is integer-merged (rank counts, histograms, finite
    counts), so the sharded result is bit-equal to the single-device one
    for ANY pop_shards."""
    if degraded:
        if n_finite is None:
            _, _, n_finite = stream_stats(rebuild, n_chunks, d, ctx)
        n = n_finite
    else:
        n = k
    rank = jnp.maximum(jnp.asarray(n, jnp.int32) - 1, 0) // 2
    key = _stream_quantile_keys(
        rebuild, n_chunks, d, rank[None] if jnp.ndim(rank) == 0 else rank,
        1, quantile=quantile, sketch_bins=sketch_bins, degraded=degraded,
        ctx=ctx,
    )
    return pallas_kernels.total_order_vals(key[0])


def stream_trimmed_mean(rebuild, *, k, d, n_chunks, trim_ratio=0.1,
                        beta=None, degraded=False, n_finite=None,
                        quantile="exact", sketch_bins=512,
                        ctx=shardctx.LOCAL, **_):
    """Streamed :func:`trimmed_mean`: kept-band boundary ranks by
    bisection/sketch, then one interior/boundary-multiplicity pass (the
    resident rank-run tie handling).  Degraded rounds adapt the trim
    budget to the finite-row count exactly like the resident sort path."""
    if degraded:
        if n_finite is None:
            _, _, n_finite = stream_stats(rebuild, n_chunks, d, ctx)
        n = jnp.asarray(n_finite, jnp.int32)
        if beta is None:
            b = (n.astype(jnp.float32) * trim_ratio).astype(jnp.int32)
        else:
            b = jnp.minimum(int(beta), jnp.maximum(n - 1, 0) // 2)
    else:
        n = jnp.int32(k)
        b = jnp.int32(int(k * trim_ratio) if beta is None else int(beta))
    ns = jnp.stack([b, jnp.maximum(n - b - 1, 0)])
    keys = _stream_quantile_keys(
        rebuild, n_chunks, d, ns, 2,
        quantile=quantile, sketch_bins=sketch_bins, degraded=degraded,
        ctx=ctx,
    )
    out = _stream_trimmed_tail(
        rebuild, n_chunks, keys[0], keys[1], n, b, degraded, ctx
    )
    if degraded:
        return jnp.where(n > 0, out, jnp.nan)
    return out


_STREAM_FNS = {
    "mean": stream_mean,
    "median": stream_median,
    "trimmed_mean": stream_trimmed_mean,
    "gm2": stream_gm2,
}


def stream_aggregate(name: str, rebuild, **kw):
    """Dispatch to the streamed realization of a ``streamable`` aggregator.

    ``rebuild(c_idx) -> [cohort, d]`` must be pure in the cohort index:
    multi-pass algorithms call it once per pass and rely on every pass
    seeing identical chunks.  Keyword surface mirrors the resident
    aggregators (guess/maxiter/tol/trim/degraded) plus the streamed-only
    knobs (n_chunks, quantile, sketch_bins, the optional precomputed
    observation-pass stats sum_all/sum_finite/n_finite, and ``ctx`` — a
    population-shard context from ``ops/shardctx.py`` under which every
    chunk pass scans per-shard ranges and merges the partials)."""
    fn = AGGREGATORS.get(name)
    for stream_name, stream_fn in _STREAM_FNS.items():
        if fn is AGGREGATORS.get(stream_name):
            return stream_fn(rebuild, **kw)
    raise ValueError(
        f"aggregator {name!r} has no streaming realization "
        f"(streamable: {sorted(_STREAM_FNS)})"
    )


def resolve(name: str):
    """Look up an aggregator by its reference-compatible CLI name."""
    return AGGREGATORS.get(name)


def needs_oma_prepass(name: str) -> bool:
    """Channel-dispatch rule (reference ``:351-352``): when ``--var`` is set,
    every aggregator *except* ``gm`` sees a one-shot per-client OMA corruption
    of the message stack before aggregating; ``gm`` instead runs its own OMA2
    inside each Weiszfeld step.  ``signmv`` (beyond-reference) also owns its
    channel: the sign votes are the over-the-air transmission, so receiver
    noise lands on the vote sum, not on pre-sign weights.  The rule reads the
    ``owns_channel`` registration metadata (shared with the defense ladder
    validation) instead of a hardcoded name pair."""
    return not AGGREGATORS.meta(name).get("owns_channel", False)
