"""Population-shard execution contexts for the streamed cohort scans.

``--pop-shards S`` splits a streamed service round's cohort chunks over S
owners: shard ``p`` scans the GLOBAL chunk indices ``[p*cpp, (p+1)*cpp)``
(``cpp = n_chunks // S``), and the per-shard partial carries are merged by
a fixed algebra.  Three interchangeable engines realize the same program:

* :data:`LOCAL` (S == 1) — today's single ``lax.scan`` over all chunks,
  byte-identical to builds that predate pop-sharding;
* :class:`SeqShardCtx` (S > 1, one device) — a ``lax.map`` over shard ids,
  each running its own chunk scan, merged by an explicit LEFT FOLD in
  shard order.  This is the sequential REFERENCE engine: it defines the
  association order the mesh engine must reproduce bit-for-bit;
* ``parallel.popmesh.MeshShardCtx`` (S > 1, a device mesh) — the same
  per-shard scan inside ``shard_map``, merged by collectives.

The merge algebra is declared per carry leaf with a SPEC tag:

* ``"sum"``  — integer leaves merge by plain addition (associative and
  commutative mod 2^32, so a mesh ``psum`` is EXACTLY the sequential
  fold: rank counts, sketch histograms, finite counts, flag counts and
  sign-vote plane sums are bit-equal under any placement).  Float leaves
  are NOT reassociation-free, so both engines stack the S partials in
  shard order and reduce them with the SAME left fold — the mesh engine
  pays one all-gather of a [d]-sized partial instead of a psum to buy
  bit-equality with the sequential engine.
* ``"min"`` / ``"max"`` — associative/commutative order statistics
  (sketch key ranges, max detector score): ``pmin``/``pmax`` == fold.
* ``"stack"`` — no merge: the caller receives the [S, ...] per-shard
  partials in shard order and owns the combine (the trainer's detector
  rows merge by disjoint-row selection, which is not leafwise).

Empty pytree leaves (``()``) pass through untouched, so feature-off
carry slots cost nothing, exactly like the trainer's donated carry.
"""

from __future__ import annotations

import base64

import jax
import jax.numpy as jnp
import numpy as np


def fold_leaves(parts, tag, n_shards: int):
    """Merge one stacked [S, ...] partial leaf under its spec tag with the
    canonical left fold.  Shared by the sequential engine and the mesh
    engine's float-sum path, so the two produce bit-identical results."""
    if tag == "stack":
        return parts
    if tag == "sum":
        op = jnp.add
    elif tag == "min":
        op = jnp.minimum
    elif tag == "max":
        op = jnp.maximum
    else:
        raise ValueError(f"unknown shard merge tag {tag!r}")
    out = parts[0]
    for p in range(1, n_shards):
        out = op(out, parts[p])
    return out


def _is_empty(x) -> bool:
    return isinstance(x, tuple) and len(x) == 0


def merge_spec_tree(spec, stacked, n_shards: int, merge_leaf):
    """Apply ``merge_leaf(tag, parts)`` across a (spec, stacked-partials)
    pytree pair, passing empty ``()`` slots through."""
    return jax.tree.map(
        lambda tag, parts: () if _is_empty(tag) else merge_leaf(tag, parts),
        spec,
        stacked,
        is_leaf=_is_empty,
    )


class LocalShardCtx:
    """S == 1: the legacy single-scan engine.  ``scan_idx_merge`` lowers to
    exactly ``lax.scan(body, init, arange(n_chunks))`` — the spec is
    ignored — so a ``pop_shards=1`` program traces byte-identically to
    builds that predate pop-sharding."""

    n_shards = 1

    def varying(self, x):
        """Mesh-engine hook (invarying -> device-varying promotion before
        per-client grads); identity off-mesh."""
        return x

    def scan_idx_merge(self, n_chunks: int, body, init, spec=None):
        def step(carry, c_idx):
            return body(carry, c_idx), None

        carry, _ = jax.lax.scan(
            step, init, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        return carry

    def scan_merge(self, rebuild, n_chunks: int, body, init, spec=None):
        return self.scan_idx_merge(
            n_chunks, lambda carry, c: body(carry, rebuild(c), c), init, spec
        )


class SeqShardCtx:
    """S > 1 on one device: the sequential reference engine.

    Every shard's chunk scan runs under one ``lax.map`` over shard ids
    (the body is traced once, not unrolled S times), and the stacked
    partials merge with :func:`fold_leaves` — the association order the
    mesh engine reproduces.  ``"sum"``-tagged INTEGER leaves make the
    result independent of S entirely; float sums fork with S exactly the
    way ``--cohort-size`` forks from the resident path (the config hash
    carries ``pop_shards`` for the same reason)."""

    def __init__(self, n_shards: int):
        if n_shards < 2:
            raise ValueError("SeqShardCtx wants n_shards >= 2; use LOCAL")
        self.n_shards = n_shards

    def varying(self, x):
        return x

    def scan_idx_merge(self, n_chunks: int, body, init, spec):
        S = self.n_shards
        if n_chunks % S:
            raise ValueError(
                f"n_chunks {n_chunks} not divisible by pop_shards {S}"
            )
        cpp = n_chunks // S

        def one_shard(p):
            idxs = p * cpp + jnp.arange(cpp, dtype=jnp.int32)

            def step(carry, c_idx):
                return body(carry, c_idx), None

            carry, _ = jax.lax.scan(step, init, idxs)
            return carry

        stacked = jax.lax.map(one_shard, jnp.arange(S, dtype=jnp.int32))
        return merge_spec_tree(
            spec, stacked, S,
            lambda tag, parts: fold_leaves(parts, tag, S),
        )

    def scan_merge(self, rebuild, n_chunks: int, body, init, spec):
        return self.scan_idx_merge(
            n_chunks, lambda carry, c: body(carry, rebuild(c), c), init, spec
        )


#: module-level singleton: the default context every streamed aggregator
#: and the trainer's observation pass use when pop-sharding is off
LOCAL = LocalShardCtx()


# --------------------------------------------------------------------------
# Serializable cross-process partials (the 2-tier edge -> root wire)
#
# The engines above merge partial carries INSIDE one process.  The tree
# topology (serve/edge.py computes a shard's partial, serve/root.py folds
# the S shards' submissions) needs the same algebra to survive a trip
# through JSON: a canonical, schema-versioned encoding of one shard's flat
# partial leaves plus their spec tags.  Design points:
#
# * canonical bytes — every leaf serializes as the raw C-order bytes of a
#   deterministic wire dtype, base64'd into JSON.  Two processes holding
#   bit-identical arrays produce byte-identical wire strings, which is
#   what lets the root HMAC-verify submissions and byte-compare result
#   consensus ("same"-style folds) without ever re-deriving floats.
# * lossless narrow downcast — integer leaves (rank counts, histograms,
#   finite counts, sign-vote plane sums) are bounded by rows-per-shard,
#   so they ship as the smallest integer dtype whose range holds their
#   actual values and are widened back to the logical dtype on decode.
#   This is the 4x on top of the packed sign channel's 32x that keeps
#   root ingress at a small fraction of the flat f32 wire.
# * float leaves ship verbatim — the left fold is association-sensitive;
#   the wire must not round.
#
# ``WIRE_VERSION`` bumps on any change to this layout so a mixed-version
# fleet fails loudly at decode instead of folding garbage.
# --------------------------------------------------------------------------

#: version stamp carried by every wire partial (checked on decode)
WIRE_VERSION = 1

#: narrowing ladder for integer leaves, smallest first
_NARROW_INTS = (
    np.uint8, np.int8, np.uint16, np.int16, np.uint32, np.int32,
    np.uint64, np.int64,
)


def flat_tags(spec, flat_leaves):
    """Spec tags aligned with a flattened partial: specs are declared
    per-leaf (matching pytrees), but a single-string spec legitimately
    covers a multi-leaf carry whose leaves all merge the same way."""
    tags = [t for t in jax.tree.leaves(spec) if not _is_empty(t)]
    if len(tags) == 1 and len(flat_leaves) > 1:
        tags = tags * len(flat_leaves)
    if len(tags) != len(flat_leaves):
        raise ValueError(
            f"spec has {len(tags)} tags for {len(flat_leaves)} leaves"
        )
    return tags


def encode_leaf(x) -> dict:
    """One array -> a canonical JSON-safe dict (dtype, wire dtype, shape,
    base64 C-order bytes).  Integer/bool leaves narrow losslessly."""
    a = np.asarray(x)
    logical = a.dtype
    wire = a
    if a.dtype.kind == "b":
        wire = a.astype(np.uint8)
    elif a.dtype.kind in "iu" and a.size:
        lo = int(a.min())
        hi = int(a.max())
        for cand in _NARROW_INTS:
            info = np.iinfo(cand)
            if lo >= info.min and hi <= info.max:
                if np.dtype(cand).itemsize < logical.itemsize:
                    wire = a.astype(cand)
                break
    return {
        "dtype": logical.str,
        "wdtype": np.asarray(wire).dtype.str,
        "shape": list(a.shape),
        "data": base64.b64encode(
            np.ascontiguousarray(wire).tobytes()
        ).decode("ascii"),
    }


def decode_leaf(obj: dict) -> np.ndarray:
    """Inverse of :func:`encode_leaf`: back to the logical dtype,
    bit-exact."""
    wire_dt = np.dtype(obj["wdtype"])
    raw = base64.b64decode(obj["data"])
    flat = np.frombuffer(raw, dtype=wire_dt)
    arr = flat.reshape(tuple(obj["shape"]))
    logical = np.dtype(obj["dtype"])
    if logical.kind == "b":
        return arr.astype(bool)
    if wire_dt != logical:
        return arr.astype(logical)
    return np.array(arr)  # own the buffer (frombuffer views are read-only)


def partial_to_wire(flat_leaves, tags) -> dict:
    """Flat partial leaves + aligned tags -> one canonical wire dict."""
    leaves = [encode_leaf(x) for x in flat_leaves]
    return {
        "wire": WIRE_VERSION,
        "tags": list(tags),
        "leaves": leaves,
    }


def partial_from_wire(obj: dict):
    """Wire dict -> ``(flat numpy leaves, tags)``; raises ``ValueError``
    on version skew or malformed payloads (the root maps decode failures
    to edge quarantine — garbage must never reach the fold)."""
    if not isinstance(obj, dict) or obj.get("wire") != WIRE_VERSION:
        raise ValueError(
            f"wire version {obj.get('wire') if isinstance(obj, dict) else obj!r}"
            f" != {WIRE_VERSION}"
        )
    tags = list(obj.get("tags") or ())
    raw = obj.get("leaves")
    if not isinstance(raw, list) or len(raw) != len(tags):
        raise ValueError("wire partial: leaves/tags arity mismatch")
    return [decode_leaf(e) for e in raw], tags


def fold_partials(stacked_leaves, tags, n_shards: int):
    """Fold per-leaf stacked [S, ...] partials under their tags with the
    canonical left fold — the root's merge, identical by construction to
    :class:`SeqShardCtx`'s (same :func:`fold_leaves`, same shard order).
    Works on numpy or jax arrays; traced under jit by the root so its
    lowerings are retrace-gated like every other hot-path program."""
    return tuple(
        fold_leaves(s, t, n_shards) for s, t in zip(stacked_leaves, tags)
    )
